// Native codec for elasticsearch_tpu: varint/zigzag integer compression,
// delta coding for sorted postings, and CRC32 for translog frame checksums.
//
// Reference counterpart: Lucene's on-disk codecs used by the Java reference
// (oal.store.DataOutput#writeVInt / ForUtil PForDelta postings blocks) and
// the translog checksum (org.elasticsearch.index.translog's
// BufferedChecksumStreamOutput, CRC32). This is the hot byte-bashing path
// that does not belong in Python; device scoring never touches it.
//
// C ABI only — bound from Python with ctypes (no pybind11 in this image).
// All sizes are uint64. Encode buffers must be >= 10*n bytes (worst case
// one varint per value). Decoders are hardened against truncated input:
// they stop and return the count decoded so far, never read past `len`.

#include <cstdint>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32 (IEEE 802.3 polynomial, same as zlib.crc32 — the Java reference's
// java.util.zip.CRC32). Table generated at first use.
// ---------------------------------------------------------------------------

static uint32_t crc_table[256];
static bool crc_ready = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    crc_ready = true;
}

uint32_t et_crc32(const uint8_t* buf, uint64_t len, uint32_t seed) {
    if (!crc_ready) crc_init();
    uint32_t c = seed ^ 0xFFFFFFFFu;
    for (uint64_t i = 0; i < len; i++)
        c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// zigzag varint (LEB128) for int64 — Lucene writeVLong/zigzag equivalents
// ---------------------------------------------------------------------------

static inline uint64_t zigzag(int64_t v) {
    return (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
}

static inline int64_t unzigzag(uint64_t u) {
    return static_cast<int64_t>(u >> 1) ^ -static_cast<int64_t>(u & 1);
}

static inline uint8_t* put_varint(uint8_t* out, uint64_t u) {
    while (u >= 0x80) {
        *out++ = static_cast<uint8_t>(u) | 0x80;
        u >>= 7;
    }
    *out++ = static_cast<uint8_t>(u);
    return out;
}

// returns bytes written
uint64_t et_vbyte_encode(const int64_t* in, uint64_t n, uint8_t* out) {
    uint8_t* p = out;
    for (uint64_t i = 0; i < n; i++)
        p = put_varint(p, zigzag(in[i]));
    return static_cast<uint64_t>(p - out);
}

// returns values decoded (stops at max_n or on truncated input)
uint64_t et_vbyte_decode(const uint8_t* in, uint64_t len, int64_t* out,
                         uint64_t max_n) {
    const uint8_t* p = in;
    const uint8_t* end = in + len;
    uint64_t count = 0;
    while (count < max_n && p < end) {
        uint64_t u = 0;
        int shift = 0;
        bool done = false;
        while (p < end && shift < 64) {
            uint8_t b = *p++;
            u |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (!(b & 0x80)) { done = true; break; }
            shift += 7;
        }
        if (!done) break;  // truncated varint: stop cleanly
        out[count++] = unzigzag(u);
    }
    return count;
}

// ---------------------------------------------------------------------------
// delta coding for sorted sequences (postings doc ids): first value as-is,
// then gaps — gaps are small, so varints shrink hard (the PForDelta idea
// without the SIMD block layout; block packing is the R3 upgrade)
// ---------------------------------------------------------------------------

uint64_t et_delta_encode(const int64_t* in, uint64_t n, uint8_t* out) {
    uint8_t* p = out;
    int64_t prev = 0;
    for (uint64_t i = 0; i < n; i++) {
        p = put_varint(p, zigzag(in[i] - prev));
        prev = in[i];
    }
    return static_cast<uint64_t>(p - out);
}

uint64_t et_delta_decode(const uint8_t* in, uint64_t len, int64_t* out,
                         uint64_t max_n) {
    uint64_t n = et_vbyte_decode(in, len, out, max_n);
    int64_t prev = 0;
    for (uint64_t i = 0; i < n; i++) {
        prev += out[i];
        out[i] = prev;
    }
    return n;
}

}  // extern "C"
