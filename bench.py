"""Headline bench through the PRODUCT path (round-3 verdict task 1).

Every timed number drives real product surfaces — `Node.search` (the mesh
query path: parse → compile → shard_map hybrid/scatter program → fetch),
`Node.msearch` (the batched fused kernel path, search/batch.py), and
`MeshSearchExecutor.search_knn` — over a 1M-doc MS-MARCO-shaped index and a
1M x 128 SIFT-shaped vector index. No raw-ops timing.

Prints ONE JSON line with the keys the driver records:
  {"metric", "value", "unit", "vs_baseline",
   "p50_ms", "p99_ms", "batched_qps", "mfu", ...}

- p50_ms/p99_ms: single-query Node.search latency on mixed Zipfian BM25
  queries (the honest unamortized product latency; on a network-tunneled
  chip this is dominated by per-call dispatch RTT).
- p50_speedup_vs_cpu: CPU-reference p50 / TPU product-path p50 — evaluates
  BASELINE.json's ">=8x p50" target directly (`target_met`), un-massaged.
- batched_qps + vs_baseline (headline): a 2048-query pure-dense _msearch
  batch through Node.msearch (one fused qw@impact streaming-top-k per
  segment) vs the CPU reference's sequential throughput (1000/cpu_p50).
- mfu: model-flops-utilization of the batched kNN product call
  (2*Q*D*dims flops over measured wall time vs the chip's peak).
- ivf_recall_curve: recall@10 vs QPS through `knn {ann: true}` at several
  num_candidates, against exact numpy top-10 — PQ-vs-exact A/B rows
  ({num_candidates, path, recall_at_10, qps, fine_rank_k}) so the
  asymmetric coarse->fine pipeline is judged against the r05 fine-rank
  cliff on identical probes; `adc_dispatch` carries the ADC kernel
  counter deltas and `backend` (plus the per-stage `stage_backends`
  map) distinguishes a cpu-fallback run from real TPU.

CPU baseline (BASELINE.json `published` empty): in-process numpy reference
with identical Lucene-5 BM25 math — idf=ln(1+(N-df+0.5)/(df+0.5)), tfNorm
k1=1.2 b=0.75 — vectorized term-at-a-time scoring + argpartition top-k (a
stronger baseline than Lucene's per-doc iterators). Each query is timed
min-of-3 so `vs_baseline` stops swinging on machine noise (r3 verdict).

The corpus loads through the product's own segment structures
(index.segment.InvertedField/TpuSegment) built vectorized — 1M docs through
the per-doc Python parser would dominate the bench with non-search work —
then queries flow through the unmodified Node/search stack.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

K1, B = 1.2, 0.75

# mid-run stall protection (the r5 capture found the tunnel can hang a
# device call AFTER a successful boot, which no init watchdog catches):
# every log() bumps the heartbeat, finished metrics accumulate in PARTIAL,
# and a watchdog emits PARTIAL as the record if the heartbeat goes stale.
_LAST_BEAT = time.monotonic()
PARTIAL: dict = {}
CURRENT_STAGE = "boot"


def log(*a):
    global _LAST_BEAT
    _LAST_BEAT = time.monotonic()
    print(*a, file=sys.stderr, flush=True)


def stage(name: str):
    global CURRENT_STAGE
    CURRENT_STAGE = name
    # record the backend SERVING each stage (ROADMAP operational note:
    # rounds 2-5 published fallback numbers indistinguishable from real
    # TPU ones — a stage's row must say which device produced it)
    backend = "unknown"
    if "jax" in sys.modules:
        try:
            backend = sys.modules["jax"].default_backend()
        except Exception:
            pass
    PARTIAL.setdefault("stage_backends", {})[name] = backend
    log(f"-- stage: {name} [backend={backend}]")


def beat():
    """Silent heartbeat for long loops (per-shape warmup compiles run
    minutes with no log lines; only a truly hung device call may stall)."""
    global _LAST_BEAT
    _LAST_BEAT = time.monotonic()


def resolve_backend(probe_timeout: float = 75.0, tries: int = 3):
    """Decide which jax backend this run will use WITHOUT risking a hang.

    The registered tunnel plugin ("axon") retries forever inside
    ``jax.devices()`` when the TPU tunnel is down, so the r4 capture died
    rc=1/never-returned at `jax.devices()` (VERDICT r4 weak #2). Probe the
    backend in a SUBPROCESS with a hard timeout, retrying with backoff; on
    persistent failure force ``JAX_PLATFORMS=cpu`` so the bench still
    produces a parseable record (CPU sanity numbers + the failure mode)
    instead of a bare traceback.

    Returns (backend, error): backend is the platform string ("tpu",
    "cpu", ...) or "cpu-fallback"; error is the last probe failure text.
    """
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        return "cpu", None
    last_err = None
    for attempt in range(tries):
        platform, last_err = _probe_once(probe_timeout)
        if platform is not None:
            return platform, None
        log(f"backend probe {attempt + 1}/{tries} failed: {last_err}")
        if attempt < tries - 1:
            backoff = 15.0 * (attempt + 1)
            log(f"retrying backend probe in {backoff:.0f}s")
            time.sleep(backoff)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu-fallback", last_err


def _probe_once(probe_timeout: float):
    """One subprocess probe → (platform | None, error | None).

    The probe runs in its own session with output to temp files, and on
    timeout the whole process GROUP is killed: with pipes + subprocess.run a
    tunnel helper grandchild holding the pipe open would block communicate()
    past the timeout (Python gh-81605) and re-introduce the hang this exists
    to prevent.
    """
    import tempfile

    with tempfile.TemporaryFile("w+") as out, \
            tempfile.TemporaryFile("w+") as err:
        try:
            p = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; print('PLATFORM=' + jax.devices()[0].platform)"],
                stdout=out, stderr=err, text=True, start_new_session=True)
        except Exception as e:  # pragma: no cover - env-specific
            return None, f"{type(e).__name__}: {e}"
        try:
            rc = p.wait(timeout=probe_timeout)
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(p.pid, signal.SIGKILL)
            except Exception:
                p.kill()
            p.wait()
            return None, (f"backend probe timed out after "
                          f"{probe_timeout:.0f}s (TPU tunnel down?)")
        out.seek(0)
        for line in out.read().splitlines():
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1], None
        err.seek(0)
        return None, (err.read().strip()[-400:]
                      or f"probe exited rc={rc} with no platform")


def emit_record(payload: dict) -> None:
    """The ONE stdout JSON line the driver records — always parseable."""
    base = {"metric": "bm25_batched_qps", "value": 0.0, "unit": "qps",
            "vs_baseline": 0.0}
    base.update(payload)
    print(json.dumps(base), flush=True)


# ---------------------------------------------------------------------------
# corpus
# ---------------------------------------------------------------------------

def build_corpus(n_docs: int, vocab: int, seed: int):
    """Term-major postings CSR for a Zipfian synthetic corpus
    (MS-MARCO-like: ~60-token passages, Zipf vocabulary). Deterministic in
    (n_docs, vocab, seed), so the ~2-minute build at the 1M default is
    disk-cached; a cache failure falls through to a fresh build."""
    # the version token guards the cache against generator/constant
    # changes (a K1/B or distribution tweak must not silently serve
    # corpora built by older code)
    ver = f"v1_k{K1}b{B}"
    cache = os.path.join(os.path.expanduser("~"), ".cache", "estpu_bench",
                         f"corpus_{ver}_{n_docs}_{vocab}_{seed}.npz")
    try:
        z = np.load(cache)
        return (z["u_doc"], z["tf"], z["tfn"], z["offsets"], z["df"],
                z["idf"], z["doc_len"])
    except Exception:
        pass
    rng = np.random.default_rng(seed)
    doc_len = np.clip(rng.normal(60, 15, n_docs), 20, 120).astype(np.int64)
    nnz_tok = int(doc_len.sum())
    terms = rng.zipf(1.15, nnz_tok).astype(np.int64)
    terms = np.where(terms >= vocab, rng.integers(1, vocab, nnz_tok), terms)
    docs = np.repeat(np.arange(n_docs, dtype=np.int64), doc_len)

    key = terms * n_docs + docs
    uniq, tf = np.unique(key, return_counts=True)
    u_term = (uniq // n_docs).astype(np.int32)
    u_doc = (uniq % n_docs).astype(np.int32)
    df = np.bincount(u_term, minlength=vocab).astype(np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)

    avg = doc_len.mean()
    tfn = (tf * (K1 + 1) / (tf + K1 * (1 - B + B * doc_len[u_doc] / avg))
           ).astype(np.float32)
    idf = np.log(1 + (n_docs - df + 0.5) / (df + 0.5)).astype(np.float32)
    tf = tf.astype(np.float32)
    try:
        os.makedirs(os.path.dirname(cache), exist_ok=True)
        tmp = cache + f".{os.getpid()}.tmp.npz"  # savez keeps .npz names
        np.savez(tmp, u_doc=u_doc, tf=tf, tfn=tfn, offsets=offsets, df=df,
                 idf=idf, doc_len=doc_len)
        os.replace(tmp, cache)
    except Exception:
        pass  # cache is best-effort
    return u_doc, tf, tfn, offsets, df, idf, doc_len


def make_msmarco_node(u_doc, tf, tfn, offsets, df, doc_len, n_docs, vocab):
    """A real Node serving the corpus: the segment is built through the
    product's own structures (vectorized load) and injected into shard 0's
    engine; every query then flows through the unmodified search stack."""
    import jax

    from elasticsearch_tpu.index.segment import InvertedField, TpuSegment
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.utils.shapes import pad_to, pow2_bucket

    D = pow2_bucket(n_docs, minimum=64)
    nnz = u_doc.shape[0]
    nnz_pad = pow2_bucket(nnz, minimum=8)
    term_ids = np.repeat(np.arange(vocab, dtype=np.int32), df)
    inv = InvertedField(
        name="body",
        vocab={f"t{t}": t for t in range(vocab)},
        terms=[f"t{t}" for t in range(vocab)],
        df=df,
        cf=df.astype(np.int64),
        offsets=offsets,
        doc_ids=jax.device_put(pad_to(u_doc, nnz_pad, D)),
        tf=jax.device_put(pad_to(tf, nnz_pad, 0.0)),
        tfnorm=jax.device_put(pad_to(tfn, nnz_pad, 0.0)),
        term_ids=jax.device_put(pad_to(term_ids, nnz_pad, vocab)),
        nnz=nnz,
        num_docs=n_docs,
        total_terms=int(doc_len.sum()),
        avg_len=float(doc_len.mean()),
        doc_ids_host=u_doc,
        tfnorm_host=tfn,
        max_docs=D,
    )
    lens = np.zeros(D, np.float32)
    lens[:n_docs] = doc_len
    seg = TpuSegment(
        num_docs=n_docs, max_docs=D,
        inverted={"body": inv}, numerics={}, keywords={}, vectors={},
        sources=[None] * n_docs, stored=[None] * n_docs,
        ids=[str(i) for i in range(n_docs)], id_map={},
        field_lengths={"body": jax.device_put(lens)},
    )
    node = Node(name="bench")
    node.create_index("msmarco", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {"body": {"type": "text"}}}})
    node.indices["msmarco"].shards[0].engine.segments.append(seg)
    return node, seg


def make_sift_node(n_vecs: int, dims: int, seed: int):
    import jax

    from elasticsearch_tpu.index.segment import TpuSegment, VectorColumn
    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.utils.shapes import pow2_bucket

    rng = np.random.default_rng(seed + 7)
    # SIFT-like: clustered enough that IVF probing is meaningful, with
    # within-cluster similarity gaps wide enough that bf16 MXU scoring
    # resolves true neighbors (SIFT1M's own gaps are comfortably > bf16 eps)
    n_clusters = 256
    cents = rng.standard_normal((n_clusters, dims)).astype(np.float32)
    assign = rng.integers(0, n_clusters, n_vecs)
    vecs = (cents[assign]
            + rng.standard_normal((n_vecs, dims)).astype(np.float32))
    D = pow2_bucket(n_vecs, minimum=64)
    vpad = np.zeros((D, dims), np.float32)
    vpad[:n_vecs] = vecs
    exists = np.zeros(D, bool)
    exists[:n_vecs] = True
    vc = VectorColumn(name="emb", vecs=jax.device_put(vpad),
                      exists=jax.device_put(exists), dims=dims,
                      vecs_host=vpad, exists_host=exists,
                      similarity="cosine")
    seg = TpuSegment(
        num_docs=n_vecs, max_docs=D,
        inverted={}, numerics={}, keywords={}, vectors={"emb": vc},
        sources=[None] * n_vecs, stored=[None] * n_vecs,
        ids=[str(i) for i in range(n_vecs)], id_map={},
        field_lengths={},
    )
    node = Node(name="bench-sift")
    node.create_index("sift", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {
            "emb": {"type": "dense_vector", "dims": dims,
                    "similarity": "cosine",
                    "index_options": {"type": "ivf"}}}}})
    node.indices["sift"].shards[0].engine.segments.append(seg)
    return node, seg, vecs


# ---------------------------------------------------------------------------
# workloads
# ---------------------------------------------------------------------------

def make_queries(n_q: int, vocab: int, df: np.ndarray, seed: int,
                 terms_per_q: int = 4, dense_only=None):
    """Mixed Zipfian queries as term-id lists; `dense_only` (a bool[V] of
    dense-row membership) restricts sampling to dense terms."""
    rng = np.random.default_rng(seed + 1)
    qs = []
    pool = np.nonzero(dense_only)[0] if dense_only is not None else None
    for _ in range(n_q):
        npick = rng.integers(2, terms_per_q + 1)
        if pool is not None:
            t = rng.choice(pool, size=npick, replace=False)
        else:
            t = rng.zipf(1.3, npick).astype(np.int64)
            t = np.where((t >= vocab) | (df[np.clip(t, 0, vocab - 1)] == 0),
                         rng.integers(1, vocab, npick), t)
        qs.append(np.unique(t))
    return qs


def percentile_ms(times, p):
    return float(np.percentile(np.asarray(times) * 1000.0, p))


# ---------------------------------------------------------------------------
# cold_start scenario (ISSUE 14): restart A/B, pre-warm off vs on
# ---------------------------------------------------------------------------

#: child process driven three ways: seed (build + serve + persist census/
#: AOT blobs + close), off (restart with the whole zero-warmup pipeline
#: disabled), on (restart + census pre-warm + AOT/XLA caches). Every run
#: measures the FIRST nreq requests after boot — the restart cliff.
_COLD_CHILD = r'''
import json, os, sys, time
mode, data = sys.argv[1], sys.argv[2]
bodies, nreq = json.loads(sys.argv[3]), int(sys.argv[4])
from elasticsearch_tpu.utils.platform import (enable_compilation_cache,
                                              ensure_cpu_if_requested)
ensure_cpu_if_requested()
if mode != "off":
    enable_compilation_cache()
from elasticsearch_tpu.node import Node
from elasticsearch_tpu.monitor import compile_cache, programs
t0 = time.perf_counter()
n = Node(name="cold-" + mode, data_path=data)
boot_ms = (time.perf_counter() - t0) * 1000.0
if mode == "seed":
    n.create_index("coldidx", {"mappings": {"properties": {
        "body": {"type": "text"}}}})
    svc = n.indices["coldidx"]
    ndocs = int(sys.argv[5])
    for i in range(ndocs):
        svc.index_doc(str(i), {"body": "common w%d w%d tail%d" % (
            i % 13, i % 7, i % 3)})
    svc.refresh()
    for b in bodies:
        assert n.search("coldidx", b)["hits"]["total"] >= 0
    n.close()  # persists census (keys + bodies) + AOT blobs stay on disk
    print("SEEDED")
    sys.exit(0)
warmup_ms, warmup_run = 0.0, None
if mode == "on":
    t0 = time.perf_counter()
    warmup_run = n.serving.warmup.run_index("coldidx", "bench")
    warmup_ms = (time.perf_counter() - t0) * 1000.0
lat = []
c0 = programs.REGISTRY.stats()["compiles"]
for i in range(nreq):
    b = bodies[i % len(bodies)]
    t0 = time.perf_counter()
    r = n.search("coldidx", b)
    lat.append((time.perf_counter() - t0) * 1000.0)
c1 = programs.REGISTRY.stats()["compiles"]
warm = {}
for row in n.metrics.summaries().get("estpu_search_duration_seconds", []):
    if row["labels"]["index"] == "coldidx":
        warm[row["labels"]["warmup"]] = row["count"]
print("RESULT " + json.dumps({
    "mode": mode, "boot_ms": round(boot_ms, 1),
    "warmup_ms": round(warmup_ms, 1), "warmup_run": warmup_run,
    "latencies_ms": [round(x, 3) for x in lat],
    "fresh_compiles_first_page": c1 - c0,
    "warm_counts": warm,
    "compile_cache": compile_cache.events_snapshot(),
    "backend": programs.backend_fingerprint()}))
n.close()
'''


def run_cold_start(args) -> dict:
    """Cold-start restart A/B through REAL process boundaries: a seeded
    node persists its census + AOT executable blobs and dies; two fresh
    processes over the same data_path then serve the identical first
    ``--cold-requests`` requests — one with the zero-warmup pipeline
    disabled (ESTPU_WARMUP=0, ESTPU_AOT_CACHE=off, ESTPU_XLA_CACHE=off),
    one with census pre-warm + the executable caches. p50/p99 of the
    first page is the restart cliff; the acceptance wants the `on` side
    at zero fresh compiles and zero warmup=true searches."""
    import shutil
    import tempfile

    stage("cold-start")
    workdir = tempfile.mkdtemp(prefix="estpu_cold_")
    data = os.path.join(workdir, "data")
    # a handful of padded shape classes (1/2/3-term queries, two k's):
    # enough programs that the compile cliff is visible, small enough
    # that the scenario stays minutes-free on CPU
    bodies = [{"query": {"match": {"body": t}}, "size": s}
              for t in ("common", "common w1", "w2 w5 tail1")
              for s in (5, 10)]
    xla_dir = os.path.join(workdir, "xla")

    def child(mode, extra_env=None):
        env = dict(os.environ)
        env.pop("ESTPU_WARMUP", None)
        env.pop("ESTPU_AOT_CACHE", None)
        # the on-side XLA dir cache lives inside the scenario workdir so
        # a developer's warm ~/.cache can never fake a cold start
        env["ESTPU_XLA_CACHE"] = xla_dir
        env.update(extra_env or {})
        argv = [sys.executable, "-c", _COLD_CHILD, mode, data,
                json.dumps(bodies), str(args.cold_requests),
                str(args.cold_docs)]
        p = subprocess.run(argv, capture_output=True, text=True,
                           timeout=600, env=env)
        beat()
        if p.returncode != 0:
            raise RuntimeError(
                f"cold_start child [{mode}] rc={p.returncode}: "
                f"{p.stderr.strip()[-400:]}")
        lines = [ln for ln in p.stdout.splitlines()
                 if ln.startswith("RESULT ")]
        return json.loads(lines[-1][len("RESULT "):]) if lines else {}

    off_env = {"ESTPU_WARMUP": "0", "ESTPU_AOT_CACHE": "off",
               "ESTPU_XLA_CACHE": "off"}
    try:
        log(f"cold_start: seeding {args.cold_docs} docs at {data}")
        child("seed")
        log("cold_start: restart with pre-warm OFF")
        off = child("off", off_env)
        log("cold_start: restart with pre-warm ON")
        on = child("on")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    def summarize(r):
        lat = r.get("latencies_ms") or [0.0]
        return {
            "p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p99_ms": round(float(np.percentile(lat, 99)), 3),
            "first_request_ms": round(lat[0], 3),
            "boot_ms": r.get("boot_ms"),
            "warmup_ms": r.get("warmup_ms"),
            "fresh_compiles_first_page": r.get(
                "fresh_compiles_first_page"),
            "warm_counts": r.get("warm_counts"),
            "compile_cache": r.get("compile_cache"),
        }

    out = {
        "requests": args.cold_requests,
        "docs": args.cold_docs,
        "bodies": len(bodies),
        "backend": on.get("backend", "unknown"),
        "off": summarize(off),
        "on": summarize(on),
        "warmup_run": on.get("warmup_run"),
    }
    o, w = out["off"], out["on"]
    if w["p99_ms"]:
        out["p99_improvement"] = round(o["p99_ms"] / w["p99_ms"], 2)
    if w["first_request_ms"]:
        out["first_request_improvement"] = round(
            o["first_request_ms"] / w["first_request_ms"], 2)
    out["zero_warmup_met"] = bool(
        w.get("fresh_compiles_first_page") == 0
        and (w.get("warm_counts") or {}).get("true", 0) == 0)
    log(f"cold_start: off p50/p99 {o['p50_ms']}/{o['p99_ms']} ms "
        f"(first {o['first_request_ms']} ms, "
        f"{o['fresh_compiles_first_page']} compiles) | on p50/p99 "
        f"{w['p50_ms']}/{w['p99_ms']} ms (first "
        f"{w['first_request_ms']} ms, "
        f"{w['fresh_compiles_first_page']} compiles) -> p99 "
        f"{out.get('p99_improvement')}x, zero_warmup_met="
        f"{out['zero_warmup_met']}")
    PARTIAL["cold_start"] = out
    return out


# sharded_qtf child: one process per side so the scatter side can never
# ride programs the mesh side compiled (and vice versa), and so the
# 8-device CPU mesh emulation (XLA_FLAGS) binds before jax initializes.
_QTF_CHILD = '''
import json, os, random, sys, time
import numpy as np

mode, batches = sys.argv[1], json.loads(sys.argv[2])
docs, reps = int(sys.argv[3]), int(sys.argv[4])
if mode == "scatter":
    os.environ["ESTPU_DISABLE_MESH"] = "1"
from elasticsearch_tpu.monitor import kernels, programs
from elasticsearch_tpu.node import Node

WORDS = [f"w{i}" for i in range(32)]
n = Node()
n.create_index("sq", {"settings": {"number_of_shards": 8},
                      "mappings": {"properties": {
                          "body": {"type": "text"}}}})
svc = n.indices["sq"]
rng = random.Random(13)
for i in range(docs):
    svc.index_doc(str(i), {"body": " ".join(rng.choices(WORDS, k=8))})
svc.refresh()

def make_bodies(q):
    r = random.Random(100 + q)
    return [{"query": {"match": {"body": " ".join(
        r.sample(WORDS, r.randint(1, 3)))}}, "size": 10}
        for _ in range(q)]

def prog_key_counts():
    return {(e["program"], e["shapes"]):
            (e["compiles"], e["calls"],
             e["compile_seconds"], e["execute_seconds"])
            for e in programs.REGISTRY.snapshot()}

out = {}
for q in batches:
    pairs = [({"index": "sq"}, b) for b in make_bodies(q)]
    n.msearch(pairs)  # warm the shape class: compile stays out of timing
    before = prog_key_counts()
    kernels.reset()
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        n.msearch(pairs)
        times.append(time.perf_counter() - t0)
    progs = {}
    for key, (c, x, cs, xs) in prog_key_counts().items():
        b = before.get(key, (0, 0, 0.0, 0.0))
        if (c, x) != (b[0], b[1]):
            progs["|".join(key)] = {
                "compiles": c - b[0], "executes": x - b[1],
                "compile_s": round(cs - b[2], 4),
                "execute_s": round(xs - b[3], 4)}
    snap = kernels.snapshot()
    out[str(q)] = {
        "wall_ms_per_batch": round(1000 * float(np.mean(times)), 3),
        "wall_ms_per_query": round(1000 * float(np.mean(times)) / q, 3),
        "kernels": {k: v for k, v in sorted(snap.items())
                    if "mesh" in k or "bm25" in k},
        "programs": progs}
print("RESULT " + json.dumps({
    "mode": mode, "batch": out,
    "backend": programs.backend_fingerprint()}))
n.close()
'''


def run_sharded_qtf(args) -> dict:
    """Mesh-collective query-then-fetch A/B (ISSUE 16): a coalesced
    msearch batch over an 8-shard index served by ONE shard_map device
    program per batch (mesh) vs the per-shard serial scatter loop
    (ESTPU_DISABLE_MESH=1), at batch sizes 1/16/64. Each side runs in
    its own process on the emulated 8-device mesh; the record carries
    per-program compile/execute deltas and honest backend labels. The
    acceptance wants mesh beating serial scatter at batch >= 16."""
    stage("sharded-qtf")
    batches = [1, 16, 64]
    docs = 4096

    def child(mode):
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        beat()
        p = subprocess.run(
            [sys.executable, "-c", _QTF_CHILD, mode, json.dumps(batches),
             str(docs), "5"],
            capture_output=True, text=True, timeout=600, env=env)
        beat()
        if p.returncode != 0:
            raise RuntimeError(
                f"sharded_qtf child [{mode}] rc={p.returncode}: "
                f"{p.stderr.strip()[-400:]}")
        lines = [ln for ln in p.stdout.splitlines()
                 if ln.startswith("RESULT ")]
        return json.loads(lines[-1][len("RESULT "):]) if lines else {}

    log(f"sharded_qtf: 8 shards, {docs} docs, batches {batches}, "
        "mesh vs serial scatter (one process each)")
    mesh = child("mesh")
    scatter = child("scatter")
    out = {
        "shards": 8,
        "docs": docs,
        "batches": batches,
        "backend": mesh.get("backend", "unknown"),
        "mesh": mesh.get("batch", {}),
        "scatter": scatter.get("batch", {}),
    }
    speedup = {}
    for q in batches:
        m = out["mesh"].get(str(q), {}).get("wall_ms_per_batch")
        s = out["scatter"].get(str(q), {}).get("wall_ms_per_batch")
        if m and s:
            speedup[str(q)] = round(s / m, 2)
        log(f"sharded_qtf: batch={q} mesh {m} ms vs scatter {s} ms "
            f"-> {speedup.get(str(q))}x")
    out["speedup"] = speedup
    out["mesh_wins_at_16"] = bool(speedup.get("16", 0) > 1.0)
    PARTIAL["sharded_qtf"] = out
    return out


# ---------------------------------------------------------------------------
# hybrid_frontier scenario (ISSUE 19): recall@10/latency frontier of the
# fused hybrid pipeline vs each engine alone, identical probes
# ---------------------------------------------------------------------------

def run_hybrid_frontier(args) -> dict:
    """Planted-relevance A/B: each probe has 10 relevant docs whose
    signal is split across the channels (75% carry the probe's rare
    term, vectors sit near the probe centroid under noise) plus
    per-channel distractors (term-only and vector-only). BM25-only,
    kNN-only, and the fused hybrid (RRF at three weightings + linear)
    answer the SAME probes; each arm reports recall@10 against the
    planted set and p50 latency through the full product path. The
    fused path must actually serve stage 1 (kernel-counter-proven) and
    every arm's stage carries its backend label."""
    from elasticsearch_tpu.monitor import kernels as _kern
    from elasticsearch_tpu.node import Node

    stage("hybrid-frontier-build")
    rng = np.random.default_rng(args.seed + 19)
    n_docs, dims, n_q, k = 4096, min(args.dims, 64), 16, args.k
    n_rel, n_lex_noise, n_vec_noise = 10, 30, 30
    vecs = rng.standard_normal((n_docs, dims)).astype(np.float32)
    body_words = [" ".join(f"w{w}" for w in
                           rng.integers(0, 50, 3))
                  for _ in range(n_docs)]
    centroids = rng.standard_normal((n_q, dims)).astype(np.float32)
    relevant = []
    pool = rng.permutation(n_docs)
    take = 0
    for qi in range(n_q):
        rel = pool[take: take + n_rel]
        lexn = pool[take + n_rel: take + n_rel + n_lex_noise]
        vecn = pool[take + n_rel + n_lex_noise:
                    take + n_rel + n_lex_noise + n_vec_noise]
        take += n_rel + n_lex_noise + n_vec_noise
        relevant.append(set(int(i) for i in rel))
        for i in rel:
            if rng.random() < 0.75:  # lexical signal is NOISY
                body_words[i] += f" rel{qi}"
            vecs[i] = centroids[qi] + 0.55 * rng.standard_normal(dims)
        for i in lexn:  # term matches, vector doesn't
            body_words[i] += f" rel{qi}"
        for i in vecn:  # vector matches, term doesn't
            vecs[i] = centroids[qi] + 0.7 * rng.standard_normal(dims)

    node = Node(name="bench-hybrid")
    node.create_index("hyf", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {
            "body": {"type": "text"},
            "emb": {"type": "dense_vector", "dims": dims,
                    "similarity": "cosine"}}}})
    svc = node.indices["hyf"]
    for i in range(n_docs):
        svc.index_doc(str(i), {"body": body_words[i],
                               "emb": [float(x) for x in vecs[i]]})
    svc.refresh()
    beat()

    def arm(name, bodies, runs=3):
        stage(f"hybrid-frontier-{name}")
        for b in bodies:  # warm every shape class
            node.search("hyf", b)
            beat()
        times = np.full(len(bodies), np.inf)
        got = []
        for run in range(runs):
            for i, b in enumerate(bodies):
                t0 = time.perf_counter()
                r = node.search("hyf", b)
                times[i] = min(times[i], time.perf_counter() - t0)
                if run == 0:
                    got.append({int(h["_id"])
                                for h in r["hits"]["hits"]})
                beat()
        rec = float(np.mean([len(g & relevant[qi]) / n_rel
                             for qi, g in enumerate(got)]))
        p50 = percentile_ms(times, 50)
        row = {"engine": name, "recall_at_10": round(rec, 3),
               "p50_ms": round(p50, 3),
               "qps": round(1000.0 / p50, 1) if p50 > 0 else 0.0}
        log(f"hybrid_frontier [{name}]: recall@10 {rec:.3f}, "
            f"p50 {p50:.2f} ms")
        return row

    nc = 100
    qv = [[float(x) for x in centroids[qi]] for qi in range(n_q)]

    def hybrid_bodies(method, weights):
        return [{"query": {"hybrid": {
            "query": {"match": {"body": f"rel{qi}"}},
            "knn": {"field": "emb", "query_vector": qv[qi], "k": k,
                    "num_candidates": nc},
            "fusion": {"method": method, "weights": list(weights),
                       "rank_constant": 60}}}, "size": k}
            for qi in range(n_q)]

    fused_before = _kern.snapshot().get("hybrid_fused_topk", 0)
    frontier = [
        arm("bm25", [{"query": {"match": {"body": f"rel{qi}"}},
                      "size": k} for qi in range(n_q)]),
        arm("knn", [{"query": {"knn": {
            "field": "emb", "query_vector": qv[qi], "k": k,
            "num_candidates": nc}}, "size": k} for qi in range(n_q)]),
        arm("hybrid_rrf_1_1", hybrid_bodies("rrf", (1.0, 1.0))),
        arm("hybrid_rrf_2_1", hybrid_bodies("rrf", (2.0, 1.0))),
        arm("hybrid_rrf_1_2", hybrid_bodies("rrf", (1.0, 2.0))),
        arm("hybrid_linear_1_1", hybrid_bodies("linear", (1.0, 1.0))),
    ]
    fused_served = _kern.snapshot().get("hybrid_fused_topk", 0) \
        - fused_before
    by = {r["engine"]: r for r in frontier}
    best_single = max(by["bm25"]["recall_at_10"],
                      by["knn"]["recall_at_10"])
    best_hybrid = max(r["recall_at_10"] for r in frontier
                      if r["engine"].startswith("hybrid"))
    out = {
        "frontier": frontier,
        "num_candidates": nc,
        "docs": n_docs, "dims": dims, "probes": n_q,
        "fused_stage1_calls": int(fused_served),
        "best_single_recall": best_single,
        "best_hybrid_recall": best_hybrid,
        "hybrid_wins": bool(best_hybrid > best_single
                            and fused_served > 0),
    }
    log(f"hybrid_frontier: best hybrid recall {best_hybrid:.3f} vs best "
        f"single-engine {best_single:.3f} "
        f"(fused stage-1 calls: {fused_served})")
    PARTIAL["hybrid_frontier"] = out
    node.close()
    return out


def bm25_product_latency(node, queries, k, runs=3):
    """Per-query Node.search wall time (the full product path)."""
    bodies = [{"query": {"match": {"body": " ".join(f"t{t}" for t in q)}},
               "size": k} for q in queries]
    for b in bodies:  # warmup: compile every shape class
        node.search("msmarco", b)
        beat()
    times = np.full(len(bodies), np.inf)
    for _ in range(runs):
        for i, b in enumerate(bodies):
            t0 = time.perf_counter()
            r = node.search("msmarco", b)
            times[i] = min(times[i], time.perf_counter() - t0)
            beat()
    return times, r


def cpu_bm25_latency(u_doc, tfn, offsets, idf, queries, n_docs, k, runs=3):
    """Numpy reference: identical math, per-query times, min-of-runs."""
    times = np.full(len(queries), np.inf)
    tops = []
    for run in range(runs):
        for qi, q in enumerate(queries):
            t0 = time.perf_counter()
            scores = np.zeros(n_docs, np.float32)
            for t in q:
                s, e = int(offsets[t]), int(offsets[t + 1])
                if e > s:
                    scores[u_doc[s:e]] += idf[t] * tfn[s:e]
            top = np.argpartition(-scores, k)[:k]
            # Lucene tie order: equal scores rank by ascending doc id
            # (argsort alone leaves tie order to argpartition's arbitrary
            # layout, flapping the top-1 agreement probe on exact ties)
            top = top[np.lexsort((top, -scores[top]))]
            times[qi] = min(times[qi], time.perf_counter() - t0)
            beat()
            if run == 0:
                # agreement-probe copy, OUTSIDE the timed region: widen
                # the partition so ties STRADDLING the k-th position also
                # resolve by ascending doc id (argpartition alone keeps an
                # arbitrary member of a boundary tie class)
                kw = min(k + 64, scores.shape[0] - 1)
                wide = np.argpartition(-scores, kw)[:kw]
                wide = wide[np.lexsort((wide, -scores[wide]))]
                tops.append(wide[:k])
    return times, tops


# fallback counters accumulated across the kernels.reset() calls below —
# the budget check at the end must see the WHOLE workload
FALLBACKS = {"mesh_fallback_total": 0, "span_clause_truncated": 0}


#: every kernel counter folded in before a scoped kernels.reset() —
#: metrics_delta reads reset-proof totals from here + the live snapshot
KERNELS_ACCUM: dict = {}


def harvest_fallbacks():
    from elasticsearch_tpu.monitor import kernels

    snap = kernels.snapshot()
    for key in FALLBACKS:
        FALLBACKS[key] += int(snap.get(key, 0))


def reset_kernels_scoped():
    """Reset the kernel-dispatch counters for a scoped measurement, but
    fold the current values into KERNELS_ACCUM first so the whole-run
    metrics_delta (executor cache hits/misses etc.) survives the reset."""
    from elasticsearch_tpu.monitor import kernels

    for k, v in kernels.snapshot().items():
        KERNELS_ACCUM[k] = KERNELS_ACCUM.get(k, 0) + v
    kernels.reset()


def batched_msearch_qps(node, queries, k):
    """One Node.msearch call: the fused batch product path."""
    from elasticsearch_tpu.monitor import kernels

    pairs = [({"index": "msmarco"},
              {"query": {"match": {"body": " ".join(f"t{t}" for t in q)}},
               "size": k}) for q in queries]
    node.msearch(pairs)  # warmup at the FULL batch shape (jit is Q-static)
    harvest_fallbacks()
    reset_kernels_scoped()
    t0 = time.perf_counter()
    resp = node.msearch(pairs)
    dt = time.perf_counter() - t0
    snap = kernels.snapshot()
    served = snap.get("bm25_fused_topk", 0) + snap.get("bm25_hybrid", 0)
    if served < len(pairs):
        log(f"WARNING: msearch batch fell back to sequential "
            f"(batched={served}/{len(pairs)}) — batched_qps is unamortized")
    assert all(r["hits"]["total"] > 0 for r in resp["responses"][:4])
    return len(pairs) / dt, dt


def coalesced_qps(node, queries, k, n_threads=64):
    """N concurrent client threads issuing SINGLE-search bodies — no
    explicit ``_msearch`` — through the serving coalescer
    (serving/coalescer.py). Directly comparable to batched_msearch_qps
    on the same query set: the adaptive micro-batch queue must recover
    most of the explicit-batch amortization (acceptance: >= 80%).
    Returns (qps, dt, stats) where stats carries the coalescer's
    batch-size histogram delta and flush-reason counters."""
    import threading as _threading

    bodies = [{"query": {"match": {"body": " ".join(f"t{t}" for t in q)}},
               "size": k} for q in queries]

    def run_round():
        errs = []
        cursor = {"i": 0}
        lock = _threading.Lock()

        def worker():
            while True:
                with lock:
                    i = cursor["i"]
                    if i >= len(bodies):
                        return
                    cursor["i"] = i + 1
                try:
                    node.search("msmarco", bodies[i])
                except Exception as e:  # a failed round must surface
                    errs.append(e)
                    return

        threads = [_threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def _hist():
        rows = node.metrics.summaries().get(
            "estpu_coalescer_batch_size") or [{"count": 0,
                                               "sum_seconds": 0.0}]
        return rows[0]["count"], rows[0]["sum_seconds"]

    def _flushes():
        import re as _re

        out = {}
        for key, v in node.metrics.counter_values().items():
            m = _re.match(
                r'estpu_coalescer_flush_total\{reason="(\w+)"\}', key)
            if m:
                out[m.group(1)] = v
        return out

    run_round()  # warmup: compiles the pow2 batch shapes the queue emits
    harvest_fallbacks()
    reset_kernels_scoped()
    c0, s0 = _hist()
    f0 = _flushes()
    t0 = time.perf_counter()
    run_round()
    dt = time.perf_counter() - t0
    c1, s1 = _hist()
    f1 = _flushes()
    batches = c1 - c0
    stats = {
        "threads": n_threads,
        "batches": batches,
        "mean_batch": round((s1 - s0) / batches, 2) if batches else 0.0,
        "flush_reasons": {r: int(f1.get(r, 0) - f0.get(r, 0))
                          for r in f1 if f1.get(r, 0) - f0.get(r, 0)},
        "queue_wait": (node.metrics.summaries().get(
            "estpu_coalescer_queue_wait_seconds") or [{}])[0],
    }
    return len(bodies) / dt, dt, stats


def _msearch_top1(node, q):
    """Top-1 doc id for one query through the product path (agreement
    probe for the bf16-impact secondary measurement)."""
    r = node.search("msmarco", {
        "query": {"match": {"body": " ".join(f"t{t}" for t in q)}},
        "size": 1})
    beat()  # first calls under fresh cache keys compile for minutes
    hits = r["hits"]["hits"]
    return hits[0]["_id"] if hits else None


def knn_product_latency(node, qvecs, k, ann=False, num_candidates=100,
                        pq=None):
    # ann (and pq) are passed EXPLICITLY both ways: the mapping's
    # index_options would otherwise route "exact" queries through
    # IVF/PQ silently, and the recall curve must A/B the two fine-rank
    # paths on identical probes
    bodies = [{"query": {"knn": {"field": "emb", "query_vector": [float(x) for x in qv],
                                 "k": k, "num_candidates": num_candidates,
                                 "ann": bool(ann),
                                 **({} if pq is None else {"pq": bool(pq)})}},
               "size": k} for qv in qvecs]
    for b in bodies[:4]:
        node.search("sift", b)
        beat()
    times = []
    results = []
    for b in bodies:
        t0 = time.perf_counter()
        r = node.search("sift", b)
        times.append(time.perf_counter() - t0)
        results.append([int(h["_id"]) for h in r["hits"]["hits"]])
        beat()
    return np.asarray(times), results


def knn_batched_mfu(node, n_q, dims, n_vecs, k, seed, reps=3):
    """Batched kNN through the MeshSearchExecutor product API (Q large
    enough that the matmul, not dispatch, dominates)."""
    ex = node.indices["sift"].mesh_executor()
    if ex is None:
        return 0.0, 0.0
    rng = np.random.default_rng(seed + 11)
    q = rng.standard_normal((n_q, dims)).astype(np.float32)
    ex.search_knn("emb", q, k=k)  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(reps):
        ex.search_knn("emb", q, k=k)
    dt = (time.perf_counter() - t0) / reps
    flops = 2.0 * n_q * n_vecs * dims
    return flops / dt, dt


def peak_flops_bf16():
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    table = [("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
             ("v6", 918e12), ("trillium", 918e12), ("v4", 275e12),
             ("v3", 123e12)]
    for key, f in table:
        if key in kind:
            return f
    return None


# ---------------------------------------------------------------------------
# main
# ---------------------------------------------------------------------------

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=1 << 20)
    ap.add_argument("--vocab", type=int, default=30000)
    ap.add_argument("--vecs", type=int, default=1 << 20)
    ap.add_argument("--dims", type=int, default=128)
    ap.add_argument("--lat-queries", type=int, default=32)
    ap.add_argument("--batch-queries", type=int, default=2048)
    ap.add_argument("--knn-queries", type=int, default=32)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--skip-knn", action="store_true")
    ap.add_argument("--scenarios", default="core",
                    help="comma list of scenarios to run: core (the full "
                         "bm25/knn suite), cold_start (the ISSUE 14 "
                         "restart A/B), sharded_qtf (mesh vs scatter), "
                         "hybrid_frontier (ISSUE 19 fused-hybrid "
                         "recall/latency frontier) — each runs "
                         "standalone when named alone")
    ap.add_argument("--cold-docs", type=int, default=2048,
                    help="cold_start scenario corpus size (compile cost "
                         "is shape-bound, not data-bound — small keeps "
                         "the A/B honest and fast)")
    ap.add_argument("--cold-requests", type=int, default=100,
                    help="cold_start first-page request count (the "
                         "acceptance measures p50/p99 of these)")
    ap.add_argument("--probe-timeout", type=float, default=75.0)
    ap.add_argument("--stall-timeout", type=float, default=420.0,
                    help="emit the partial record and exit if no stage "
                         "progress for this many seconds (tunnel hang); "
                         "<= 0 disables; raise it for much-larger-than-"
                         "default workloads whose un-beaten phases "
                         "(corpus build, device transfers, batch compile) "
                         "legitimately run longer")
    args = ap.parse_args()
    scenarios = {s.strip() for s in args.scenarios.split(",") if s.strip()}
    unknown = scenarios - {"core", "cold_start", "sharded_qtf",
                           "hybrid_frontier"}
    if unknown or not scenarios:
        ap.error(f"unknown --scenarios {sorted(unknown)}; "
                 "choose from: core, cold_start, sharded_qtf, "
                 "hybrid_frontier")

    backend, backend_err = resolve_backend(probe_timeout=args.probe_timeout)
    if backend == "cpu-fallback":
        log(f"TPU backend unreachable ({backend_err}) — CPU sanity mode "
            f"with a reduced workload so the record still lands")
        defaults = ap.parse_args([])
        if args.docs == defaults.docs:
            args.docs = 1 << 18  # 262k: full-stack CPU run measures ~1 min
        if args.vecs == defaults.vecs:
            args.vecs = 1 << 16
        if args.batch_queries == defaults.batch_queries:
            args.batch_queries = 256

    from elasticsearch_tpu.utils.platform import (enable_compilation_cache,
                                                   ensure_cpu_if_requested)

    ensure_cpu_if_requested()
    enable_compilation_cache()  # amortize the per-shape compile zoo
    import threading

    import jax

    # the tunnel can drop BETWEEN the successful probe and this process's
    # own backend init, where jax.devices() retries forever — a watchdog
    # thread cannot interrupt the hung call, so it emits the record and
    # hard-exits instead of silently recurring the r4 rc=1/no-output run
    booted = threading.Event()

    def _watchdog():
        if not booted.wait(args.probe_timeout * 2):
            emit_record({
                "backend": backend,
                "backend_error": "in-process backend init hung after a "
                                 "successful probe (tunnel dropped?)",
                "target_met": False,
            })
            os._exit(1)

    threading.Thread(target=_watchdog, daemon=True).start()
    log(f"backend: {backend}; devices: {jax.devices()}")
    booted.set()

    # mid-run stall watchdog: a device call that never returns (tunnel
    # drop under load — observed during the r5 capture attempt) would
    # otherwise hang the whole capture with nothing on stdout. When the
    # heartbeat goes stale, emit every metric that already landed
    # (PARTIAL) plus the stage that hung, then hard-exit: partial perf
    # evidence beats none.
    # stages whose body is ONE un-beatable device call that may legitimately
    # compile for minutes on a cold compilation cache (Q=2048 batch jit)
    compile_heavy = ("batched-msearch", "batched-msearch-mixed",
                     "batched-msearch-bf16", "batched-msearch-xla-ab",
                     "knn-batched-mfu",
                     # sharded_qtf children compile the Q=64 shard_map
                     # program cold in their own processes
                     "sharded-qtf",
                     # the 1M-vec IVF build (kmeans at freeze) runs
                     # minutes un-beaten on the CPU-sanity path
                     "ivf-recall-curve")

    def _stall_watchdog():
        while True:
            time.sleep(10.0)
            idle = time.monotonic() - _LAST_BEAT
            allowed = args.stall_timeout * (
                2.0 if CURRENT_STAGE in compile_heavy else 1.0)
            if idle > allowed:
                try:
                    # snapshot defensively: the main thread may mutate
                    # PARTIAL (or the aliased knn dict) mid-copy
                    snap = {}
                    for _ in range(3):
                        try:
                            snap = {k: (dict(v) if isinstance(v, dict)
                                        else v)
                                    for k, v in list(PARTIAL.items())}
                            break
                        except RuntimeError:
                            continue
                    emit_record({
                        "target_met": False,  # snap overrides once measured
                        **snap,
                        "backend": backend,
                        "error": f"stalled: no progress for {idle:.0f}s "
                                 f"during stage '{CURRENT_STAGE}' "
                                 f"(tunnel hang?); record holds all "
                                 f"metrics captured before the stall",
                    })
                finally:
                    os._exit(1)  # the watchdog must never die silently

    if args.stall_timeout > 0:
        threading.Thread(target=_stall_watchdog, daemon=True).start()
    try:
        payload = {}
        if "core" in scenarios:
            payload = run_bench(args, jax)
        if "cold_start" in scenarios:
            cold = run_cold_start(args)
            payload["cold_start"] = cold
            if "core" not in scenarios:
                # standalone cold_start: the headline IS the restart A/B
                payload.update({
                    "metric": "cold_start_p99_improvement",
                    "value": cold.get("p99_improvement", 0.0),
                    "unit": "x",
                    "vs_baseline": cold.get("p99_improvement", 0.0),
                    "target_met": bool(cold.get("zero_warmup_met")),
                    "stage_backends": PARTIAL.get("stage_backends", {}),
                })
        if "sharded_qtf" in scenarios:
            qtf = run_sharded_qtf(args)
            payload["sharded_qtf"] = qtf
            if scenarios == {"sharded_qtf"}:
                # standalone: the headline is batch-16 mesh vs scatter
                payload.update({
                    "metric": "sharded_qtf_speedup_batch16",
                    "value": qtf.get("speedup", {}).get("16", 0.0),
                    "unit": "x",
                    "vs_baseline": qtf.get("speedup", {}).get("16", 0.0),
                    "target_met": bool(qtf.get("mesh_wins_at_16")),
                    "stage_backends": PARTIAL.get("stage_backends", {}),
                })
        if "hybrid_frontier" in scenarios:
            hyf = run_hybrid_frontier(args)
            payload["hybrid_frontier"] = hyf
            if scenarios == {"hybrid_frontier"}:
                # standalone: the headline is fused recall vs the best
                # single engine on identical probes
                payload.update({
                    "metric": "hybrid_frontier_best_recall_at_10",
                    "value": hyf.get("best_hybrid_recall", 0.0),
                    "unit": "recall",
                    "vs_baseline": hyf.get("best_single_recall", 0.0),
                    "target_met": bool(hyf.get("hybrid_wins")),
                    "stage_backends": PARTIAL.get("stage_backends", {}),
                })
    except Exception:
        import traceback

        tb = traceback.format_exc()
        log(tb)
        emit_record({
            "backend": backend,
            "backend_error": backend_err,
            "error": tb.strip().splitlines()[-1][:400],
            "target_met": False,
        })
        sys.exit(1)  # stdout stays parseable; rc still signals the crash
    payload["backend"] = backend
    if backend_err:
        payload["backend_error"] = backend_err
    emit_record(payload)


def run_bench(args, jax) -> dict:
    t_start = time.perf_counter()
    # continuous-metrics snapshot (monitor/metrics.py): the same counters
    # /_prometheus/metrics exposes, deltaed over the whole run so the
    # bench trajectory carries cache-hit/compile/eviction numbers
    from elasticsearch_tpu.monitor.metrics import (counters_delta,
                                                   process_counters)
    from elasticsearch_tpu.tracing import retrace

    # install the jit trace auditor BEFORE any ops module binds jax.jit,
    # so the delta's compile count covers the whole run (otherwise the
    # before-snapshot reads -1 = unknown and poisons the delta)
    retrace.ensure_installed()
    metrics_before = process_counters()
    stage("dispatch-floor")
    # per-call dispatch floor: the minimum round trip of ANY device call on
    # this host↔device link (tunneled chips: network RTT). Single-query
    # latency can never beat a few multiples of this — reported so p50 is
    # read against the floor, not assumed to be compute.
    tiny = jax.jit(lambda x: x + 1.0)
    tiny(0.0).block_until_ready()
    floors = []
    for _ in range(20):
        t0 = time.perf_counter()
        tiny(1.0).block_until_ready()
        floors.append(time.perf_counter() - t0)
    dispatch_floor_ms = float(np.percentile(np.asarray(floors) * 1000, 50))
    log(f"device dispatch floor (p50 of a trivial jitted call): "
        f"{dispatch_floor_ms:.2f} ms")
    PARTIAL["dispatch_floor_ms"] = round(dispatch_floor_ms, 3)
    stage("static-analysis")
    # tpulint self-measurement: rule findings + the pass-3 shapeflow
    # reach over the shipping tree ride the bench record, so a perf run
    # also documents the static health of the exact code it measured
    # (and the analyzer's own wall time is tracked release over release)
    try:
        t0 = time.perf_counter()
        from tools.tpulint import shapeflow as _shapeflow
        from tools.tpulint.project import build_project, lint_index

        _root = os.path.dirname(os.path.abspath(__file__))
        _idx, _errs = build_project(
            [os.path.join(_root, "elasticsearch_tpu"),
             os.path.join(_root, "tools"),
             os.path.join(_root, "bench.py")], root=_root)
        _found = lint_index(_idx) + _errs
        _rep = _shapeflow.analyze(_idx)
        _counts: dict = {}
        for _viol in _found:
            _counts[_viol.rule] = _counts.get(_viol.rule, 0) + 1
        PARTIAL["analysis"] = {
            "wall_s": round(time.perf_counter() - t0, 2),
            "rule_counts": dict(sorted(_counts.items())),
            "traced_fns": len(_idx.traced),
            "collective_fns": len(_idx.collective),
            "shapeflow_functions": _rep.functions,
            "shapeflow_factories": len(_rep.factories),
            "dims_classified": dict(_rep.dims_classified),
        }
        log(f"tpulint: {sum(_counts.values())} finding(s) in "
            f"{PARTIAL['analysis']['wall_s']}s; {_rep.functions} fns / "
            f"{len(_rep.factories)} factories in shapeflow reach")
    except Exception as e:  # the gate lives in CI; never sink a perf run
        PARTIAL["analysis"] = {"error": f"{type(e).__name__}: {e}"}
    stage("corpus-build")
    log(f"corpus: {args.docs} docs, vocab {args.vocab}")
    u_doc, tf, tfn, offsets, df, idf, doc_len = build_corpus(
        args.docs, args.vocab, args.seed)
    log(f"postings nnz: {u_doc.shape[0]} (built in "
        f"{time.perf_counter() - t_start:.1f}s)")
    stage("segment-device-transfer")
    node, seg = make_msmarco_node(u_doc, tf, tfn, offsets, df, doc_len,
                                  args.docs, args.vocab)

    # force the dense impact block now (product lazy build) so workloads see
    # the steady state; report its shape
    stage("dense-impact-block")
    block = seg.inverted["body"].dense_block()
    dense_rows = None
    if block is not None:
        dense_rows, impact = block
        log(f"dense impact block: F={impact.shape[0]} "
            f"({impact.shape[0] * impact.shape[1] * 4 >> 20} MB)")

    # -- single-query product latency (the headline) -------------------------
    stage("bm25-single-query-latency")
    lat_q = make_queries(args.lat_queries, args.vocab, df, args.seed)
    t0 = time.perf_counter()
    tpu_times, last = bm25_product_latency(node, lat_q, args.k)
    log(f"product latency pass done in {time.perf_counter() - t0:.1f}s; "
        f"sample total hits={last['hits']['total']}")
    p50, p99 = percentile_ms(tpu_times, 50), percentile_ms(tpu_times, 99)
    PARTIAL.update(p50_ms=round(p50, 3), p99_ms=round(p99, 3))

    stage("cpu-baseline")
    cpu_times, cpu_tops = cpu_bm25_latency(u_doc, tfn, offsets, idf, lat_q,
                                           args.docs, args.k)
    cpu_p50 = percentile_ms(cpu_times, 50)
    vs = cpu_p50 / p50 if p50 > 0 else 0.0
    log(f"bm25 single-query p50: tpu {p50:.2f} ms, p99 {p99:.2f} ms; "
        f"cpu p50 {cpu_p50:.2f} ms -> {vs:.1f}x (target >= 8x)")
    PARTIAL.update(cpu_p50_ms=round(cpu_p50, 3),
                   p50_speedup_vs_cpu=round(vs, 2),
                   target_p50_speedup=8.0, target_met=bool(vs >= 8.0))

    # correctness spot check: product top-1 vs numpy oracle top-1
    n_chk = min(16, len(lat_q))

    def top1_agreement(nd) -> int:
        got = 0
        for q, cpu_top in zip(lat_q[:n_chk], cpu_tops[:n_chk]):
            r = nd.search("msmarco", {
                "query": {"match": {"body": " ".join(f"t{t}" for t in q)}},
                "size": 1})
            if r["hits"]["hits"] \
                    and int(r["hits"]["hits"][0]["_id"]) == cpu_top[0]:
                got += 1
            beat()  # size-1 shape class may compile on first call
        return got

    agree = top1_agreement(node)
    log(f"top-1 agreement vs numpy oracle: {agree}/{n_chk}")
    PARTIAL["top1_agreement"] = round(agree / max(n_chk, 1), 3)
    stage("tuned-single-query-latency")

    # SECONDARY: the tuned single-query config (ranking-grade matmul
    # precision + blocked top-k staging) on the SAME node — the knobs
    # are read at dispatch time and key every jit/program cache
    # (ops/scoring.py::impact_precision/topk_block_config), so flipping
    # the env compiles tuned programs next to the exact ones with no
    # second corpus in HBM. Clearly labeled: the headline p50 above
    # stays the untouched exact default.
    fast_env = {"ESTPU_IMPACT_PRECISION": "default",
                "ESTPU_BLOCKED_TOPK": "1"}
    old_env = {name: os.environ.get(name) for name in fast_env}
    os.environ.update(fast_env)
    p50_fast, fast_agree = 0.0, 0
    try:
        try:
            fast_times, _ = bm25_product_latency(node, lat_q, args.k)
            p50_fast = percentile_ms(fast_times, 50)
        except Exception as e:  # the secondary must never sink the capture
            log(f"tuned-config latency pass failed: {e}")
        if p50_fast > 0:
            try:
                fast_agree = top1_agreement(node)
            except Exception as e:  # keep the measured p50 regardless
                log(f"tuned-config agreement probe failed: {e}")
            log(f"tuned single-query p50 (prec=default + blocked topk): "
                f"{p50_fast:.2f} ms -> {cpu_p50 / p50_fast:.1f}x; top-1 "
                f"agreement {fast_agree}/{n_chk}")
    finally:
        for name, v in old_env.items():
            if v is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = v

    stage("tail-mode-ab")
    # A/B the single-query tail construction: candidate-set (TPU default;
    # scatter-free) vs the [D] scatter-add. Whichever loses informs the
    # auto default; the record carries both.
    _tm_old = os.environ.get("ESTPU_TAIL_MODE")
    try:
        mode = (_tm_old or "auto").lower()
        if mode == "auto":  # resolve the platform default being measured
            mode = ("candidates" if jax.default_backend() == "tpu"
                    else "scatter")
        other = "scatter" if mode == "candidates" else "candidates"
        os.environ["ESTPU_TAIL_MODE"] = other
        ab_times, _ = bm25_product_latency(node, lat_q, args.k)
        p50_ab = percentile_ms(ab_times, 50)
        log(f"tail-mode A/B ({other}): p50 {p50_ab:.2f} ms "
            f"(default-mode p50 {p50:.2f} ms)")
        PARTIAL[f"p50_ms_tail_{other}"] = round(p50_ab, 3)
    except Exception as e:  # secondary: never sink the capture
        log(f"tail-mode A/B failed: {e}")
    finally:
        if _tm_old is None:
            os.environ.pop("ESTPU_TAIL_MODE", None)
        else:
            os.environ["ESTPU_TAIL_MODE"] = _tm_old

    # -- batched product path ------------------------------------------------
    stage("batched-msearch")
    PARTIAL.update(
        p50_ms_tuned=round(p50_fast, 3),
        p50_speedup_vs_cpu_tuned=round(
            cpu_p50 / p50_fast if p50_fast > 0 else 0.0, 2),
        tuned_top1_agreement=round(fast_agree / max(n_chk, 1), 3))
    if dense_rows is not None:
        dense_mask = np.zeros(args.vocab, bool)
        dense_tids = np.nonzero(dense_rows >= 0)[0]
        dense_mask[dense_tids[dense_tids < args.vocab]] = True
        bat_q = make_queries(args.batch_queries, args.vocab, df, args.seed,
                             dense_only=dense_mask)
        batched_qps, bdt = batched_msearch_qps(node, bat_q, args.k)
        bm25_mfu_flops = 4.0 * len(bat_q) * impact.shape[0] * seg.max_docs
        log(f"batched msearch: {len(bat_q)} pure-dense queries in "
            f"{bdt * 1000:.0f} ms -> {batched_qps:.0f} qps")
        cpu_qps_now = 1000.0 / cpu_p50 if cpu_p50 > 0 else 1.0
        PARTIAL.update(batched_qps=round(batched_qps, 1),
                       value=round(batched_qps, 1),
                       vs_baseline=round(batched_qps / cpu_qps_now, 2))
        stage("batched-msearch-xla-ab")
        # A/B the batch kernel: the fused Pallas selection vs XLA's
        # chunked matmul + top_k (ESTPU_BM25_BATCH_KERNEL). Whichever
        # wins informs the default; both numbers land in the record.
        try:
            os.environ["ESTPU_BM25_BATCH_KERNEL"] = "xla"
            qps_xla, xdt = batched_msearch_qps(node, bat_q, args.k)
            log(f"batched msearch (XLA kernel): {len(bat_q)} queries in "
                f"{xdt * 1000:.0f} ms -> {qps_xla:.0f} qps "
                f"(pallas: {batched_qps:.0f})")
            PARTIAL["batched_qps_xla"] = round(qps_xla, 1)
        except Exception as e:  # the A/B must never sink the capture
            log(f"XLA batch A/B failed: {e}")
        finally:
            os.environ.pop("ESTPU_BM25_BATCH_KERNEL", None)
        stage("batched-msearch-mixed")
        # mixed Zipfian batch (rare-term scatter tails allowed): the
        # tier-2 hybrid batch path — realistic msearch traffic, not the
        # pure-dense best case
        mixed_q = make_queries(args.batch_queries, args.vocab, df,
                               args.seed + 9)
        batched_qps_mixed, mdt = batched_msearch_qps(node, mixed_q, args.k)
        log(f"batched msearch mixed: {len(mixed_q)} queries in "
            f"{mdt * 1000:.0f} ms -> {batched_qps_mixed:.0f} qps")
        PARTIAL["batched_qps_mixed"] = round(batched_qps_mixed, 1)
        stage("coalesced-qps")
        # cross-request coalescing (serving/): N concurrent clients
        # firing SINGLE-search bodies — no explicit _msearch — must
        # recover most of the explicit-batch amortization through the
        # adaptive micro-batch queue (ROADMAP item #1 acceptance >= 80%)
        try:
            co_qps, cdt, co_stats = coalesced_qps(node, bat_q, args.k)
            frac = co_qps / batched_qps if batched_qps else 0.0
            log(f"coalesced: {len(bat_q)} single-search bodies over "
                f"{co_stats['threads']} threads in {cdt * 1000:.0f} ms "
                f"-> {co_qps:.0f} qps ({frac * 100:.0f}% of explicit "
                f"msearch), mean batch {co_stats['mean_batch']}, "
                f"flushes {co_stats['flush_reasons']}")
            PARTIAL["coalesced_qps"] = round(co_qps, 1)
            PARTIAL["coalesced_vs_batched"] = round(frac, 3)
            PARTIAL["coalescer"] = co_stats
        except Exception as e:  # the scenario must never sink the capture
            log(f"coalesced_qps failed: {e}")
        stage("batched-msearch-bf16")
        # secondary: bf16-quantized impact block (SURVEY §6 lever) — same
        # batch, block rebuilt in bf16; report throughput AND top-1
        # agreement vs the f32 path so the quantization cost is visible
        import os as _os

        inv = seg.inverted["body"]
        sample = bat_q[:64]
        tops32 = [_msearch_top1(node, q) for q in sample]
        _os.environ["ESTPU_IMPACT_BF16"] = "1"
        try:
            with inv._dense_lock:
                # dropping the handle releases its fielddata-breaker
                # charge (resources/residency.py finalizer); the next
                # dense_block() rebuilds in bf16
                inv._dense = None
                inv._dense_host = None
            beat()
            blk16 = inv.dense_block()
            beat()  # bf16 block rebuild + transfer just completed
            if blk16 is not None:
                batched_qps_bf16, bdt16 = batched_msearch_qps(
                    node, bat_q, args.k)
                tops16 = [_msearch_top1(node, q) for q in sample]
                bf16_agree = float(np.mean([a == b for a, b in
                                            zip(tops32, tops16)]))
                log(f"batched msearch bf16 impacts: {bdt16 * 1000:.0f} ms "
                    f"-> {batched_qps_bf16:.0f} qps, top-1 agreement "
                    f"{bf16_agree:.3f}")
                PARTIAL.update(batched_qps_bf16=round(batched_qps_bf16, 1),
                               bf16_top1_agreement=round(bf16_agree, 3))
            else:
                batched_qps_bf16, bf16_agree = 0.0, 0.0
        finally:
            del _os.environ["ESTPU_IMPACT_BF16"]
    else:
        batched_qps, bm25_mfu_flops, bdt = 0.0, 0.0, 1.0
        batched_qps_bf16, bf16_agree = 0.0, 0.0
        batched_qps_mixed = 0.0
        log("no dense block — batched path skipped")

    peak = peak_flops_bf16()
    bm25_mfu = (bm25_mfu_flops / bdt / peak) if peak else 0.0
    PARTIAL["bm25_batched_mfu"] = round(bm25_mfu, 4)

    # -- kNN product path ----------------------------------------------------
    stage("knn-segment-build")
    knn = {}
    mfu = 0.0
    if not args.skip_knn:
        sift_node, sift_seg, vecs = make_sift_node(args.vecs, args.dims,
                                                   args.seed)
        rng = np.random.default_rng(args.seed + 3)
        # queries near corpus points (recall is defined against real nbrs)
        qidx = rng.integers(0, args.vecs, args.knn_queries)
        qvecs = vecs[qidx] + 0.1 * rng.standard_normal(
            (args.knn_queries, args.dims)).astype(np.float32)

        stage("knn-exact-latency")
        times, got = knn_product_latency(sift_node, qvecs, args.k)
        knn["p50_ms"] = percentile_ms(times, 50)
        knn["p99_ms"] = percentile_ms(times, 99)
        PARTIAL["knn"] = knn  # knn dict mutations flow into the record

        # exact numpy reference (same metric: cosine)
        qs = qvecs / np.linalg.norm(qvecs, axis=1, keepdims=True)
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        cpu_t = np.full(args.knn_queries, np.inf)
        exact = []
        for run in range(3):
            for i in range(args.knn_queries):
                t0 = time.perf_counter()
                sc = vn @ qs[i]
                top = np.argpartition(-sc, args.k)[: args.k]
                top = top[np.argsort(-sc[top])]
                cpu_t[i] = min(cpu_t[i], time.perf_counter() - t0)
                if run == 0:
                    exact.append(top)
        knn["cpu_p50_ms"] = percentile_ms(cpu_t, 50)
        knn["vs_cpu"] = knn["cpu_p50_ms"] / knn["p50_ms"]
        rec = np.mean([len(set(g) & set(e.tolist())) / args.k
                       for g, e in zip(got, exact)])
        knn["recall_at_10"] = float(rec)
        log(f"knn exact: tpu p50 {knn['p50_ms']:.2f} ms vs cpu "
            f"{knn['cpu_p50_ms']:.2f} ms ({knn['vs_cpu']:.1f}x), "
            f"recall@10 {rec:.3f}")

        stage("knn-batched-mfu")
        flops_rate, kdt = knn_batched_mfu(sift_node, 256, args.dims,
                                          args.vecs, args.k, args.seed)
        mfu = (flops_rate / peak) if peak else 0.0
        log(f"knn batched (executor.search_knn, Q=256): {kdt * 1000:.0f} ms, "
            f"mfu {mfu:.3f}")
        PARTIAL["mfu"] = round(mfu, 4)

        # IVF recall@10-vs-QPS curve through the product ANN path:
        # PQ-vs-exact A/B on identical probes. "exact" is the r05
        # fine-rank path (f32 re-score of EVERY probed candidate —
        # the measured 389 -> 12.6 qps cliff); "pq" is the asymmetric
        # coarse->fine pipeline (ADC over codes, exact re-rank of the
        # top fine_rank_k survivors only).
        stage("ivf-recall-curve")
        import jax as _jax_mod

        from elasticsearch_tpu.utils.shapes import pow2_bucket as _p2

        knn["backend"] = _jax_mod.default_backend()
        fine_rank_k = int(min(_p2(max(8 * args.k, 128)),
                              sift_seg.max_docs))
        curve = []
        from elasticsearch_tpu.monitor import kernels as _kern

        adc_before = {c: _kern.snapshot().get(c, 0)
                      for c in ("adc_pallas", "adc_xla", "knn_ivf_pq",
                                "adc_pallas_failed", "pq_build",
                                "pq_cache_hit")}
        for nc in (1000, 4000, 16000):
            for path, use_pq in (("exact", False), ("pq", True)):
                times, got = knn_product_latency(sift_node, qvecs, args.k,
                                                 ann=True,
                                                 num_candidates=nc,
                                                 pq=use_pq)
                r = np.mean([len(set(g) & set(e.tolist())) / args.k
                             for g, e in zip(got, exact)])
                curve.append({
                    "num_candidates": nc, "path": path,
                    "recall_at_10": round(float(r), 3),
                    "qps": round(1000.0 / percentile_ms(times, 50), 1),
                    "fine_rank_k": fine_rank_k if use_pq else None,
                })
                log(f"ivf nc={nc} [{path}]: recall@10 {r:.3f}, "
                    f"p50 {percentile_ms(times, 50):.2f} ms")
        knn["ivf_recall_curve"] = curve
        snap = _kern.snapshot()
        knn["adc_dispatch"] = {c: snap.get(c, 0) - v
                               for c, v in adc_before.items()}
        by_nc = {(row["num_candidates"], row["path"]): row for row in curve}
        exact16 = by_nc.get((16000, "exact"))
        pq16 = by_nc.get((16000, "pq"))
        if exact16 and pq16 and exact16["qps"] > 0:
            knn["pq_speedup_at_16k"] = round(pq16["qps"] / exact16["qps"], 2)
            log(f"pq speedup at nc=16000: {knn['pq_speedup_at_16k']}x "
                f"(recall {pq16['recall_at_10']})")

    # fallback budget (r4 verdict weak #5): the bench workload must be
    # served by the device product path — any host fallback or span
    # truncation on it is a regression, reported first-class
    harvest_fallbacks()
    mesh_fallback = FALLBACKS["mesh_fallback_total"]
    span_trunc = FALLBACKS["span_clause_truncated"]
    if mesh_fallback or span_trunc:
        log(f"WARNING: fallback budget exceeded — mesh_fallback_total="
            f"{mesh_fallback}, span_clause_truncated={span_trunc}")

    stage("steady-state-floor")
    # steady-state floor: the same trivial call AFTER the workload ran —
    # some host-device links (tunneled chips) settle into a slower
    # synchronized mode once large transfers have occurred; p50 should be
    # read against THIS floor, not the pristine-session one
    floors = []
    for _ in range(20):
        t0 = time.perf_counter()
        tiny(1.0).block_until_ready()
        floors.append(time.perf_counter() - t0)
    floor_steady_ms = float(np.percentile(np.asarray(floors) * 1000, 50))
    log(f"steady-state dispatch floor: {floor_steady_ms:.2f} ms "
        f"(pristine was {dispatch_floor_ms:.2f} ms)")
    log(f"total bench wall time: {time.perf_counter() - t_start:.0f}s")
    # headline: batched product-path throughput vs the CPU reference's
    # sequential throughput (1000/cpu_p50). Single-query p50 and the
    # BASELINE >=8x p50 target are reported alongside, un-massaged — on a
    # network-tunneled chip per-call dispatch RTT dominates single-query
    # latency (see p50_ms vs batched amortization).
    # the record IS the PARTIAL dict (every metric was written into it at
    # measurement time, so a stall record is a strict prefix of this one)
    # plus the end-only fields
    metrics_after = process_counters()
    # re-add the kernel counts the scoped resets wiped (batched_msearch_qps
    # resets to attribute fallbacks; the run total must not lose them)
    for k, v in KERNELS_ACCUM.items():
        metrics_after[f"kernels.{k}"] = \
            metrics_after.get(f"kernels.{k}", 0.0) + v
    delta = counters_delta(metrics_before, metrics_after)
    PARTIAL["metrics_delta"] = {
        # the headline counters, named (executor cache economics, device
        # compiles, HBM tier churn) ...
        "executor_prep_hits": delta.get("kernels.executor_prep_hit", 0),
        "executor_prep_misses": delta.get("kernels.executor_prep_miss", 0),
        "executor_data_hits": delta.get("kernels.executor_data_hit", 0),
        "executor_data_misses": delta.get("kernels.executor_data_miss", 0),
        # null = trace auditor not installed (unknown, never a fake 0 and
        # never a -1 sentinel that leaks into sums)
        "jit_compiles": delta.get("jit.traces_total"),
        # AOT executable cache (parallel/aot.py): per-source resolution
        # counts + deserialize cost — null (not 0) while the AOT layer
        # never resolved, same typed-absence contract as jit_compiles
        "compile_cache_aot_hits": delta.get("compile_cache.aot_hit"),
        "compile_cache_xla_dir_hits": delta.get(
            "compile_cache.xla_dir_hit"),
        "compile_cache_fresh": delta.get("compile_cache.fresh"),
        "compile_cache_deserialize_seconds": delta.get(
            "compile_cache.deserialize_seconds"),
        "evictions": delta.get("residency.evictions", 0),
        "rehydrations": delta.get("residency.rehydrations", 0),
        "breaker_tripped": sum(
            v for k, v in delta.items()
            if k.startswith("breakers.") and v > 0),
        # stall watchdog (monitor/watchdog.py): a detector tripping (or
        # an incident dump captured) DURING a bench round is exactly the
        # kind of anomaly that silently corrupts a perf number — surface
        # it in the artifact, not only in the node's flight ring
        "watchdog_trips": delta.get("watchdog.trips", 0),
        "incidents": delta.get("watchdog.incidents", 0),
        # ... plus every other counter that moved during the run (None =
        # unavailable keys are dropped here; `jit_compiles` above carries
        # the typed null)
        "counters": {k: v for k, v in delta.items() if v},
    }
    # device-program observatory (monitor/programs.py): per-key
    # compile/execute deltas over the whole run — which programs this
    # workload compiled, what tracing+compilation cost vs cached
    # execution, ranked by execute time so the hot keys lead
    prog_delta = {
        k: v for k, v in delta.items()
        if k.startswith("programs.") and v
    }
    from elasticsearch_tpu.monitor import programs as _programs

    prog_rows = _programs.REGISTRY.snapshot()
    prog_rows.sort(key=lambda r: -r["execute_seconds"])
    PARTIAL["programs"] = {
        "backend": _programs.backend_fingerprint(),
        "totals": _programs.REGISTRY.stats(),
        "delta": prog_delta,
        "top_by_execute": [
            {k: r[k] for k in ("program", "shapes", "compiles",
                               "compile_seconds", "calls",
                               "execute_seconds", "execute_p50_seconds",
                               "execute_p99_seconds", "cold")}
            for r in prog_rows[:12]],
    }
    jc = PARTIAL['metrics_delta']['jit_compiles']
    log(f"metrics delta: prep {PARTIAL['metrics_delta']['executor_prep_hits']}"
        f"/{PARTIAL['metrics_delta']['executor_prep_misses']} hit/miss, "
        f"{'unknown' if jc is None else jc} jit traces, "
        f"{PARTIAL['metrics_delta']['evictions']} evictions; "
        f"programs: {PARTIAL['programs']['totals']}")
    cpu_qps = 1000.0 / cpu_p50 if cpu_p50 > 0 else 1.0
    PARTIAL.update({
        "metric": "bm25_batched_qps",
        "value": round(batched_qps, 1),
        "unit": "qps",
        "vs_baseline": round(batched_qps / cpu_qps, 2),
        "batched_qps": round(batched_qps, 1),
        "batched_qps_mixed": round(batched_qps_mixed, 1),
        "batched_qps_bf16": round(batched_qps_bf16, 1),
        "bf16_top1_agreement": round(bf16_agree, 3),
        "mfu": round(mfu, 4),
        "dispatch_floor_steady_ms": round(floor_steady_ms, 3),
        "mesh_fallback_total": mesh_fallback,
        "span_clause_truncated": span_trunc,
        "fallback_budget_met": bool(mesh_fallback == 0 and span_trunc == 0),
        "docs": args.docs,
        "knn": knn,
    })
    return dict(PARTIAL)


if __name__ == "__main__":
    main()
