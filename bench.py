"""Headline bench: batched BM25 QPS on a synthetic MS-MARCO-like corpus.

Prints ONE JSON line:
  {"metric": "bm25_batched_qps", "value": <tpu qps>, "unit": "qps",
   "vs_baseline": <tpu qps / cpu-reference qps>}

Baseline (SURVEY.md §6 / BASELINE.json "published" empty): an in-process
CPU reference computing the identical Lucene-5-style BM25 math
(idf = ln(1+(N-df+0.5)/(df+0.5)), tfNorm k1=1.2 b=0.75) with vectorized
numpy term-at-a-time scoring + argpartition top-k — a *stronger* baseline
than Lucene's per-doc iterators. The TPU path scores whole-segment dense
vectors per query batch (vmapped scatter-add + fused top-k) from
device-resident postings.

Corpus: Zipfian vocabulary, ~60-token passages (MS-MARCO-like shape).
Secondary diagnostics (kNN SIFT-like, latency split) go to stderr.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

K1, B = 1.2, 0.75


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_corpus(n_docs: int, vocab: int, seed: int):
    """Postings CSR (term-major) for a Zipfian synthetic corpus."""
    rng = np.random.default_rng(seed)
    doc_len = np.clip(rng.normal(60, 15, n_docs), 20, 120).astype(np.int64)
    nnz_tok = int(doc_len.sum())
    terms = rng.zipf(1.15, nnz_tok).astype(np.int64)
    terms = np.where(terms >= vocab, rng.integers(1, vocab, nnz_tok), terms)
    docs = np.repeat(np.arange(n_docs, dtype=np.int64), doc_len)

    # (term, doc) -> tf
    key = terms * n_docs + docs
    uniq, tf = np.unique(key, return_counts=True)
    u_term = (uniq // n_docs).astype(np.int32)
    u_doc = (uniq % n_docs).astype(np.int32)
    # already sorted by term then doc (uniq is sorted)
    df = np.bincount(u_term, minlength=vocab).astype(np.int32)
    offsets = np.zeros(vocab + 1, np.int64)
    offsets[1:] = np.cumsum(df)

    avg = doc_len.mean()
    tfn = (tf * (K1 + 1) / (tf + K1 * (1 - B + B * doc_len[u_doc] / avg))
           ).astype(np.float32)
    idf = np.log(1 + (n_docs - df + 0.5) / (df + 0.5)).astype(np.float32)
    return u_doc, tfn, offsets, df, idf


def make_queries(n_q: int, vocab: int, df: np.ndarray, seed: int,
                 terms_per_q: int = 4):
    rng = np.random.default_rng(seed + 1)
    qs = []
    for _ in range(n_q):
        t = rng.zipf(1.3, terms_per_q).astype(np.int64)
        t = np.where((t >= vocab) | (df[np.clip(t, 0, vocab - 1)] == 0),
                     rng.integers(1, vocab, terms_per_q), t)
        qs.append(np.unique(t))
    return qs


def chunk_tables(queries, offsets, idf):
    """Per-query (starts, lens, ws) via the product path's run splitter
    (search/context.py split_runs); common T bucket."""
    from elasticsearch_tpu.search.context import split_runs

    tabs = []
    maxlen, maxT = 1, 1
    for q in queries:
        runs = [(int(offsets[t]), int(offsets[t + 1] - offsets[t]),
                 float(idf[t])) for t in q]
        st, ln, ws, ml = split_runs(runs)
        maxlen = max(maxlen, ml)
        maxT = max(maxT, len(st))
        tabs.append((st, ln, ws))
    P = 1
    while P < maxlen:
        P *= 2
    T = 1
    while T < maxT:
        T *= 2
    starts = np.zeros((len(queries), T), np.int32)
    lens = np.zeros((len(queries), T), np.int32)
    ws = np.zeros((len(queries), T), np.float32)
    for i, (s, l, w) in enumerate(tabs):
        starts[i, : len(s)] = s
        lens[i, : len(l)] = l
        ws[i, : len(w)] = w
    return starts, lens, ws, P, T


def hybrid_tables(queries, offsets, idf, dense_rows, F):
    """Per-query dense-row weight matrix qw[Q, F] + CSR tail chunk tables —
    the product path's hybrid split (search/context.py hybrid_slices)."""
    from elasticsearch_tpu.search.context import split_runs

    Q = len(queries)
    qw = np.zeros((Q, F), np.float32)
    tabs = []
    maxlen, maxT = 1, 1
    for i, q in enumerate(queries):
        runs = []
        for t in q:
            row = dense_rows[t]
            if row >= 0:
                qw[i, row] += idf[t]
            else:
                runs.append((int(offsets[t]), int(offsets[t + 1] - offsets[t]),
                             float(idf[t])))
        st, ln, ws, ml = split_runs(runs) if runs else ([], [], [], 1)
        maxlen = max(maxlen, ml)
        maxT = max(maxT, len(st))
        tabs.append((st, ln, ws))
    P = 1
    while P < maxlen:
        P *= 2
    T = 1
    while T < max(maxT, 1):
        T *= 2
    starts = np.zeros((Q, T), np.int32)
    lens = np.zeros((Q, T), np.int32)
    ws = np.zeros((Q, T), np.float32)
    for i, (s, l, w) in enumerate(tabs):
        starts[i, : len(s)] = s
        lens[i, : len(l)] = l
        ws[i, : len(w)] = w
    return qw, starts, lens, ws, P, T


def cpu_reference(u_doc, tfn, tabs, n_docs, k):
    """Vectorized numpy term-at-a-time BM25 + argpartition top-k."""
    starts, lens, ws = tabs
    out = []
    t0 = time.perf_counter()
    for qi in range(starts.shape[0]):
        scores = np.zeros(n_docs, np.float32)
        for ci in range(starts.shape[1]):
            ln = lens[qi, ci]
            if ln == 0:
                continue
            s = starts[qi, ci]
            d = u_doc[s:s + ln]
            scores[d] += ws[qi, ci] * tfn[s:s + ln]
        top = np.argpartition(-scores, k)[:k]
        out.append(top[np.argsort(-scores[top])])
    return time.perf_counter() - t0, out


def tpu_path(u_doc, tfn, offsets, df, idf, queries, n_docs, k, qbatch):
    """Hybrid dense/sparse scoring: frequent terms via ONE MXU matmul
    (qw[Q,F] @ impact[F,D]), short tail via scatter — the product path's
    layout (index/segment.py build_dense_impact + ops bm25_score_hybrid_batch).
    """
    import jax

    from elasticsearch_tpu.index.segment import build_dense_impact
    from elasticsearch_tpu.ops.scoring import (
        bm25_score_batch, bm25_score_hybrid_batch, topk_batch)

    D = 1
    while D < n_docs:
        D *= 2
    nnz = u_doc.shape[0]
    nnz_pad = 1
    while nnz_pad < nnz:
        nnz_pad *= 2
    d_doc = np.full(nnz_pad, D, np.int32)
    d_doc[:nnz] = u_doc
    d_tfn = np.zeros(nnz_pad, np.float32)
    d_tfn[:nnz] = tfn
    dev_doc = jax.device_put(d_doc)
    dev_tfn = jax.device_put(d_tfn)
    mask = jax.device_put(np.ones(D, bool))

    block = build_dense_impact(u_doc, tfn, offsets, df, D)
    if block is not None:
        dense_rows, impact_np = block
        impact = jax.device_put(impact_np)
        F = impact_np.shape[0]
        log(f"dense block: F={F} rows ({impact_np.nbytes >> 20} MB)")
        qw, starts, lens, ws, P, T = hybrid_tables(
            queries, offsets, idf, dense_rows, F)
        log(f"hybrid tail: T={T} P={P}")

        def run_batch(q, s, l, w):
            scores = bm25_score_hybrid_batch(
                impact, q, dev_doc, dev_tfn, s, l, w, P=P, D=D)
            return topk_batch(scores, mask, k=k)
    else:
        qw = None
        starts, lens, ws, P, T = chunk_tables(queries, offsets, idf)
        log(f"chunk tables: T={T} P={P}")

        def run_batch(q, s, l, w):
            scores = bm25_score_batch(dev_doc, dev_tfn, s, l, w, P=P, D=D)
            return topk_batch(scores, mask, k=k)

    nq = len(queries)

    def pad_rows(a):
        """Pad Q to a qbatch multiple so every timed dispatch reuses the one
        compiled [qbatch, ...] program."""
        rem = (-a.shape[0]) % qbatch
        if rem:
            a = np.concatenate([a, np.zeros((rem,) + a.shape[1:], a.dtype)])
        return a

    starts, lens, ws = pad_rows(starts), pad_rows(lens), pad_rows(ws)
    d_s = jax.device_put(starts)
    d_l = jax.device_put(lens)
    d_w = jax.device_put(ws)
    d_q = jax.device_put(pad_rows(qw)) if qw is not None else None

    def batches():
        for q0 in range(0, starts.shape[0], qbatch):
            sl = slice(q0, q0 + qbatch)
            yield (d_q[sl] if d_q is not None else None,
                   d_s[sl], d_l[sl], d_w[sl])

    # warmup / compile
    v, i = run_batch(*next(iter(batches())))
    v.block_until_ready()

    out = []
    t0 = time.perf_counter()
    for qb, sb, lb, wb in batches():
        v, idx = run_batch(qb, sb, lb, wb)
        out.append(idx)  # device array — no host sync inside the timed loop
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    return dt, np.concatenate([np.asarray(o) for o in out], axis=0)[:nq]


def knn_bench(n_vecs: int, dims: int, n_q: int, k: int, seed: int):
    import jax

    from elasticsearch_tpu.ops.knn import knn_topk

    rng = np.random.default_rng(seed + 7)
    vecs = rng.standard_normal((n_vecs, dims)).astype(np.float32)
    qs = rng.standard_normal((n_q, dims)).astype(np.float32)
    dv = jax.device_put(vecs)
    dm = jax.device_put(np.ones(n_vecs, bool))
    dq = jax.device_put(qs)
    v, i = knn_topk(dq, dv, dm, k=k, metric="dot")
    v.block_until_ready()
    t0 = time.perf_counter()
    v, i = knn_topk(dq, dv, dm, k=k, metric="dot")
    v.block_until_ready()
    tpu_dt = time.perf_counter() - t0

    t0 = time.perf_counter()
    sc = qs @ vecs.T
    top = np.argpartition(-sc, k, axis=1)[:, :k]
    cpu_dt = time.perf_counter() - t0
    # recall of bf16 top-k vs exact numpy
    got = np.asarray(i)
    hits = sum(len(set(got[r].tolist()) & set(top[r].tolist()))
               for r in range(n_q))
    return tpu_dt, cpu_dt, hits / (n_q * k)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=1 << 16)
    ap.add_argument("--vocab", type=int, default=30000)
    ap.add_argument("--queries", type=int, default=2048)
    ap.add_argument("--qbatch", type=int, default=256)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--skip-knn", action="store_true")
    args = ap.parse_args()

    from elasticsearch_tpu.utils.platform import ensure_cpu_if_requested

    ensure_cpu_if_requested()
    import jax

    log(f"devices: {jax.devices()}")
    log(f"corpus: {args.docs} docs, vocab {args.vocab}")
    u_doc, tfn, offsets, df, idf = build_corpus(args.docs, args.vocab, args.seed)
    log(f"postings nnz: {u_doc.shape[0]}")
    queries = make_queries(args.queries, args.vocab, df, args.seed)

    tpu_dt, tpu_top = tpu_path(u_doc, tfn, offsets, df, idf, queries,
                               args.docs, args.k, args.qbatch)
    starts, lens, ws, P, T = chunk_tables(queries, offsets, idf)
    cpu_dt, cpu_top = cpu_reference(u_doc, tfn, (starts, lens, ws),
                                    args.docs, args.k)

    # sanity: top-1 agreement (floating-point tie order may differ below)
    agree = sum(1 for a, b in zip(tpu_top, cpu_top) if a[0] == b[0])
    log(f"top-1 agreement: {agree}/{len(cpu_top)}")

    tpu_qps = args.queries / tpu_dt
    cpu_qps = args.queries / cpu_dt
    log(f"tpu: {tpu_dt*1000:.1f} ms total, {tpu_qps:.1f} qps "
        f"({tpu_dt/args.queries*1000:.3f} ms/q amortized)")
    log(f"cpu: {cpu_dt*1000:.1f} ms total, {cpu_qps:.1f} qps")

    if not args.skip_knn:
        try:
            t_tpu, t_cpu, recall = knn_bench(1 << 16, 128, 1024, 10, args.seed)
            log(f"knn 65536x128: tpu {t_tpu*1000:.1f} ms, cpu {t_cpu*1000:.1f} ms, "
                f"recall@10 {recall:.3f}, speedup {t_cpu/t_tpu:.1f}x")
        except Exception as e:  # diagnostics only — never break the headline
            log(f"knn bench failed: {e}")

    print(json.dumps({
        "metric": "bm25_batched_qps",
        "value": round(tpu_qps, 2),
        "unit": "qps",
        "vs_baseline": round(tpu_qps / cpu_qps, 3),
    }))


if __name__ == "__main__":
    main()
