"""REST layer: ES-compatible HTTP JSON API.

Reference: org/elasticsearch/rest/ — RestController.java (method+path
routing), rest/action/* handlers (124 of them: document CRUD, bulk, search,
msearch, count, explain, analyze, mappings, settings, aliases, templates,
cat family, cluster health/state/stats, node stats, refresh/flush/optimize,
mget, scroll), and http/netty/NettyHttpServerTransport.java for the server.

Implementation: stdlib ThreadingHTTPServer (the HTTP layer is control-plane
only — all heavy work is device programs), a route table of
(method, compiled-regex) → handler, and ES-shaped JSON error envelopes.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.tracing import TaskCancelledException
from elasticsearch_tpu.utils.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
    IndexNotFoundException,
)

Handler = Callable[..., Tuple[int, Any]]

# guards the get-or-register of a scroll context's persistent task
# (rest/_scroll): concurrent pages for one scroll_id race on it
_SCROLL_TASK_LOCK = threading.Lock()


class RestController:
    def __init__(self, node: Node):
        self.node = node
        self.routes: List[Tuple[str, re.Pattern, Handler]] = []
        # compiled regex -> the registered pattern string: the metrics
        # endpoint label (a raw request path would be unbounded-cardinality
        # — every doc id its own series; the ROUTE pattern is the bounded
        # name ES uses for its own handler stats)
        self._pattern_of: Dict[re.Pattern, str] = {}
        _register_all(self)

    def add(self, method: str, pattern: str, handler: Handler):
        # {name} -> named group (no slashes); {index} additionally excludes a
        # leading underscore so /_bulk, /_search etc. never bind as an index
        # (ES forbids index names starting with _, RestController does the same
        # disambiguation via path registration order)
        def group(m):
            name = m.group(1)
            if name == "index":
                # _all is the one _-prefixed segment that IS an index
                # expression (reference: /_all/_mapping, /_all/_warmer/x)
                return r"(?P<index>_all|[^/_][^/]*)"
            return rf"(?P<{name}>[^/]+)"

        rx = re.sub(r"\{(\w+)\}", group, pattern)
        compiled = re.compile(f"^{rx}/?$")
        self.routes.append((method, compiled, handler))
        self._pattern_of[compiled] = pattern

    @staticmethod
    def pool_for(method: str, path: str) -> str:
        """Route → thread pool name (reference: each TransportAction names
        its executor; here whole path SEGMENTS decide — substring matching
        would misroute index names like `logs_search`)."""
        parts = [p for p in path.split("/") if p]
        seg_set = set(parts)
        if "_bulk" in seg_set:
            return "bulk"
        if seg_set & {"_search", "_msearch", "_count", "_suggest",
                      "_percolate", "_validate", "_explain", "_field_stats",
                      "_knn_search"}:
            return "search"
        if "_mget" in seg_set:
            return "get"
        if seg_set & {"_update", "_doc", "_create"}:
            return "get" if method in ("GET", "HEAD") else "index"
        if len(parts) >= 2 and not parts[-1].startswith("_") \
                and not parts[0].startswith("_"):
            # /{index}/{type}/{id}-style document CRUD
            return "get" if method in ("GET", "HEAD") else "index"
        return "management"

    def dispatch(self, method: str, path: str, params: Dict[str, str],
                 body: bytes,
                 headers: Optional[Dict[str, str]] = None) -> Tuple[int, Any]:
        for m, rx, handler in self.routes:
            if m != method:
                continue
            match = rx.match(path)
            if match:
                # in_flight_requests breaker (reference: the netty-level
                # inflight-requests accounting): body bytes held in
                # memory while the request runs; trip → 429 before any
                # handler work. Search-family routes admit through the
                # per-tenant QoS layer (serving/qos.py) over the SAME
                # breaker: the tenant (X-Tenant-Id header / ?tenant=)
                # charges its weighted share, so a greedy tenant 429s
                # while other tenants keep serving.
                from elasticsearch_tpu import resources

                t0 = time.perf_counter()
                pool = self.pool_for(method, path)
                inflight = resources.BREAKERS.breaker("in_flight_requests")
                nbytes = len(body or b"")
                qos_token = None
                try:
                    if pool == "search":
                        tenant = params.get("tenant") or (
                            headers or {}).get("x-tenant-id")
                        qos_token = self.node.serving.qos.admit(
                            tenant, nbytes)
                    else:
                        inflight.break_or_reserve(nbytes, "<http_request>")
                except ElasticsearchTpuException as e:
                    return self._finish(rx, method, t0, e.status,
                                        _error_body(e))
                try:
                    # run on the route's named pool: bounded concurrency,
                    # full queues reject with 429 (ThreadPool.java contract)
                    status, out = self.node.thread_pool.execute(
                        pool,
                        handler, self.node, params, body,
                        **{k: _decode_path_part(v)
                           for k, v in match.groupdict().items()})
                except ElasticsearchTpuException as e:
                    status, out = e.status, _error_body(e)
                except json.JSONDecodeError as e:
                    status, out = 400, {
                        "error": {"type": "parse_exception",
                                  "reason": str(e)}, "status": 400}
                except Exception as e:  # noqa: BLE001 — a handler bug must
                    # surface as an ES-style 500 envelope, never a dropped
                    # connection (mirrors ES catching Throwable per request)
                    status, out = 500, {
                        "error": {"type": "internal_server_error",
                                  "reason": f"{type(e).__name__}: {e}"},
                        "status": 500,
                    }
                finally:
                    if qos_token is not None:
                        self.node.serving.qos.release(qos_token)
                    else:
                        inflight.release(nbytes)
                return self._finish(rx, method, t0, status, out)
        return 400, {
            "error": {"type": "illegal_argument_exception",
                      "reason": f"no handler found for uri [{path}] and method [{method}]"},
            "status": 400,
        }

    def _finish(self, rx: re.Pattern, method: str, t0: float,
                status: int, out: Any) -> Tuple[int, Any]:
        """Per-endpoint REST metrics: latency histogram + status-class
        counter, labeled by the registered ROUTE pattern (bounded set —
        never the raw path). Recording failures are swallowed: dropping
        one sample must never fail the request it measured."""
        try:
            endpoint = self._pattern_of.get(rx, "<unregistered>")
            m = self.node.metrics
            m.histogram(
                "estpu_rest_request_duration_seconds",
                "REST dispatch latency by route pattern",
                ("endpoint", "method"),
            ).labels(endpoint, method).observe(time.perf_counter() - t0)
            m.counter(
                "estpu_rest_requests_total",
                "REST requests by route pattern and status class",
                ("endpoint", "method", "status"),
            ).labels(endpoint, method, f"{int(status) // 100}xx").inc()
        except Exception:  # tpulint: allow[R006] — dropping one metric
            pass           # sample must never fail the measured request
        return status, out


def _decode_path_part(v: Optional[str]) -> Optional[str]:
    """Routes match the %-encoded request path; handlers get decoded
    values (non-ASCII doc ids). Raw UTF-8 request lines arrive read as
    latin-1 by http.server — rescue those too when they round-trip."""
    if v is None:
        return None
    from urllib.parse import unquote

    v = unquote(v)
    try:
        return v.encode("latin-1").decode("utf-8")
    except (UnicodeEncodeError, UnicodeDecodeError):
        return v


def _refresh_requested(p) -> bool:
    """refresh=true|1|''|wait_for all force visibility (2.0 treats the
    param as a boolean-ish flag; wait_for refreshes inline here)."""
    return p.get("refresh") in ("true", "", "1", "wait_for")


def _error_body(e: ElasticsearchTpuException) -> dict:
    return {
        "error": {"type": e.error_type, "reason": str(e),
                  "root_cause": [{"type": e.error_type, "reason": str(e)}]},
        "status": e.status,
    }


def _json(body: bytes) -> dict:
    if not body:
        return {}
    try:
        return json.loads(body)
    except json.JSONDecodeError:
        # the reference's Jackson parser is lenient about unquoted field
        # names — quote them and retry (no YAML-style scalar coercion:
        # values must stay exactly what strict JSON would produce)
        import re as _re

        text = body.decode() if isinstance(body, bytes) else str(body)
        fixed = _re.sub(r'([,{]\s*)([A-Za-z_][A-Za-z0-9_.]*)(\s*:)',
                        r'\1"\2"\3', text)
        try:
            return json.loads(fixed)
        except json.JSONDecodeError:
            pass
        raise


def _ndjson(body: bytes) -> List[dict]:
    return [json.loads(line) for line in body.decode().splitlines() if line.strip()]


# ---------------------------------------------------------------------------
# handlers (grouped like rest/action/*)
# ---------------------------------------------------------------------------

def _register_all(rc: RestController):
    add = rc.add
    # root / info / health
    add("GET", "/", lambda n, p, b: (200, n.info()))
    add("HEAD", "/", lambda n, p, b: (200, None))
    add("GET", "/_cluster/health", _cluster_health)
    add("GET", "/_cluster/state", lambda n, p, b: (200, n.cluster_state.to_json()))
    add("GET", "/_cluster/stats", _cluster_stats)
    add("GET", "/_nodes/stats", _nodes_info)
    add("GET", "/_nodes", _nodes_info)
    add("GET", "/_stats", lambda n, p, b: _index_stats(n, p, b, None))

    # task management API over tracing/tasks.py (reference: rest/action/
    # admin/cluster/node/tasks — RestListTasksAction, RestCancelTasksAction)
    add("GET", "/_tasks", _tasks_list)
    add("GET", "/_tasks/{task_id}", _task_get)
    add("POST", "/_tasks/{task_id}/_cancel", _task_cancel)
    add("GET", "/_cat/tasks", _cat_tasks)
    # chrome-trace dump of the local span ring (tracing/tracer.py) —
    # registered before the /_nodes/{nodeid}/... patterns so the literal
    # path wins
    add("GET", "/_nodes/_local/trace", _node_trace)
    # device-program observatory (monitor/programs.py): per-key
    # compile/execute attribution + per-index census — also before the
    # /_nodes/{nodeid} patterns so the literal path wins
    add("GET", "/_nodes/_local/xla/programs", _node_programs)
    # flight recorder + watchdog + incident surface (monitor/flight.py,
    # monitor/watchdog.py): per-node black box, cluster-wide support
    # bundle, cat listing of captured incidents
    add("GET", "/_nodes/_local/flight", _node_flight)
    # pre-warm pipeline (serving/warmup.py): manual census-replay
    # trigger + status (the background runs appear as cancellable
    # cluster:admin/warmup parent tasks in GET /_tasks)
    add("POST", "/_warmup", _warmup_trigger)
    add("GET", "/_warmup", _warmup_status)
    add("POST", "/{index}/_warmup", _warmup_trigger_index)
    add("GET", "/_cat/incidents", _cat_incidents)
    add("GET", "/_cluster/diagnostics", _cluster_diagnostics)
    add("GET", "/_cluster/diagnostics/incidents/{incident_id}",
        _get_incident)
    # continuous metrics scrape (text exposition format 0.0.4): the node
    # registry + the process-shared families (monitor/metrics.py)
    add("GET", "/_prometheus/metrics", _prometheus_metrics)

    # cat API (text/plain-ish, returned as JSON rows when format=json)
    add("GET", "/_cat/indices", _cat_indices)
    add("GET", "/_cat/health", _cat_health)
    add("GET", "/_cat/shards", _cat_shards)
    add("GET", "/_cat/nodes", _cat_nodes)
    add("GET", "/_cat/count", _cat_count)
    add("GET", "/_cat/count/{index}", _cat_count)
    add("GET", "/_cat/templates", lambda n, p, b: (200, [
        {"name": k, "index_patterns": v.get("index_patterns", [v.get("template", "")])}
        for k, v in n.cluster_state.templates.items()]))
    add("GET", "/_cat/master", _cat_master)
    add("GET", "/_cat/aliases", _cat_aliases)
    add("GET", "/_cat/allocation", _cat_allocation)
    add("GET", "/_cat/segments", _cat_segments)
    add("GET", "/_cat/recovery", _cat_recovery)
    add("GET", "/_cat/plugins", lambda n, p, b: (200, []))
    add("GET", "/_cat/pending_tasks", _cat_pending_tasks)
    add("GET", "/_cat/programs", _cat_programs)
    add("GET", "/_cat/thread_pool", _cat_thread_pool)
    add("GET", "/_cat/fielddata", _cat_fielddata)
    add("GET", "/_cat/repositories", lambda n, p, b: (200, [
        {"id": name, "type": "fs"} for name in n.repositories]))
    add("GET", "/_cat/snapshots/{repo}", _cat_snapshots)

    # REST-spec tail (r4 sweep): cluster admin, global-index forms, JSON
    # segments/recovery, mpercolate/mtermvectors/mlt, search_exists/shards,
    # snapshot status/verify, indexed scripts. Registered before the
    # snapshot + /{index} blocks so literal _-prefixed paths win.
    add("GET", "/_cluster/settings", _cluster_get_settings)
    add("PUT", "/_cluster/settings", _cluster_put_settings)
    add("GET", "/_cluster/pending_tasks", _cluster_pending_tasks)
    add("POST", "/_cluster/reroute", _cluster_reroute)
    add("GET", "/_nodes/hot_threads", _hot_threads)
    add("GET", "/_nodes/{nodeid}/hot_threads",
        lambda n, p, b, nodeid: _hot_threads(n, p, b))
    add("GET", "/_cat", _cat_help)
    add("GET", "/_count", lambda n, p, b: _count(n, p, b, None))
    add("POST", "/_count", lambda n, p, b: _count(n, p, b, None))
    add("GET", "/_field_stats", lambda n, p, b: _field_stats(n, p, b, None))
    add("POST", "/_field_stats", lambda n, p, b: _field_stats(n, p, b, None))
    add("POST", "/_flush", lambda n, p, b: _flush(n, p, b, None))
    add("GET", "/_flush", lambda n, p, b: _flush(n, p, b, None))
    add("POST", "/_optimize", lambda n, p, b: _optimize(n, p, b, None))
    add("POST", "/_forcemerge", lambda n, p, b: _optimize(n, p, b, None))
    add("GET", "/_segments", _segments_json)
    add("GET", "/_recovery", _recovery_json)
    add("POST", "/_cache/clear", _clear_cache)
    add("POST", "/_upgrade", _upgrade)
    add("GET", "/_upgrade", _get_upgrade)
    add("POST", "/_mpercolate", _mpercolate)
    add("POST", "/_mtermvectors", _mtermvectors)
    add("GET", "/_mtermvectors", _mtermvectors)
    add("GET", "/_search/scroll", _scroll)
    add("GET", "/_search/template", lambda n, p, b: _search_template(n, p, b, None))
    add("POST", "/_search/template", lambda n, p, b: _search_template(n, p, b, None))
    add("GET", "/_mapping/field/{field}",
        lambda n, p, b, field: _get_field_mapping(n, p, b, field))
    add("GET", "/_snapshot/_status",
        lambda n, p, b: _snapshot_status(n, p, b))
    add("PUT", "/_scripts/{lang}/{id}", _put_script)
    add("POST", "/_scripts/{lang}/{id}", _put_script)
    add("GET", "/_scripts/{lang}/{id}", _get_script)
    add("DELETE", "/_scripts/{lang}/{id}", _delete_script)
    add("HEAD", "/_alias/{alias}",
        lambda n, p, b, alias: _alias_exists(n, p, b, alias))
    add("HEAD", "/_template/{name}", _template_exists)
    add("GET", "/_snapshot/{repo}/{snap}/_status",
        lambda n, p, b, repo, snap: _snapshot_status(n, p, b, repo, snap))
    add("POST", "/_snapshot/{repo}/_verify", _verify_repo)

    # snapshot API (before /{index} patterns so the literal prefix wins)
    add("PUT", "/_snapshot/{repo}", _put_repo)
    add("POST", "/_snapshot/{repo}", _put_repo)
    add("GET", "/_snapshot", _get_repos)
    add("GET", "/_snapshot/{repo}", _get_repo)
    add("DELETE", "/_snapshot/{repo}", _delete_repo)
    add("PUT", "/_snapshot/{repo}/{snap}", _put_snapshot)
    add("GET", "/_snapshot/{repo}/{snap}", _get_snapshot)
    add("DELETE", "/_snapshot/{repo}/{snap}", _delete_snapshot)
    add("POST", "/_snapshot/{repo}/{snap}/_restore", _restore_snapshot)

    # rest-api-spec sweep: root-scoped + alternate-spelling + GET forms
    add("GET", "/_cat/aliases/{name}", _cat_aliases)
    add("GET", "/_cat/allocation/{nodeid}", _cat_allocation)
    add("GET", "/_cat/fielddata/{fields}",
        lambda n, p, b, fields: _cat_fielddata(n, p, b, fields))
    add("GET", "/_cat/indices/{index}", _cat_indices)
    add("GET", "/_cat/recovery/{index}", _cat_recovery)
    add("GET", "/_cat/segments/{index}", _cat_segments)
    add("GET", "/_cat/shards/{index}", _cat_shards)
    add("DELETE", "/_search/scroll/{scroll_id}",
        lambda n, p, b, scroll_id: _clear_scroll(
            n, {**p, "scroll_id": scroll_id}, b))  # body ids win
    add("GET", "/_cluster/health/{index}",
        lambda n, p, b, index: _cluster_health(n, p, b))
    add("GET", "/_cluster/state/{metric}", _cluster_state_metric)
    add("GET", "/_cluster/state/{metric}/{index}",
        lambda n, p, b, metric, index: _cluster_state_metric(
            n, p, b, metric, index))
    add("GET", "/_cluster/stats/nodes/{nodeid}",
        lambda n, p, b, nodeid: _cluster_stats(n, p, b))
    add("GET", "/_mapping", _get_mapping_root)
    add("GET", "/_mappings", _get_mapping_root)
    add("GET", "/_mapping/{type}", _get_mapping_root)
    add("PUT", "/_mapping/{type}", _put_mapping_root)
    add("PUT", "/_mappings/{type}", _put_mapping_root)
    add("POST", "/_mapping/{type}", _put_mapping_root)
    add("POST", "/_mappings/{type}", _put_mapping_root)
    add("GET", "/_settings", _get_settings_root)
    add("GET", "/_settings/{name}", _get_settings_root)
    add("PUT", "/_settings", _put_settings_root)
    add("GET", "/_alias", _get_aliases)
    add("GET", "/_aliases/{alias}", _get_alias)
    add("GET", "/_template",
        lambda n, p, b: _get_template(n, p, b, None))
    add("POST", "/_template/{name}", lambda n, p, b, name: (
        200, n.put_template(name, _json(b), create=str(
            p.get("create", "false")).lower() in ("", "true"))))
    add("GET", "/_warmer", _get_warmers_root)
    add("GET", "/_warmer/{name}", _get_warmers_root)
    add("PUT", "/_warmer/{name}", _put_warmer_root)
    add("PUT", "/_warmers/{name}", _put_warmer_root)
    add("POST", "/_warmer/{name}", _put_warmer_root)
    add("POST", "/_warmers/{name}", _put_warmer_root)
    add("GET", "/_refresh", _refresh_all)
    add("GET", "/_optimize", lambda n, p, b: _optimize(n, p, b, None))
    add("GET", "/_cache/clear", _clear_cache)
    add("GET", "/_mget", _mget)
    add("GET", "/_mpercolate", _mpercolate)
    add("GET", "/_msearch", _msearch)
    add("GET", "/_search/scroll/{scroll_id}",
        lambda n, p, b, scroll_id: _scroll(n, {**p, "scroll_id": scroll_id}, b))
    add("POST", "/_search/scroll/{scroll_id}",
        lambda n, p, b, scroll_id: _scroll(n, {**p, "scroll_id": scroll_id}, b))
    add("GET", "/_search/exists", lambda n, p, b: _search_exists(n, p, b, None))
    add("POST", "/_search/exists", lambda n, p, b: _search_exists(n, p, b, None))
    add("GET", "/_search_shards", lambda n, p, b: _search_shards(n, p, b, None))
    add("POST", "/_search_shards", lambda n, p, b: _search_shards(n, p, b, None))
    add("GET", "/_validate/query", lambda n, p, b: _validate_query(n, p, b, None))
    add("POST", "/_validate/query", lambda n, p, b: _validate_query(n, p, b, None))
    add("GET", "/_stats/{metric}",
        lambda n, p, b, metric: _index_stats(n, p, b, None, metric))
    add("POST", "/_snapshot/{repo}/{snap}", _put_snapshot)
    add("PUT", "/_snapshot/{repo}/{snap}/_create", _put_snapshot)
    add("POST", "/_snapshot/{repo}/{snap}/_create", _put_snapshot)
    add("POST", "/_search/template/{id}", _put_search_template)
    add("GET", "/_mapping/{type}/field/{field}",
        lambda n, p, b, type, field: _get_field_mapping(
            n, p, b, field, None, doc_type=type))
    # nodes.info / nodes.stats scoped forms (single node: node_id/metric
    # selectors accept anything and return this node's full view)
    add("GET", "/_nodes/hotthreads", _hot_threads)
    add("GET", "/_nodes/{nodeid}/hotthreads",
        lambda n, p, b, nodeid: _hot_threads(n, p, b))
    add("GET", "/_cluster/nodes/hotthreads", _hot_threads)
    add("GET", "/_cluster/nodes/hot_threads", _hot_threads)
    add("GET", "/_cluster/nodes/{nodeid}/hotthreads",
        lambda n, p, b, nodeid: _hot_threads(n, p, b))
    add("GET", "/_cluster/nodes/{nodeid}/hot_threads",
        lambda n, p, b, nodeid: _hot_threads(n, p, b))
    add("GET", "/_nodes/stats/{metric}", _nodes_info)
    add("GET", "/_nodes/stats/{metric}/{imetric}", _nodes_info)
    add("GET", "/_nodes/{nodeid}/stats", _nodes_info)
    add("GET", "/_nodes/{nodeid}/stats/{metric}", _nodes_info)
    add("GET", "/_nodes/{nodeid}/stats/{metric}/{imetric}", _nodes_info)
    add("GET", "/_nodes/{nodeid}", _nodes_info)
    add("GET", "/_nodes/{nodeid}/{metric}", _nodes_info)

    # index admin
    add("PUT", "/{index}", _create_index)
    add("POST", "/{index}", _create_index)
    add("DELETE", "/{index}", lambda n, p, b, index: (200, n.delete_index(index)))
    add("HEAD", "/{index}", _index_exists)
    add("GET", "/{index}/_mapping", _get_mapping_index)
    add("GET", "/{index}/_mapping/{type}", _get_mapping_typed)
    add("GET", "/{index}/_mappings/{type}", _get_mapping_typed)
    for _m in ("PUT", "POST"):
        add(_m, "/{index}/{type}/_mapping",
            lambda n, p, b, index, type: (
                200, n.put_mapping(index,
                                   _typed_mapping_body(type, _json(b)))))
        add(_m, "/{index}/{type}/_mappings",
            lambda n, p, b, index, type: (
                200, n.put_mapping(index,
                                   _typed_mapping_body(type, _json(b)))))
    add("GET", "/{index}/_settings/{name}",
        lambda n, p, b, index, name: _get_settings_name(n, p, b, index, name))
    add("PUT", "/{index}/_mapping", lambda n, p, b, index: (200, n.put_mapping(index, _json(b))))
    add("PUT", "/{index}/_mapping/{type}", lambda n, p, b, index, type: (
        200, n.put_mapping(index, _typed_mapping_body(type, _json(b)))))
    add("GET", "/{index}/_settings", _get_settings)
    add("PUT", "/{index}/_settings", _put_settings)
    add("POST", "/{index}/_close", _close_index)
    add("POST", "/{index}/_open", _open_index)
    add("GET", "/{index}", _get_index_meta)
    add("POST", "/_aliases", lambda n, p, b: (200, n.update_aliases(_json(b).get("actions", []))))
    add("GET", "/_aliases", _get_aliases)
    add("GET", "/_alias/{alias}", _get_alias)
    add("PUT", "/_template/{name}", lambda n, p, b, name: (
        200, n.put_template(name, _json(b), create=str(
            p.get("create", "false")).lower() in ("", "true"))))
    add("GET", "/_template/{name}", _get_template)
    add("DELETE", "/_template/{name}", lambda n, p, b, name: (200, n.delete_template(name)))

    # index lifecycle ops
    add("POST", "/{index}/_refresh", _refresh)
    add("GET", "/{index}/_refresh", _refresh)
    add("POST", "/_refresh", _refresh_all)
    add("POST", "/{index}/_flush", _flush)
    add("POST", "/{index}/_optimize", _optimize)  # ES 2.0 name
    add("POST", "/{index}/_forcemerge", _optimize)
    add("GET", "/{index}/_stats", _index_stats)
    add("GET", "/{index}/_count", _count)
    add("POST", "/{index}/_count", _count)

    # analyze
    add("GET", "/_analyze", _analyze)
    add("POST", "/_analyze", _analyze)
    add("GET", "/{index}/_analyze", _analyze_index)
    add("POST", "/{index}/_analyze", _analyze_index)

    # documents
    add("PUT", "/{index}/_doc/{id}", _index_doc)
    add("POST", "/{index}/_doc/{id}", _index_doc)
    add("POST", "/{index}/_doc", _index_doc_auto)
    add("PUT", "/{index}/_create/{id}", _create_doc)
    add("GET", "/{index}/_doc/{id}", _get_doc)
    add("HEAD", "/{index}/_doc/{id}", _doc_exists)
    add("DELETE", "/{index}/_doc/{id}", _delete_doc)
    add("POST", "/{index}/_update/{id}", _update_doc)
    add("POST", "/{index}/_delete_by_query", _delete_by_query)
    add("DELETE", "/{index}/_query", _delete_by_query)  # ES 2.0 plugin path
    add("POST", "/{index}/_update_by_query", _update_by_query)
    add("GET", "/{index}/_source/{id}", _get_source)
    add("POST", "/_mget", _mget)
    add("POST", "/{index}/_mget", _mget_index)

    # bulk
    add("POST", "/_bulk", _bulk)
    add("PUT", "/_bulk", _bulk)
    add("POST", "/{index}/_bulk", _bulk_index)

    # search family
    add("GET", "/_search", _search_all)
    add("POST", "/_search", _search_all)
    add("GET", "/{index}/_search", _search)
    add("POST", "/{index}/_search", _search)
    add("POST", "/_msearch", _msearch)
    add("POST", "/{index}/_msearch", _msearch_index)
    add("POST", "/_search/scroll", _scroll)
    add("DELETE", "/_search/scroll", _clear_scroll)
    add("GET", "/{index}/_search/template", _search_template)
    add("POST", "/{index}/_search/template", _search_template)
    add("POST", "/_render/template", _render_template_ep)
    add("PUT", "/_search/template/{id}", _put_search_template)
    add("GET", "/_search/template/{id}", _get_search_template)
    add("DELETE", "/_search/template/{id}", _delete_search_template)
    add("PUT", "/{index}/_warmer/{name}", _put_warmer)
    add("PUT", "/{index}/_warmers/{name}", _put_warmer)
    add("GET", "/{index}/_warmer", _get_warmers)
    add("GET", "/{index}/_warmer/{name}", _get_warmer)
    add("DELETE", "/{index}/_warmer/{name}", _delete_warmer)
    add("POST", "/{index}/_validate/query", _validate_query)
    add("GET", "/{index}/_validate/query", _validate_query)
    add("POST", "/{index}/_explain/{id}", _explain)
    add("GET", "/{index}/_explain/{id}", _explain)
    add("GET", "/{index}/_field_stats", _field_stats)
    add("POST", "/{index}/_field_stats", _field_stats)
    add("GET", "/{index}/_termvectors/{id}", _termvectors)
    add("GET", "/{index}/{type}/_percolate", _typed(_percolate, keep_type=True))
    add("POST", "/{index}/{type}/_percolate", _typed(_percolate, keep_type=True))
    add("GET", "/{index}/{type}/{id}/_percolate", _typed(_percolate_existing, keep_type=True))
    add("POST", "/{index}/{type}/{id}/_percolate", _typed(_percolate_existing, keep_type=True))
    add("POST", "/_suggest", _suggest_all)
    add("GET", "/_suggest", _suggest_all)
    add("POST", "/{index}/_suggest", _suggest)
    add("GET", "/{index}/_suggest", _suggest)


    # REST-spec tail, per-index forms
    add("PUT", "/{index}/_alias/{name}", _put_alias)
    add("POST", "/{index}/_alias/{name}", _put_alias)
    add("PUT", "/{index}/_aliases/{name}", _put_alias)
    add("DELETE", "/{index}/_alias/{name}", _delete_alias)
    add("DELETE", "/{index}/_aliases/{name}", _delete_alias)
    add("HEAD", "/{index}/_alias/{name}", _index_alias_exists)
    add("HEAD", "/{index}/_aliases/{name}", _index_alias_exists)
    add("HEAD", "/{index}/_alias", _index_any_alias)
    add("GET", "/{index}/_alias", _get_index_alias)
    add("GET", "/{index}/_aliases", _get_index_alias)
    add("GET", "/{index}/_aliases/{alias}",
        lambda n, p, b, index, alias: _get_index_alias(
            n, p, b, index, alias, legacy=True))
    add("GET", "/{index}/_alias/{alias}",
        lambda n, p, b, index, alias: _get_index_alias(n, p, b, index, alias))
    add("HEAD", "/{index}/_mapping/{type}", _type_exists)
    add("GET", "/{index}/_mapping/field/{field}",
        lambda n, p, b, index, field: _get_field_mapping(n, p, b, field, index))
    add("GET", "/{index}/_segments",
        lambda n, p, b, index: _segments_json(n, p, b, index))
    add("GET", "/{index}/_recovery",
        lambda n, p, b, index: _recovery_json(n, p, b, index))
    add("POST", "/{index}/_cache/clear",
        lambda n, p, b, index: _clear_cache(n, p, b, index))
    add("POST", "/{index}/_upgrade",
        lambda n, p, b, index: _upgrade(n, p, b, index))
    add("GET", "/{index}/_upgrade",
        lambda n, p, b, index: _get_upgrade(n, p, b, index))
    add("POST", "/{index}/_mpercolate",
        lambda n, p, b, index: _mpercolate(n, p, b, index))
    add("POST", "/{index}/_mtermvectors",
        lambda n, p, b, index: _mtermvectors(n, p, b, index))
    add("GET", "/{index}/_mtermvectors",
        lambda n, p, b, index: _mtermvectors(n, p, b, index))
    add("GET", "/{index}/_search/exists", _search_exists)
    add("POST", "/{index}/_search/exists", _search_exists)
    add("GET", "/{index}/_search_shards", _search_shards)
    add("POST", "/{index}/_search_shards", _search_shards)
    add("POST", "/{index}/_termvectors/{id}", _termvectors)
    add("GET", "/{index}/{type}/{id}/_termvectors", _typed(_termvectors))
    add("POST", "/{index}/{type}/{id}/_termvectors", _typed(_termvectors))
    add("GET", "/{index}/{type}/_percolate/count", _typed(_percolate_count, keep_type=True))
    add("POST", "/{index}/{type}/_percolate/count", _typed(_percolate_count, keep_type=True))
    add("GET", "/{index}/{type}/{id}/_mlt", _typed(_mlt, keep_type=True))

    # index-scoped GET/alternate forms (rest-api-spec sweep)
    add("GET", "/{index}/_flush", _flush)
    add("GET", "/{index}/_optimize", _optimize)
    add("GET", "/{index}/_cache/clear",
        lambda n, p, b, index: _clear_cache(n, p, b, index))
    add("GET", "/{index}/_mget", _mget_index)
    add("GET", "/{index}/_mpercolate",
        lambda n, p, b, index: _mpercolate(n, p, b, index))
    add("GET", "/{index}/_msearch", _msearch_index)
    add("POST", "/{index}/_mapping", lambda n, p, b, index: (
        200, n.put_mapping(index, _json(b))))
    add("POST", "/{index}/_mapping/{type}", lambda n, p, b, index, type: (
        200, n.put_mapping(index, _typed_mapping_body(type, _json(b)))))
    add("PUT", "/{index}/_mappings", lambda n, p, b, index: (
        200, n.put_mapping(index, _json(b))))
    add("PUT", "/{index}/_mappings/{type}", lambda n, p, b, index, type: (
        200, n.put_mapping(index, _typed_mapping_body(type, _json(b)))))
    add("POST", "/{index}/_mappings", lambda n, p, b, index: (
        200, n.put_mapping(index, _json(b))))
    add("POST", "/{index}/_mappings/{type}", lambda n, p, b, index, type: (
        200, n.put_mapping(index, _typed_mapping_body(type, _json(b)))))
    add("GET", "/{index}/_mappings", lambda n, p, b, index: (
        200, n.get_mapping(index)))
    add("GET", "/{index}/_mapping/{type}/field/{field}",
        lambda n, p, b, index, type, field:
        _get_field_mapping(n, p, b, field, index, doc_type=type))
    add("GET", "/{index}/_stats/{metric}",
        lambda n, p, b, index, metric: _index_stats(n, p, b, index, metric))
    add("GET", "/{index}/_warmers", _get_warmers)
    add("GET", "/{index}/_warmers/{name}",
        lambda n, p, b, index, name: _get_warmer(n, p, b, index, name))

    # ES 2.0 typed forms — registered LAST so every /_-prefixed
    # sub-resource above wins the route (RestController does the same via
    # explicit registration order). {type} segments that start with an
    # underscore are rejected by the handlers, not silently bound.
    add("GET", "/{index}/{type}/_search", _typed(_search_typed, keep_type=True))
    add("POST", "/{index}/{type}/_search", _typed(_search_typed, keep_type=True))
    add("GET", "/{index}/{type}/_count", _typed(_count_typed, keep_type=True))
    add("POST", "/{index}/{type}/_count", _typed(_count_typed, keep_type=True))
    add("POST", "/{index}/{type}/_msearch", _typed(
        lambda n, p, b, index, type=None: _msearch(n, p, b, index,
                                                   doc_type=type),
        keep_type=True))
    add("GET", "/{index}/{type}/_msearch", _typed(
        lambda n, p, b, index, type=None: _msearch(n, p, b, index,
                                                   doc_type=type),
        keep_type=True))
    add("POST", "/{index}/{type}/_mget", _typed(
        lambda n, p, b, index, type=None: _mget_typed(n, p, b, index, type),
        keep_type=True))
    add("GET", "/{index}/{type}/_mget", _typed(
        lambda n, p, b, index, type=None: _mget_typed(n, p, b, index, type),
        keep_type=True))
    add("POST", "/{index}/{type}/_bulk", _typed(
        lambda n, p, b, index, type=None: _bulk(n, p, b, index,
                                                doc_type=type),
        keep_type=True))
    add("PUT", "/{index}/{type}/_bulk", _typed(
        lambda n, p, b, index, type=None: _bulk(n, p, b, index,
                                                doc_type=type),
        keep_type=True))
    add("GET", "/{index}/{type}/_suggest",
        _typed(lambda n, p, b, index: _suggest(n, p, b, index)))
    add("POST", "/{index}/{type}/_suggest",
        _typed(lambda n, p, b, index: _suggest(n, p, b, index)))
    add("GET", "/{index}/{type}/_termvectors", _typed(_termvectors_noid))
    add("POST", "/{index}/{type}/_termvectors", _typed(_termvectors_noid))
    add("POST", "/{index}/{type}/_mtermvectors",
        lambda n, p, b, index, type: _mtermvectors(n, p, b, index, type))
    add("GET", "/{index}/{type}/_mtermvectors",
        lambda n, p, b, index, type: _mtermvectors(n, p, b, index, type))
    add("GET", "/{index}/{type}/_search/template", _typed(_search_template))
    add("POST", "/{index}/{type}/_search/template", _typed(_search_template))
    add("GET", "/{index}/{type}/_search/exists", _typed(_search_exists))
    add("POST", "/{index}/{type}/_search/exists", _typed(_search_exists))
    add("GET", "/{index}/{type}/_validate/query", _typed(_validate_query))
    add("POST", "/{index}/{type}/_validate/query", _typed(_validate_query))
    add("GET", "/{index}/{type}/_warmer/{name}", _typed(_get_warmer))
    add("PUT", "/{index}/{type}/_warmer/{name}", _typed(_put_warmer))
    add("PUT", "/{index}/{type}/_warmers/{name}", _typed(_put_warmer))
    add("POST", "/{index}/{type}/_warmer/{name}", _typed(_put_warmer))
    add("POST", "/{index}/{type}/_warmers/{name}", _typed(_put_warmer))
    add("POST", "/{index}/_warmer/{name}", _put_warmer)
    add("POST", "/{index}/_warmers/{name}", _put_warmer)
    add("GET", "/{index}/{type}/{id}/_explain", _typed(_explain))
    add("POST", "/{index}/{type}/{id}/_explain", _typed(_explain))
    add("GET", "/{index}/{type}/{id}/_source", _typed(
        lambda n, p, b, index, id, type=None: (
            _check_read_routing(n, index, type, id, p)
            or _get_source(n, p, b, index, id)), keep_type=True))
    add("POST", "/{index}/{type}/{id}/_update", _typed(
        lambda n, p, b, index, id, type=None: (
            _check_read_routing(n, index, type, id, p)
            or _update_doc(n, p, b, index, id, doc_type=type)),
        keep_type=True))
    add("GET", "/{index}/{type}/{id}/_percolate/count",
        _typed(_percolate_count_existing, keep_type=True))
    add("POST", "/{index}/{type}/{id}/_percolate/count",
        _typed(_percolate_count_existing, keep_type=True))
    add("POST", "/{index}/{type}/{id}/_mlt", _typed(_mlt, keep_type=True))
    add("PUT", "/{index}/{type}/{id}/_create", _create_doc_typed)
    add("POST", "/{index}/{type}/{id}/_create", _create_doc_typed)
    add("HEAD", "/{index}/{type}/{id}", _doc_exists_typed)
    add("PUT", "/{index}/{type}/{id}", _index_doc_typed)
    add("POST", "/{index}/{type}/{id}", _index_doc_typed)
    add("GET", "/{index}/{type}/{id}", _get_doc_typed)
    add("DELETE", "/{index}/{type}/{id}", _delete_doc_typed)
    add("HEAD", "/{index}/{type}", _type_exists_head)
    add("POST", "/{index}/{type}", _index_doc_auto_typed)
    add("PUT", "/{index}/{type}", _index_doc_auto_typed)
    # indices.get feature form — LAST of all: only segments no literal
    # route claimed can land here, and non-feature values 400
    add("GET", "/{index}/{feature}", _get_index_feature)


# -- snapshot helpers --------------------------------------------------------

def _put_repo(n: Node, p, b, repo: str):
    from elasticsearch_tpu.index.snapshots import FsRepository

    body = _json(b)
    rtype = body.get("type")
    settings = body.get("settings", {})
    if rtype == "fs":
        loc = settings.get("location")
        if not loc:
            raise IllegalArgumentException(
                "fs repository requires [settings.location]")
        r = FsRepository(repo, loc,
                         compress=bool(settings.get("compress", True)))
    elif rtype == "url":
        # read-only repository over a file: URL (reference:
        # repositories/uri/URLRepository.java — file scheme)
        url = str(settings.get("url", ""))
        if not url:
            raise IllegalArgumentException(
                "url repository requires [settings.url]")
        from urllib.parse import urlparse as _up
        from urllib.request import url2pathname

        is_file = url.startswith("file:")
        loc = url2pathname(_up(url).path) if is_file else url
        # read-only: never create directories (a non-file URL location is
        # not a path at all; reads against it 404 as snapshot-missing)
        r = FsRepository(repo, loc, compress=True, create=False)
        r.readonly = True
    else:
        raise IllegalArgumentException(
            f"repository type [{rtype}] not supported (fs, url)")
    r.rtype = rtype
    r.repo_settings = dict(settings)
    n.repositories[repo] = r
    return 200, {"acknowledged": True}


def _repo_or_404(n: Node, repo: str):
    from elasticsearch_tpu.index.snapshots import SnapshotMissingException

    r = n.repositories.get(repo)
    if r is None:
        raise SnapshotMissingException(f"[{repo}] missing")
    return r


def _repo_json(r):
    return {"type": getattr(r, "rtype", None) or "fs",
            "settings": getattr(r, "repo_settings", None)
            or {"location": r.location}}


def _get_repos(n: Node, p, b):
    return 200, {name: _repo_json(r) for name, r in n.repositories.items()}


def _get_repo(n: Node, p, b, repo: str):
    import fnmatch

    if any(c in repo for c in "*,") or repo == "_all":
        pats = [x.strip() for x in repo.split(",")]
        out = {name: _repo_json(r) for name, r in n.repositories.items()
               if any(fnmatch.fnmatch(name, pt) or pt == "_all"
                      for pt in pats)}
        if not out and not any("*" in pt or pt == "_all" for pt in pats):
            from elasticsearch_tpu.index.snapshots import                 SnapshotMissingException

            raise SnapshotMissingException(f"[{repo}] missing")
        return 200, out
    r = _repo_or_404(n, repo)
    return 200, {repo: _repo_json(r)}


def _delete_repo(n: Node, p, b, repo: str):
    _repo_or_404(n, repo)
    del n.repositories[repo]
    return 200, {"acknowledged": True}


def _put_snapshot(n: Node, p, b, repo: str, snap: str):
    from elasticsearch_tpu.index.snapshots import create_snapshot

    body = _json(b)
    indices = body.get("indices")
    if isinstance(indices, str):
        indices = [i for part in indices.split(",") if (i := part.strip())]
    if indices:
        indices = [name for pat in indices for name in n.resolve_indices(pat)]
    r = _repo_or_404(n, repo)
    _reject_readonly_repo(r)
    c = _mh(n)
    if c is not None:
        # multi-host: each shard's owner writes its own blobs into the
        # shared repository; the master assembles the manifest
        return 200, c.data.create_snapshot(
            r.location, snap, indices=indices,
            include_global_state=body.get("include_global_state", True),
            repo_name=repo)
    return 200, create_snapshot(
        n, r, snap, indices=indices,
        include_global_state=body.get("include_global_state", True))


def _get_snapshot(n: Node, p, b, repo: str, snap: str):
    from elasticsearch_tpu.index.snapshots import snapshot_info

    r = _repo_or_404(n, repo)
    if snap == "_all":
        return 200, {"snapshots": [snapshot_info(r, s) for s in r.catalog()]}
    return 200, {"snapshots": [snapshot_info(r, snap)]}


def _reject_readonly_repo(r):
    """Writes against a url repository fail cleanly (reference:
    URLRepository is read-only; snapshot creation raises a repository
    exception instead of touching the location)."""
    if getattr(r, "readonly", False):
        raise IllegalArgumentException(
            f"repository [{r.name}] is read-only; cannot write snapshots")


def _delete_snapshot(n: Node, p, b, repo: str, snap: str):
    r = _repo_or_404(n, repo)
    _reject_readonly_repo(r)
    r.delete_snapshot(snap)
    return 200, {"acknowledged": True}


def _restore_snapshot(n: Node, p, b, repo: str, snap: str):
    from elasticsearch_tpu.index.snapshots import restore_snapshot

    body = _json(b)
    indices = body.get("indices")
    if isinstance(indices, str):
        indices = [i for part in indices.split(",") if (i := part.strip())]
    r = _repo_or_404(n, repo)
    c = _mh(n)
    if c is not None:
        # multi-host: the master computes a fresh cross-host shard
        # assignment, then every assigned copy replays from the repo
        return 200, c.data.restore_snapshot(
            r.location, snap, indices=indices,
            rename_pattern=body.get("rename_pattern"),
            rename_replacement=body.get("rename_replacement"),
            partial=bool(body.get("partial", False)),
            repo_name=repo)
    return 200, restore_snapshot(
        n, r, snap, indices=indices,
        rename_pattern=body.get("rename_pattern"),
        rename_replacement=body.get("rename_replacement"),
        partial=bool(body.get("partial", False)))


# -- admin helpers -----------------------------------------------------------

def _prometheus_metrics(n: Node, p, b):
    """GET /_prometheus/metrics: the node registry (+ process-shared
    families) in text exposition format 0.0.4. Returned as a str so the
    HTTP layer serves text/plain, the content type every scraper
    accepts."""
    return 200, n.metrics.expose()


def _local_cluster_stats(n: Node) -> dict:
    """THIS node's contribution to /_cluster/stats: its local shards'
    index stats and its own node section (reference: ClusterStatsNode-
    Response — each node reports itself, the coordinator aggregates).
    ``_index_names`` is a merge helper the coordinator strips: in a
    distributed index every member holds an IndexService for it, so
    counting per-node indices would multiply the cluster index count."""
    docs = 0
    store = seg_count = seg_mem = 0
    fd_mem = fd_ev = 0
    shards_total = primaries = 0
    for svc in n.indices.values():
        for g in svc.groups:
            primaries += 1
            for shard in g.copies:
                st = shard.stats()
                shards_total += 1
                if shard is g.primary:
                    # docs count PRIMARIES only (reference:
                    # ClusterStatsIndices — replica copies hold the same
                    # documents; counting them would inflate by the
                    # replication factor and disagree with hits.total)
                    docs += st["docs"]["count"]
                # store/segments/fielddata count EVERY copy — each holds
                # its own device-resident structures (reference: store
                # size in cluster stats includes replicas)
                seg_count += st["segments"]["count"]
                seg_mem += st["segments"]["memory_in_bytes"]
                store += st["segments"]["memory_in_bytes"]
                fd_mem += st["fielddata"]["memory_size_in_bytes"]
                fd_ev += st["fielddata"]["evictions"]
    from elasticsearch_tpu import __version__, resources
    from elasticsearch_tpu.monitor.stats import process_stats
    from elasticsearch_tpu.tracing import retrace

    proc = process_stats()
    fds = proc["open_file_descriptors"]
    tp = {"completed": 0, "rejected": 0, "queue": 0}
    if n._thread_pool is not None:
        for st in n._thread_pool.stats().values():
            for k in tp:
                tp[k] += st[k]
    tripped = sum(br.get("tripped", 0)
                  for br in resources.BREAKERS.stats().values())
    a = retrace.auditor()
    return {
        "cluster_name": n.cluster_state.cluster_name,
        "_index_names": sorted(n.indices),
        "indices": {
            "count": len(n.indices),
            "shards": {"total": shards_total, "primaries": primaries},
            "docs": {"count": docs},
            "store": {"size_in_bytes": store},
            "fielddata": {"memory_size_in_bytes": fd_mem,
                          "evictions": fd_ev},
            "segments": {"count": seg_count, "memory_in_bytes": seg_mem},
        },
        "nodes": {
            "count": {"total": 1},
            "versions": [__version__],
            "process": {
                "mem": {"resident_in_bytes": proc["mem"]["resident_in_bytes"]},
                "open_file_descriptors": {"min": fds, "max": fds,
                                          "avg": fds},
            },
            "thread_pool": tp,
            "breakers": {"tripped": tripped},
            "jit": {"traces_total": a.total() if a is not None else 0},
        },
    }


def _merge_cluster_stats(parts: List[dict], failed: int = 0) -> dict:
    """Aggregate per-node contributions (reference: ClusterStatsResponse
    merges ClusterStatsNodeResponses): index names UNION (every member
    of a distributed index reports it), numeric sections sum, versions
    union, fd min/max/avg combine."""
    names: set = set()
    versions: List[str] = []
    for pt in parts:
        names.update(pt.pop("_index_names", ()))
        for v in pt["nodes"].pop("versions", ()):
            if v not in versions:
                versions.append(v)
    fds = [pt["nodes"]["process"].pop("open_file_descriptors")
           for pt in parts]
    out = _sum_stats(parts)
    out["indices"]["count"] = len(names)
    out["nodes"]["versions"] = versions
    good = [f for f in fds if f.get("min", -1) >= 0]
    out["nodes"]["process"]["open_file_descriptors"] = {
        "min": min((f["min"] for f in good), default=-1),
        "max": max((f["max"] for f in good), default=-1),
        "avg": (sum(f["avg"] for f in good) // len(good)) if good else -1,
    }
    if failed:
        out["_nodes"] = {"total": len(parts) + failed,
                         "successful": len(parts), "failed": failed}
    return out


def _cluster_stats(n: Node, p, b):
    """GET /_cluster/stats: fans over every cluster member via the REST
    proxy (each answers with its local contribution under
    ``_local_only``) and aggregates indices + nodes sections — real
    numbers instead of the former three-field stub. A dead peer is
    counted in ``_nodes.failed``, the response stays 200 (reference:
    TransportClusterStatsAction tolerates node-level failures)."""
    local = _local_cluster_stats(n)
    c = _mh(n)
    if c is not None and "_local_only" in p:
        # proxied member contribution: RAW and unmerged, `_index_names`
        # kept — merging here would strip the names the coordinator's
        # union needs, undercounting indices that live only on this
        # member
        return 200, local
    parts = [local]
    failed = 0
    if c is not None:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        for nid in c.data._other_nodes():
            try:
                res = c.data._send(nid, ACTION_REST_PROXY, {
                    "method": "GET", "path": "/_cluster/stats",
                    "params": {}})
                if res.get("status") == 200 and res.get("payload"):
                    parts.append(res["payload"])
                else:
                    failed += 1
            except Exception:
                failed += 1
    out = _merge_cluster_stats(parts, failed=failed)
    out["cluster_name"] = n.cluster_state.cluster_name
    out["timestamp"] = int(time.time() * 1000)
    try:
        out["status"] = _cluster_health(n, {"_local_only": "1"}, b"")[1][
            "status"]
    except Exception:
        out["status"] = "green"
    return 200, out


def _sum_stats(dicts):
    out: Dict[str, Any] = {}
    for d in dicts:
        for k, v in d.items():
            if isinstance(v, dict):
                out[k] = _sum_stats([out.get(k, {}), v])
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = out.get(k, 0) + v
            else:
                out.setdefault(k, v)
    return out


# every section the IndicesStatsResponse carries; sections our runtime has
# no meaningful numbers for report zeroed structures (they exist so metric
# scoping and client consumers see the full 2.0 shape). fielddata reports
# the currently-RESIDENT device column bytes + real eviction counters
# (columns load lazily and evict under HBM pressure — see
# TpuSegment.fielddata_field_bytes / resources/residency.py)
_STATS_SECTIONS = {
    "docs": {"count": 0, "deleted": 0},
    "store": {"size_in_bytes": 0, "throttle_time_in_millis": 0},
    "indexing": {"index_total": 0, "index_time_in_millis": 0,
                 "delete_total": 0},
    "get": {"total": 0, "time_in_millis": 0},
    "search": {"query_total": 0, "query_time_in_millis": 0,
               "fetch_total": 0, "open_contexts": 0},
    "merges": {"total": 0, "total_time_in_millis": 0},
    "refresh": {"total": 0, "total_time_in_millis": 0},
    "flush": {"total": 0, "total_time_in_millis": 0},
    "warmer": {"current": 0, "total": 0, "total_time_in_millis": 0},
    "filter_cache": {"memory_size_in_bytes": 0, "evictions": 0},
    "id_cache": {"memory_size_in_bytes": 0},
    "fielddata": {"memory_size_in_bytes": 0, "evictions": 0},
    "percolate": {"total": 0, "time_in_millis": 0, "current": 0,
                  "queries": 0},
    "completion": {"size_in_bytes": 0},
    "segments": {"count": 0, "memory_in_bytes": 0},
    "translog": {"operations": 0, "size_in_bytes": 0},
    "suggest": {"total": 0, "time_in_millis": 0, "current": 0},
    "recovery": {"current_as_source": 0, "current_as_target": 0,
                 "throttle_time_in_millis": 0},
    # replication safety (index/seqno.py): what checkpoint-based
    # recovery negotiates on (reference: SeqNoStats)
    "seq_no": {"max_seq_no": -1, "local_checkpoint": -1,
               "global_checkpoint": -1, "primary_term": 0},
    "query_cache": {"memory_size_in_bytes": 0, "evictions": 0,
                    "hit_count": 0, "miss_count": 0},
}


def _full_sections(st: dict) -> dict:
    """Shard/primary stats dict -> all sections present (zero-filled)."""
    import copy

    out = copy.deepcopy(_STATS_SECTIONS)
    for k, v in st.items():
        if k in out and isinstance(v, dict):
            out[k].update(v)
    # store size: segment memory is the closest store analogue
    if not out["store"]["size_in_bytes"]:
        out["store"]["size_in_bytes"] = st.get("segments", {}).get(
            "memory_in_bytes", 0)
    return out


def _name_filter(spec):
    """Comma-separated name/wildcard list -> predicate (None = not asked)."""
    if spec in (None, ""):
        return None
    import fnmatch

    pats = [x.strip() for x in str(spec).split(",") if x.strip()]
    return lambda nm: any(fnmatch.fnmatchcase(nm, pt) for pt in pats)


def _stats_envelope(n: Node, names, metric: Optional[str] = None,
                    level: str = "indices",
                    params: Optional[dict] = None) -> dict:
    """IndicesStatsResponse shape: _shards + _all.primaries/total +
    per-index entries (total == primaries here: replica stats mirror the
    primary), every section present, metric-scoped when asked. The
    fields/fielddata_fields/completion_fields/groups/types params scope
    the per-field / per-group / per-type breakdowns exactly like
    CommonStatsFlags: absent param -> the breakdown key is absent."""
    params = params or {}
    fd_keep = _name_filter(params.get("fielddata_fields")
                           or params.get("fields"))
    comp_keep = _name_filter(params.get("completion_fields")
                             or params.get("fields"))
    grp_keep = _name_filter(params.get("groups"))
    type_keep = _name_filter(params.get("types"))

    def _scope_breakdowns(st):
        for section, key, keep in (("fielddata", "fields", fd_keep),
                                   ("completion", "fields", comp_keep),
                                   ("search", "groups", grp_keep),
                                   ("indexing", "types", type_keep)):
            d = st.get(section)
            if not isinstance(d, dict):
                continue
            if keep is None:
                d.pop(key, None)
            else:
                d[key] = {k2: v2 for k2, v2 in (d.get(key) or {}).items()
                          if keep(k2)}
        return st

    per = {}
    shards_per = {}
    for nm in names:
        raw = n.indices[nm].stats()
        shard_stats = {}
        for sid, sh in raw.get("shards", {}).items():
            full = _full_sections(sh)
            if "commit" in sh:  # CommitStats rides the shards level only
                full["commit"] = sh["commit"]
            shard_stats[sid] = full
        total = _full_sections(_sum_stats(raw.get("shards", {}).values()))
        qc = getattr(n.indices[nm], "query_cache_stats", None)
        if qc:  # shard query cache lives at the index level here
            total["query_cache"].update(
                hit_count=qc["hits"], miss_count=qc["misses"],
                evictions=qc["evictions"])
        per[nm] = total
        shards_per[nm] = shard_stats
    keep = None
    if metric and metric not in ("_all", ""):
        # metric name aliases the API accepts (merge -> merges section)
        alias = {"merge": "merges", "doc": "docs", "warmers": "warmer"}
        keep = {alias.get(m.strip(), m.strip())
                for m in str(metric).split(",")}
    def scope(st):
        return _scope_breakdowns(
            {k: v for k, v in st.items() if k in keep} if keep else st)
    agg = _full_sections(_sum_stats(per.values()))
    out = {
        "_shards": _shards_header(n, names),
        "_all": {"primaries": scope(agg), "total": scope(agg)},
        "indices": {nm: {"primaries": scope(st), "total": scope(st)}
                    for nm, st in per.items()},
    }
    if level == "shards":
        for nm in out["indices"]:
            out["indices"][nm]["shards"] = {
                sid: [scope(sh)] for sid, sh in shards_per[nm].items()}
    elif level == "cluster":
        out.pop("indices")  # cluster level: only the _all rollup
    return out


def _all_stats(n: Node) -> dict:
    return _stats_envelope(n, list(n.indices))


def _index_stats(n: Node, p, b, index: str, metric: Optional[str] = None):
    """GET /{index}/_stats[/{metric}] with multi-index expressions and
    level=indices|shards scoping."""
    names = _resolve_indices_options(n, index, p)
    return 200, _stats_envelope(n, names,
                                metric=metric or p.get("metric"),
                                level=p.get("level", "indices"),
                                params=p)



# -- cat column schemas (RestTable defaults + help listings, ES 2.0) ---------

_CAT_SHARD_TAIL = [
    "completion.size", "fielddata.memory_size", "fielddata.evictions",
    "filter_cache.memory_size", "filter_cache.evictions", "flush.total",
    "flush.total_time", "get.current", "get.time", "get.total",
    "get.exists_time", "get.exists_total", "get.missing_time",
    "get.missing_total", "id_cache.memory_size", "indexing.delete_current",
    "indexing.delete_time", "indexing.delete_total",
    "indexing.index_current", "indexing.index_time", "indexing.index_total",
    "merges.current", "merges.current_docs", "merges.current_size",
    "merges.total", "merges.total_docs", "merges.total_size",
    "merges.total_time", "percolate.current", "percolate.memory_size",
    "percolate.queries", "percolate.time", "percolate.total",
    "refresh.total", "refresh.time", "search.fetch_current",
    "search.fetch_time", "search.fetch_total", "search.open_contexts",
    "search.query_current", "search.query_time", "search.query_total",
    "segments.count", "segments.memory", "segments.index_writer_memory",
    "segments.index_writer_max_memory", "segments.version_map_memory",
    "segments.fixed_bitset_memory", "warmer.current", "warmer.total",
    "warmer.total_time"]

# endpoint (2nd path segment) -> help column list (RestTable's declared
# columns; the row handlers emit the leading subset that carries data)
_CAT_HELP = {
    "aliases": ["alias", "index", "filter", "routing.index",
                "routing.search"],
    "allocation": ["shards", "disk.used", "disk.avail", "disk.total",
                   "disk.percent", "host", "ip", "node"],
    "count": ["epoch", "timestamp", "count"],
    "fielddata": ["id", "host", "ip", "node", "total"],
    "health": ["epoch", "timestamp", "cluster", "status", "node.total",
               "node.data", "shards", "pri", "relo", "init", "unassign",
               "pending_tasks"],
    "indices": ["health", "status", "index", "pri", "rep", "docs.count",
                "docs.deleted", "store.size", "pri.store.size"],
    "master": ["id", "host", "ip", "node"],
    "nodes": ["host", "ip", "heap.percent", "ram.percent", "load",
              "node.role", "master", "name"],
    "pending_tasks": ["insertOrder", "timeInQueue", "priority", "source"],
    "tasks": ["action", "task_id", "parent_task_id", "type", "start_time",
              "running_time", "node"],
    "plugins": ["id", "name", "component", "version", "type", "url",
                "description"],
    "recovery": ["index", "shard", "time", "type", "stage", "source_host",
                 "target_host", "repository", "snapshot", "files",
                 "files_percent", "bytes", "bytes_percent", "total_files",
                 "total_bytes", "translog", "translog_percent",
                 "total_translog"],
    "segments": ["index", "shard", "prirep", "ip", "id", "segment",
                 "generation", "docs.count", "docs.deleted", "size",
                 "size.memory", "committed", "searchable", "version",
                 "compound"],
    "shards": ["index"] + ["shard", "prirep", "state", "docs", "store",
                           "ip", "id", "node"] + _CAT_SHARD_TAIL,
    "thread_pool": ["host", "ip", "bulk.active", "bulk.queue",
                    "bulk.rejected", "index.active", "index.queue",
                    "index.rejected", "search.active", "search.queue",
                    "search.rejected"],
}


def _cat_help_text(path: str):
    """`help` listing for a cat endpoint, or None when unknown."""
    parts = [x for x in path.split("/") if x]
    if len(parts) < 2:
        return None
    cols = _CAT_HELP.get(parts[1])
    if cols is None:
        return None
    width = max(len(c) for c in cols)
    return "\n".join(f"{c.ljust(width)} | | column" for c in cols) + "\n"



def _human_size(n: int) -> str:
    """ES ByteSizeValue text: scaled to kb/mb/gb/tb with one decimal."""
    n = int(n)
    for mul, suf in ((1 << 40, "tb"), (1 << 30, "gb"), (1 << 20, "mb"),
                     (1 << 10, "kb")):
        if n >= mul:
            v = n / mul
            return f"{v:.1f}{suf}" if v < 10 else f"{v:.0f}{suf}"
    return f"{n}b"


def _cat_scope(n: Node, index: Optional[str]):
    """Index names a scoped _cat route covers. A concrete name that
    resolves to nothing is a 404 (reference convention); wildcards and
    _all just narrow to the empty set."""
    names = n.resolve_indices(index)
    if not names and index not in (None, "", "_all", "*") \
            and "*" not in str(index) and "?" not in str(index):
        raise IndexNotFoundException(index)
    return names


def _cat_indices(n: Node, p, b, index: Optional[str] = None):
    rows = []
    for name in _cat_scope(n, index):
        svc = n.indices[name]
        size = sum(seg.memory_bytes() for sh in svc.shards
                   for seg in sh.segments)
        rows.append({
            "health": "green",
            "status": "close" if svc.closed else "open",
            "index": name,
            "pri": str(svc.num_shards), "rep": str(svc.num_replicas),
            "docs.count": str(svc.num_docs),
            "docs.deleted": str(sum(seg.deleted_count for sh in svc.shards
                                    for seg in sh.segments)),
            "store.size": _human_size(size),
            "pri.store.size": _human_size(size),
        })
    return 200, rows


def _cat_health(n: Node, p, b):
    import time as _t

    h = n.cluster_state.health()
    now = int(_t.time())
    return 200, [{
        "epoch": str(now),
        "timestamp": _t.strftime("%H:%M:%S", _t.gmtime(now)),
        "cluster": h["cluster_name"], "status": h["status"],
        "node.total": str(h["number_of_nodes"]),
        "node.data": str(h["number_of_nodes"]),
        "shards": str(h["active_shards"]),
        "pri": str(h["active_shards"]), "relo": "0", "init": "0",
        "unassign": "0",
        "pending_tasks": str(len(_all_pending_tasks(n, p))),
    }]


def _cat_master(n: Node, p, b):
    """RestMasterAction: the ELECTED master's own row — id, transport
    host, name — resolved from the cluster state's node map (the master
    is usually NOT the node serving this request in a multi-host world).
    A headless node answers the ES no-master shape (``-`` columns) with
    200: cat output keeps working under the NO_MASTER block."""
    st = n.cluster_state
    m = st.nodes.get(st.master_node_id) if st.master_node_id else None
    if m is None:
        return 200, [{"id": "-", "host": "-", "ip": "-", "node": "-"}]
    host = (m.transport_address.rsplit(":", 1)[0]
            if ":" in m.transport_address else "local")
    return 200, [{"id": m.node_id, "host": host, "ip": host,
                  "node": m.name or m.node_id}]


def _peer_shard_counts(n: Node, c) -> Dict[str, Dict[tuple, tuple]]:
    """{node_id: {(index, shard): (docs, store)}} from each peer's LOCAL
    cat-shards rows (the `_local_only` pin makes peers report their own
    engines) — one round per request, shared by the shard rows."""
    from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

    out: Dict[str, Dict[tuple, tuple]] = {}
    for nid in c.data._other_nodes():
        try:
            res = c.data._send(nid, ACTION_REST_PROXY, {
                "method": "GET", "path": "/_cat/shards",
                "params": {"format": "json"}, "body": ""})
        except Exception:
            continue
        if res["status"] != 200 or not isinstance(res["payload"], list):
            continue
        out[nid] = {(row["index"], row["shard"]):
                    (row.get("docs", "0"), row.get("store", "0b"))
                    for row in res["payload"]
                    if row.get("prirep") == "p"}
    return out


def _cat_shards(n: Node, p, b, index: Optional[str] = None):
    """One row per shard COPY (primary + each replica), RestShardsAction
    columns; in-process replicas report STARTED on this node (they are
    real copies here, where a one-node reference cluster shows them
    UNASSIGNED — both shapes are legal cat output)."""
    scope = set(_cat_scope(n, index))
    c = _mh(n)
    rows = []
    for iname, svc in n.indices.items():
        if iname not in scope:
            continue
        idx_settings = svc.settings.get("index", svc.settings)
        shadow = str(idx_settings.get("shadow_replicas", "false")
                     ).lower() in ("true", "1")
        dmeta = (c.dist_indices.get(iname)
                 if c is not None and not p.get("_local_only") else None)
        if dmeta is not None:
            # distributed: rows come from the published assignment —
            # one per copy, on its owning NODE; declared replicas with
            # no surviving copy print UNASSIGNED (RoutingTable shape).
            # docs/store come from the copy's OWNER (the coordinator's
            # local engine is empty for remote-owned shards)
            node_names = {nid: dn.name for nid, dn
                          in n.cluster_state.nodes.items()}
            init = dmeta.get("initializing", {})
            peer_counts = _peer_shard_counts(n, c)
            local_id = c.data._local_id()
            for sid in range(dmeta["num_shards"]):
                owners = dmeta["assignment"].get(str(sid), [])
                pending = init.get(str(sid), [])
                want = 1 + int(dmeta.get("replicas", 0))
                for i in range(max(want, len(owners) + len(pending))):
                    if i < len(owners):
                        nid = owners[i]
                        state = "STARTED"
                    elif i < len(owners) + len(pending):
                        nid = pending[i - len(owners)]
                        state = "INITIALIZING"
                    else:
                        nid, state = None, "UNASSIGNED"
                    row = {"index": iname, "shard": str(sid),
                           "prirep": ("p" if i == 0
                                      else "s" if shadow else "r"),
                           "state": state}
                    if state == "UNASSIGNED":
                        row.update(docs="", store="", ip="", node="")
                    else:
                        if nid == local_id:
                            docs = str(svc.shards[sid].engine.num_docs)
                            store = _human_size(sum(
                                seg.memory_bytes()
                                for seg in svc.shards[sid].segments))
                        else:
                            docs, store = peer_counts.get(nid, {}).get(
                                (iname, str(sid)), ("0", "0b"))
                        row.update(docs=docs, store=store, ip="127.0.0.1",
                                   node=node_names.get(nid, nid or ""))
                    rows.append(row)
            continue
        for g in svc.groups:
            for copy in g.copies:
                docs = copy.engine.num_docs
                size = sum(seg.memory_bytes() for seg in copy.segments)
                rows.append({
                    "index": iname, "shard": str(g.shard_id),
                    # shadow replicas print "s" (RestShardsAction)
                    "prirep": ("p" if copy is g.primary
                               else "s" if shadow else "r"),
                    "state": copy.state if copy.state != "CREATED"
                    else "INITIALIZING",
                    "docs": str(docs), "store": _human_size(size),
                    "ip": "127.0.0.1", "node": n.name})
    return 200, rows


def _cat_fielddata(n: Node, p, b, fields: Optional[str] = None):
    """RestFielddataAction: one row per node with `total` plus one column
    per LOADED field; ?fields= (or the path form) narrows the field
    columns. Columns load lazily into the evictable fielddata tier
    (resources/residency.py), so like the reference only fields whose
    device copies are currently resident show up — an evicted column
    drops out until the next search rehydrates it."""
    per_field: Dict[str, int] = {}
    for svc in n.indices.values():
        for shard in svc.shards:
            for seg in shard.segments:
                for fname, nbytes in seg.fielddata_field_bytes().items():
                    if fname.startswith("_"):
                        continue
                    per_field[fname] = per_field.get(fname, 0) + nbytes
    if not per_field:
        return 200, []
    want = fields or p.get("fields")
    shown = per_field
    if want:
        import fnmatch

        pats = [x.strip() for x in str(want).split(",") if x.strip()]
        shown = {f: v for f, v in per_field.items()
                 if any(fnmatch.fnmatchcase(f, pt) for pt in pats)}
    row = {"id": n.node_id[:4], "host": "localhost", "ip": "127.0.0.1",
           "node": n.name, "total": _human_size(sum(per_field.values()))}
    row.update({f: _human_size(v) for f, v in sorted(shown.items())})
    return 200, _cat_rows(
        [row], ["id", "host", "ip", "node", "total"] + sorted(shown))


def _cat_nodes(n: Node, p, b):
    from elasticsearch_tpu.monitor.stats import process_stats

    proc = process_stats()
    rss = proc["mem"]["resident_in_bytes"]
    row = {"host": "localhost", "ip": "127.0.0.1",
           "heap.percent": "0", "ram.percent": "0", "load": "0.00",
           "node.role": "d", "master": "*", "name": n.name,
           # selectable extras (RestNodesAction's full column table)
           "id": n.node_id[:4], "pid": str(os.getpid()), "port": "-",
           "heap.current": _human_size(rss), "heap.max": _human_size(rss),
           "ram.current": _human_size(rss), "ram.max": _human_size(rss),
           "uptime": "0s", "version": "2.0.0", "jdk": "-",
           "disk.avail": "-", "cpu": "0",
           "file_desc.current": str(proc.get("open_file_descriptors", 0)
                                    or 0),
           "file_desc.percent": "1",
           "file_desc.max": str(1 << 16)}
    return 200, _cat_rows([row], ["host", "ip", "heap.percent",
                                  "ram.percent", "load", "node.role",
                                  "master", "name"])


def _cat_aliases(n: Node, p, b, name: Optional[str] = None):
    import fnmatch

    rows = []
    for iname, svc in n.indices.items():
        for alias, spec in svc.aliases.items():
            if name is not None and not any(
                    fnmatch.fnmatch(alias, pat.strip())
                    for pat in name.split(",")):
                continue
            rows.append({"alias": alias, "index": iname,
                         "filter": "*" if spec.get("filter") else "-",
                         "routing.index": spec.get("index_routing", "-"),
                         "routing.search": spec.get("search_routing", "-")})
    return 200, rows


def _cat_allocation(n: Node, p, b, nodeid: Optional[str] = None):
    import shutil

    nid = nodeid or p.get("node_id")
    c = _mh(n)
    if c is not None and "_local_only" not in p:
        # multi-host: one row per member with its copy count, HBM bytes
        # over the breakers' capacity, and watermark state — the same
        # usage fan the allocator's deciders read, so the table an
        # operator sees IS the signal placement runs on (drain runbook:
        # a draining node's `shards` column reaching 0 means kill-safe)
        alloc = c.allocator
        rows = []
        for node_id in sorted(c.node.cluster_state.nodes):
            dn = c.node.cluster_state.nodes[node_id]
            if nid and nid not in ("_master", "_local", "_all", "*",
                                   node_id, dn.name):
                continue
            r = alloc._probe(node_id) or {}
            used = int(r.get("hbm_used", 0))
            cap = int(r.get("hbm_capacity", 0))
            rows.append({
                "shards": str(r.get("shards", 0)),
                "hbm.used": _human_size(used),
                "hbm.total": _human_size(cap),
                "hbm.percent": str(int(used * 100 / cap)) if cap else "-",
                "watermark": alloc.watermark_level(node_id),
                "draining": str(alloc.filter.excludes(dn)).lower(),
                "host": dn.transport_address, "ip": dn.transport_address,
                "node": dn.name or node_id, "node_id": node_id,
            })
        return 200, rows
    if nid and nid not in ("_master", "_local", "_all", "*",
                           n.node_id, n.name):
        return 200, []  # no such node: empty table, like the reference
    shards = 0
    for svc in n.indices.values():
        for g in svc.groups:
            for sh in g.copies:  # primaries AND replicas, same basis
                shards += 1
    du = shutil.disk_usage("/")
    pct = int(du.used * 100 / du.total) if du.total else 0
    return 200, [{"shards": str(shards),
                  "disk.used": _human_size(du.used),
                  "disk.avail": _human_size(du.free),
                  "disk.total": _human_size(du.total),
                  "disk.percent": str(pct), "host": "localhost",
                  "ip": "127.0.0.1", "node": n.name}]


def _cat_segments(n: Node, p, b, index: Optional[str] = None):
    from elasticsearch_tpu.cluster.metadata import check_open

    rows = []
    for iname in _cat_scope(n, index):
        svc = n.indices[iname]
        check_open(svc, op="read")  # closed index: 403, like the reference
        for g in svc.groups:
            for sh in g.copies:  # primaries and replicas, like _cat_shards
                prirep = "p" if sh is g.primary else "r"
                for ordn, seg in enumerate(sh.segments):
                    # PER-SHARD ordinals, like Lucene's per-writer
                    # generations (process-global seg ids stay internal)
                    mem = seg.memory_bytes()
                    rows.append({
                        "index": iname, "shard": str(sh.shard_id),
                        "prirep": prirep, "ip": "127.0.0.1",
                        "segment": f"_{ordn}",
                        "generation": str(ordn),
                        "docs.count": str(seg.live_docs),
                        "docs.deleted": str(seg.deleted_count),
                        "size": _human_size(mem),
                        "size.memory": str(mem),
                        "committed": "true", "searchable": "true",
                        "version": "0.1.0", "compound": "false",
                    })
    c = _mh(n)
    if c is not None and not p.get("_local_only"):
        # segments live where the DOCS live: union every peer's local
        # rows (a dist index's remote-owned shards have no local segments)
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        path = "/_cat/segments" + (f"/{index}" if index else "")
        for nid in c.data._other_nodes():
            try:
                res = c.data._send(nid, ACTION_REST_PROXY, {
                    "method": "GET", "path": path,
                    "params": {"format": "json"}, "body": ""})
            except Exception:
                continue
            if res["status"] == 200 and isinstance(res["payload"], list):
                rows.extend(res["payload"])
    return 200, rows


def _cat_recovery(n: Node, p, b, index: Optional[str] = None):
    """Real rows from each index's RecoveryRegistry: `type` distinguishes
    checkpoint-based ops replay (`ops_replay`) from the full-copy
    fallback (`full_copy`) and gateway translog replay; `translog` is the
    actual ops-replayed count. Shards with no recorded recovery keep the
    synthetic done/gateway row."""
    rows = []
    for iname in _cat_scope(n, index):
        svc = n.indices[iname]
        for g in svc.groups:
            entries = svc.recoveries.entries(g.shard_id)
            if not entries:
                entries = [{"type": "gateway", "stage": "done",
                            "source": "local", "target": "local",
                            "ops_replayed": 0, "docs_copied": 0,
                            "total_time_in_millis": 0, "mode": None}]
            for e in entries:
                mode = e.get("mode")
                rtype = ("ops_replay" if mode == "ops"
                         else "full_copy" if mode == "full"
                         else e.get("type", "gateway"))
                rows.append({
                    "index": iname, "shard": str(g.shard_id),
                    "time": str(e.get("total_time_in_millis", 0)),
                    "type": rtype,
                    "stage": e.get("stage", "done"),
                    "source_host": str(e.get("source", "localhost")),
                    "target_host": str(e.get("target", "localhost")),
                    "repository": "n/a", "snapshot": "n/a",
                    "files": "0", "files_percent": "100.0%",
                    "bytes": str(e.get("docs_copied", 0)),
                    "bytes_percent": "100.0%",
                    "total_files": "0", "total_bytes": "0",
                    "translog": str(e.get("ops_replayed", 0)),
                    "translog_percent": "100.0%",
                    "total_translog": str(e.get("ops_replayed", 0))})
    return 200, rows


def _cat_snapshots(n: Node, p, b, repo: str):
    from elasticsearch_tpu.index.snapshots import snapshot_info

    r = _repo_or_404(n, repo)
    return 200, [snapshot_info(r, s) for s in r.catalog()]


def _cat_count(n: Node, p, b, index: Optional[str] = None):
    import time as _t

    names = n.resolve_indices(index)
    total = sum(n.indices[x].num_docs for x in names)
    now = int(_t.time())
    return 200, [{"epoch": str(now),
                  "timestamp": _t.strftime("%H:%M:%S", _t.gmtime(now)),
                  "count": str(total)}]


def _index_exists(n: Node, p, b, index: str):
    return (200, None) if n.index_exists(index) else (404, None)


def _get_settings(n: Node, p, b, index: str):
    """All setting values render as STRINGS (the reference's Settings is a
    string map); ?flat_settings=true flattens to 'index.x.y' keys."""
    flat = str(p.get("flat_settings", "false")).lower() in ("", "true")
    out = {}
    for name in n.resolve_indices(index):
        svc = n.indices[name]
        idx = {
            "number_of_shards": str(svc.num_shards),
            "number_of_replicas": str(svc.num_replicas),
            **{k: str(v) for k, v in svc.settings.get("index", {}).items()
               if k not in ("number_of_shards", "number_of_replicas")},
            **{k: str(v) for k, v in svc.settings.items() if k != "index"},
        }
        if flat:
            out[name] = {"settings": {f"index.{k}": v
                                      for k, v in idx.items()}}
        else:
            out[name] = {"settings": {"index": idx}}
    if not out:
        raise IndexNotFoundException(index)
    return 200, out


def _put_settings(n: Node, p, b, index: str):
    from elasticsearch_tpu.cluster.metadata import update_index_settings

    names = _resolve_indices_options(n, index, p)
    body = _json(b)
    for nm in names:  # multi-index expressions, like the reference
        update_index_settings(n.indices[nm], body, node=n)
    return 200, {"acknowledged": True}


def _close_index(n: Node, p, b, index: str):
    from elasticsearch_tpu.cluster.metadata import close_index

    names = n.resolve_indices(index)
    if not names:
        raise IndexNotFoundException(index)
    c = _mh(n)
    for nm in names:
        close_index(n, nm)
        if c is not None and nm in c.dist_indices:
            # closed-ness is cluster state: peers adopt it on publish, so
            # a search scattered to shard owners is refused everywhere
            c.data.set_closed(nm, True)
    return 200, {"acknowledged": True}


def _open_index(n: Node, p, b, index: str):
    from elasticsearch_tpu.cluster.metadata import open_index

    names = n.resolve_indices(index)
    if not names:
        raise IndexNotFoundException(index)
    c = _mh(n)
    for nm in names:
        open_index(n, nm)
        if c is not None and nm in c.dist_indices:
            c.data.set_closed(nm, False)
    # a re-opened index serves cold — queue its census replay
    # (serving/warmup.py; cooldown-guarded, no-op without a census)
    wu = getattr(getattr(n, "serving", None), "warmup", None)
    if wu is not None:
        wu.kick("index_open", names)
    return 200, {"acknowledged": True}


def _expand_wildcards(n: Node, names, index_expr, p):
    """expand_wildcards=open|closed|open,closed filtering for WILDCARD
    index expressions (concrete names always resolve)."""
    expr = str(index_expr or "")
    if "*" not in expr and expr not in ("_all", ""):
        return names
    want = {x.strip() for x in str(p.get("expand_wildcards", "open")
                                   ).split(",")}
    if {"open", "closed"} <= want or "all" in want:
        return names
    closed_ok = "closed" in want
    return [nm for nm in names if n.indices[nm].closed == closed_ok]


def _get_index_meta(n: Node, p, b, index: str):
    names = _expand_wildcards(n, n.resolve_indices(index), index, p)
    settings_out = _get_settings(n, p, b, index)[1] if names else {}
    out = {}
    for name in names:
        svc = n.indices[name]
        mj = svc.mappings.to_json()
        out[name] = {
            "aliases": svc.aliases,
            "mappings": ({t: mj for t in svc.mappings.type_names}
                         if svc.mappings.type_names else mj),
            "warmers": {k: {"source": v} for k, v in svc.warmers.items()},
            **settings_out.get(name, {}),
        }
    if not out:
        # a wildcard that narrows to nothing (or ignore_unavailable /
        # allow_no_indices) answers {}; only a concrete miss 404s
        wildcard = any(c in str(index) for c in "*,")
        allow_none = str(p.get("allow_no_indices",
                               "true" if wildcard else "false")
                         ).lower() in ("", "true")
        ignore_missing = str(p.get("ignore_unavailable", "false")
                             ).lower() in ("", "true")
        if not ((wildcard and allow_none)
                or (not wildcard and ignore_missing)):
            raise IndexNotFoundException(index)
    return 200, out


def _get_aliases(n: Node, p, b):
    return 200, {name: {"aliases": svc.aliases} for name, svc in n.indices.items()}


def _get_alias(n: Node, p, b, alias: str):
    import fnmatch

    pats = [x.strip() for x in alias.split(",")]
    out = {}
    for name, svc in n.indices.items():
        matched = {a: fa for a, fa in svc.aliases.items()
                   if any(pt in ("_all", "*") or fnmatch.fnmatch(a, pt)
                          for pt in pats)}
        if matched:
            out[name] = {"aliases": matched}
    if not out:
        # concrete name miss -> 404; patterns narrow to empty 200
        if any("*" in pt or pt in ("_all",) for pt in pats):
            return 200, {}
        return 404, {"error": f"alias [{alias}] missing", "status": 404}
    return 200, out


def _refresh(n: Node, p, b, index: str):
    names = _resolve_indices_options(n, index, p)
    for name in names:
        data = _mh_for(n, name)
        if data is not None:
            data.refresh(name)  # refreshes every process's copies
        else:
            n.indices[name].refresh()
    return 200, {"_shards": _shards_header(n, names)}


def _refresh_all(n: Node, p, b):
    for svc in n.indices.values():
        svc.refresh()
    return 200, {"_shards": _shards_header(n, list(n.indices))}


def _shards_header(n: Node, names) -> dict:
    total = sum(n.indices[nm].num_shards
                * (1 + n.indices[nm].num_replicas) for nm in names)
    return {"total": total, "successful": total, "failed": 0}


def _flush(n: Node, p, b, index: str):
    names = n.resolve_indices(index)
    for name in names:
        n.indices[name].flush()
    return 200, {"_shards": _shards_header(n, names)}


def _optimize(n: Node, p, b, index: str):
    max_seg = int(p.get("max_num_segments", 1))
    names = n.resolve_indices(index)
    # cancellable task: engine.merge checkpoints between source segments
    with n.tasks.task("indices:admin/optimize",
                      description=f"force-merge {names}"):
        for name in names:
            n.indices[name].force_merge(max_seg)
    return 200, {"_shards": _shards_header(n, names)}


def _count_with_body(n: Node, index: Optional[str], body: dict):
    svc_names = n.resolve_indices(index)
    if not svc_names:
        if index in (None, "", "_all", "*"):
            return 200, {"count": 0, "_shards": {"total": 0,
                                                 "successful": 0,
                                                 "failed": 0}}
        raise IndexNotFoundException(index)
    total = 0
    nshards = 0
    for name in svc_names:
        data = _mh_for(n, name)
        if data is not None:
            # cross-host count = a size-0 scatter/gather round
            r = data.search(name, {"query": body.get("query",
                                                     {"match_all": {}}),
                                   "size": 0})
            total += r["hits"]["total"]
        else:
            total += n.indices[name].count(body)["count"]
        nshards += n.indices[name].num_shards
    return 200, {"count": total, "_shards": {"total": nshards,
                                             "successful": nshards,
                                             "failed": 0}}


def _count(n: Node, p, b, index: str):
    body = _json(b)
    if "q" in p:
        body = {"query": {"query_string": {"query": p["q"]}}}
    return _count_with_body(n, index, body)


def _analyze_body(p, b) -> dict:
    body = _json(b)
    for k in ("text", "analyzer", "tokenizer", "filters", "filter",
              "char_filters", "char_filter", "field"):
        if k in p:
            body.setdefault(k, p[k])
    return body


def _analyze(n: Node, p, b):
    from elasticsearch_tpu.analysis.registry import AnalysisRegistry

    body = _analyze_body(p, b)
    reg = AnalysisRegistry()
    return 200, _do_analyze(reg, body)


def _analyze_index(n: Node, p, b, index: str):
    svc = n.get_index(index)
    return 200, _do_analyze(svc.analysis, _analyze_body(p, b), svc)


def _do_analyze(reg, body: dict, svc=None) -> dict:
    text = body.get("text", "")
    texts = text if isinstance(text, list) else [text]
    if "field" in body and svc is not None:
        fm = svc.mappings.get(body["field"])
        analyzer = reg.get(fm.analyzer) if fm is not None and fm.is_text else reg.get("keyword")
    elif "tokenizer" in body:
        # one-off chain: tokenizer + filters/char_filters params
        # (RestAnalyzeAction's ad-hoc analyzer)
        from elasticsearch_tpu.analysis.analyzer import \
            build_custom_analyzer

        def _lst(v):
            if v is None:
                return []
            if isinstance(v, str):
                return [x.strip() for x in v.split(",") if x.strip()]
            return list(v)

        analyzer = build_custom_analyzer("_adhoc", {
            "tokenizer": body["tokenizer"],
            "filter": _lst(body.get("filters", body.get("filter"))),
            "char_filter": _lst(body.get("char_filters",
                                         body.get("char_filter")))})
    else:
        analyzer = reg.get(body.get("analyzer", "standard"))
    tokens = []
    for t in texts:
        for tok, pos in analyzer.analyze(t):
            tokens.append({"token": tok, "position": pos, "type": "<ALPHANUM>"})
    return {"tokens": tokens}


# -- task management (tracing/tasks.py) ---------------------------------------

def _split_task_id(task_id: str):
    """"node:seq" → (node, seq); a bare number targets the local node."""
    node_id, _, num = str(task_id).rpartition(":")
    if not num.isdigit():
        raise IllegalArgumentException(
            f"malformed task id [{task_id}] (expected nodeId:taskNumber)")
    return node_id, int(num)


def _local_tasks_entry(n: Node, p) -> dict:
    tasks = {t.tagged_id: t.to_json()
             for t in n.tasks.list_tasks(actions=p.get("actions"))}
    return {n.node_id: {
        "name": n.name,
        "transport_address": n._transport_info()["publish_address"],
        "tasks": tasks}}


def _tasks_list(n: Node, p, b):
    """GET /_tasks (RestListTasksAction): every node's in-flight tasks.
    Multi-host fans through the REST proxy (each member reports its own
    registry); a dead peer lands in ``node_failures``, never silently
    missing — its tasks are exactly what an operator hunting a runaway
    delete-by-query needs to see."""
    out: Dict[str, Any] = {"nodes": _local_tasks_entry(n, p)}
    mh = _mh(n)
    if mh is not None and "_local_only" not in p:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        failures = []
        params = {k: p[k] for k in ("actions",) if k in p}
        for nid in mh.data._other_nodes():
            try:
                res = mh.data._send(nid, ACTION_REST_PROXY, {
                    "method": "GET", "path": "/_tasks", "params": params})
                if res.get("status") == 200:
                    out["nodes"].update(
                        (res.get("payload") or {}).get("nodes", {}))
            except Exception as e:
                failures.append({"node_id": nid, "reason": str(e)})
        if failures:
            out["node_failures"] = failures
    return 200, out


def _task_get(n: Node, p, b, task_id: str):
    """GET /_tasks/{id}: the task's detail from its owning node."""
    from elasticsearch_tpu.tracing.tasks import ResourceNotFoundException

    node_id, num = _split_task_id(task_id)
    if node_id in ("", "_local", n.node_id):
        t = n.tasks.get(num)
        if t is None:
            raise ResourceNotFoundException(
                f"task [{task_id}] isn't running and hasn't stored its "
                "results")
        return 200, {"completed": False, "task": t.to_json()}
    mh = _mh(n)
    if mh is not None and "_local_only" not in p \
            and node_id in n.cluster_state.nodes:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        res = mh.data._send(node_id, ACTION_REST_PROXY, {
            "method": "GET", "path": f"/_tasks/{task_id}", "params": {}})
        return res["status"], res["payload"]
    # not a member (typo'd or departed node): 404, never a generic 500
    # from an unresolvable transport address
    raise ResourceNotFoundException(
        f"task [{task_id}] belongs to an unknown node")


def _task_cancel(n: Node, p, b, task_id: str):
    """POST /_tasks/{id}/_cancel (RestCancelTasksAction): cancel the task
    AND its descendants — local children directly, remote children via
    the parent-id fanout (cluster/search_action.py::cancel_task_children),
    so cancelling a coordinator by-query stops the remote shard scans."""
    node_id, num = _split_task_id(task_id)
    mh = _mh(n)
    if node_id in ("", "_local", n.node_id):
        reason = "by user request"
        cancelled = n.tasks.cancel(num, reason)  # 404s when absent
        out: Dict[str, Any] = {"nodes": {}}
        if cancelled:
            out["nodes"][n.node_id] = {
                "name": n.name,
                "tasks": {t.tagged_id: t.to_json() for t in cancelled}}
        if mh is not None:
            remote = mh.data.cancel_task_children(n.node_id, num, reason)
            out["nodes"].update(remote.get("nodes", {}))
            if remote.get("node_failures"):
                out["node_failures"] = remote["node_failures"]
        return 200, out
    if mh is not None and "_local_only" not in p \
            and node_id in n.cluster_state.nodes:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        # the task lives on another member: relay — the owner cancels
        # locally and runs the child fanout itself
        res = mh.data._send(node_id, ACTION_REST_PROXY, {
            "method": "POST", "path": f"/_tasks/{task_id}/_cancel",
            "params": {}})
        return res["status"], res["payload"]
    from elasticsearch_tpu.tracing.tasks import ResourceNotFoundException

    # not a member (typo'd or departed node): 404, never a generic 500
    # from an unresolvable transport address
    raise ResourceNotFoundException(
        f"task [{task_id}] belongs to an unknown node")


def _cat_tasks(n: Node, p, b):
    """GET /_cat/tasks: the /_tasks listing as cat rows."""
    _status, body = _tasks_list(n, p, b)
    rows = []
    from elasticsearch_tpu.tracing.tasks import human_time

    for nid, entry in sorted(body["nodes"].items()):
        for tid, t in sorted(entry.get("tasks", {}).items()):
            nanos = t.get("running_time_in_nanos", 0)
            rows.append({
                "action": t.get("action", ""),
                "task_id": tid,
                "parent_task_id": t.get("parent_task_id", "-"),
                "type": t.get("type", "transport"),
                "start_time": str(t.get("start_time_in_millis", "")),
                # human-scaled (the task's own to_json form when present:
                # remote members computed it from THEIR monotonic clock)
                "running_time": t.get("running_time",
                                      human_time(nanos)),
                "running_time_in_nanos": str(nanos),
                "node": entry.get("name", nid),
                "description": t.get("description", ""),
            })
    return 200, _cat_rows(rows, ["action", "task_id", "parent_task_id",
                                 "type", "start_time", "running_time",
                                 "node"])


def _all_pending_tasks(n: Node, p) -> List[dict]:
    """Cluster-wide pending set: the local registry plus every member's
    (recovery streams queue on whichever member scheduled them, so a
    local-only view would show 0 to an operator polling a different
    node). Best-effort like nodes_fan — a dead peer's queue is
    unknowable and simply absent."""
    rows = list(n.tasks.pending_tasks())
    mh = _mh(n)
    if mh is not None and "_local_only" not in p:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        for nid in mh.data._other_nodes():
            try:
                res = mh.data._send(nid, ACTION_REST_PROXY, {
                    "method": "GET", "path": "/_cluster/pending_tasks",
                    "params": {}})
            except Exception:
                continue  # unreachable peer: its queue stays absent
            if res.get("status") == 200:
                rows.extend((res.get("payload") or {}).get("tasks", []))
    return rows


def _cluster_pending_tasks(n: Node, p, b):
    """GET /_cluster/pending_tasks: queued-but-not-running tasks (e.g.
    recovery streams waiting behind earlier ones) from the registries
    of EVERY member — the reference reports the master's cluster-state
    update queue; our serialized queue-like work is the pending task
    set."""
    return 200, {"tasks": _all_pending_tasks(n, p)}


def _cat_pending_tasks(n: Node, p, b):
    rows = [{"insertOrder": str(t["insert_order"]),
             "timeInQueue": t["time_in_queue"],
             "priority": t["priority"],
             "source": t["source"]} for t in _all_pending_tasks(n, p)]
    return 200, _cat_rows(rows, ["insertOrder", "timeInQueue", "priority",
                                 "source"])


def _node_trace(n: Node, p, b):
    """GET /_nodes/_local/trace: the local span ring in Chrome
    trace-event format for offline flamegraph inspection (chrome://
    tracing / Perfetto / speedscope)."""
    return 200, n.tracer.chrome_trace()


def _node_programs(n: Node, p, b):
    """GET /_nodes/_local/xla/programs: the device-program observatory —
    per-(program, shapes, backend) compile counts, compile seconds,
    cached-execute calls with p50/p99, cold flags, plus the per-index
    (program, shapes, field) census sets (monitor/programs.py). The
    registry is process-global (the device is process-shared), hence the
    _local spelling."""
    from elasticsearch_tpu.monitor import programs

    reg = programs.REGISTRY
    return 200, {
        "backend": programs.backend_fingerprint(),
        "totals": reg.stats(),
        "programs": reg.snapshot(),
        "census": {ix: reg.census(ix) for ix in reg.census_indices()},
    }


def _node_flight(n: Node, p, b):
    """GET /_nodes/_local/flight: this node's flight-recorder rings
    (bounded black box: metric deltas, slow ops, breaker trips, compile
    events, cluster transitions, engine failures, watchdog trips), plus
    the watchdog's own state and the incident listing."""
    return 200, {
        "flight": n.flight.snapshot(),
        "watchdog": n.watchdog.stats(),
        "incidents": n.watchdog.incidents.list(),
    }


def _warmup_trigger(n: Node, p, b):
    """POST /_warmup: queue a census replay for every open local index
    (serving/warmup.py). Cooldown-guarded — steady-state re-triggers are
    recorded no-ops; the run itself is a cancellable
    ``cluster:admin/warmup`` task."""
    queued = n.serving.warmup.kick("api")
    return 200, {"acknowledged": True, "queued": queued}


def _warmup_trigger_index(n: Node, p, b, index: str):
    """POST /{index}/_warmup: queue a census replay for one index."""
    names = n.resolve_indices(index)
    if not names:
        raise IndexNotFoundException(index)
    queued = n.serving.warmup.kick("api", names)
    return 200, {"acknowledged": True, "queued": queued}


def _warmup_status(n: Node, p, b):
    """GET /_warmup: the pre-warm service's queue + per-index last-run
    results (also in the ``serving`` section of /_nodes/stats)."""
    return 200, n.serving.warmup.stats()


def _incident_rows(n: Node, p) -> List[dict]:
    """_cat/incidents rows: local incidents plus every member's (the
    _tasks fan) — dedup'd by id, since in-process members share the
    blob cache's persisted index."""
    rows = []
    for e in n.watchdog.incidents.list():
        rows.append({
            "id": str(e.get("id", "")),
            "detector": str(e.get("detector", "")),
            "node": str(e.get("node_name") or e.get("node") or ""),
            "timestamp": str(e.get("timestamp_ms", "")),
            "persisted": "true" if e.get("persisted") else "false",
            "reason": str(e.get("reason", ""))[:120],
        })
    mh = _mh(n)
    if mh is not None and "_local_only" not in p:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        for nid in mh.data._other_nodes():
            try:
                res = mh.data._send(nid, ACTION_REST_PROXY, {
                    "method": "GET", "path": "/_cat/incidents",
                    "params": {}})
            except Exception:
                continue  # unreachable peer: its incidents stay absent
            if res.get("status") == 200:
                rows.extend(r for r in (res.get("payload") or [])
                            if isinstance(r, dict))
    seen: set = set()
    out = []
    for r in rows:
        if r["id"] in seen:
            continue
        seen.add(r["id"])
        out.append(r)
    out.sort(key=lambda r: r["timestamp"])
    return out


def _cat_incidents(n: Node, p, b):
    """GET /_cat/incidents: one row per captured incident dump,
    cluster-wide, oldest first."""
    return 200, _cat_rows(_incident_rows(n, p),
                          ["id", "detector", "node", "timestamp",
                           "reason"])


def _get_incident(n: Node, p, b, incident_id: str):
    """GET /_cluster/diagnostics/incidents/{id}: one incident's full
    payload — the in-memory copy, the digest-verified persisted blob, or
    (when the id names another live member) that member's copy."""
    payload = n.watchdog.incidents.load(incident_id)
    if payload is not None:
        return 200, payload
    owner, _, _seq = incident_id.partition(":")
    mh = _mh(n)
    if mh is not None and "_local_only" not in p \
            and owner and owner != n.node_id \
            and owner in n.cluster_state.nodes:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        try:
            res = mh.data._send(owner, ACTION_REST_PROXY, {
                "method": "GET",
                "path": f"/_cluster/diagnostics/incidents/{incident_id}",
                "params": {}})
            return res["status"], res["payload"]
        except Exception:  # tpulint: allow[R006] — unreachable owner:
            # the owner just died — exactly the outage incidents exist
            # for; fall through to the typed 404, never an untyped 500
            pass
    from elasticsearch_tpu.tracing.tasks import ResourceNotFoundException

    raise ResourceNotFoundException(f"incident [{incident_id}] not found")


def _local_diagnostics(n: Node, p) -> dict:
    """One node's contribution to the diagnostics bundle. The key set is
    part of the bundle's schema contract (tier-1 gate)."""
    from elasticsearch_tpu import resources
    from elasticsearch_tpu.monitor import programs
    from elasticsearch_tpu.monitor.watchdog import hot_threads_snapshot

    try:
        k = int(p.get("incidents", 2))
    except (TypeError, ValueError):
        k = 2
    k = max(0, min(k, 8))
    return {
        "name": n.name,
        "flight": n.flight.snapshot(),
        "watchdog": n.watchdog.stats(),
        "incidents": n.watchdog.incidents.list(),
        "incident_payloads": n.watchdog.incidents.recent(k),
        "hot_threads": hot_threads_snapshot(),
        "tasks": [t.to_json() for t in n.tasks.list_tasks()][:64],
        "programs": {
            "totals": programs.REGISTRY.stats(),
            "inflight": programs.REGISTRY.inflight_snapshot(),
        },
        "breakers": resources.BREAKERS.stats(),
        "thread_pool": (n._thread_pool.stats()
                        if n._thread_pool is not None else {}),
    }


def _cluster_diagnostics(n: Node, p, b):
    """GET /_cluster/diagnostics: the cluster-wide support bundle — one
    schema-stable JSON artifact merging every member's flight rings,
    watchdog state, incidents (with the most recent payloads inline),
    hot-threads snapshot, in-flight programs and task list. Fans over
    members via the REST proxy; a dead peer is counted in
    ``_nodes.failed`` and listed under ``failures`` — the response stays
    200, because a support bundle gathered DURING an outage is the whole
    point (the /_cluster/stats fan-out discipline)."""
    local = _local_diagnostics(n, p)
    c = _mh(n)
    if c is not None and "_local_only" in p:
        # proxied member contribution: raw and unmerged
        return 200, local
    nodes = {n.node_id: local}
    failures: List[dict] = []
    if c is not None:
        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

        params = {k: p[k] for k in ("incidents",) if k in p}
        for nid in c.data._other_nodes():
            try:
                res = c.data._send(nid, ACTION_REST_PROXY, {
                    "method": "GET", "path": "/_cluster/diagnostics",
                    "params": params})
                if res.get("status") == 200 and res.get("payload"):
                    nodes[nid] = res["payload"]
                else:
                    failures.append({"node_id": nid,
                                     "reason": f"status {res.get('status')}"})
            except Exception as e:
                failures.append({"node_id": nid, "reason": str(e)})
    return 200, {
        "version": 1,
        "cluster_name": n.cluster_state.cluster_name,
        "timestamp": int(time.time() * 1000),
        "master_node": n.cluster_state.master_node_id,
        "_nodes": {"total": len(nodes) + len(failures),
                   "successful": len(nodes), "failed": len(failures)},
        "nodes": nodes,
        "failures": failures,
    }


def _cat_programs(n: Node, p, b):
    """GET /_cat/programs: one row per (program, shapes, backend) key —
    compiles, compile_seconds, cached calls, execute p50/p99, cold flag
    (never served a cached execute in this process), and the AOT
    cache-source ledger (``aot:2,fresh:1`` — parallel/aot.py; ``-`` for
    keys the AOT layer never resolved, e.g. trace-level census rows)."""
    from elasticsearch_tpu.monitor import programs

    def _cache(sources: dict) -> str:
        short = {"aot_hit": "aot", "xla_dir_hit": "xla_dir"}
        return ",".join(f"{short.get(k, k)}:{v}"
                        for k, v in sorted(sources.items())) or "-"

    rows = [{
        "program": r["program"],
        "shapes": r["shapes"],
        "backend": r["backend"],
        "compiles": str(r["compiles"]),
        "compile_seconds": f"{r['compile_seconds']:.3f}",
        "calls": str(r["calls"]),
        "execute_p50_ms": f"{r['execute_p50_seconds'] * 1000.0:.2f}",
        "execute_p99_ms": f"{r['execute_p99_seconds'] * 1000.0:.2f}",
        "cold": "true" if r["cold"] else "false",
        "cache": _cache(r["cache_sources"]),
    } for r in programs.REGISTRY.snapshot()]
    return 200, _cat_rows(rows, ["program", "shapes", "backend", "compiles",
                                 "compile_seconds", "calls",
                                 "execute_p50_ms", "execute_p99_ms",
                                 "cold", "cache"])


# -- document handlers --------------------------------------------------------

def _nodes_info(n: Node, p, b, **_sel):
    """/_nodes[/...] — single node returns its own view; in a multi-host
    world the coordinator merges every member's self-reported entry
    (reference: TransportNodesInfoAction). `_local_only` (set by the
    cross-host REST proxy) pins to this process to prevent re-fanning.
    Node-id/metric selectors are accepted and return the full view, the
    same single-node simplification the scoped stats routes make."""
    mh = _mh(n)
    if mh is not None and "_local_only" not in p:
        return 200, mh.data.nodes_fan()
    return 200, n.nodes_stats()


def _mh(n: Node):
    """The multi-host data plane, when this node runs in a jax.distributed
    world (cluster/bootstrap.py sets node.multihost). REST operations on
    distributed indices route through it so writes land on shard-owner
    processes and searches scatter/gather cross-host."""
    return getattr(n, "multihost", None)


def _mh_for(n: Node, index: Optional[str]):
    """The data service IF `index` names (or aliases) a distributed
    index — an alias-named request must ride the cross-host data plane,
    not fall to the node-local path with only local shards."""
    c = _mh(n)
    if c is not None and index is not None \
            and c.data.resolve_index(index) in c.dist_indices:
        return c.data
    return None


def _create_index(n: Node, p, b, index: str):
    c = _mh(n)
    if c is not None:
        # multi-host world: every create goes through the master so the
        # shard→node assignment is computed once and published; the wire
        # result's assignment map stays internal — clients get the
        # standard create envelope
        c.data.create_index(index, _json(b))
        return 200, {"acknowledged": True, "shards_acknowledged": True,
                     "index": index}
    return 200, n.create_index(index, _json(b))


def _index_kw(p, doc_type: Optional[str]) -> dict:
    """The index-op kwargs every write route forwards (version checks,
    op_type, parent-as-routing, timestamp/ttl meta)."""
    kw: Dict[str, Any] = {}
    if "version" in p:
        kw["version"] = int(p["version"])
        kw["version_type"] = p.get("version_type", "internal")
    if p.get("op_type") == "create":
        kw["op_type"] = "create"
    if doc_type:
        kw["doc_type"] = doc_type
    if p.get("parent"):
        # parent id doubles as the routing key so parent and child land on
        # the same shard (reference: ParentFieldMapper + routing resolution)
        kw["parent"] = p["parent"]
    if p.get("timestamp"):  # _timestamp meta field (TimestampFieldMapper)
        kw["timestamp"] = p["timestamp"]
    if p.get("ttl"):  # _ttl meta field (TTLFieldMapper)
        kw["ttl"] = p["ttl"]
    return kw


def _index_doc(n: Node, p, b, index: str, id: str, doc_type: Optional[str] = None):
    kw = _index_kw(p, doc_type)
    data = _mh_for(n, index)
    if data is not None:
        r = data.index_doc(index, id, _json(b),
                           routing=p.get("routing") or p.get("parent"),
                           **kw)
        if _refresh_requested(p):
            data.refresh(index)
        return (201 if r.get("created") else 200), r
    svc = n.get_or_autocreate(index)
    r = svc.index_doc(id, _json(b), routing=p.get("routing") or p.get("parent"), **kw)
    if _refresh_requested(p):
        svc.refresh()
    return (201 if r.get("created") else 200), r


def _index_doc_auto(n: Node, p, b, index: str):
    data = _mh_for(n, index)
    if data is not None:
        r = data.index_doc(index, None, _json(b),
                           routing=p.get("routing"))
        if _refresh_requested(p):
            data.refresh(index)
        return 201, r
    svc = n.get_or_autocreate(index)
    r = svc.index_doc(None, _json(b), routing=p.get("routing"))
    if _refresh_requested(p):
        svc.refresh()
    return 201, r


def _create_doc(n: Node, p, b, index: str, id: str):
    data = _mh_for(n, index)
    if data is not None:
        return 201, data.index_doc(index, id, _json(b), op_type="create",
                                   routing=p.get("routing"))
    svc = n.get_or_autocreate(index)
    r = svc.index_doc(id, _json(b), op_type="create", routing=p.get("routing"))
    return 201, r


def _index_doc_typed(n: Node, p, b, index: str, type: str, id: str):
    # any leading-underscore segment is a mis-bound meta path, not a type
    if type.startswith("_"):
        raise IllegalArgumentException(f"unsupported path [{index}/{type}/{id}]")
    return _index_doc(n, p, b, index, id, doc_type=type)


def _create_doc_typed(n: Node, p, b, index: str, type: str, id: str):
    """PUT /{index}/{type}/{id}/_create — the create API: op_type=create
    forced, conflict on an existing id (reference:
    rest/action/document/RestIndexAction CREATE registration)."""
    return _index_doc_typed(n, dict(p, op_type="create"), b, index, type, id)


def _check_read_routing(n: Node, index: str, type: str, id: str, p) -> None:
    """Typed reads/deletes of a parent-mapped or routing-required type
    without routing/parent are rejected (RoutingMissingException), like
    the reference's read-side routing resolution."""
    from elasticsearch_tpu.utils.errors import (ElasticsearchTpuException,
                                                RoutingMissingException)

    if p.get("routing") or p.get("parent"):
        return
    try:
        m = n.get_index(index).mappings
    except ElasticsearchTpuException:
        return
    if m.routing_required or (type not in ("_all", "_doc")
                              and type in m.parent_types):
        raise RoutingMissingException(index, type, str(id))


def _type_mismatch(n: Node, index: str, type: str, id: str,
                   routing: Optional[str] = None) -> bool:
    """Requested {type} filters doc reads (reference: GetRequest.type) —
    _all/_doc match anything."""
    if type in ("_all", "_doc"):
        return False
    from elasticsearch_tpu.utils.errors import ElasticsearchTpuException

    try:
        svc = n.get_index(index)
        loc = svc.route(str(id), routing).engine._locations.get(str(id))
    except ElasticsearchTpuException:
        return False
    return (loc is not None and not loc.deleted
            and (loc.doc_type or "_doc") != type)


def _get_doc_typed(n: Node, p, b, index: str, type: str, id: str):
    if type.startswith("_") and type != "_all":
        raise IllegalArgumentException(f"unsupported path [{index}/{type}/{id}]")
    _check_read_routing(n, index, type, id, p)
    if _type_mismatch(n, index, type, id,
                      p.get("routing") or p.get("parent")):
        return 404, {"_index": index, "_type": type, "_id": id,
                     "found": False}
    return _get_doc(n, p, b, index, id)


def _delete_doc_typed(n: Node, p, b, index: str, type: str, id: str):
    if type.startswith("_") and type != "_all":
        raise IllegalArgumentException(f"unsupported path [{index}/{type}/{id}]")
    _check_read_routing(n, index, type, id, p)
    if _type_mismatch(n, index, type, id,
                      p.get("routing") or p.get("parent")):
        from elasticsearch_tpu.utils.errors import DocumentMissingException

        raise DocumentMissingException(index, id)
    return _delete_doc(n, p, b, index, id)


def _realtime_kw(n, p, index: str) -> dict:
    """GET-API realtime/refresh params: realtime=false reads only
    refreshed state; refresh=true refreshes first (GetRequest.realtime/
    refresh). refresh on a distributed index refreshes CLUSTER-wide."""
    if str(p.get("refresh", "false")).lower() in ("", "true", "1"):
        data = _mh_for(n, index)
        if data is not None:
            data.refresh(index)
        else:
            n.get_index(index).refresh()
    rt = str(p.get("realtime", "true")).lower() not in ("false", "0")
    return {"realtime": rt}


def _loc_from_meta(meta):
    """A location-shaped view over the `_meta` dict a cross-host get
    attaches (the coordinator can't reach a remote shard's table)."""
    if not meta:
        return None
    from types import SimpleNamespace

    return SimpleNamespace(routing=meta.get("routing"),
                           parent=meta.get("parent"),
                           timestamp=meta.get("timestamp"),
                           ttl_expiry=meta.get("ttl_expiry"))


def _get_doc(n: Node, p, b, index: str, id: str):
    from elasticsearch_tpu.search.service import _filter_source

    data = _mh_for(n, index)
    if data is not None:
        # cross-host routed read, then the SAME response shaping as the
        # local path; location meta (routing/parent/timestamp/ttl) rides
        # the response so the fields extraction below works for remote docs
        r = data.get_doc(index, id,
                         routing=p.get("routing") or p.get("parent"),
                         with_meta=True, **_realtime_kw(n, p, index))
        loc = _loc_from_meta(r.pop("_meta", None))
    else:
        svc = n.get_index(index)
        r = svc.get_doc(id, routing=p.get("routing") or p.get("parent"),
                        **_realtime_kw(n, p, index))
        loc = svc.route(id, p.get("routing")).engine._locations.get(str(id))
    if not r.get("found"):
        return 404, r
    if "version" in p and p.get("version_type") != "force" \
            and int(p["version"]) != r.get("_version"):
        # version-checked read: ANY mismatch conflicts, internal or
        # external — force never does (VersionType.isVersionConflictForReads)
        from elasticsearch_tpu.utils.errors import VersionConflictException

        raise VersionConflictException(index, id, r.get("_version"),
                                       int(p["version"]))
    sf = p.get("_source")
    if sf is not None:
        if sf.lower() in ("true", "false"):
            sf = sf.lower() == "true"
        elif "," in sf:
            sf = sf.split(",")
        filtered = _filter_source(r.get("_source"), sf)
        r.pop("_source", None)
        if filtered is not None:
            r["_source"] = filtered
    elif "_source_include" in p or "_source_exclude" in p:
        filtered = _filter_source(r.get("_source"), {
            "include": (p.get("_source_include") or "").split(","),
            "exclude": [x for x in
                        (p.get("_source_exclude") or "").split(",") if x]})
        r.pop("_source", None)
        if filtered is not None:
            r["_source"] = filtered
    fields = p.get("fields")
    if fields:
        names = [f.strip() for f in fields.split(",") if f.strip()]
        src = r.get("_source") or {}
        out: Dict[str, Any] = {}
        for f in names:
            if f == "_source":
                continue
            if f == "_routing":
                if loc is not None and loc.routing is not None:
                    out["_routing"] = loc.routing
                continue
            if f == "_parent":
                if loc is not None and loc.parent is not None:
                    out["_parent"] = loc.parent
                continue
            if f == "_timestamp":
                if loc is not None and loc.timestamp is not None:
                    out["_timestamp"] = loc.timestamp
                continue
            if f == "_ttl":
                # remaining millis, as TTLFieldMapper serves it
                if loc is not None and loc.ttl_expiry:
                    import time as _t

                    out["_ttl"] = max(
                        0, loc.ttl_expiry - int(_t.time() * 1000))
                continue
            from elasticsearch_tpu.search.service import source_path

            cur = source_path(src, f)
            if cur is not None:
                out[f] = cur if isinstance(cur, list) else [cur]
        r["fields"] = out
        if "_source" not in names and "_source" not in p \
                and "_source_include" not in p \
                and "_source_exclude" not in p:
            # fields suppress _source unless ANY explicit _source request
            # (true or a filter list) asked for it
            r.pop("_source", None)
    return 200, r


def _doc_exists(n: Node, p, b, index: str, id: str):
    r = n.get_index(index).get_doc(id, routing=p.get("routing")
                                   or p.get("parent"),
                                   **_realtime_kw(n, p, index))
    return (200 if r.get("found") else 404), None


def _get_source(n: Node, p, b, index: str, id: str):
    from elasticsearch_tpu.search.service import _filter_source

    r = n.get_index(index).get_doc(id, routing=p.get("routing")
                                   or p.get("parent"),
                                   **_realtime_kw(n, p, index))
    if not r.get("found"):
        return 404, {"error": "not found", "status": 404}
    src = r["_source"]
    sf = p.get("_source")
    if sf is not None and sf.lower() not in ("true", "false"):
        src = _filter_source(src, sf.split(","))
    elif "_source_include" in p or "_source_exclude" in p:
        src = _filter_source(src, {
            "include": [x for x in (p.get("_source_include") or ""
                                    ).split(",") if x],
            "exclude": [x for x in (p.get("_source_exclude") or ""
                                    ).split(",") if x]})
    return 200, src


def _delete_doc(n: Node, p, b, index: str, id: str):
    kw = {}
    if "version" in p:  # optimistic concurrency, like the index route
        kw["version"] = int(p["version"])
        kw["version_type"] = p.get("version_type", "internal")
    data = _mh_for(n, index)
    if data is not None:
        r = data.delete_doc(index, id,
                            routing=p.get("routing") or p.get("parent"),
                            **kw)
        if _refresh_requested(p):
            data.refresh(index)
        return 200, r
    svc = n.get_index(index)
    r = svc.delete_doc(id, routing=p.get("routing") or p.get("parent"), **kw)
    if _refresh_requested(p):
        svc.refresh()
    return 200, r


def _update_doc(n: Node, p, b, index: str, id: str,
                doc_type: Optional[str] = None):
    # update auto-creates the index (reference: TransportUpdateAction
    # routes through auto-create like index does)
    body = _json(b)
    if "script" in p and "script" not in body:
        # 2.0-era request-param script form (?script=...&lang=groovy)
        body["script"] = p["script"]
    if "lang" in p and "lang" not in body:
        body["lang"] = p["lang"]
    kw: Dict[str, Any] = {}
    if "version" in p:
        kw["version"] = int(p["version"])
        kw["version_type"] = p.get("version_type", "internal")
    if p.get("parent"):
        kw["parent"] = p["parent"]
    if p.get("timestamp"):
        kw["timestamp"] = p["timestamp"]
    if p.get("ttl"):
        kw["ttl"] = p["ttl"]
    fields = p.get("fields") or body.get("fields")

    def _get_env(got) -> Dict[str, Any]:
        # UpdateResponse "get" envelope (UpdateHelper.extractGetResult)
        names = ([f.strip() for f in fields.split(",")]
                 if isinstance(fields, str) else list(fields))
        env: Dict[str, Any] = {"found": bool(got.get("found"))}
        src = got.get("_source") or {}
        fl: Dict[str, Any] = {}
        for f in names:
            if f == "_source":
                env["_source"] = src
                continue
            cur: Any = src
            for part in f.split("."):
                cur = cur.get(part) if isinstance(cur, dict) else None
            if cur is not None:
                fl[f] = cur if isinstance(cur, list) else [cur]
        if fl:
            env["fields"] = fl
        return env

    data = _mh_for(n, index)
    if data is not None:
        # routed to the primary owner — the partial-update merge must
        # read the current source there
        r = data.update_doc(index, id, body,
                            routing=p.get("routing") or p.get("parent"),
                            doc_type=doc_type, **kw)
        if fields:
            r["get"] = _get_env(data.get_doc(
                index, id, routing=p.get("routing") or p.get("parent")))
        if _refresh_requested(p):
            data.refresh(index)
        return 200, r
    svc = n.get_or_autocreate(index)
    r = svc.update_doc(id, body,
                       routing=p.get("routing") or p.get("parent"),
                       doc_type=doc_type, **kw)
    if fields:
        r["get"] = _get_env(svc.get_doc(id, routing=p.get("routing")))
    if _refresh_requested(p):
        svc.refresh()
    return 200, r


def _delete_by_query(n: Node, p, b, index: str):
    from elasticsearch_tpu.search.byquery import failure_entry, run_by_query

    data = _mh_for(n, index)
    if data is not None:
        # distributed index: each primary owner scans + deletes its own
        # shards' docs, replicas follow through the write hop
        return 200, data.by_query(index, _json(b), "delete")
    svc = n.get_index(index)
    svc.refresh()
    body = _json(b)
    counts = {"deleted": 0}
    failures: list = []
    processed: set = set()

    def apply(doc_id, loc):
        # docs indexed with routing/parent don't route by id — the stored
        # routing comes off the location table; EVERY live copy is walked
        # (the same id can live on several shards under different routings)
        processed.add(doc_id)
        try:
            svc.delete_doc(doc_id, routing=loc.routing if loc else None)
            counts["deleted"] += 1
        except ElasticsearchTpuException as e:
            failures.append(failure_entry(svc.name, doc_id, e))

    # cancellable task: the scan loop's checkpoints (search/byquery.py)
    # stop between docs; a cancelled run reports the PARTIAL counts with
    # "canceled" (reference: BulkByScrollResponse reasonCancelled)
    canceled = None
    with n.tasks.task("indices:data/write/delete/byquery",
                      description=f"delete-by-query [{index}]"):
        try:
            run_by_query(svc, body.get("query"), apply)
        except TaskCancelledException as e:
            canceled = str(e)
    out = {"took": 0, "deleted": counts["deleted"],
           "total": len(processed), "failures": failures,
           "timed_out": False}
    if canceled is not None:
        out["canceled"] = canceled
    return 200, out


def _update_by_query(n: Node, p, b, index: str):
    from elasticsearch_tpu.search.byquery import failure_entry, run_by_query

    body = _json(b)
    data = _mh_for(n, index)
    if data is not None:
        return 200, data.by_query(index, body, "update",
                                  script=body.get("script"),
                                  params=body.get("params"))
    svc = n.get_index(index)
    svc.refresh()
    script = body.get("script")
    s_params = body.get("params")  # 2.0 form: sibling body params
    counts = {"updated": 0, "noops": 0}
    failures: list = []
    processed: set = set()

    def apply(doc_id, loc):
        routing = loc.routing if loc else None
        processed.add(doc_id)
        try:
            if script is not None:
                svc.update_doc(doc_id,
                               {"script": script, "params": s_params},
                               routing=routing)
                counts["updated"] += 1
            else:
                # no script: a re-index touch (picks up mapping changes).
                # Carry the doc's _type/_parent/routing meta through the
                # re-index or a routed / parent-child doc would land on a
                # different shard and sever its joins (Engine.update
                # carries meta unconditionally — mirror that).
                got = svc.get_doc(doc_id, routing=routing)
                if got.get("found"):
                    kw = {}
                    if loc is not None and loc.doc_type:
                        kw["doc_type"] = loc.doc_type
                    if loc is not None and loc.parent:
                        kw["parent"] = loc.parent
                    svc.index_doc(doc_id, got["_source"], routing=routing,
                                  **kw)
                    counts["updated"] += 1
                else:
                    # deleted between scan and get: account for it (ES
                    # reports these as noops, never silently)
                    counts["noops"] += 1
        except ElasticsearchTpuException as e:
            failures.append(failure_entry(svc.name, doc_id, e))

    canceled = None
    with n.tasks.task("indices:data/write/update/byquery",
                      description=f"update-by-query [{index}]"):
        try:
            run_by_query(svc, body.get("query"), apply)
        except TaskCancelledException as e:
            canceled = str(e)
    out = {"took": 0, "updated": counts["updated"],
           "total": len(processed), "noops": counts["noops"],
           "failures": failures, "timed_out": False}
    if canceled is not None:
        out["canceled"] = canceled
    return 200, out


def _mget_one(n: Node, spec: dict, default_index: Optional[str], p) -> dict:
    from elasticsearch_tpu.search.service import (_filter_source,
                                                  source_path)
    from elasticsearch_tpu.utils.errors import ElasticsearchTpuException

    iname = spec.get("_index", default_index)
    want_type = spec.get("_type")
    doc_id = str(spec.get("_id"))
    try:
        svc = n.get_index(iname)
    except ElasticsearchTpuException as e:
        # a missing index reads as a per-doc miss with the request's
        # coordinates echoed (MultiGetResponse keeps the failure per item)
        out = {"_index": iname, "_id": doc_id, "found": False,
               "error": {"type": e.error_type, "reason": str(e)}}
        if want_type is not None:
            out["_type"] = want_type
        return out
    rt = (spec.get("routing") or spec.get("_routing")
          or spec.get("parent") or spec.get("_parent"))
    rt = str(rt) if rt is not None else None
    # realtime only — the refresh param is handled ONCE per index by the
    # mget driver, never per doc (a dist refresh fans to every peer)
    rt_kw = {"realtime":
             str(p.get("realtime", "true")).lower() not in ("false", "0")}
    data = _mh_for(n, svc.name)
    if data is not None:
        got = data.get_doc(svc.name, doc_id, routing=rt, with_meta=True,
                           **rt_kw)
        rloc = _loc_from_meta(got.pop("_meta", None))
    else:
        got = svc.get_doc(doc_id, routing=rt, **rt_kw)
        rloc = svc.route(doc_id, rt).engine._locations.get(doc_id)
    got["_index"] = svc.name  # concrete index, even via an alias
    got["_id"] = doc_id
    if (got.get("found") and want_type not in (None, "_all", "_doc")
            and got.get("_type") != want_type):
        # requested type mismatch reads as not-found (MultiGetRequest)
        got = {"_index": svc.name, "_id": doc_id, "found": False}
    if want_type is not None and not got.get("found"):
        got["_type"] = want_type
    flds = spec.get("fields") or spec.get("_fields") or p.get("fields")
    if flds and got.get("found"):
        names = (flds.split(",") if isinstance(flds, str) else list(flds))
        loc = rloc
        src = got.get("_source") or {}
        if "_source" not in names:
            # requesting fields suppresses _source unless asked for
            # explicitly (GetRequest.fields semantics)
            got.pop("_source", None)
        fl: Dict[str, Any] = {}
        for f in names:
            if f == "_routing" and loc is not None \
                    and loc.routing is not None:
                fl["_routing"] = loc.routing
            elif f == "_parent" and loc is not None \
                    and loc.parent is not None:
                fl["_parent"] = loc.parent
            elif f not in ("_routing", "_parent"):
                cur = source_path(src, f)
                if cur is not None:
                    fl[f] = cur if isinstance(cur, list) else [cur]
        got["fields"] = fl
    sf = spec.get("_source", p.get("_source"))
    if sf is None and ("_source_include" in p or "_source_exclude" in p):
        sf = {"include": [x for x in
                          (p.get("_source_include") or "").split(",") if x],
              "exclude": [x for x in
                          (p.get("_source_exclude") or "").split(",") if x]}
    if isinstance(sf, str) and sf.lower() in ("true", "false"):
        sf = sf.lower() == "true"
    if isinstance(sf, str) and "," in sf:
        sf = sf.split(",")
    if got.get("found") and sf is not None:
        filtered = _filter_source(got.get("_source"), sf)
        got.pop("_source", None)
        if filtered is not None:
            got["_source"] = filtered
    return got


def _mget(n: Node, p, b, index: Optional[str] = None,
          doc_type: Optional[str] = None):
    from elasticsearch_tpu.utils.errors import \
        ActionRequestValidationException

    body = _json(b)
    # body-level index/type are per-request defaults (MultiGetRequest)
    index = index or body.get("index")
    doc_type = doc_type or body.get("type")
    if "ids" in body:
        specs = [{"_id": i} for i in body["ids"]]
    else:
        specs = list(body.get("docs") or [])
    if not specs:
        raise ActionRequestValidationException("no documents to get")
    problems = []
    for spec in specs:
        if doc_type is not None and doc_type != "_all":
            spec.setdefault("_type", doc_type)
        if spec.get("_id") is None:
            problems.append("id is missing")
        if spec.get("_index", index) is None:
            problems.append("index is missing")
    if problems:
        raise ActionRequestValidationException(*problems)
    if str(p.get("refresh", "false")).lower() in ("", "true", "1"):
        # ONCE per distinct index, not once per doc — on a distributed
        # index a refresh fans to every peer
        for iname in {spec.get("_index", index) for spec in specs}:
            try:
                _realtime_kw(n, p, iname)
            except ElasticsearchTpuException:
                pass  # a missing index reads as per-doc misses below
    return 200, {"docs": [_mget_one(n, spec, index, p) for spec in specs]}


def _mget_index(n: Node, p, b, index: str):
    return _mget(n, p, b, index)


def _bulk(n: Node, p, b, index: Optional[str] = None,
          doc_type: Optional[str] = None):
    ops = _ndjson(b)
    if index is not None or doc_type is not None:
        for line in ops:
            if len(line) == 1:
                (op, meta), = line.items()
                if op in ("index", "create", "update", "delete") and isinstance(meta, dict):
                    if index is not None:
                        meta.setdefault("_index", index)
                    if doc_type is not None:
                        meta.setdefault("_type", doc_type)
    r = n.bulk(ops)
    if _refresh_requested(p):
        for svc in n.indices.values():
            svc.refresh()
    return 200, r


def _mget_typed(n: Node, p, b, index: str, type: Optional[str]):
    """Typed mget: the path {type} becomes each doc spec's default _type
    (then the usual type-filtered read applies) — ids lists included."""
    return _mget(n, p, b, index, doc_type=type)


def _termvectors_noid(n: Node, p, b, index: str):
    """/{index}/{type}/_termvectors — id carried in the body."""
    body = _json(b)
    if not isinstance(body, dict):
        raise IllegalArgumentException("termvectors expects an object body")
    return _termvectors(n, p, b, index, str(body.get("_id") or ""))


def _bulk_index(n: Node, p, b, index: str):
    return _bulk(n, p, b, index)


# -- search handlers ----------------------------------------------------------

def _search_body(p, b) -> dict:
    body = _json(b)
    if "q" in p:
        body.setdefault("query", {"query_string": {"query": p["q"]}})
    for k in ("size", "from"):
        if k in p:
            body.setdefault(k, int(p[k]))
    if "sort" in p:
        body.setdefault("sort", p["sort"].split(","))
    if "scroll" in p:
        body["scroll"] = p["scroll"]
    if "search_type" in p:
        body["search_type"] = p["search_type"]
    prof_p = p.get("profile")
    if prof_p is not None and str(prof_p).lower() in ("", "1", "true"):
        # ?profile=true (case-insensitive, like the other boolean
        # params): per-shard phase breakdown with the device
        # compile/execute split (tracing/profiler.py)
        body["profile"] = True
    if "timeout" in p:
        # ?timeout= caps the per-shard collect loops AND (on distributed
        # indices) the coordinator's scatter/fetch deadline — blown
        # deadlines degrade to partial results with timed_out=true
        body.setdefault("timeout", p["timeout"])
    if "query_cache" in p:
        # per-request shard query-cache override (reference:
        # ShardSearchRequest.queryCache beats the index setting)
        body["_query_cache"] = p["query_cache"].lower() in ("", "1", "true")
    if "_source" in p:
        v = p["_source"]
        if v == "":  # bare ?_source flag = true
            body["_source"] = True
        else:
            body["_source"] = (v.lower() == "true" if v.lower()
                               in ("true", "false") else v.split(","))
    if "_source_include" in p or "_source_exclude" in p:
        # URL-level source filtering OVERRIDES the body spec
        # (RestSearchAction fetchSourceContext from params)
        body["_source"] = {
            "include": [x for x in
                        (p.get("_source_include") or "").split(",") if x],
            "exclude": [x for x in
                        (p.get("_source_exclude") or "").split(",") if x]}
    return body


def _with_type_filter(body: dict, type: Optional[str]) -> dict:
    """/{index}/{type}/_search scoping: AND a `_type` filter into the query
    (reference: SearchRequest types -> TypeFilter)."""
    if not type or type == "_all":
        return body
    body = dict(body or {})
    q = body.get("query", {"match_all": {}})
    types = [t.strip() for t in str(type).split(",") if t.strip()]
    tf = ({"term": {"_type": types[0]}} if len(types) == 1
          else {"terms": {"_type": types}})
    body["query"] = {"bool": {"must": [q], "filter": [tf]}}
    return body


def _search(n: Node, p, b, index: str):
    data = _mh_for(n, index)
    if data is not None:
        # distributed index: scatter the query phase to shard-owner
        # processes, merge, fetch (cluster/search_action.py — registers
        # its own coordinator task + root span)
        return 200, data.search(index, _search_body(p, b))
    with n.tasks.task("indices:data/read/search",
                      description=f"indices[{index}]"):
        with n.tracer.span("search", index=index):
            return 200, n.search(index, _search_body(p, b),
                                 preference=p.get("preference"))


def _search_typed(n: Node, p, b, index: str, type: str):
    data = _mh_for(n, index)
    if data is not None:
        return 200, data.search(index,
                                _with_type_filter(_search_body(p, b), type))
    return 200, n.search(index, _with_type_filter(_search_body(p, b), type),
                         preference=p.get("preference"))


def _count_typed(n: Node, p, b, index: str, type: str):
    body = _json(b)
    if "q" in p:
        body = {"query": {"query_string": {"query": p["q"]}}}
    return _count_with_body(n, index, _with_type_filter(body, type))


def _search_all(n: Node, p, b):
    with n.tasks.task("indices:data/read/search",
                      description="indices[_all]"):
        with n.tracer.span("search", index="_all"):
            return 200, n.search(None, _search_body(p, b),
                                 preference=p.get("preference"))


def _msearch(n: Node, p, b, index: Optional[str] = None,
             doc_type: Optional[str] = None):
    lines = _ndjson(b)
    pairs = []
    for i in range(0, len(lines) - 1, 2):
        header = lines[i]
        if index is not None:
            header.setdefault("index", index)
        body = lines[i + 1]
        if doc_type is not None and "type" not in header:
            body = _with_type_filter(body, doc_type)
        pairs.append((header, body))
    return 200, n.msearch(pairs)


def _msearch_index(n: Node, p, b, index: str):
    return _msearch(n, p, b, index)


def _scroll(n: Node, p, b):
    from elasticsearch_tpu.search.service import (clear_scroll,
                                                  scroll_next,
                                                  scroll_state)
    from elasticsearch_tpu.tracing.tasks import reset_current, set_current

    body = _json(b)
    sid = body.get("scroll_id", p.get("scroll_id"))
    # ONE persistent task per scroll CONTEXT, not per page: it lives on
    # the state across page requests, so an operator can find a client
    # draining a huge scroll in /_tasks and cancel it — the NEXT page
    # hits the checkpoint, returns the typed 400, and the context frees.
    # (A per-page task would unregister microseconds after it appeared;
    # the cancel could never land.)
    state = scroll_state(sid) if sid else None
    task = None
    if state is not None:

        def _free_on_cancel(t, _sid=sid):
            # EAGER cleanup on the cancelling thread: an abandoned
            # client may never send the next page, so the context (a
            # full snapshot) and the task must not wait on it — later
            # pages 404 as a missing context, like a cleared scroll; a
            # page already in flight raises at its checkpoint (the
            # typed 400)
            clear_scroll(_sid)
            n.tasks.unregister(t)

        # under a lock: two concurrent pages for one scroll_id
        # (ThreadingHTTPServer + a client retry) must not EACH register
        # a task — the loser would be a permanent ghost /_tasks row
        with _SCROLL_TASK_LOCK:
            task = state.get("_task")
            if task is None or n.tasks.get(task.id) is not task:
                # on_cancel rides register(): the task is cancellable
                # the instant it publishes, and a cancel before a late
                # assignment would lose the cleanup forever
                task = n.tasks.register(
                    "indices:data/read/scroll",
                    description=f"scroll [{str(sid)[:16]}]",
                    on_cancel=_free_on_cancel)
                state["_task"] = task
    token = set_current(task) if task is not None else None
    try:
        return 200, scroll_next(sid)
    finally:
        if token is not None:
            reset_current(token)


def _clear_scroll(n: Node, p, b):
    from elasticsearch_tpu.search.service import (clear_scroll,
                                                  scroll_state)
    from elasticsearch_tpu.utils.errors import \
        SearchContextMissingException

    body = _json(b)
    ids = body.get("scroll_id", p.get("scroll_id", []))
    if isinstance(ids, str):
        ids = ids.split(",")
    for s in ids:
        st = scroll_state(s)
        if st is not None and st.get("_task") is not None:
            # the context's persistent scroll task dies with it
            n.tasks.unregister(st["_task"])
    freed = sum(1 for s in ids if clear_scroll(s))
    if ids and ids != ["_all"] and freed == 0:
        raise SearchContextMissingException(
            f"no search context found for ids {ids}")
    return 200, {"succeeded": True, "num_freed": freed}


def _validate_query(n: Node, p, b, index: str):
    from elasticsearch_tpu.search.queries import parse_query
    from elasticsearch_tpu.utils.errors import QueryParsingException

    body = _json(b)
    try:
        q = parse_query(body.get("query"))
        resp = {"valid": True,
                "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if p.get("explain") in ("true", ""):
            # explanation text: the reference prints the rewritten Lucene
            # query; match_all rewrites to *:*
            qtype = type(q).__name__
            text = "*:*" if qtype == "MatchAllQuery" else qtype
            resp["explanations"] = [
                {"index": nm, "valid": True, "explanation": text}
                for nm in n.resolve_indices(index)]
        return 200, resp
    except QueryParsingException as e:
        if p.get("explain") in ("true", ""):
            names = n.resolve_indices(index)
            return 200, {"valid": False, "explanations": [
                {"index": nm, "valid": False, "error": str(e)}
                for nm in (names or [index])]}
        return 200, {"valid": False}


def _forward_doc_op(n: Node, index: str, doc_id, p, b, segment: str):
    """Forward a doc-level op (explain / termvectors) to the doc's
    primary owner; None → serve locally. The `_local_only` param pins a
    PROXIED request to the receiving node — without it, divergent
    ownership views during a reassignment window would re-forward the
    request in an unbounded ping-pong between nodes."""
    if p.get("_local_only"):
        return None
    data = _mh_for(n, index)
    if data is None:
        return None
    from urllib.parse import quote

    return data.proxy_doc_rest(
        index, str(doc_id), p.get("routing"), "POST",
        f"/{quote(index, safe='')}/{segment}/{quote(str(doc_id), safe='')}",
        p, b)


def _explain(n: Node, p, b, index: str, id: str):
    """Per-doc score explanation (RestExplainAction): run the query on the
    owning segment and report the doc's score + matched state."""
    fwd = _forward_doc_op(n, index, id, p, b, "_explain")
    if fwd is not None:
        return fwd
    import numpy as np

    from elasticsearch_tpu.search.context import SegmentContext
    from elasticsearch_tpu.search.queries import parse_query

    svc = n.get_index(index)
    body = _json(b)
    query = parse_query(body.get("query"))
    shard = svc.route(id, p.get("routing"))
    from elasticsearch_tpu.search.joins import prepare_tree

    prepare_tree(query, shard.segments, svc.mappings, svc.analysis)
    loc = shard.engine._locations.get(str(id))
    if loc is None or loc.deleted or loc.where == "buffer":
        return 404, {"_index": svc.name, "_type": "_doc", "_id": id,
                     "matched": False}
    for seg in shard.segments:
        if seg.seg_id == loc.where:
            ctx = SegmentContext(seg, svc.mappings, svc.analysis)
            scores, mask = query.score_or_mask(ctx)
            # transfer each array to host once and index the copies — the
            # pattern every per-hit consumer must follow (tpulint R002);
            # scalar pulls would re-sync per field as this path grows
            mask_h = np.asarray(mask)
            scores_h = np.asarray(scores)
            matched = bool(mask_h[loc.local_id])
            score = float(scores_h[loc.local_id])
            resp = {
                "_index": svc.name,
                "_type": (loc.doc_type or "_doc"),
                "_id": id, "matched": matched,
                "explanation": {
                    "value": score if matched else 0.0,
                    "description": "sum of per-term BM25 impact scores (tpu segment program)",
                    "details": [],
                },
            }
            if any(k in p for k in ("_source", "_source_include",
                                    "_source_exclude", "fields")):
                # RestExplainAction's GetResult envelope: the doc rides
                # along under `get`, with the same source filtering the
                # GET API applies
                _st, got = _get_doc(n, p, b"", svc.name, id)
                if got.get("found"):
                    env: Dict[str, Any] = {"found": True}
                    if "_source" in got:
                        env["_source"] = got["_source"]
                    if "fields" in got:
                        env["fields"] = got["fields"]
                    resp["get"] = env
            return 200, resp
    return 404, {"_index": svc.name, "_type": "_doc", "_id": id,
                 "matched": False}


def _resolve_template(n: Node, body: dict):
    from elasticsearch_tpu.utils.errors import ElasticsearchTpuException

    tmpl = body.get("inline", body.get("template"))
    if isinstance(tmpl, dict) and ("inline" in tmpl or "id" in tmpl):
        body = {**body, **tmpl}
        tmpl = tmpl.get("inline")
    if isinstance(tmpl, str) and "{" not in tmpl:
        # a bare name is an indexed/on-disk script reference, not an
        # inline source (RestSearchTemplateAction lookup order)
        found = n.search_templates.get(tmpl)
        if found is None:
            raise ElasticsearchTpuException(
                f"Unable to find on disk script {tmpl}")
        tmpl = found
    if tmpl is None and "id" in body:
        tmpl = n.search_templates.get(body["id"])
        if tmpl is None:
            raise ElasticsearchTpuException(
                f"Unable to find on disk script {body['id']}")
    if tmpl is None:
        raise ElasticsearchTpuException("search template requires [inline] or [id]")
    return tmpl, body.get("params")


def _search_template(n: Node, p, b, index: str):
    from elasticsearch_tpu.search.templates import render_template

    body = _json(b)
    tmpl, params = _resolve_template(n, body)
    rendered = render_template(tmpl, params)
    return _search(n, p, json.dumps(rendered).encode(), index)


def _render_template_ep(n: Node, p, b):
    from elasticsearch_tpu.search.templates import render_template

    body = _json(b)
    tmpl, params = _resolve_template(n, body)
    return 200, {"template_output": render_template(tmpl, params)}


def _put_search_template(n: Node, p, b, id: str):
    body = _json(b)
    tmpl = body.get("template", body)
    if "{{}}" in json.dumps(tmpl):
        # empty mustache tag: the reference's compile step rejects it
        # (ScriptService.validate -> MustacheException)
        raise IllegalArgumentException(
            "Unable to parse mustache template: empty tag {{}}")
    created = id not in n.search_templates
    n.search_templates[id] = tmpl
    ver = n.search_template_versions.get(id, 0) + 1
    n.search_template_versions[id] = ver
    return (201 if created else 200), {
        "acknowledged": True, "_id": id, "_version": ver,
        "created": created}


def _get_search_template(n: Node, p, b, id: str):
    """GetIndexedScriptResponse: the stored source echoes as a STRING
    (scripts are text documents in the .scripts index)."""
    t = n.search_templates.get(id)
    if t is None:
        return 404, {"_id": id, "found": False, "lang": "mustache"}
    return 200, {"_id": id, "found": True, "lang": "mustache",
                 "_version": n.search_template_versions.get(id, 1),
                 "template": (t if isinstance(t, str)
                              else json.dumps(t, separators=(",", ":")))}


def _delete_search_template(n: Node, p, b, id: str):
    found = n.search_templates.pop(id, None) is not None
    if found:
        ver = n.search_template_versions.get(id, 0) + 1
        n.search_template_versions[id] = ver
    else:
        ver = 1
    return (200 if found else 404), {"_id": id, "found": found,
                                     "_index": ".scripts",
                                     "_version": ver}


def _put_warmer(n: Node, p, b, index: str, name: str):
    names = n.resolve_indices(index)
    if not names:
        raise IndexNotFoundException(index)
    body = _json(b)
    for nm in names:  # multi-index expressions, like the reference
        n.indices[nm].warmers[name] = body
    return 200, {"acknowledged": True}


def _get_warmers(n: Node, p, b, index: str):
    out = {}
    for nm in n.resolve_indices(index):
        svc = n.indices[nm]
        out[nm] = {"warmers": {
            k: {"source": v} for k, v in svc.warmers.items()}}
    return 200, out


def _get_warmer(n: Node, p, b, index: str, name: str):
    """RestGetWarmerAction: a missing INDEX 404s; a name that matches
    nothing on existing indices is an empty 200 body (the reference
    returns the empty GetWarmersResponse)."""
    out = {}
    for nm in _resolve_indices_options(n, index, p):
        svc = n.indices[nm]
        ws = {k: {"source": v} for k, v in svc.warmers.items()
              if _warmer_name_match(k, name)}
        if ws:
            out[nm] = {"warmers": ws}
    return 200, out


def _delete_warmer(n: Node, p, b, index: str, name: str):
    """RestDeleteWarmerAction: comma lists / wildcards / _all name forms;
    404 only when a CONCRETE name matched nothing."""
    names = _resolve_indices_options(n, index, p)
    if not names:
        raise IndexNotFoundException(index)
    found = False
    for nm in names:
        svc = n.indices[nm]
        for w in [w for w in list(svc.warmers)
                  if _warmer_name_match(w, name)]:
            svc.warmers.pop(w, None)
            found = True
    if not found and not (any(c in str(name) for c in "*,")
                          or name == "_all"):
        return 404, {"acknowledged": False}
    return 200, {"acknowledged": True}


def _dist_percolate(n: Node, c, index: str, type: str, body: dict):
    """Percolate on a distributed index: registered .percolator queries
    are hash-routed docs, fanned to each PRIMARY owner and merged with
    per-query-id dedup — replica fanout copies a registration onto
    replica holders' registries too, so without the dedup (and the
    primary-owner targeting) the same query would match once per copy.
    Aggs-under-percolate run as a DISTRIBUTED search over the matched
    registration docs after the fan (ids filter + size 0), so partials
    reduce through the same query-then-fetch agg machinery as any other
    search — per-node FINAL aggs never need merging."""
    import json as _json_mod
    from urllib.parse import quote

    from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY

    aggs_spec = body.get("aggs") or body.get("aggregations")
    # owners must not compute (and discard) local FINAL aggs, and must not
    # truncate their match pages — "total", and the aggs below, are over
    # ALL matches; the coordinator applies size itself after the merge
    fan_body = {k: v for k, v in body.items()
                if k not in ("aggs", "aggregations", "size")}
    rname = c.data.resolve_index(index)
    meta = c.data._meta(rname)
    by_owner: Dict[str, int] = {}
    failed_shards = 0
    for sid in range(meta["num_shards"]):
        owners = meta["assignment"][str(sid)]
        if owners:
            by_owner[owners[0]] = by_owner.get(owners[0], 0) + 1
        else:
            failed_shards += 1
    req = {"method": "POST",
           "path": (f"/{quote(index, safe='')}/"
                    f"{quote(type, safe='')}/_percolate"),
           "params": {}, "body": _json_mod.dumps(fan_body)}
    matches: list = []
    seen_ids: set = set()
    for owner, n_shards in sorted(by_owner.items()):
        try:
            if owner == c.data._local_id():
                res = c.data._on_rest_proxy(dict(req))
            else:
                res = c.data._send(owner, ACTION_REST_PROXY, dict(req))
        except Exception:
            failed_shards += n_shards
            continue
        if res["status"] != 200:
            failed_shards += n_shards
            continue
        for m in res["payload"].get("matches", []):
            key = (m.get("_index"), m.get("_id"))
            if key not in seen_ids:
                seen_ids.add(key)
                matches.append(m)
    total = len(matches)
    size = body.get("size")
    full_ids = [m.get("_id") for m in matches]
    if size is not None:
        matches = matches[: int(size)]
    total_shards = meta["num_shards"]
    out = {"took": 0,
           "_shards": {"total": total_shards,
                       "successful": total_shards - failed_shards,
                       "failed": failed_shards},
           "total": total, "matches": matches}
    if aggs_spec is not None:
        from elasticsearch_tpu.search.percolator import PERCOLATOR_TYPE

        # same semantics as IndexService.percolate: aggregate over ALL
        # matched registrations' metadata (not the size-truncated page),
        # via the distributed search's shard-partial agg reduce
        r = c.data.search(index, {"query": {"bool": {"filter": [
            {"term": {"_type": PERCOLATOR_TYPE}},
            {"ids": {"values": full_ids}}]}},
            "size": 0, "aggs": aggs_spec})
        out["aggregations"] = r.get("aggregations", {})
    return 200, out


def _percolate(n: Node, p, b, index: str, type: str):
    c = _mh(n)
    if c is not None and not p.get("_local_only") \
            and c.data.resolve_index(index) in c.dist_indices:
        return _dist_percolate(n, c, index, type, _json(b))
    svc = n.get_index(index)
    return 200, svc.percolate(_json(b))


def _percolate_existing(n: Node, p, b, index: str, type: str, id: str):
    """Percolate an already-indexed doc (RestPercolateAction existing-doc
    form: GET /{index}/{type}/{id}/_percolate). percolate_index/
    percolate_type redirect WHICH index's registered queries run
    (TransportPercolateAction getRequest indirection); a version param
    must match the doc's current version."""
    c = _mh(n)
    dist = (c is not None and not p.get("_local_only")
            and c.data.resolve_index(index) in c.dist_indices)
    if dist:
        got = c.data.get_doc(index, str(id), routing=p.get("routing"))
    else:
        svc = n.get_index(index)
        got = svc.get_doc(id, routing=p.get("routing"))
    if not got.get("found"):
        return 404, {"_index": index, "_id": id, "found": False}
    if "version" in p and int(p["version"]) != got.get("_version"):
        from elasticsearch_tpu.utils.errors import VersionConflictException

        raise VersionConflictException(index, id, got.get("_version"),
                                       int(p["version"]))
    body = _json(b)
    body["doc"] = got["_source"]
    target = p.get("percolate_index")
    # the fan-out gates on the TARGET registry's index being distributed
    # — percolate_index can redirect a local source doc at a distributed
    # registry (and vice versa)
    tname = target or index
    if c is not None and not p.get("_local_only") \
            and c.data.resolve_index(tname) in c.dist_indices:
        return _dist_percolate(n, c, tname, type, body)
    psvc = n.get_index(target) if target else n.get_index(index)
    return 200, psvc.percolate(body)


def _suggest(n: Node, p, b, index: str):
    c = _mh(n)
    if c is not None and not p.get("_local_only") \
            and c.data.resolve_index(index) in c.dist_indices:
        # distributed index: one request per primary owner, merged per
        # entry (freq sums, score maxes) — cluster/search_action.py
        from elasticsearch_tpu.search.suggest import validate_suggest_body

        body = _json(b)
        validate_suggest_body(body)  # 400 BEFORE the fan, not shard noise
        res, shards = c.data.suggest_fan(index, body)
        res["_shards"] = shards
        return 200, res
    svc = n.get_index(index)
    sh = p.get("_shards")  # internal: the multi-host fan's shard filter
    shard_ids = [int(i) for i in sh.split(",")] if sh else None
    res = svc.suggest(_json(b), shard_ids=shard_ids)
    served = len(shard_ids) if shard_ids is not None else svc.num_shards
    res["_shards"] = {"total": served, "successful": served, "failed": 0}
    return 200, res


def _suggest_all(n: Node, p, b):
    """Reference: RestSuggestAction with no index = all indices; each index
    runs under its own analysis registry, merged per entry. Distributed
    indices fan per primary owner first (coordinator-local shards of a
    dist index would under-count), then merge like any other index."""
    from elasticsearch_tpu.search.suggest import (execute_suggest_multi,
                                                  validate_suggest_body)

    body = _json(b)
    validate_suggest_body(body)  # a malformed body 400s BEFORE any fan
    c = _mh(n)
    dist_names = (set() if c is None or p.get("_local_only")
                  else set(c.dist_indices))
    groups = [(svc.shards, svc.analysis, svc.mappings)
              for name, svc in n.indices.items()
              if name not in dist_names]
    extra = []
    failed = 0
    for name in sorted(dist_names):
        fanned, sh = c.data.suggest_fan(name, body)
        extra.append(fanned)
        failed += sh.get("failed", 0)
    res = execute_suggest_multi(groups, body, extra_results=extra)
    total = (sum(len(g[0]) for g in groups)
             + sum(c.dist_indices[nm]["num_shards"] for nm in dist_names))
    res["_shards"] = {"total": total, "successful": total - failed,
                      "failed": failed}
    return 200, res


def _field_stats(n: Node, p, b, index: str):
    """RestFieldStatsAction: per-field stats (max_doc/doc_count/density/
    sum_doc_freq/sum_total_term_freq + numeric min/max). Default level is
    `cluster` (everything merged under indices._all); level=indices keys
    per index."""
    import numpy as np

    body = _json(b)
    want = body.get("fields") or ([f.strip() for f in p["fields"].split(",")]
                                  if p.get("fields") else None)

    def _bump(cur, add):
        for k in ("doc_count", "sum_doc_freq", "sum_total_term_freq",
                  "max_doc"):
            cur[k] = cur.get(k, 0) + add.get(k, 0)
        for k, fn in (("min_value", min), ("max_value", max)):
            if add.get(k) is not None:
                cur[k] = (add[k] if cur.get(k) is None
                          else fn(cur[k], add[k]))

    def _dist_fields(c, name: str) -> Dict[str, dict]:
        """Fan to each primary owner (its primary shards only — replica
        copies would double doc counts) and merge with _bump."""
        import json as _json_mod

        from elasticsearch_tpu.cluster.search_action import ACTION_REST_PROXY
        from urllib.parse import quote

        meta = c.data._meta(name)
        by_owner: Dict[str, list] = {}
        for sid in range(meta["num_shards"]):
            owners = meta["assignment"][str(sid)]
            if owners:
                by_owner.setdefault(owners[0], []).append(sid)
        fields: Dict[str, dict] = {}
        for owner, sids in sorted(by_owner.items()):
            params = {"level": "indices",
                      "_shards": ",".join(map(str, sids))}
            if want is not None:
                # filter at the SOURCE: owners must not compute + ship
                # stats for fields the request never asked about
                params["fields"] = ",".join(want)
            req = {"method": "GET",
                   "path": f"/{quote(name, safe='')}/_field_stats",
                   "params": params, "body": _json_mod.dumps(body)}
            try:
                if owner == c.data._local_id():
                    res = c.data._on_rest_proxy(dict(req))
                else:
                    res = c.data._send(owner, ACTION_REST_PROXY, dict(req))
            except Exception:
                continue  # dead owner: its shards' stats are unavailable
            if res["status"] != 200:
                continue
            for fname, st in res["payload"].get("indices", {}).get(
                    name, {}).get("fields", {}).items():
                st.pop("density", None)  # recomputed after the merge
                _bump(fields.setdefault(fname, {}), st)
        return fields

    sh_filter = p.get("_shards")  # internal: the multi-host fan's filter
    shard_ids = ([int(i) for i in sh_filter.split(",")]
                 if sh_filter else None)
    c = _mh(n)
    out = {}
    for name in n.resolve_indices(index):
        if c is not None and not p.get("_local_only") \
                and name in c.dist_indices:
            fields = _dist_fields(c, name)
            for st in fields.values():
                md = st.get("max_doc", 0)
                st["density"] = (int(100 * st.get("doc_count", 0) / md)
                                 if md else 0)
            if want is not None:
                fields = {k: v for k, v in fields.items() if k in want}
            out[name] = {"fields": fields}
            continue
        svc = n.indices[name]
        fields: Dict[str, dict] = {}
        shard_list = (svc.shards if shard_ids is None
                      else [svc.shards[i] for i in shard_ids])
        for shard in shard_list:
            for seg in shard.segments:
                md = int(seg.num_docs)
                for fname, col in seg.numerics.items():
                    ex = col.exact[seg.live_host[: len(col.exact)]
                                   & np.asarray(col.exists)]
                    if ex.size == 0:
                        continue
                    _bump(fields.setdefault(fname, {}), {
                        "doc_count": int(ex.size), "max_doc": md,
                        "min_value": ex.min(), "max_value": ex.max()})
                for fname, inv in seg.inverted.items():
                    if fname.startswith("_") or inv.num_docs == 0:
                        continue
                    add = {
                        "doc_count": int(inv.num_docs), "max_doc": md,
                        "sum_doc_freq": int(inv.df.sum()),
                        "sum_total_term_freq": int(inv.total_terms)}
                    live_terms = [t for i, t in enumerate(inv.terms)
                                  if int(inv.df[i]) > 0]
                    if live_terms:
                        # min/max TERM of the field (FieldStats.Text)
                        add["min_value"] = min(live_terms)
                        add["max_value"] = max(live_terms)
                    _bump(fields.setdefault(fname, {}), add)
        for st in fields.values():
            md = st.get("max_doc", 0)
            st["density"] = (int(100 * st.get("doc_count", 0) / md)
                             if md else 0)
        if want is not None:
            fields = {k: v for k, v in fields.items() if k in want}
        out[name] = {"fields": {
            k: {kk: (int(vv) if isinstance(vv, np.integer) else vv)
                for kk, vv in v.items()} for k, v in fields.items()}}
    if p.get("level", "cluster") != "indices":
        merged: Dict[str, dict] = {}
        for entry in out.values():
            for fname, st in entry["fields"].items():
                _bump(merged.setdefault(fname, {}), st)
        for st in merged.values():
            md = st.get("max_doc", 0)
            st["density"] = (int(100 * st.get("doc_count", 0) / md)
                             if md else 0)
        out = {"_all": {"fields": merged}}
    return 200, {"indices": out}


def _termvectors(n: Node, p, b, index: str, id: str):
    """RestTermVectorsAction (reference: action/termvectors/
    TermVectorsRequest.java): per-field term vectors with positions,
    offsets, term_statistics (doc_freq, ttf) and field_statistics
    (sum_doc_freq, doc_count, sum_ttf). Statistics come from the doc's
    frozen segment; a doc still in the indexing buffer reports vectors
    only (ES reads stats from the shard's live reader the same way).
    Offsets are recovered by cursor-scanning the source text for each
    token (the index stores positions, not offsets); stemmed tokens whose
    surface form can't be located omit offsets."""
    fwd = _forward_doc_op(n, index, id, p, b, "_termvectors")
    if fwd is not None:
        return fwd
    body = _json(b)
    opts = {}
    for k, default in (("positions", True), ("offsets", True),
                       ("term_statistics", False), ("field_statistics", True)):
        v = body.get(k, p.get(k, default))
        opts[k] = str(v).lower() != "false"
    svc = n.get_index(index)
    shard = svc.route(id, p.get("routing"))
    # realtime=false reads only REFRESHED state: a doc still in the
    # indexing buffer is found:false (TermVectorsRequest.realtime)
    realtime = str(p.get("realtime", body.get("realtime", "true"))
                   ).lower() not in ("false", "0")
    got = shard.engine.get(id, realtime=realtime)
    if got is None:
        out = {"_index": index, "_id": id, "found": False}
        loc0 = shard.engine._locations.get(str(id))
        if loc0 is not None and loc0.doc_type:
            out["_type"] = loc0.doc_type
        return 200 if loc0 is not None else 404, out
    parsed = shard.engine.parser.parse(str(id), got["_source"])
    loc = shard.engine._locations.get(str(id))
    seg = None
    if loc is not None and loc.where != "buffer":
        seg = next((s for s in shard.engine.segments
                    if s.seg_id == loc.where), None)
    sel = body.get("fields", p.get("fields"))
    if isinstance(sel, str):
        sel = [f.strip() for f in sel.split(",")]
    term_vectors = {}
    for fname, toks in parsed.text_tokens.items():
        if sel and fname not in sel:
            continue
        inv = seg.inverted.get(fname) if seg is not None else None
        src_text = got["_source"].get(fname)
        src_low = src_text.lower() if isinstance(src_text, str) else None
        terms: Dict[str, dict] = {}
        cursor = 0
        for t, pos in toks:
            e = terms.setdefault(t, {"term_freq": 0, "tokens": []})
            e["term_freq"] += 1
            tok: Dict[str, Any] = {}
            if opts["positions"]:
                tok["position"] = pos
            if opts["offsets"] and src_low is not None:
                at = src_low.find(t, cursor)
                if at < 0:  # stemmed form: try the token as a prefix match
                    at = src_low.find(t[:4], cursor) if len(t) >= 4 else -1
                if at >= 0:
                    end = at + len(t)
                    tok["start_offset"] = at
                    tok["end_offset"] = end
                    cursor = end
            if tok:
                e["tokens"].append(tok)
        if opts["term_statistics"] and inv is not None:
            for t, e in terms.items():
                tid = inv.term_id(t)
                if tid >= 0:
                    e["doc_freq"] = int(inv.df[tid])
                    e["ttf"] = int(inv.cf[tid])
        fv: Dict[str, Any] = {"terms": terms}
        if opts["field_statistics"] and inv is not None:
            fv["field_statistics"] = {
                "sum_doc_freq": int(inv.df.sum()),
                "doc_count": int(inv.num_docs),
                "sum_ttf": int(inv.cf.sum()),
            }
        term_vectors[fname] = fv
    return 200, {"_index": index, "_id": id, "found": True,
                 "term_vectors": term_vectors}


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------

# -- REST-spec tail (r4 sweep vs /root/reference/rest-api-spec/api) ----------
# Each handler cites its reference action class; together these close the
# spec files that had no route: cluster.get/put_settings, pending_tasks,
# reroute, nodes.hot_threads, count/field_stats/flush/optimize without an
# index, alias single-ops + HEAD forms, exists_template/exists_type,
# get_field_mapping, indices.segments/recovery (JSON forms), upgrade,
# clear_cache, count_percolate, mpercolate, mtermvectors, mlt,
# search_exists, search_shards, snapshot.status/verify, indexed scripts,
# cat.help, GET scroll, un-indexed search_template.


def _cluster_get_settings(n: Node, p, b):
    """RestClusterGetSettingsAction: the two dynamic settings maps."""
    return 200, {"persistent": n.cluster_settings["persistent"],
                 "transient": n.cluster_settings["transient"]}


def _cluster_put_settings(n: Node, p, b):
    """RestClusterUpdateSettingsAction (ClusterUpdateSettingsRequest.java):
    merge dotted-key maps; stored settings are returned by GET and surfaced
    to allocation/recovery code via Node.cluster_settings — settings no
    component reads are stored-but-inert, same as unknown settings in 2.0
    (pre-5.x ES did not validate setting names). The breaker family
    (indices.breaker.* / network.breaker.*) applies LIVE to the resource
    service, like the reference's dynamic HierarchyCircuitBreakerService
    settings; a null value resets to the default."""
    from elasticsearch_tpu.cluster.metadata import flatten_settings

    body = _json(b)
    for scope in ("persistent", "transient"):
        # ES accepts nested and dotted bodies interchangeably; flatten so
        # both forms store (and reset) under the same dotted keys
        for k, v in flatten_settings(body.get(scope) or {}).items():
            if v is None:
                n.cluster_settings[scope].pop(k, None)
            else:
                n.cluster_settings[scope][k] = v
    from elasticsearch_tpu import resources

    merged = {**n.cluster_settings["persistent"],
              **n.cluster_settings["transient"]}
    resources.apply_cluster_settings(merged)
    # serving front-end settings (serving.coalescer.* / serving.qos.*)
    # apply live through the same idempotent full-map path
    n.serving.apply_cluster_settings(merged)
    c = _mh(n)
    if c is not None:
        # the allocation family (cluster.routing.allocation.*: drain
        # exclusions, watermarks, relocation throttle) applies live to
        # this node's allocator — and the change is BROADCAST so a PUT
        # reaching any member drives the MASTER's allocation loop (the
        # rolling-restart drain lever must not depend on which node the
        # operator happened to address)
        c.allocator.apply_cluster_settings(merged)
        if "_local_only" not in p:
            from elasticsearch_tpu.cluster.search_action import \
                ACTION_CLUSTER_SETTINGS

            payload = {"cluster_settings": n.cluster_settings,
                       "merged": merged}
            for nid in c.data._other_nodes():
                try:
                    c.data._send(nid, ACTION_CLUSTER_SETTINGS, payload,
                                 timeout=5.0)
                except Exception:  # tpulint: allow[R006] — an unreachable
                    pass           # member adopts via the next broadcast
    return 200, {"acknowledged": True,
                 "persistent": n.cluster_settings["persistent"],
                 "transient": n.cluster_settings["transient"]}


def _cluster_health(n: Node, p, b):
    """RestClusterHealthAction: the health summary + pending-task gauges;
    level=indices adds per-index sections (our single-node health is
    uniform, so each index reports its own shard counts). The
    coordination fields ride every response: the master's id, the
    cluster TERM it was elected under, and whether the NO_MASTER write
    block is in force (a headless node keeps answering health — that is
    the point of serving reads under the block)."""
    state = n.cluster_state
    h = dict(state.health())
    h["master_node"] = state.master_node_id
    h["term"] = getattr(state, "term", 0)
    no_master = state.master_node_id is None \
        or state.global_block("write") is not None
    h["no_master_block"] = bool(no_master)
    if no_master:
        h["status"] = "red"  # an unquorate node cannot vouch for shards
        h["cluster_blocks"] = [
            dict(blk) for blk in state.blocks.get("global", [])]
    h["number_of_pending_tasks"] = len(_all_pending_tasks(n, p))
    h.setdefault("number_of_in_flight_fetch", 0)
    h.setdefault("delayed_unassigned_shards", 0)
    h.setdefault("task_max_waiting_in_queue_millis", 0)
    c = _mh(n)
    alloc = getattr(c, "allocator", None) if c is not None else None
    if alloc is not None:
        # live relocation + drain progress (the rolling-restart signal:
        # an operator polls health until the excluded node's count hits
        # zero — then, and only then, kill is safe)
        h["relocating_shards"] = len(alloc.inflight_snapshot())
        drain = alloc.drain_status()
        if drain:
            h["draining_nodes"] = {nid: {"remaining_copies": left,
                                         "drained": left == 0}
                                   for nid, left in sorted(drain.items())}
    if p.get("level") in ("indices", "shards"):
        idx = {}
        for name, svc in n.indices.items():
            entry = {
                "status": "green", "number_of_shards": svc.num_shards,
                "number_of_replicas": svc.num_replicas,
                "active_primary_shards": svc.num_shards,
                "active_shards": svc.num_shards
                * (1 + svc.num_replicas),
                "relocating_shards": 0, "initializing_shards": 0,
                "unassigned_shards": 0,
            }
            if p.get("level") == "shards":
                entry["shards"] = {str(g.shard_id): {
                    "status": "green", "primary_active": True,
                    "active_shards": len(g.copies),
                    "relocating_shards": 0, "initializing_shards": 0,
                    "unassigned_shards": 0,
                } for g in svc.groups}
            idx[name] = entry
        h["indices"] = idx
    return 200, h


def _resolve_indices_options(n: Node, index_expr: str, p) -> List[str]:
    """IndicesOptions resolution (reference: IndicesOptions.fromParameters
    + IndexNameExpressionResolver.concreteIndices): expand_wildcards scopes
    which states wildcards see, ignore_unavailable forgives named misses,
    allow_no_indices forgives wildcard no-matches."""
    import fnmatch

    ew = {x.strip() for x in str(p.get("expand_wildcards", "open")
                                 ).split(",")}
    if ew & {"both", "all"}:
        ew = {"open", "closed"}
    ignore_unavailable = str(p.get("ignore_unavailable", "false")
                             ).lower() in ("true", "1", "")
    allow_no = str(p.get("allow_no_indices", "true")
                   ).lower() not in ("false", "0")
    out: List[str] = []
    for part in str(index_expr or "_all").split(","):
        part = part.strip()
        if not part:
            continue
        if part == "_all" or any(c in part for c in "*?"):
            pat = "*" if part == "_all" else part
            matched = [
                nm for nm in n.indices
                if fnmatch.fnmatchcase(nm, pat)
                and (("open" in ew and not n.indices[nm].closed)
                     or ("closed" in ew and n.indices[nm].closed))]
            if not matched and not allow_no:
                raise IndexNotFoundException(part)
            out.extend(sorted(matched))
            continue
        resolved = n.resolve_indices(part)
        if not resolved:
            if not ignore_unavailable:
                raise IndexNotFoundException(part)
            continue
        out.extend(resolved)
    seen = set()
    return [nm for nm in out if not (nm in seen or seen.add(nm))]


def _cluster_state_metric(n: Node, p, b, metric: str,
                          index: Optional[str] = None):
    """RestClusterStateAction metric scoping: only the requested sections
    appear (blocks is always available and empty — no block levels here);
    an index expression filters metadata/routing_table to the concrete
    indices it resolves to under the request's IndicesOptions."""
    import copy

    from elasticsearch_tpu.cluster.metadata import _block

    full = copy.deepcopy(n.cluster_state.to_json())
    # blocks built live from index state/settings (reference:
    # ClusterBlocks — ids: 4 = INDEX_CLOSED_BLOCK, 5 = INDEX_READ_ONLY,
    # 7 = INDEX_READ, 8 = INDEX_WRITE) plus any global blocks the
    # coordination layer set (2 = NO_MASTER_BLOCK, ES dict-keyed shape)
    blocks: Dict[str, Any] = {}
    for gb in n.cluster_state.blocks.get("global", []):
        blocks.setdefault("global", {})[str(gb.get("id"))] = {
            "description": gb.get("description", ""),
            "retryable": bool(gb.get("retryable")),
            "levels": list(gb.get("levels", []))}
    _BLOCKS = (("read_only", "5", "index read-only (api)",
                ["write", "metadata_write"]),
               ("read", "7", "index read (api)", ["read"]),
               ("write", "8", "index write (api)", ["write"]))
    for nm, svc in n.indices.items():
        bl = {}
        if getattr(svc, "closed", False):
            bl["4"] = {"description": "index closed", "retryable": False,
                       "levels": ["read", "write"]}
        for key, bid, desc, levels in _BLOCKS:
            if _block(svc, key):
                bl[bid] = {"description": desc, "retryable": False,
                           "levels": levels}
        if bl:
            blocks.setdefault("indices", {})[nm] = bl
    full["blocks"] = blocks
    # routing_nodes: the per-node view of the same shard routings
    if "routing_nodes" not in full:
        rt = full.get("routing_table", {}).get("indices", {})
        assigned = [sh for idx in rt.values()
                    for shards in idx.get("shards", {}).values()
                    for sh in shards]
        nid = full.get("master_node") or "local"
        full["routing_nodes"] = {"unassigned": [], "nodes": {nid: assigned}}
    if index is not None:
        names = set(_resolve_indices_options(n, index, p))
        for section, key in (("metadata", "indices"),
                             ("routing_table", "indices")):
            sec = full.get(section)
            if isinstance(sec, dict) and isinstance(sec.get(key), dict):
                sec[key] = {nm: v for nm, v in sec[key].items()
                            if nm in names}
    keep = {m.strip() for m in metric.split(",")}
    if "_all" in keep or "*" in keep:
        return 200, full
    out = {"cluster_name": full["cluster_name"]}
    for key in ("version", "state_uuid", "master_node", "nodes", "metadata",
                "routing_table", "routing_nodes", "blocks"):
        if key in keep and key in full:
            out[key] = full[key]
    return 200, out


def _resolve_member(c, ref: Optional[str]) -> Optional[str]:
    """A reroute command's node argument (name or id) → member node id."""
    if not ref:
        return None
    nodes = c.node.cluster_state.nodes
    if ref in nodes:
        return ref
    for nid, dn in nodes.items():
        if dn.name == ref:
            return nid
    return None


def _cluster_reroute_mh(c, n: Node, p, b):
    """The REAL reroute, against the live allocator (reference:
    TransportClusterRerouteAction → AllocationService.reroute with
    AllocationCommands): ``move`` starts a relocation stream through the
    decider chain, ``cancel`` pulls an in-flight move's cancel gate
    (releasing its throttle slot), ``allocate``/``allocate_replica``
    starts a recovery of a new copy onto the named node. ``?explain``
    answers with per-node decider verdicts from the same chain the
    command ran through; ``?dry_run`` explains without acting."""
    body = _json(b)
    explain = str(p.get("explain", "false")).lower() in ("true", "", "1")
    dry_run = str(p.get("dry_run", "false")).lower() in ("true", "", "1")
    alloc = c.allocator
    explanations = []
    acked = True
    for cmd in body.get("commands", []):
        if not isinstance(cmd, dict) or len(cmd) != 1:
            raise IllegalArgumentException(
                "a reroute command must be an object with exactly one "
                "command name key")
        ((name, args),) = cmd.items()
        if name not in ("move", "cancel", "allocate", "allocate_replica",
                        "allocate_stale_primary", "allocate_empty_primary"):
            raise IllegalArgumentException(
                f"unknown reroute command [{name}]")
        if not isinstance(args, dict):
            raise IllegalArgumentException(
                f"[{name}] command expects an object body")
        iname = args.get("index")
        if not iname:
            raise IllegalArgumentException(
                f"[{name}] command missing required [index] parameter")
        sid = int(args.get("shard", 0))
        meta = c.dist_indices.get(iname)
        if meta is None or sid >= int(meta.get("num_shards", 0)):
            raise IllegalArgumentException(
                f"shard [{sid}] of [{iname}] cannot be found")
        owners = list(meta["assignment"].get(str(sid), []))
        params = {"index": iname, "shard": sid}
        decisions = []
        if name == "move":
            src = _resolve_member(c, args.get("from_node"))
            dst = _resolve_member(c, args.get("to_node"))
            params.update({"from_node": args.get("from_node"),
                           "to_node": args.get("to_node")})
            if src is None or dst is None:
                raise IllegalArgumentException(
                    f"[move] unknown node in "
                    f"[{args.get('from_node')}]->[{args.get('to_node')}]")
            if src not in owners:
                decisions.append({
                    "decider": "move_allocation_command", "decision": "NO",
                    "explanation": f"node [{src}] holds no copy of "
                                   f"[{iname}][{sid}]"})
                acked = False
            else:
                decisions.extend(alloc.explain(iname, sid, dst))
                if not dry_run:
                    task = alloc._start_relocation(iname, sid, src, dst,
                                                   "reroute", set())
                    if task is None:
                        acked = False
        elif name == "cancel":
            dst = _resolve_member(c, args.get("node"))
            params["node"] = args.get("node")
            cancelled = dst is not None and alloc.cancel_relocation(
                (iname, sid, dst), reason="reroute cancel")
            decisions.append({
                "decider": "cancel_allocation_command",
                "decision": "YES" if cancelled else "NO",
                "explanation": (f"cancelled the relocation of "
                                f"[{iname}][{sid}] to [{dst}]" if cancelled
                                else f"no relocation of [{iname}][{sid}] "
                                     f"to [{args.get('node')}] in flight")})
            acked = acked and cancelled
        else:  # allocate / allocate_replica / allocate_*_primary
            dst = _resolve_member(c, args.get("node"))
            params["node"] = args.get("node")
            if dst is None:
                raise IllegalArgumentException(
                    f"[{name}] unknown node [{args.get('node')}]")
            decisions.extend(alloc.explain(iname, sid, dst))
            pend = meta.get("initializing", {}).get(str(sid), [])
            if dst in owners or dst in pend:
                decisions.append({
                    "decider": f"{name}_allocation_command",
                    "decision": "NO",
                    "explanation": f"node [{dst}] already holds a copy "
                                   f"of [{iname}][{sid}]"})
                acked = False
            elif not owners:
                decisions.append({
                    "decider": f"{name}_allocation_command",
                    "decision": "NO",
                    "explanation": f"[{iname}][{sid}] has no active copy "
                                   "to recover from (resurrect_lost "
                                   "handles primaries)"})
                acked = False
            elif not dry_run:
                # a NEW copy recovers onto the node through the standard
                # top-up path: initializing + publish, then the stream,
                # then graduation into assignment + in_sync
                with c._indices_lock:
                    live = c.dist_indices.get(iname)
                    if live is not None:
                        live.setdefault("initializing", {}) \
                            .setdefault(str(sid), []).append(dst)
                c.publish_indices()
                c.data.start_recoveries([{
                    "index": iname, "shard": sid, "target": dst,
                    "source": owners[0], "body": meta.get("body")}])
        explanations.append({"command": name, "parameters": params,
                             "decisions": decisions})
    state = {"cluster_name": n.cluster_state.cluster_name,
             "version": n.cluster_state.version,
             "master_node": n.cluster_state.master_node_id,
             "relocations": alloc.inflight_snapshot()}
    resp = {"acknowledged": acked, "state": state}
    if explain or dry_run:
        resp["explanations"] = explanations
    return 200, resp


def _cluster_reroute(n: Node, p, b):
    """RestClusterRerouteAction. Commands are validated against the routing
    table; with a single node and static shard→device placement every legal
    move/allocate is already satisfied (there is exactly one node to be
    on), so accepted commands change nothing — the same outcome reroute has
    on a one-node reference cluster. cancel fails the shard, which re-runs
    recovery (AllocationService.reroute's cancel semantics). In a
    multi-host world the commands are REAL: they drive the live allocator
    (_cluster_reroute_mh), and a non-master member forwards to the master
    (reference: TransportMasterNodeAction) — only the master's allocator
    may start or cancel moves."""
    c = _mh(n)
    if c is not None:
        master = c.node.cluster_state.master_node_id
        if not c.is_master and master is not None \
                and "_local_only" not in p:
            from elasticsearch_tpu.cluster.search_action import \
                ACTION_REST_PROXY

            try:
                res = c.data._send(
                    master, ACTION_REST_PROXY,
                    {"method": "POST", "path": "/_cluster/reroute",
                     "params": {k: str(v) for k, v in p.items()},
                     "body": (b or b"").decode()}, timeout=30.0)
                return res["status"], res["payload"]
            except Exception:  # tpulint: allow[R006] — unreachable master:
                pass           # fall through to the local explain-only view
        return _cluster_reroute_mh(c, n, p, b)
    body = _json(b)
    explanations = []
    for cmd in body.get("commands", []):
        if not isinstance(cmd, dict) or len(cmd) != 1:
            raise IllegalArgumentException(
                "a reroute command must be an object with exactly one "
                "command name key")
        ((name, args),) = cmd.items()
        if name not in ("move", "cancel", "allocate", "allocate_replica",
                        "allocate_stale_primary", "allocate_empty_primary"):
            raise IllegalArgumentException(f"unknown reroute command [{name}]")
        if not isinstance(args, dict):
            raise IllegalArgumentException(
                f"[{name}] command expects an object body")
        iname = args.get("index")
        if not iname:
            raise IllegalArgumentException(
                f"[{name}] command missing required [index] parameter")
        # absent -> False; a bare valueless flag ("") -> True
        explain = str(p.get("explain", "false")).lower() in ("true", "", "1")
        dry_run = str(p.get("dry_run", "false")).lower() in ("true", "", "1")
        shard_id = int(args.get("shard", 0))
        svc = n.get_index(iname)
        valid = shard_id < svc.num_shards
        if not valid and not explain:
            raise IllegalArgumentException(
                f"shard [{shard_id}] out of range for [{iname}]")
        if valid and name == "cancel" and not dry_run:
            if svc.groups[shard_id].replicas:
                svc.fail_shard(shard_id)
            # a sole primary cancels into an immediate local re-recovery —
            # on one node the recovered state IS the current state, so the
            # observable outcome matches the reference's cancel+recover
        params = {"index": iname, "shard": shard_id,
                  "node": args.get("node"),
                  "allow_primary": bool(args.get("allow_primary", False))}
        if valid:
            decision = {"decider": "same_node", "decision": "YES",
                        "explanation": "single-node placement is already "
                                       "satisfied"}
        else:
            # an impossible command EXPLAINS as a NO decision instead of
            # erroring (RerouteExplanation from the allocation deciders)
            decision = {"decider": f"{name}_allocation_command",
                        "decision": "NO",
                        "explanation": f"shard [{shard_id}] of [{iname}] "
                                       f"cannot be found or is not there"}
        explanations.append({"command": name, "parameters": params,
                             "decisions": [decision]})
    # the echoed state defaults to everything EXCEPT metadata; an explicit
    # ?metric= keeps only the requested sections (RestClusterRerouteAction
    # response filtering)
    import copy as _copy

    state = _copy.deepcopy(n.cluster_state.to_json())
    metric = p.get("metric")
    if metric:
        keep = {m.strip() for m in str(metric).split(",")}
        state = {k: v for k, v in state.items()
                 if k in keep or k == "cluster_name"}
    else:
        state.pop("metadata", None)
    resp = {"acknowledged": True, "state": state}
    if str(p.get("explain", "false")).lower() in ("true", "", "1"):
        resp["explanations"] = explanations
    return 200, resp


# stack tops that mean "parked, waiting for work" — the threads
# ignore_idle_threads (default true) filters, the reference's known-idle
# frame list (ThreadPool.Info idle states) translated to stdlib waits
_IDLE_TOPS = {
    ("threading.py", "wait"),
    ("threading.py", "_wait_for_tstate_lock"),
    ("queue.py", "get"),
    ("selectors.py", "select"),
    ("socketserver.py", "serve_forever"),
    ("socketserver.py", "service_actions"),
}


def _stack_is_idle(stack: tuple) -> bool:
    if not stack:
        return True
    fname, _line, func = stack[-1]
    return (os.path.basename(fname), func) in _IDLE_TOPS


def _hot_threads(n: Node, p, b):
    """RestNodesHotThreadsAction with the reference's sampling semantics:
    N snapshots taken ``?interval=`` apart (``?snapshots=``, default 10 ×
    500ms), identical stacks collated per thread ("M/N snapshots sharing
    following K elements"), busiest threads first, idle threads filtered
    unless ``ignore_idle_threads=false``. Python exposes no per-thread
    CPU clock, so "busy" is the fraction of snapshots in which the
    thread sat in a non-idle frame — honest sampling, not fake
    percentages."""
    import sys
    import traceback

    from elasticsearch_tpu.search.service import _parse_timeout

    limit = int(p.get("threads", 3))
    snapshots = max(1, min(int(p.get("snapshots", 10)), 64))
    interval = _parse_timeout(p.get("interval", "500ms")) or 0.5
    # bound one request's sampling wall time: the management pool has 2
    # workers — a 10-minute interval ask must not wedge half of it
    interval = max(0.0, min(interval, 10.0 / snapshots))
    ignore_idle = str(p.get("ignore_idle_threads", "true")).lower() \
        not in ("false", "0")

    # per-thread: sample-count per distinct stack signature
    seen: Dict[int, Dict[tuple, int]] = {}
    names: Dict[int, Any] = {}
    busy: Dict[int, int] = {}
    me = threading.get_ident()
    for i in range(snapshots):
        if i:
            time.sleep(interval)
        frames = sys._current_frames()
        for t in threading.enumerate():
            fr = frames.get(t.ident)
            # skip the sampler itself: it is non-idle in every snapshot
            # by construction and would permanently occupy one of the
            # busiest-N output slots
            if fr is None or t.ident == me:
                continue
            stack = tuple((f.filename, f.lineno, f.name)
                          for f in traceback.extract_stack(fr))
            names[t.ident] = t
            seen.setdefault(t.ident, {})
            seen[t.ident][stack] = seen[t.ident].get(stack, 0) + 1
            if not _stack_is_idle(stack):
                busy[t.ident] = busy.get(t.ident, 0) + 1

    ranked = sorted(seen, key=lambda i: (-busy.get(i, 0),
                                         names[i].name or ""))
    if ignore_idle:
        ranked = [i for i in ranked if busy.get(i, 0) > 0]
    out = [f"::: {{{n.name}}}{{{n.node_id}}}",
           f"   Hot threads sampling: interval={int(interval * 1000)}ms, "
           f"snapshots={snapshots}, busiestThreads={limit}, "
           f"ignoreIdleThreads={str(ignore_idle).lower()}:"]
    for ident in ranked[:limit]:
        t = names[ident]
        b_ct = busy.get(ident, 0)
        pct = 100.0 * b_ct / snapshots
        out.append(f"\n   {pct:.1f}% ({b_ct} out of {snapshots} snapshots "
                   f"non-idle) usage by thread '{t.name}'")
        # collate identical stacks, most-sampled first (the reference's
        # "N/M snapshots sharing following K elements" lines)
        for stack, ct in sorted(seen[ident].items(),
                                key=lambda kv: -kv[1]):
            out.append(f"     {ct}/{snapshots} snapshots sharing "
                       f"following {len(stack)} elements")
            out.extend(f"       {fname}:{line} {func}"
                       for fname, line, func in stack)
    return 200, "\n".join(out)


def _put_alias(n: Node, p, b, index: str, name: str):
    """RestIndexPutAliasAction → IndicesAliasesRequest add. Only the
    alias metadata keys are read from the body — a stray "index"/"alias"
    there must not override the URL targets."""
    body = _json(b)
    extras = {k: v for k, v in body.items()
              if k in ("routing", "index_routing", "search_routing",
                       "filter")}
    action = {"add": {"index": index, "alias": name, **extras}}
    return 200, n.update_aliases([action])


def _delete_alias(n: Node, p, b, index: str, name: str):
    import fnmatch

    names = n.resolve_indices(index)
    if not names:
        raise IndexNotFoundException(index)
    pats = [x.strip() for x in name.split(",")]
    found = False
    for nm in names:
        svc = n.indices[nm]
        for a in list(svc.aliases):
            if any(pt in ("_all", "*") or fnmatch.fnmatch(a, pt)
                   for pt in pats):
                found = True
                n.update_aliases([{"remove": {"index": nm, "alias": a}}])
    if not found:
        return 404, {"error": f"aliases [{name}] missing", "status": 404}
    return 200, {"acknowledged": True}


def _alias_exists(n: Node, p, b, alias: str, index: Optional[str] = None):
    """RestAliasesExistAction (HEAD /_alias/{name}); name may be a
    comma list / wildcard / _all."""
    import fnmatch

    pats = [x.strip() for x in str(alias).split(",")]
    names = n.resolve_indices(index) if index else list(n.indices)
    for iname in names:
        svc = n.indices[iname]
        for a in svc.aliases:
            if any(pt in ("_all", "*") or fnmatch.fnmatch(a, pt)
                   for pt in pats):
                return 200, None
    return 404, None


def _index_alias_exists(n: Node, p, b, index: str, name: str):
    return _alias_exists(n, p, b, name, index)


def _get_index_alias(n: Node, p, b, index: str, alias: Optional[str] = None,
                     legacy: bool = False):
    """RestGetAliasesAction scoped to an index; {name} supports comma
    lists / wildcards / _all; partial matches return the existing subset.
    A name matching NOTHING is an empty 200 body — the new `_alias` API
    omits empty index entries entirely, the legacy `_aliases` form keeps
    each index with an empty aliases map."""
    import fnmatch

    names = n.resolve_indices(index)
    if not names:
        raise IndexNotFoundException(index)
    pats = ([x.strip() for x in alias.split(",")]
            if alias is not None else None)

    def hit(a: str) -> bool:
        return pats is None or any(
            pt in ("_all", "*") or fnmatch.fnmatch(a, pt) for pt in pats)

    out = {}
    for iname in names:
        svc = n.indices[iname]
        matched = {a: (fa or {}) for a, fa in svc.aliases.items() if hit(a)}
        if matched or pats is None or legacy:
            out[iname] = {"aliases": matched}
    return 200, out


def _template_json(body: dict, flat: bool) -> dict:
    """GetIndexTemplatesResponse echo: order/template plus flat-string
    settings (nested when ?flat_settings=false)."""
    def _flatten(d, prefix=""):
        out = {}
        for k, v in (d or {}).items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                out.update(_flatten(v, f"{key}."))
            else:
                out[key] = str(v)
        return out

    raw = dict(body.get("settings") or {})
    if raw and "index" not in raw:
        raw = {"index": raw}
    flat_map = _flatten(raw)
    if flat:
        settings = flat_map
    else:
        settings: dict = {}
        for k, v in flat_map.items():
            cur = settings
            parts = k.split(".")
            for part in parts[:-1]:
                cur = cur.setdefault(part, {})
            cur[parts[-1]] = v
    return {
        "order": int(body.get("order", 0)),
        "template": body.get("template", ""),
        "settings": settings,
        "mappings": body.get("mappings", {}),
        "aliases": body.get("aliases", {}),
    }


def _get_template(n: Node, p, b, name: Optional[str]):
    import fnmatch

    # GetIndexTemplates default is the NESTED settings form;
    # ?flat_settings=true flattens (opposite default to index settings GET)
    flat = str(p.get("flat_settings", "false")).lower() in ("", "true")
    tmpls = n.cluster_state.templates
    if name is None:
        names = list(tmpls)
    else:
        pats = [x.strip() for x in name.split(",")]
        names = [t for t in tmpls
                 if any(pt in ("_all", "*") or fnmatch.fnmatch(t, pt)
                        for pt in pats)]
        if not names and not any("*" in pt or pt == "_all" for pt in pats):
            raise IndexNotFoundException(name)
    return 200, {t: _template_json(tmpls[t], flat) for t in names}


def _template_exists(n: Node, p, b, name: str):
    return (200 if name in n.cluster_state.templates else 404), None


def _type_exists(n: Node, p, b, index: str, type: str):
    """RestTypesExistsAction: our single-type model registers the mapped
    _type names per index (doc_parser stores _type per doc)."""
    for iname in n.resolve_indices(index):
        svc = n.indices[iname]
        if type in ("_doc", "_default_"):
            return 200, None
        if type in svc.mappings.type_names:  # typed-mapping blocks
            return 200, None
        for shard in svc.shards:
            if any(loc.doc_type == type and not loc.deleted
                   for loc in shard.engine._locations.values()):
                return 200, None
    return 404, None


def _get_field_mapping(n: Node, p, b, field: str,
                       index: Optional[str] = None,
                       doc_type: Optional[str] = None):
    """RestGetFieldMappingAction / TransportGetFieldMappingsIndexAction:
    per-index leaf mapping for field patterns. A pattern is tried against
    the FULL name first (key = full name); failing that, against the leaf
    ("index") name — then the response key is the leaf name with
    `full_name` pointing at the real path. Indices with no matching
    fields are omitted; an explicit missing index or type 404s;
    include_defaults echoes the implicit analyzer as `default`."""
    import fnmatch

    from elasticsearch_tpu.index.mappings import _field_to_json
    from elasticsearch_tpu.utils.errors import TypeMissingException

    pats = [f.strip() for f in field.split(",")]
    include_defaults = str(p.get("include_defaults", "false")
                           ).lower() in ("true", "1", "")
    names = _resolve_indices_options(n, index, p)
    type_pats = None
    if doc_type not in (None, "", "_all", "*"):
        type_pats = [t.strip() for t in str(doc_type).split(",")]
    out = {}
    type_matched = False
    for iname in names:
        svc = n.indices[iname]
        tnames = svc.mappings.type_names or ["_doc"]
        if type_pats is not None:
            tnames = [t for t in tnames
                      if any(fnmatch.fnmatchcase(t, tp)
                             for tp in type_pats)]
            if not tnames:
                continue
        type_matched = True
        leaves = []
        for fname, fm in svc.mappings.fields.items():
            leaves.append((fname, fm))
            # multi-field sub-fields ("title.raw") live only under their
            # parent's fields map, not in the flat index
            leaves.extend((f"{fname}.{sub}", sfm)
                          for sub, sfm in fm.fields.items())
        fields = {}

        def entry(fname, fm, leaf):
            mj = _field_to_json(fm)
            if include_defaults and fm.is_text:
                mj.setdefault("analyzer", "default")
            return {"full_name": fname, "mapping": {leaf: mj}}

        # pass 1: full-name matches (keyed by full name); pass 2:
        # leaf-name matches fill remaining keys only — a relative match
        # must never shadow a full-name one (t* keeps {t1, t2} even though
        # obj.t1's leaf also matches)
        taken = set()
        for fname, fm in leaves:
            leaf = fname.rpartition(".")[2]
            if not fname.startswith("_") and any(
                    fnmatch.fnmatchcase(fname, pat) for pat in pats):
                fields[fname] = entry(fname, fm, leaf)
                taken.add(fname)
        for fname, fm in leaves:
            leaf = fname.rpartition(".")[2]
            if fname.startswith("_") or fname in taken or leaf in fields:
                continue
            if any(fnmatch.fnmatchcase(leaf, pat) for pat in pats):
                fields[leaf] = entry(fname, fm, leaf)
        if fields:
            out[iname] = {"mappings": {t: dict(fields) for t in tnames}}
    if type_pats is not None and not type_matched and names:
        raise TypeMissingException(",".join(type_pats))
    return 200, out


def _segments_json(n: Node, p, b, index: Optional[str] = None):
    """RestIndicesSegmentsAction (JSON form of _cat/segments). Segment
    names/generations are PER-SHARD ordinals in this response (fresh
    shard → `_0`), like Lucene's per-IndexWriter generations — process-
    global seg ids stay internal. An explicitly named CLOSED index is
    forbidden (IndexClosedException)."""
    from elasticsearch_tpu.cluster.metadata import IndexClosedException

    names = _resolve_indices_options(n, index, p)
    explicit = {x.strip() for x in str(index or "").split(",")
                if x.strip() and not any(c in x for c in "*?")}
    ignore_unavail = str(p.get("ignore_unavailable", "false")
                         ).lower() in ("true", "1", "")
    out = {}
    for iname in names:
        svc = n.indices[iname]
        if svc.closed:
            if iname in explicit and not ignore_unavail:
                raise IndexClosedException(f"closed index [{iname}]")
            continue
        shards = {}
        for g in svc.groups:
            entries = []
            for sh in g.copies:
                segs = {f"_{i}": {
                    "generation": i,
                    "num_docs": seg.live_docs,
                    "deleted_docs": seg.deleted_count,
                    "size_in_bytes": seg.memory_bytes(),
                    "memory_in_bytes": seg.memory_bytes(),
                    "search": True, "committed": True, "compound": False,
                    "version": "5.2.1",
                } for i, seg in enumerate(sh.segments)}
                entries.append({
                    "routing": {"state": sh.state,
                                "primary": sh is g.primary,
                                "node": n.node_id},
                    "num_committed_segments": len(segs),
                    "num_search_segments": len(segs), "segments": segs})
            shards[str(g.primary.shard_id)] = entries
        out[iname] = {"shards": shards}
    return 200, {"indices": out,
                 "_shards": {"total": sum(len(n.indices[i].shards)
                                          for i in out),
                             "successful": sum(len(n.indices[i].shards)
                                               for i in out),
                             "failed": 0}}


def _recovery_entry_json(n: Node, sh, primary: bool, e: dict) -> dict:
    """One RecoveryState row (reference: RecoveryState.toXContent) built
    from a RecoveryRegistry entry. ``mode``/``ops_replayed`` are the
    replication-safety extras: mode "ops" with translog.recovered < the
    shard's doc count PROVES the recovery replayed a checkpoint suffix
    instead of re-shipping the shard."""
    type_map = {"gateway": "GATEWAY", "replica": "REPLICA",
                "peer": "REPLICA", "relocation": "RELOCATION"}
    size = sum(seg.memory_bytes() for seg in sh.segments)
    full = e.get("mode") == "full"
    docs = e.get("docs_copied", 0)
    ops = e.get("ops_replayed", 0)
    return {
        "id": sh.shard_id, "type": type_map.get(e["type"], "REPLICA"),
        "mode": e.get("mode") or ("translog" if e["type"] == "gateway"
                                  else None),
        "primary": primary,
        "stage": e["stage"].upper(),
        "source": ({} if e.get("source") in (None, "local")
                   else {"id": e["source"]}),
        "target": {"id": n.node_id, "name": n.name,
                   "ip": "127.0.0.1", "host": "localhost"},
        "start_time_in_millis": e.get("start_millis", 0),
        "total_time_in_millis": e.get("total_time_in_millis", 0),
        "index": {
            "files": {"total": 0, "reused": 0, "recovered": 0,
                      "percent": "100.0%"},
            "size": {"total_in_bytes": size,
                     "reused_in_bytes": 0 if full else size,
                     "recovered_in_bytes": size if full else 0,
                     "percent": "100.0%"},
            "docs_recovered": docs,
            "docs_skipped": e.get("docs_skipped", 0),
            "source_throttle_time_in_millis": 0,
            "target_throttle_time_in_millis": 0,
            "total_time_in_millis": e.get("total_time_in_millis", 0),
        },
        "translog": {
            "recovered": ops,
            "total": ops,
            "total_on_start": ops,
            "percent": "100.0%",
            "total_time_in_millis": e.get("total_time_in_millis", 0),
        },
        "verify_index": {"check_index_time_in_millis": 0,
                         "total_time_in_millis": 0},
        # what checkpoint-based recovery negotiates on (index/seqno.py)
        "seq_no": sh.engine.seq_no_stats(),
    }


def _recovery_json(n: Node, p, b, index: Optional[str] = None):
    """RestRecoveryAction: real RecoveryState JSON driven by each index's
    RecoveryRegistry (index/recovery.py) — type GATEWAY for a primary
    recovered from local state (the 2.0 name; EMPTY_STORE is the 5.x
    rename), REPLICA for copies, with stage/mode/ops counters from the
    actual recovery executions. ?active_only=true filters to in-flight
    streams (the reference param)."""
    active_only = str(p.get("active_only", "false")).lower() \
        in ("", "true")
    out = {}
    for iname in _resolve_indices_options(n, index, p):
        svc = n.indices[iname]
        shards = []
        for g in svc.groups:
            entries = svc.recoveries.entries(g.shard_id)
            if active_only:
                entries = [e for e in entries
                           if e["stage"] not in ("done", "failed")]
            for e in entries:
                tgt = g.primary
                if e["type"] == "replica" and g.replicas:
                    tgt = g.replicas[0]
                shards.append(_recovery_entry_json(
                    n, tgt, e["type"] == "gateway", e))
            if not entries and not active_only:
                # no recorded recovery (a fresh in-memory shard): a
                # synthetic DONE gateway row keeps the 2.0 shape
                for sh in g.copies:
                    shards.append(_recovery_entry_json(
                        n, sh, sh is g.primary,
                        {"type": "gateway" if sh is g.primary
                         else "replica", "stage": "done"}))
        out[iname] = {"shards": shards}
    return 200, out


def _upgrade(n: Node, p, b, index: Optional[str] = None):
    """RestUpgradeAction. Segments here have no versioned on-disk codec to
    migrate (device arrays are regenerated from _source at freeze), so
    upgrade completes with zero bytes to recover — the same response shape
    a fully-current Lucene index returns."""
    names = n.resolve_indices(index)
    total = sum(n.indices[x].num_shards for x in names)
    return 200, {"_shards": {"total": total, "successful": total, "failed": 0},
                 "upgraded_indices": {x: {"upgrade_version": "2.0.0"}
                                      for x in names}}


def _get_upgrade(n: Node, p, b, index: Optional[str] = None):
    names = n.resolve_indices(index)
    return 200, {"indices": {x: {"size_to_upgrade_in_bytes": 0,
                                 "size_to_upgrade_ancient_in_bytes": 0}
                             for x in names}}


def _clear_cache(n: Node, p, b, index: Optional[str] = None):
    """RestClearIndicesCacheAction. Our cache tiers: compiled scripts,
    IVF probe programs, suggest vocab/bigram/completion caches, and each
    index's warmed query programs. Segment arrays themselves are the
    index, not a cache, and stay resident."""
    from elasticsearch_tpu.ops import ivf as _ivf
    from elasticsearch_tpu.search import scripting as _scr
    from elasticsearch_tpu.search import suggest as _sug

    _scr._CACHE.clear()
    _ivf._PROGRAMS.clear()
    if getattr(_sug, "_VOCAB_CACHE", None) is not None:
        _sug._VOCAB_CACHE.clear()
    names = n.resolve_indices(index)
    total = 0
    for iname in names:
        svc = n.indices[iname]
        total += svc.num_shards
        svc.clear_query_cache()  # shard query cache is part of the contract
        for shard in svc.shards:
            for seg in shard.segments:
                for attr in ("_bigram_cache", "_completion_cache"):
                    if hasattr(seg, attr):
                        delattr(seg, attr)
    return 200, {"_shards": {"total": total, "successful": total, "failed": 0}}


def _percolate_count(n: Node, p, b, index: str, type: str):
    """RestPercolateAction count form (count_percolate.json)."""
    c = _mh(n)
    if c is not None and not p.get("_local_only") \
            and c.data.resolve_index(index) in c.dist_indices:
        status, res = _dist_percolate(n, c, index, type, _json(b))
        return status, {"total": res["total"], "_shards": res["_shards"]}
    svc = n.get_index(index)
    res = svc.percolate(_json(b))
    return 200, {"total": res["total"], "_shards": {
        "total": svc.num_shards, "successful": svc.num_shards, "failed": 0}}


def _mpercolate(n: Node, p, b, index: Optional[str] = None):
    """RestMultiPercolateAction: NDJSON of {percolate: header} / doc pairs."""
    c = _mh(n)
    lines = _ndjson(b)
    responses = []
    for i in range(0, len(lines) - 1, 2):
        head = lines[i].get("percolate", {})
        iname = head.get("index", index)
        try:
            if (c is not None and not p.get("_local_only") and iname
                    and c.data.resolve_index(iname) in c.dist_indices):
                _st, res = _dist_percolate(
                    n, c, iname, head.get("type", "_all"), lines[i + 1])
                responses.append(res)
                continue
            svc = n.get_index(iname)
            responses.append(svc.percolate(lines[i + 1]))
        except ElasticsearchTpuException as e:
            legacy = {"index_not_found_exception": "IndexMissingException"}
            nm = legacy.get(e.error_type, e.error_type)
            responses.append({"error": f"{nm}[{e}]", "status": e.status})
    return 200, {"responses": responses}


def _mtermvectors(n: Node, p, b, index: Optional[str] = None,
                  doc_type: Optional[str] = None):
    """RestMultiTermVectorsAction: {docs: [{_index,_id,...}]}, body ids,
    or the ?ids= query-param form with a path index."""
    body = _json(b)
    docs = body.get("docs")
    if docs is None:
        ids = body.get("ids")
        if ids is None and p.get("ids"):
            ids = [x for x in str(p["ids"]).split(",") if x]
        docs = [{"_index": index, "_id": i} for i in (ids or [])]
    out = []
    for d in docs:
        iname = d.get("_index", index)
        did = d.get("_id")
        sub = {k: v for k, v in d.items() if not k.startswith("_")}
        try:
            status, tv = _termvectors(n, dict(p), json.dumps(sub).encode(),
                                      iname, str(did))
            tv.setdefault("_index", iname)
            out.append(tv)
        except ElasticsearchTpuException as e:
            out.append({"_index": iname, "_id": did,
                        "error": _error_body(e)["error"]})
    return 200, {"docs": out}


def _mlt(n: Node, p, b, index: str, type: str, id: str):
    """RestMoreLikeThisAction (mlt.json, GET /{index}/{type}/{id}/_mlt):
    runs a more_like_this query seeded with the stored doc."""
    fields = p.get("mlt_fields")
    like = {"_index": index, "_id": id}
    q: Dict[str, Any] = {"like": [like],
                         "min_term_freq": int(p.get("min_term_freq", 2)),
                         "min_doc_freq": int(p.get("min_doc_freq", 5))}
    if fields:
        q["fields"] = [f.strip() for f in fields.split(",")]
    body = _json(b) or {}
    body.setdefault("query", {"more_like_this": q})
    return 200, n.search(index, body)


def _search_exists(n: Node, p, b, index: str):
    """RestSearchExistsAction: terminate after the first hit."""
    body = _search_body(p, b)
    body["size"] = 0
    body["terminate_after"] = 1
    res = n.search(index, body)
    total = res["hits"]["total"]
    total = total["value"] if isinstance(total, dict) else total
    if total == 0:
        return 404, {"exists": False}
    return 200, {"exists": True}


def _search_shards(n: Node, p, b, index: str):
    """RestClusterSearchShardsAction: which shard copies a search fans out
    to (query-then-fetch scatter targets)."""
    nodes = {n.node_id: {"name": n.name,
                         "transport_address": "local[in-process]"}}
    groups = []
    indices_meta = {}
    for iname in n.resolve_indices(index):
        svc = n.indices[iname]
        indices_meta[iname] = {}
        for g in svc.groups:
            groups.append([{
                "index": iname, "shard": sh.shard_id,
                "node": n.node_id, "primary": sh is g.primary,
                "state": sh.state,
            } for sh in g.copies])
    return 200, {"nodes": nodes, "indices": indices_meta, "shards": groups}


def _snapshot_status(n: Node, p, b, repo: Optional[str] = None,
                     snap: Optional[str] = None):
    """RestSnapshotsStatusAction: per-snapshot shard accounting from the
    manifest (all our snapshots are complete by the time the manifest is
    written, so stage is always DONE)."""
    if repo is None:
        return 200, {"snapshots": []}
    r = _repo_or_404(n, repo)
    names = [snap] if snap else r.catalog()
    out = []
    for name in names:
        from elasticsearch_tpu.index.snapshots import snapshot_info

        info = snapshot_info(r, name)
        manifest = r.get_manifest(name)
        shard_count = sum(len(i["shards"])
                         for i in manifest["indices"].values())
        out.append({
            "snapshot": name, "repository": repo,
            "state": info.get("state", "SUCCESS"),
            "shards_stats": {"done": shard_count, "failed": 0,
                             "total": shard_count},
            "indices": {iname: {"shards_stats": {"done": len(im["shards"]),
                                                 "total": len(im["shards"])}}
                        for iname, im in manifest["indices"].items()},
        })
    return 200, {"snapshots": out}


def _verify_repo(n: Node, p, b, repo: str):
    """RestVerifyRepositoryAction: prove the repository location is
    writable by round-tripping a marker blob."""
    import os as _os

    r = _repo_or_404(n, repo)
    if getattr(r, "readonly", False):
        # url repositories are read-only: verification never writes
        # (reference: URLRepository has no write verification marker)
        return 200, {"nodes": {n.node_id: {"name": n.name}}}
    probe = _os.path.join(r.location, f".verify-{n.node_id}")
    try:
        with open(probe, "w") as fh:
            fh.write("ok")
        _os.unlink(probe)
    except OSError as e:
        raise IllegalArgumentException(
            f"repository [{repo}] location not writable: {e}")
    return 200, {"nodes": {n.node_id: {"name": n.name}}}


def _put_script(n: Node, p, b, lang: str, id: str):
    """RestPutIndexedScriptAction → ScriptService indexed scripts."""
    from elasticsearch_tpu.search import scripting

    body = _json(b)
    src = body.get("script", body.get("source", ""))
    if isinstance(src, dict):
        src = src.get("inline", src.get("source", ""))
    if lang not in ("groovy", "painless", "painless-lite", "expression",
                    "mustache"):
        raise IllegalArgumentException(f"script_lang not supported [{lang}]")
    created = scripting.get_stored_script(lang, id) is None
    from elasticsearch_tpu.utils.errors import ScriptException

    try:
        ver = scripting.store_script(
            lang, id, src, version=p.get("version"),
            version_type=p.get("version_type", "internal"))
    except ScriptException as e:
        # reference message shape (GroovyScriptEngineService compile
        # failures): "Unable to parse ..."
        raise ScriptException(f"Unable to parse [{src}]: {e}")
    return (201 if created else 200), {"_id": id, "created": created,
                                       "_version": ver}


def _get_script(n: Node, p, b, lang: str, id: str):
    from elasticsearch_tpu.search import scripting
    from elasticsearch_tpu.utils.errors import VersionConflictException

    src = scripting.get_stored_script(lang, id)
    if src is None:
        return 404, {"_id": id, "found": False, "lang": lang,
                     "_index": ".scripts"}
    ver = scripting.stored_script_version(lang, id)
    if (p.get("version") is not None
            and p.get("version_type") != "force"
            and ver != int(p["version"])):
        raise VersionConflictException(".scripts", id, ver or 0,
                                       int(p["version"]))
    return 200, {"_id": id, "found": True, "lang": lang, "script": src,
                 "_version": ver}


def _delete_script(n: Node, p, b, lang: str, id: str):
    """DELETE /_scripts/{lang}/{id}: indexed scripts live in the
    .scripts index, so the response carries document-delete versioning
    (the tombstone bumps the version)."""
    from elasticsearch_tpu.search import scripting

    ver = scripting.stored_script_version(lang, id)
    found = scripting.delete_stored_script(
        lang, id, version=p.get("version"),
        version_type=p.get("version_type", "internal"))
    body = {"_id": id, "found": found, "_index": ".scripts",
            "lang": lang,
            # the reference reports version 1 for a missing-doc delete
            "_version": ((ver or 0) + 1) if found else 1}
    return (200 if found else 404), body


# -- rest-api-spec sweep: root-scoped and typed route forms ------------------
# (tests/integration/test_rest_spec_coverage.py asserts every path x method
# of the reference's rest-api-spec/api/*.json resolves in our route table)

def _get_mapping_index(n: Node, p, b, index: str):
    """GET /{index}/_mapping honoring expand_wildcards (incl. `none`,
    which expands wildcards to nothing → empty 200 body)."""
    if "expand_wildcards" in p and any(c in str(index) for c in "*?"):
        names = _resolve_indices_options(n, index, p)
        out = {}
        for nm in names:
            out.update(n.get_mapping(nm))
        return 200, out
    return 200, n.get_mapping(index)


def _get_mapping_root(n: Node, p, b, type: Optional[str] = None):
    """GET /_mapping[/{type}] (indices.get_mapping root forms)."""
    if type:
        return _get_mapping_typed(n, p, b, None, type)
    return 200, n.get_mapping(None)


def _type_name_matches(svc, pat: str):
    """Type names of `svc` matching a pattern/comma/_all expression. The
    single-type model records typed-mapping block names in
    mappings.type_names; '_doc' stands in when none were declared."""
    import fnmatch

    known = list(svc.mappings.type_names) or ["_doc"]
    out = []
    for part in str(pat).split(","):
        part = part.strip()
        if part in ("_all", "*", ""):
            out.extend(known)
        else:
            out.extend(t for t in known if fnmatch.fnmatch(t, part))
    return sorted(dict.fromkeys(out))


def _get_mapping_typed(n: Node, p, b, index: Optional[str], type: str):
    """GET [/{index}]/_mapping/{type}: mappings keyed by the matched type
    names. A missing INDEX 404s; a missing type reads back {} (the
    RestGetMappingAction distinction)."""
    names = n.resolve_indices(index)
    if not names and index not in (None, "", "_all", "*") \
            and "*" not in str(index):
        raise IndexNotFoundException(index)
    out = {}
    for iname in names:
        svc = n.indices[iname]
        tnames = _type_name_matches(svc, type)
        if tnames:
            mj = svc.mappings.to_json()
            out[iname] = {"mappings": {t: mj for t in tnames}}
    if not out:
        return 200, {}  # missing types read back empty (RestGetMapping)
    return 200, out


def _typed_mapping_body(type: Optional[str], body: dict) -> dict:
    """A path {type} wraps an untyped body so Mappings.merge records the
    type name (response echo / exists_type)."""
    if type and type not in body:
        return {type: body}
    return body


def _put_mapping_root(n: Node, p, b, type: Optional[str] = None):
    """PUT/POST /_mapping/{type}: apply to every index (all-or-nothing per
    index set, same as MetaDataMappingService over a wildcard)."""
    return 200, n.put_mapping(None, _typed_mapping_body(type, _json(b)))


def _get_settings_name(n: Node, p, b, index: Optional[str], name: str):
    """GET /{index}/_settings/{name}: filter setting keys by pattern —
    comma lists, wildcards, and _all (= no filtering) all valid."""
    import fnmatch

    st, out = _get_settings(n, p, b, index)
    pats = [x.strip() for x in str(name).split(",") if x.strip()]
    if any(pt in ("_all", "*") for pt in pats):
        return st, out

    def keep(k: str) -> bool:
        return any(fnmatch.fnmatch(k, pt) for pt in pats)

    for entry in out.values():
        if "index" in entry["settings"]:
            idx = entry["settings"]["index"]
            entry["settings"]["index"] = {
                k: v for k, v in idx.items()
                if keep(f"index.{k}") or keep(k)}
        else:  # flat_settings form
            entry["settings"] = {k: v for k, v in entry["settings"].items()
                                 if keep(k)}
    return st, out


def _get_settings_root(n: Node, p, b, name: Optional[str] = None):
    """GET /_settings[/{name}] — {name} filters setting keys (wildcard).
    An empty cluster answers 200 {} (only a concrete missing index 404s)."""
    if not n.indices:
        return 200, {}
    if name:
        return _get_settings_name(n, p, b, None, name)
    return _get_settings(n, p, b, None)


def _put_settings_root(n: Node, p, b):
    from elasticsearch_tpu.cluster.metadata import update_index_settings

    body = _json(b)
    for iname in n.resolve_indices(None):
        update_index_settings(n.indices[iname], body, node=n)
    return 200, {"acknowledged": True}


_INDEX_FEATURES = {"_settings": "_settings", "_mappings": "_mappings",
                   "_mapping": "_mappings", "_aliases": "_aliases",
                   "_alias": "_aliases", "_warmers": "_warmers",
                   "_warmer": "_warmers"}


def _get_index_feature(n: Node, p, b, index: str, feature: str):
    """GET /{index}/{feature} (indices.get): feature is a comma list of
    _settings/_mappings/_aliases/_warmers. Registered after every literal
    /{index}/_x route, so only unclaimed segments land here."""
    feats = set()
    for f in feature.split(","):
        f = f.strip()
        if f not in _INDEX_FEATURES:
            raise IllegalArgumentException(f"unknown index feature [{f}]")
        feats.add(_INDEX_FEATURES[f])
    out = {}
    _st, settings_out = (_get_settings(n, p, b, index)
                         if "_settings" in feats else (200, {}))
    for iname in _expand_wildcards(n, n.resolve_indices(index), index, p):
        svc = n.indices[iname]
        entry: Dict[str, Any] = {}
        if "_settings" in feats:
            entry.update(settings_out.get(iname, {}))
        if "_mappings" in feats:
            mj = svc.mappings.to_json()
            entry["mappings"] = ({t: mj for t in svc.mappings.type_names}
                                 if svc.mappings.type_names else mj)
        if "_aliases" in feats:
            entry["aliases"] = svc.aliases
        if "_warmers" in feats:
            entry["warmers"] = {k: {"source": v}
                                for k, v in svc.warmers.items()}
        out[iname] = entry
    if not out:
        raise IndexNotFoundException(index)
    return 200, out


def _warmer_name_match(k: str, name: Optional[str]) -> bool:
    import fnmatch

    if name in (None, "", "_all", "*"):
        return True
    return any(fnmatch.fnmatch(k, pat.strip()) for pat in str(name).split(","))


def _get_warmers_root(n: Node, p, b, name: Optional[str] = None):
    """GET /_warmer[/{name}] across all indices ({name}: pattern/comma/
    _all). The unnamed form lists every index (empty maps included); a
    name only the indices carrying a match."""
    out = {}
    for iname in n.resolve_indices(None):
        svc = n.indices[iname]
        ws = {k: {"source": v} for k, v in svc.warmers.items()
              if _warmer_name_match(k, name)}
        if ws or name is None:
            out[iname] = {"warmers": ws}
    return 200, out


def _put_warmer_root(n: Node, p, b, name: str):
    """PUT/POST /_warmer/{name}: register on every index."""
    body = _json(b)
    for iname in n.resolve_indices(None):
        n.indices[iname].warmers[name] = body
    return 200, {"acknowledged": True}


def _index_any_alias(n: Node, p, b, index: str):
    """HEAD /{index}/_alias — any alias at all on the target indices."""
    for iname in n.resolve_indices(index):
        if n.indices[iname].aliases:
            return 200, None
    return 404, None


def _percolate_count_existing(n: Node, p, b, index: str, type: str, id: str):
    """GET/POST /{index}/{type}/{id}/_percolate/count (count_percolate
    existing-doc form)."""
    status, res = _percolate_existing(n, p, b, index, type, id)
    svc = n.get_index(index)
    return status, {"total": res.get("total", 0), "_shards": {
        "total": svc.num_shards, "successful": svc.num_shards, "failed": 0}}


def _index_doc_auto_typed(n: Node, p, b, index: str, type: str):
    """POST/PUT /{index}/{type} — auto-id index with an explicit type.
    Registered LAST: any unclaimed /_x segment must not become a type.
    Delegates to _index_doc so version/op_type/parent/timestamp/ttl params
    behave identically to every other index route."""
    if type.startswith("_") and type != "_all":
        raise IllegalArgumentException(f"unsupported path [{index}/{type}]")
    return _index_doc(n, p, b, index, None, doc_type=type)


def _doc_exists_typed(n: Node, p, b, index: str, type: str, id: str):
    if type.startswith("_") and type != "_all":
        raise IllegalArgumentException(f"unsupported path [{index}/{type}/{id}]")
    _check_read_routing(n, index, type, id, p)
    if _type_mismatch(n, index, type, id,
                      p.get("routing") or p.get("parent")):
        return 404, None
    return _doc_exists(n, p, b, index, id)


def _type_exists_head(n: Node, p, b, index: str, type: str):
    if type.startswith("_"):
        raise IllegalArgumentException(f"unsupported path [{index}/{type}]")
    return _type_exists(n, p, b, index, type)


def _typed(handler, keep_type: bool = False):
    """Wrap a handler for a /{index}/{type}/... route: a {type} segment
    that starts with an underscore is a mis-bound meta path, not a type —
    reject it instead of silently serving (the reference answers 400 'no
    handler'). keep_type forwards the validated type to handlers that use
    it (percolate, mlt, exists_type)."""
    def h(n, p, b, **kw):
        t = kw.get("type", "")
        if t.startswith("_") and t != "_all":
            raise IllegalArgumentException(f"unsupported path segment [{t}]")
        if not keep_type:
            kw.pop("type", None)
        return handler(n, p, b, **kw)
    return h


def _cat_thread_pool(n: Node, p, b):
    """One row per node, 2.0 columns (bulk/index/search counters); the
    per-pool detail rows come via ?pools=true (format=json). Both forms
    honor the reference's `h=` column selection (RestTable), and the
    pool rows carry `largest`/`queue_size` so saturation history is
    readable without /_nodes/stats."""
    stats = n.thread_pool.stats()
    if str(p.get("pools", "false")).lower() in ("", "true"):
        rows = [
            {"node_name": n.name, "name": name, "active": st["active"],
             "queue": st["queue"], "queue_size": st["queue_size"],
             "rejected": st["rejected"], "threads": st["threads"],
             "largest": st["largest"], "completed": st["completed"]}
            for name, st in stats.items()]
        # _CatRows so the ONE serialization layer (_cat_table /
        # _cat_json_rows) applies h= selection exactly like every other
        # _cat endpoint; default = every column, so format=json keeps
        # threads/queue_size for existing consumers
        return 200, _cat_rows(rows, ["node_name", "name", "active",
                                     "queue", "queue_size", "rejected",
                                     "threads", "largest", "completed"])
    def c(pool, key):
        return str(stats.get(pool, {}).get(key, 0))
    row = {
        "host": "localhost", "ip": "127.0.0.1",
        "bulk.active": c("bulk", "active"),
        "bulk.queue": c("bulk", "queue"),
        "bulk.rejected": c("bulk", "rejected"),
        "index.active": c("index", "active"),
        "index.queue": c("index", "queue"),
        "index.rejected": c("index", "rejected"),
        "search.active": c("search", "active"),
        "search.queue": c("search", "queue"),
        "search.rejected": c("search", "rejected"),
    }
    # selectable extras + the reference's short aliases (RestThreadPool-
    # Action SUPPORTED_NAMES/ALIASES): <x>a/<x>q/<x>r per pool, pid/id/
    # h/i/po for the node columns
    row.update({"pid": str(os.getpid()), "id": n.node_id[:4],
                "h": "localhost", "i": "127.0.0.1", "po": "-",
                "port": "-"})
    for pool, alias in (("bulk", "b"), ("flush", "f"), ("generic", "ge"),
                        ("get", "g"), ("index", "i"), ("management", "ma"),
                        ("optimize", "o"), ("percolate", "p"),
                        ("refresh", "r"), ("search", "s"),
                        ("snapshot", "sn"), ("suggest", "su"),
                        ("warmer", "w"), ("listener", "l"),
                        ("fetch_shard_started", "fs"),
                        ("fetch_shard_store", "fss")):
        row[f"{alias}a"] = c(pool, "active")
        row[f"{alias}q"] = c(pool, "queue")
        row[f"{alias}r"] = c(pool, "rejected")
        # full declared detail columns (RestThreadPoolAction table);
        # blanks render as empty cells, exactly like unset pool config
        row.update({
            f"{pool}.type": "fixed",
            f"{pool}.active": c(pool, "active"),
            f"{pool}.size": c(pool, "threads"),
            f"{pool}.queue": c(pool, "queue"),
            f"{pool}.queueSize": "",
            f"{pool}.rejected": c(pool, "rejected"),
            f"{pool}.largest": c(pool, "threads"),
            f"{pool}.completed": c(pool, "completed"),
            f"{pool}.min": "", f"{pool}.max": "",
            f"{pool}.keepAlive": "",
        })
    return 200, _cat_rows([row], [
        "host", "ip", "bulk.active", "bulk.queue", "bulk.rejected",
        "index.active", "index.queue", "index.rejected", "search.active",
        "search.queue", "search.rejected"])


def _cat_help(n: Node, p, b):
    """GET /_cat (cat.help.json): list of cat endpoints."""
    return 200, "\n".join([
        "=^.^=",
        "/_cat/aliases", "/_cat/allocation", "/_cat/count",
        "/_cat/fielddata", "/_cat/health", "/_cat/incidents",
        "/_cat/indices", "/_cat/master",
        "/_cat/nodes", "/_cat/pending_tasks", "/_cat/plugins",
        "/_cat/recovery", "/_cat/repositories", "/_cat/segments",
        "/_cat/shards", "/_cat/snapshots/{repository}", "/_cat/tasks",
        "/_cat/templates", "/_cat/thread_pool",
    ])


_SIZE_RE = re.compile(r"^(\d+(?:\.\d+)?)(b|kb|mb|gb|tb)$")
_NUM_RE = re.compile(r"^-?\d[\d.]*[a-z%]*$")


class _CatRows(list):
    """Row list carrying a DEFAULT column order: rows may hold extra
    selectable columns (h=...) that the bare listing doesn't print —
    RestTable's declared-vs-displayed column split."""

    default: Optional[List[str]] = None


def _cat_rows(rows: List[dict], default: List[str]) -> "_CatRows":
    out = _CatRows(rows)
    out.default = default
    return out


def _cat_json_rows(rows: List[dict], params: dict) -> List[dict]:
    """format=json row objects restricted to the displayed columns (the
    default set, or the h= selection)."""
    cols = getattr(rows, "default", None)
    if params.get("h"):
        req = [c.strip() for c in str(params["h"]).split(",") if c.strip()]
        cols = [c for c in req if any(c in r for r in rows)]
    if cols is None:
        return list(rows)
    return [{c: r.get(c, "") for c in cols} for r in rows]


def _cat_table(rows: List[dict], params: dict) -> str:
    """Aligned text rendering of _cat rows (RestTable): `h` selects and
    orders columns, `v` prints the header line, `bytes` re-scales size
    values to a fixed unit, numeric columns right-justify (all reference
    client regexes rely on these RestTable behaviors)."""
    if not rows:
        return ""
    cols = getattr(rows, "default", None) or list(rows[0].keys())
    if params.get("h"):
        cols = [c.strip() for c in str(params["h"]).split(",") if c.strip()]
        if getattr(rows, "default", None):
            # endpoints with a declared column table DROP unknown h
            # selections (RestTable; e.g. 2.0 has no merge pool, so
            # h=ma silently disappears from _cat/thread_pool)
            cols = [c for c in cols if any(c in r for r in rows)]
    unit = str(params.get("bytes", "")).lower()
    mult = {"b": 1, "k": 1 << 10, "kb": 1 << 10, "m": 1 << 20,
            "mb": 1 << 20, "g": 1 << 30, "gb": 1 << 30, "t": 1 << 40,
            "tb": 1 << 40}.get(unit)

    def cell(v) -> str:
        v = str(v)
        if mult:
            m = _SIZE_RE.match(v)
            if m:
                raw = float(m.group(1)) * {"b": 1, "kb": 1 << 10,
                                           "mb": 1 << 20, "gb": 1 << 30,
                                           "tb": 1 << 40}[m.group(2)]
                return str(int(raw // mult))
        return v

    table = [[cell(r.get(c, "")) for c in cols] for r in rows]
    # RestTable right-justifies numeric columns (sizes/counts/percents)
    right = [all(_NUM_RE.match(row[i]) for row in table if row[i])
             for i in range(len(cols))]
    header = str(params.get("v", "false")).lower() in ("", "true")
    if header:
        table.insert(0, cols)
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    out = []
    for ri, row in enumerate(table):
        is_header = header and ri == 0
        line = " ".join(
            (v.ljust(w) if is_header or not right[i] else v.rjust(w))
            for i, (v, w) in enumerate(zip(row, widths)))
        out.append(line + " \n")
    return "".join(out)


class RestServer:
    def __init__(self, node: Node, host: str = "127.0.0.1", port: int = 9200):
        self.controller = RestController(node)
        controller = self.controller

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _handle(self, method: str):
                parsed = urlparse(self.path)
                params = {k: v[0] for k, v in
                          parse_qs(parsed.query,
                                   keep_blank_values=True).items()}
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                # lower-cased header map: the QoS layer reads the tenant
                # id (X-Tenant-Id) case-insensitively, like HTTP demands
                hdrs = {k.lower(): v for k, v in self.headers.items()}
                if (parsed.path.startswith("/_cat/")
                        and str(params.get("help", "false")).lower()
                        in ("", "true", "1")):
                    help_text = _cat_help_text(parsed.path)
                    if help_text is not None:
                        status, payload = 200, help_text
                    else:
                        status, payload = controller.dispatch(
                            method, parsed.path, params, body,
                            headers=hdrs)
                else:
                    status, payload = controller.dispatch(
                        method, parsed.path, params, body, headers=hdrs)
                ctype = "application/json; charset=UTF-8"
                if isinstance(payload, str):
                    # text endpoints (hot_threads, _cat help): raw body
                    data = payload.encode()
                    ctype = "text/plain; charset=UTF-8"
                elif (parsed.path.startswith("/_cat")
                      and isinstance(payload, list)
                      and params.get("format") != "json"):
                    # _cat default form is a text table (format=json opts
                    # into the row-object form)
                    data = _cat_table(payload, params).encode()
                    ctype = "text/plain; charset=UTF-8"
                elif (parsed.path.startswith("/_cat")
                      and isinstance(payload, list)):
                    # format=json renders only the DISPLAYED columns —
                    # declared-but-unselected extras stay internal
                    # (RestTable renders the same column set every format)
                    data = json.dumps(
                        _cat_json_rows(payload, params),
                        default=_json_default).encode()
                else:
                    data = b"" if payload is None else json.dumps(
                        payload, default=_json_default).encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                if method != "HEAD" and data:
                    self.wfile.write(data)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_DELETE(self):
                self._handle("DELETE")

            def do_HEAD(self):
                self._handle("HEAD")

            def log_message(self, fmt, *args):
                pass

        class _Server(ThreadingHTTPServer):
            # socketserver's default listen backlog (5) RESETS concurrent
            # connection bursts — exactly the traffic shape the serving
            # coalescer exists for; deep backlog, bounded work via pools
            request_queue_size = 128
            daemon_threads = True

        self.httpd = _Server((host, port), _Handler)
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self, background: bool = True):
        # a node serving HTTP is a production node: the stall watchdog
        # ticks for its lifetime (monitor/watchdog.py; ESTPU_WATCHDOG=0
        # opts out, library-embedded Nodes never start it)
        node = self.controller.node
        wd = getattr(node, "watchdog", None)
        if wd is not None:
            wd.ensure_started()
        # ... and pre-warms: replay each index's persisted census through
        # the real search path BEFORE traffic lands (serving/warmup.py;
        # ESTPU_WARMUP=0 opts out, indices without a census are no-ops)
        wu = getattr(getattr(node, "serving", None), "warmup", None)
        if wu is not None:
            try:
                wu.kick("boot")
            except Exception:  # tpulint: allow[R006] — pre-warm must
                pass           # never block a server from binding
        if background:
            self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
            self._thread.start()
        else:
            self.httpd.serve_forever()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _json_default(o):
    import numpy as np

    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")
