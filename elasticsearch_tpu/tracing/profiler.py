"""Search profiler: per-shard phase timings with a TPU phase breakdown.

Reference: org/elasticsearch/search/profile/ — Profiler.java /
ProfileResult (the ``?profile=true`` response tree). The reference
times Lucene Weight/Scorer stages; a TPU shard has different phases, so
the per-shard profile here keeps the reference's envelope (``profile.
shards[].searches[].query[]``) and adds a ``tpu`` section with the
phases that actually decide latency on this engine:

  rewrite         query parse + join/MLT prepare (host)
  executor_build  SegmentContext construction, program selection (host)
  device_compile  time inside device calls whose jit trace count moved
                  (tracing + XLA compilation; first shape class only)
  device_execute  time inside device calls running cached programs
  topk            top-k selection + result packing (device)
  host_sync       device→host pulls of packed results
  aggs            aggregation partials (device + host reduce)
  rehydrate       fielddata-tier device copies re-placed after eviction
                  (resources/residency.py — the `tpu.rehydrate` tracer
                  span's time, attributed via the attached() contextvar)

``retraces`` counts the jit traces the request triggered
(tools.tpulint.trace_audit via tracing/retrace.py); null = auditor
unavailable (``ESTPU_NO_TRACE_AUDIT`` / tools package missing — a typed
absence, never a sentinel that could leak into arithmetic).
Separating compile from execute is the point: BM25S-style
eager scoring (PAPERS.md) makes steady-state ``device_execute`` the
tuning signal, while a nonzero steady ``device_compile`` means shape
bucketing is broken (tpulint R001 territory).

Clock discipline (tpulint R007): all durations from
``time.perf_counter()``.
"""
from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Dict, Iterator, Optional

from elasticsearch_tpu.tracing import retrace

PHASES = ("rewrite", "executor_build", "device_compile", "device_execute",
          "topk", "host_sync", "aggs", "rehydrate", "fuse", "rerank")

# the PhaseTimer of the profiled query phase running on THIS logical
# flow — lets out-of-band instrumentation (residency rehydration) file
# time without threading the timer through every layer. Explicitly
# scoped by attached(): a stale pointer must never absorb a later
# request's rehydrates into an already-serialized profile.
_ACTIVE_TIMER: contextvars.ContextVar[Optional["PhaseTimer"]] = \
    contextvars.ContextVar("estpu-active-phase-timer", default=None)


def attached(timer: Optional["PhaseTimer"]):
    """Context manager scoping ``timer`` as the flow's rehydrate sink
    (no-op for None — unprofiled requests pay nothing)."""
    if timer is None:
        return nullcontext()

    @contextmanager
    def _cm():
        tok = _ACTIVE_TIMER.set(timer)
        try:
            yield
        finally:
            _ACTIVE_TIMER.reset(tok)

    return _cm()


def record_rehydrate(ns: int) -> None:
    """File ``ns`` under the attached timer's `rehydrate` phase (called
    by resources/residency.py; dropped when no profile is active)."""
    t = _ACTIVE_TIMER.get()
    if t is not None:
        t.nanos["rehydrate"] = t.nanos.get("rehydrate", 0) + int(ns)


def _block(out: Any) -> None:
    """Wait for device work referenced by ``out`` (tolerates host values,
    tuples, None — profiling must never change results, only timing)."""
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:
        pass  # host-only value / jax unavailable: nothing to wait for


class PhaseTimer:
    """Accumulates named phase durations (nanos) for ONE shard's query
    phase. Not thread-safe — one per query_phase call."""

    def __init__(self):
        self.nanos: Dict[str, int] = {p: 0 for p in PHASES}
        self.retraces = 0
        self._unknown_retraces = retrace.auditor() is None
        self.device_calls = 0
        self.segments = 0
        self._t0 = time.perf_counter()

    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.nanos[name] = self.nanos.get(name, 0) + int(
                (time.perf_counter() - t0) * 1e9)

    def device_call(self, fn: Callable[[], Any],
                    bucket: Optional[str] = None) -> Any:
        """Run a device call, block for its results, and attribute its
        wall time to device_compile (trace count moved) or
        device_execute (cached program). ``bucket`` additionally files
        the time under a named phase (e.g. "topk")."""
        snap = retrace.snapshot()
        t0 = time.perf_counter()
        out = fn()
        _block(out)
        ns = int((time.perf_counter() - t0) * 1e9)
        delta = retrace.traces_since(snap)
        self.device_calls += 1
        if delta > 0:
            self.retraces += delta
            self.nanos["device_compile"] += ns
        else:
            self.nanos["device_execute"] += ns
        if bucket is not None:
            self.nanos[bucket] = self.nanos.get(bucket, 0) + ns
        return out

    def to_json(self) -> dict:
        return {
            "phases": {f"{k}_nanos": v for k, v in self.nanos.items()},
            # measured wall time since the timer opened — NOT a phase
            # sum: the named ``bucket`` buckets (topk) deliberately
            # double-file time also counted under device_compile/
            # device_execute, so summing phases over-reports
            "query_total_nanos": int(
                (time.perf_counter() - self._t0) * 1e9),
            # null = auditor unavailable (unknown, NOT zero): the typed
            # absence keeps consumers from mixing a sentinel into sums —
            # the same convention bench metrics_delta uses
            "retraces": None if self._unknown_retraces else self.retraces,
            "device_calls": self.device_calls,
            "segments": self.segments,
        }


def shard_profile_entry(shard_label: str, query_nanos: int,
                        tpu: Optional[dict],
                        description: str = "whole-segment score/mask "
                                           "program") -> dict:
    """One ``profile.shards[]`` element: reference envelope + tpu extras."""
    out: Dict[str, Any] = {
        "id": shard_label,
        "searches": [{
            "query": [{
                "type": "CompiledSegmentProgram",
                "description": description,
                "time_in_nanos": int(query_nanos),
            }],
            "rewrite_time": (tpu or {}).get("phases", {}).get(
                "rewrite_nanos", 0),
            "collector": [{
                "name": "TopKMaskCollector",
                "reason": "search_top_hits",
                "time_in_nanos": (tpu or {}).get("phases", {}).get(
                    "topk_nanos", 0),
            }],
        }],
    }
    if tpu is not None:
        out["tpu"] = tpu
    return out
