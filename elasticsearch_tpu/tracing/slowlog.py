"""Index search/indexing slow logs.

Reference: org/elasticsearch/index/search/stats/ShardSearchSlowLog.java
and index/indexing/slowlog/IndexingSlowLog.java — per-index thresholds
(``index.search.slowlog.threshold.query.warn`` … ``.trace``,
``index.indexing.slowlog.threshold.index.*``) route slow operations to
a dedicated logger at the matching level.

Adaptation: thresholds are read from the live index settings on every
record (dynamic updates through ``PUT /{index}/_settings`` take effect
immediately, like the reference's dynamic settings), entries go to the
stdlib logger ``index.search.slowlog`` / ``index.indexing.slowlog`` AND
to a bounded in-memory ring surfaced through node stats — operators of
an embedded node get the last-N slow operations without configuring
logging. ``/_nodes`` shows a per-NODE slow-op count aggregated from the
node's own indices' rings (monitor/stats.py::aggregate_slowlog — never
a process-global sum; in-process multi-node harnesses must not bleed
counts across nodes).
"""
from __future__ import annotations

import json
import logging
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

_LEVELS = ("warn", "info", "debug", "trace")
_PY_LEVEL = {"warn": logging.WARNING, "info": logging.INFO,
             "debug": logging.DEBUG, "trace": logging.DEBUG}


def parse_time_millis(v: Any) -> Optional[float]:
    """Threshold value → millis ("500ms", "1s", "2m", numeric millis);
    None / -1 / "-1" / garbage disable the level. Delegates to the ONE
    ES duration grammar (search/service.py::_parse_timeout — lazy
    import keeps this module light) so the two parsers can never drift;
    only the slowlog-specific sub-milli units and the never-raise
    disable semantics live here."""
    if v in (None, -1, "-1", ""):
        return None
    s = str(v).strip().lower()
    for suf, mul in (("nanos", 1e-6), ("micros", 1e-3)):
        if s.endswith(suf):
            head = s[: -len(suf)]
            if head.replace(".", "", 1).isdigit():
                return float(head) * mul
    from elasticsearch_tpu.search.service import _parse_timeout

    try:
        sec = _parse_timeout(s)
    except Exception:
        return None  # an unparseable threshold disables, never 500s
    return None if sec is None else sec * 1000.0


def _setting(settings: dict, dotted: str) -> Any:
    """Read a dotted settings key tolerating both flat dotted keys and
    nested dicts, with or without the leading ``index.`` level (the same
    tolerance update_index_settings / _query_cache_enabled show)."""
    for root in (settings.get("index", settings), settings):
        if not isinstance(root, dict):
            continue
        if dotted in root:
            return root[dotted]
        if f"index.{dotted}" in root:
            return root[f"index.{dotted}"]
        cur: Any = root
        for part in dotted.split("."):
            if not isinstance(cur, dict) or part not in cur:
                cur = None
                break
            cur = cur[part]
        if cur is not None:
            return cur
    return None


class SlowLog:
    """One slow-log stream (search.query / search.fetch / indexing.index):
    threshold lookup per record, leveled stdlib logging, bounded ring."""

    def __init__(self, index_name: str, kind: str, op: str,
                 settings_fn: Callable[[], dict], max_entries: int = 128):
        self.index_name = index_name
        self.kind = kind  # "search" | "indexing"
        self.op = op      # "query" | "fetch" | "index"
        self._settings_fn = settings_fn
        self._lock = threading.Lock()
        self.entries: deque = deque(maxlen=max_entries)
        self.total = 0
        self._logger = logging.getLogger(f"index.{kind}.slowlog")

    def level_for(self, took_ms: float) -> Optional[str]:
        settings = self._settings_fn() or {}
        for level in _LEVELS:  # warn first: the most severe match wins
            thr = parse_time_millis(_setting(
                settings, f"{self.kind}.slowlog.threshold.{self.op}.{level}"))
            if thr is not None and took_ms >= thr:
                return level
        return None

    def maybe_record(self, took_ms: float,
                     source_fn: Optional[Callable[[], Optional[str]]] = None,
                     **detail: Any) -> Optional[dict]:
        """``source_fn`` is LAZY: the request-body serialization it
        usually wraps must only run for entries that actually record —
        with no thresholds configured (the default), every search would
        otherwise pay a json.dumps of its whole body for nothing."""
        level = self.level_for(took_ms)
        if level is None:
            return None
        if source_fn is not None:
            detail["source"] = source_fn()
        entry = {"index": self.index_name, "level": level, "op": self.op,
                 "took_millis": int(took_ms)}
        entry.update({k: v for k, v in detail.items() if v is not None})
        with self._lock:
            self.entries.append(entry)
            self.total += 1
        try:
            self._logger.log(
                _PY_LEVEL[level],
                "[%s] took[%dms], %s",
                self.index_name, int(took_ms),
                ", ".join(f"{k}[{v}]" for k, v in entry.items()
                          if k not in ("index", "level")))
        except Exception:  # logging config must never fail the request
            pass  # tpulint: allow[R006] — best-effort log emission
        return entry

    def to_json(self) -> dict:
        with self._lock:
            return {"total": self.total, "entries": list(self.entries)}


class IndexSlowLog:
    """The per-index bundle: search query slow log + indexing slow log
    (reference: one ShardSearchSlowLog + IndexingSlowLog per index)."""

    def __init__(self, index_name: str, settings_fn: Callable[[], dict]):
        self.query = SlowLog(index_name, "search", "query", settings_fn)
        self.index = SlowLog(index_name, "indexing", "index", settings_fn)

    def on_search(self, took_ms: float, body: Optional[dict],
                  response: Optional[dict] = None) -> Optional[dict]:
        hits = None
        shards = None
        if isinstance(response, dict):
            hits = (response.get("hits") or {}).get("total")
            shards = (response.get("_shards") or {}).get("total")

        def _source() -> Optional[str]:
            if not body:
                return None
            try:
                return json.dumps(body, sort_keys=True, default=str)[:512]
            except (TypeError, ValueError):
                return None

        return self.query.maybe_record(took_ms, source_fn=_source,
                                       total_hits=hits,
                                       total_shards=shards)

    def on_index(self, took_ms: float, doc_id: Optional[str]) -> Optional[dict]:
        return self.index.maybe_record(took_ms, id=doc_id)

    def stats(self) -> dict:
        return {"search": self.query.to_json(),
                "indexing": self.index.to_json()}
