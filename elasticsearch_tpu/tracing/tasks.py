"""Task management: node-level registry with cooperative cancellation.

Reference: org/elasticsearch/tasks/ — TaskManager.java (register/
unregister around every transport action), Task.java / CancellableTask
(the ``isCancelled`` flag long-running actions poll), and
action/admin/cluster/node/tasks/ (the list/cancel transport actions
behind ``GET /_tasks`` and ``POST /_tasks/{id}/_cancel``).

Adaptation: tasks are identified as ``node_id:seq`` exactly like the
reference. Cancellation is COOPERATIVE — long-running loops (by-query
scans, scroll paging, recovery streaming, force-merge) call
``check_cancelled()`` at their natural yield points (between docs /
segments — whole-segment device programs are not interruptible, the
same boundary Lucene's per-leaf cancellation uses). Parent/child links
propagate across the TCP transport in the same wire header the tracer
rides (utils/wire.py::attach_ctx), so cancelling a coordinator task
fans out to its remote children.
"""
from __future__ import annotations

import contextvars
import itertools
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from elasticsearch_tpu.utils.errors import ElasticsearchTpuException


class TaskCancelledException(ElasticsearchTpuException):
    """Raised at a cooperative checkpoint of a cancelled task
    (reference: tasks/TaskCancelledException.java). 400, like the
    reference's RestStatus mapping."""

    status = 400


class ResourceNotFoundException(ElasticsearchTpuException):
    status = 404


ParentId = Tuple[str, int]  # (node_id, task seq)


def human_time(nanos: int) -> str:
    """Human-scaled duration (reference: TimeValue.toString — the form
    every `_cat` duration column prints): ``850micros``, ``770ms``,
    ``12.3s``, ``4.5m``, ``1.2h``. The point of printing it beside the
    nanos: an operator scanning `_cat/tasks` tells a fresh task from
    one wedged for minutes at a glance."""
    n = max(0, int(nanos))
    if n < 1_000_000:
        return f"{n // 1000}micros"
    ms = n / 1e6
    if ms < 1000:
        return f"{ms:.1f}ms" if ms < 10 else f"{int(ms)}ms"
    s = ms / 1000.0
    if s < 60:
        return f"{s:.1f}s"
    m = s / 60.0
    if m < 60:
        return f"{m:.1f}m"
    return f"{m / 60.0:.1f}h"


class Task:
    def __init__(self, task_id: int, node: str, action: str,
                 description: str = "", parent: Optional[ParentId] = None,
                 cancellable: bool = True, status: str = "running"):
        self.id = task_id
        self.node = node
        self.action = action
        self.description = description
        self.parent = parent
        self.cancellable = cancellable
        self.status = status  # "pending" | "running"
        self.start_time_ms = int(time.time() * 1000)  # display only
        self._start = time.monotonic()
        self._cancelled = threading.Event()
        self.cancel_reason: Optional[str] = None
        # optional eager-cleanup hook, fired ONCE on the cancelling
        # thread: tasks guarding a resource no cooperative checkpoint
        # may ever revisit (an abandoned scroll context) free it here
        # instead of waiting for a client that might never return
        self.on_cancel: Optional[Callable[["Task"], None]] = None

    @property
    def tagged_id(self) -> str:
        return f"{self.node}:{self.id}"

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self, reason: str = "by user request") -> bool:
        if not self.cancellable:
            return False
        if not self._cancelled.is_set():
            self.cancel_reason = reason
            self._cancelled.set()
            cb = self.on_cancel
            if cb is not None:
                try:
                    cb(self)
                except Exception:
                    pass  # cleanup is best-effort; the flag is what counts
        return True

    def check_cancelled(self) -> None:
        if self._cancelled.is_set():
            raise TaskCancelledException(
                f"task [{self.tagged_id}] ({self.action}) was cancelled "
                f"[{self.cancel_reason or 'by user request'}]")

    def start(self) -> None:
        """pending → running (queued work that just began executing)."""
        self.status = "running"
        self._start = time.monotonic()

    def running_time_nanos(self) -> int:
        return int((time.monotonic() - self._start) * 1e9)

    def to_json(self) -> dict:
        nanos = self.running_time_nanos()
        out = {
            "node": self.node,
            "id": self.id,
            "type": "transport",
            "action": self.action,
            "description": self.description,
            "status": self.status,
            "start_time_in_millis": self.start_time_ms,
            "running_time_in_nanos": nanos,
            # the human form beside the nanos (computed from the task's
            # monotonic start): GET /_tasks consumers get both without
            # re-deriving the scale
            "running_time": human_time(nanos),
            "cancellable": self.cancellable,
            "cancelled": self.cancelled,
        }
        if self.parent is not None:
            out["parent_task_id"] = f"{self.parent[0]}:{self.parent[1]}"
        return out


# the task the CURRENT flow of execution runs under (set by
# TaskRegistry.task); checkpoints read it without plumbing a handle
# through every call signature
_CURRENT_TASK: contextvars.ContextVar[Optional[Task]] = \
    contextvars.ContextVar("estpu-current-task", default=None)
# the parent task adopted from a transport wire header (remote parent —
# there is no local Task object for it)
_WIRE_PARENT: contextvars.ContextVar[Optional[ParentId]] = \
    contextvars.ContextVar("estpu-wire-parent-task", default=None)


def current_task() -> Optional[Task]:
    return _CURRENT_TASK.get()


def set_current(task: Optional[Task]):
    """Make ``task`` the current task of this flow; returns the reset
    token (for callers whose enter/exit can't be a with-block, e.g. the
    recovery runner driving several sequential task lifetimes)."""
    return _CURRENT_TASK.set(task)


def reset_current(token) -> None:
    _CURRENT_TASK.reset(token)


def check_cancelled() -> None:
    """Cooperative checkpoint: no-op when the current flow runs under no
    task; raises TaskCancelledException when its task was cancelled."""
    task = _CURRENT_TASK.get()
    if task is not None:
        task.check_cancelled()


def task_header() -> Optional[dict]:
    """The current task as a wire-header dict for parent propagation."""
    task = _CURRENT_TASK.get()
    if task is None:
        return None
    return {"node": task.node, "id": task.id}


@contextmanager
def adopt_parent(header: Optional[dict]) -> Iterator[None]:
    """Adopt a remote parent task from a wire header: tasks registered
    inside become its children (and die with it on cascade cancel).
    Defensive on top of wire.sanitize_ctx: a non-int id is ignored, not
    raised — a junk observability header must never fail a valid
    frame."""
    tid = (header or {}).get("id")
    if not isinstance(tid, int) or isinstance(tid, bool):
        yield
        return
    token = _WIRE_PARENT.set((str(header.get("node") or ""), tid))
    try:
        yield
    finally:
        _WIRE_PARENT.reset(token)


def wire_parent() -> Optional[ParentId]:
    return _WIRE_PARENT.get()


class TaskRegistry:
    """All in-flight tasks of one node (reference: TaskManager)."""

    #: bounded ban memory: cancelled parent ids a LATE-registering child
    #: must still die under (see register); FIFO-evicted past this many
    _BAN_CAP = 1024

    def __init__(self, node_id: str = ""):
        self.node_id = node_id
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._tasks: Dict[int, Task] = {}
        # parent id -> cancel reason (reference: TransportCancelTasksAction
        # sets a BAN on the parent so children registering after the
        # cancel fanout processed still cancel at registration — without
        # it, a cancel racing the coordinator's in-flight child dispatch
        # reports "canceled" while the remote destructive pass runs to
        # completion)
        from collections import OrderedDict

        self._banned: "OrderedDict[ParentId, str]" = OrderedDict()
        self.completed_total = 0
        self.cancelled_total = 0

    # -- lifecycle -----------------------------------------------------------

    def register(self, action: str, description: str = "",
                 parent: Optional[ParentId] = None,
                 cancellable: bool = True,
                 status: str = "running",
                 on_cancel: Optional[Callable[[Task], None]] = None) -> Task:
        """Register a task. ``parent`` defaults to the current local task
        or, failing that, the remote parent adopted from the transport
        wire header — the reference resolves parentTaskId the same way.
        ``on_cancel`` must be given HERE (not assigned afterwards) when
        the task guards a resource: the task is cancellable the instant
        it publishes — a cancel (or the born-cancelled ban path below)
        landing before a late assignment would skip the cleanup
        forever."""
        if parent is None:
            cur = _CURRENT_TASK.get()
            if cur is not None:
                parent = (cur.node, cur.id)
            else:
                parent = _WIRE_PARENT.get()
        task = Task(next(self._seq), self.node_id, action,
                    description=description, parent=parent,
                    cancellable=cancellable, status=status)
        task.on_cancel = on_cancel
        with self._lock:
            self._tasks[task.id] = task
            ban_reason = (self._banned.get(parent)
                          if parent is not None else None)
        if ban_reason is not None:
            # born cancelled: the parent was cancelled before this child
            # registered — its first checkpoint raises immediately
            task.cancel(ban_reason)
        return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            gone = self._tasks.pop(task.id, None)
            if gone is not None:
                self.completed_total += 1
                if gone.cancelled:
                    self.cancelled_total += 1

    @contextmanager
    def task(self, action: str, description: str = "",
             parent: Optional[ParentId] = None,
             cancellable: bool = True) -> Iterator[Task]:
        """Run a block as a registered task: the task becomes the current
        task of this flow (checkpoints see it, children parent to it,
        the transport stamps it on outgoing wire headers)."""
        t = self.register(action, description=description, parent=parent,
                          cancellable=cancellable)
        token = _CURRENT_TASK.set(t)
        try:
            yield t
        finally:
            _CURRENT_TASK.reset(token)
            self.unregister(t)

    # -- views ---------------------------------------------------------------

    def get(self, task_id: int) -> Optional[Task]:
        with self._lock:
            return self._tasks.get(task_id)

    def list_tasks(self, actions: Optional[str] = None) -> List[Task]:
        """Snapshot, optionally filtered by a comma-joined action pattern
        list (``*`` wildcards, reference: ListTasksRequest.actions)."""
        import fnmatch

        with self._lock:
            tasks = sorted(self._tasks.values(), key=lambda t: t.id)
        if not actions:
            return tasks
        pats = [a.strip() for a in str(actions).split(",") if a.strip()]
        return [t for t in tasks
                if any(fnmatch.fnmatch(t.action, p) for p in pats)]

    def pending_tasks(self) -> List[dict]:
        """Registered-but-not-yet-running tasks in /_cluster/pending_tasks
        shape (insertOrder = task seq, timeInQueue from the monotonic
        clock)."""
        out = []
        for t in self.list_tasks():
            if t.status != "pending":
                continue
            ms = t.running_time_nanos() // 1_000_000
            out.append({"insert_order": t.id, "priority": "NORMAL",
                        "source": t.action or t.description,
                        "time_in_queue_millis": ms,
                        "time_in_queue": f"{ms}ms"})
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"current": len(self._tasks),
                    "completed_total": self.completed_total,
                    "cancelled_total": self.cancelled_total}

    # -- cancellation --------------------------------------------------------

    def _ban(self, parent: ParentId, reason: str) -> None:
        with self._lock:
            self._banned[parent] = reason
            self._banned.move_to_end(parent)
            while len(self._banned) > self._BAN_CAP:
                self._banned.popitem(last=False)

    def cancel(self, task_id: int,
               reason: str = "by user request") -> List[Task]:
        """Cancel a task and (recursively) its LOCAL descendants. Remote
        children are the transport layer's job
        (cluster/search_action.py::cancel_task_children fans the parent
        id to every member). Returns the tasks actually cancelled."""
        task = self.get(task_id)
        if task is None:
            raise ResourceNotFoundException(
                f"task [{self.node_id}:{task_id}] isn't running and "
                "hasn't stored its results")
        out = []
        if task.cancel(reason):
            out.append(task)
        self._ban((self.node_id, task_id), reason)
        out.extend(self.cancel_by_parent(self.node_id, task_id, reason))
        return out

    def cancel_by_parent(self, parent_node: str, parent_id: int,
                         reason: str = "by user request") -> List[Task]:
        """Cancel every local task descending from (parent_node,
        parent_id) — the receiving half of cross-node cascade cancel.
        The parent id is also BANNED: a child that registers after this
        fanout (the coordinator's dispatch was in flight) is born
        cancelled instead of escaping the cascade."""
        self._ban((parent_node, parent_id), reason)
        with self._lock:
            snapshot = list(self._tasks.values())
        out: List[Task] = []
        want = {(parent_node, parent_id)}
        # fixed point over the local parent links: children of cancelled
        # children cancel too
        changed = True
        while changed:
            changed = False
            for t in snapshot:
                if t.parent in want and (t.node, t.id) not in want:
                    if t.cancel(reason):
                        out.append(t)
                    want.add((t.node, t.id))
                    self._ban((t.node, t.id), reason)
                    changed = True
        return out
