"""Span tracer: monotonic-clock spans with parent/child links.

Reference: there is no tracer in ES 2.x — the closest ancestors are the
search Profile API's timing tree (search/profile/Profiler.java) and the
task manager's start-time accounting. This module is the shared
substrate both ride here: every instrumented layer (REST dispatch,
coordinator scatter, transport send/handle, shard query/fetch phases)
opens a span; the profiler and the slow logs read the same clocks.

Clock discipline (tpulint R007): span *durations* come from
``time.perf_counter()`` — wall clock (``time.time()``) steps under NTP
adjustments and would corrupt durations; it is used only for the
epoch-millis display timestamp a span carries for humans.

Propagation is ``contextvars``-based so it follows the request across
threadpool workers within one thread of execution, and crosses the TCP
transport as a wire header (utils/wire.py::attach_ctx — the counterpart
of the reference's ThreadContext headers riding every transport
message).
"""
from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple


@dataclass(frozen=True)
class SpanContext:
    """The propagated identity of an active span (local or remote)."""

    trace_id: str
    span_id: str


# the active span context for THIS logical flow of execution; survives
# nested tracer.span() blocks and is restored on exit
_ACTIVE: contextvars.ContextVar[Optional[SpanContext]] = \
    contextvars.ContextVar("estpu-active-span", default=None)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    node: str
    # perf_counter seconds at open; duration filled on close
    start: float
    duration: float = 0.0
    # wall-clock display timestamp (epoch millis) — NOT used for any
    # duration math
    timestamp_ms: int = 0
    thread: int = 0
    tags: Dict[str, Any] = field(default_factory=dict)
    error: Optional[str] = None

    def to_json(self) -> dict:
        out = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "node": self.node,
            "timestamp_ms": self.timestamp_ms,
            "duration_nanos": int(self.duration * 1e9),
        }
        if self.tags:
            out["tags"] = dict(self.tags)
        if self.error:
            out["error"] = self.error
        return out


def current_context() -> Optional[SpanContext]:
    return _ACTIVE.get()


def trace_header() -> Optional[dict]:
    """The active span as a wire-header dict (None when untraced)."""
    ctx = _ACTIVE.get()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}


@contextmanager
def adopt(header: Optional[dict]) -> Iterator[None]:
    """Adopt a remote parent span from a wire header: spans opened inside
    join the remote trace as children of the sender's span."""
    if not header or not header.get("trace_id"):
        yield
        return
    token = _ACTIVE.set(SpanContext(str(header["trace_id"]),
                                    str(header.get("span_id") or "")))
    try:
        yield
    finally:
        _ACTIVE.reset(token)


class Tracer:
    """Per-node span recorder with a bounded finished-span ring.

    The ring bounds memory the way the translog-recovery event ring does
    (monitor/stats.py): counters stay exact forever, per-span detail is
    last-N. 4096 spans ≈ a few hundred requests of full detail — enough
    for the flamegraph dump to show the recent past.
    """

    def __init__(self, node_id: str = "", max_spans: int = 4096):
        self.node_id = node_id
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=max_spans)
        self.started_total = 0
        self.finished_total = 0
        # optional finished-span sink (monitor/metrics.py::span_sink):
        # every close also lands in a latency histogram, so the span
        # substrate doubles as continuous time-series without
        # re-instrumenting call sites
        self._sink = None

    def set_sink(self, sink) -> None:
        """``sink(span)`` called after every span close (outside the
        ring lock). It must be cheap and must not raise; a sink failure
        is swallowed — dropping one metric sample must never fail the
        request the span measured."""
        self._sink = sink

    @contextmanager
    def span(self, name: str, **tags: Any) -> Iterator[Span]:
        parent = _ACTIVE.get()
        trace_id = parent.trace_id if parent else _new_id()
        sp = Span(name=name, trace_id=trace_id, span_id=_new_id(),
                  parent_id=parent.span_id if parent else None,
                  node=self.node_id, start=time.perf_counter(),
                  timestamp_ms=int(time.time() * 1000),
                  thread=threading.get_ident(), tags=dict(tags))
        with self._lock:
            self.started_total += 1
        token = _ACTIVE.set(SpanContext(trace_id, sp.span_id))
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            _ACTIVE.reset(token)
            sp.duration = time.perf_counter() - sp.start
            with self._lock:
                self.finished_total += 1
                self._spans.append(sp)
            sink = self._sink
            if sink is not None:
                try:
                    sink(sp)
                except Exception:
                    pass  # a metrics failure must never fail the request

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def stats(self) -> dict:
        with self._lock:
            return {"started_total": self.started_total,
                    "finished_total": self.finished_total,
                    "retained": len(self._spans)}

    def chrome_trace(self) -> dict:
        """The finished-span ring in Chrome trace-event format (chrome://
        tracing, Perfetto, speedscope all read it): complete events
        ("ph": "X") with microsecond ts/dur on the perf_counter timebase,
        one row per originating thread."""
        events = []
        pid = os.getpid()
        for sp in self.spans():
            args = {"trace_id": sp.trace_id, "span_id": sp.span_id,
                    "node": sp.node}
            if sp.parent_id:
                args["parent_id"] = sp.parent_id
            args.update({k: v for k, v in sp.tags.items()
                         if isinstance(v, (str, int, float, bool))})
            if sp.error:
                args["error"] = sp.error
            events.append({
                "name": sp.name, "cat": "estpu", "ph": "X",
                "ts": int(sp.start * 1e6),
                "dur": max(1, int(sp.duration * 1e6)),
                "pid": pid, "tid": sp.thread, "args": args,
            })
        return {"traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {"node": self.node_id}}


def find_trace_ids(spans: List[Span]) -> Dict[str, List[Span]]:
    """Group spans by trace id (test/debug helper)."""
    out: Dict[str, List[Span]] = {}
    for sp in spans:
        out.setdefault(sp.trace_id, []).append(sp)
    return out
