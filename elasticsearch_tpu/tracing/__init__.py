"""Observability subsystem: tracing, tasks, profiling, slow logs.

One substrate, four consumers:

- ``tracer``   — monotonic-clock spans with parent/child links,
                 contextvar propagation, Chrome-trace dump
                 (``GET /_nodes/_local/trace``).
- ``tasks``    — node-level task registry with cooperative cancellation
                 and cross-node parent links (``GET/POST /_tasks``).
- ``profiler`` — ``?profile=true`` per-shard phase timings splitting
                 device compile from device execute via jit trace counts.
- ``slowlog``  — ``index.search.slowlog.threshold.*``-driven slow logs.

This module owns the COMBINED wire context: :func:`wire_context`
captures the active span + task as one JSON-safe header dict that the
TCP transport attaches to every frame (utils/wire.py::attach_ctx), and
:func:`adopt_wire_context` restores both on the receiving node — so a
coordinator search yields one trace spanning every remote shard owner,
and cancelling a coordinator task reaches its remote children.

Import cost: no jax, no numpy — safe for the transport layer.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Iterator, Optional

from elasticsearch_tpu.tracing import tasks as _tasks
from elasticsearch_tpu.tracing import tracer as _tracer
from elasticsearch_tpu.tracing.tasks import (TaskCancelledException,
                                             TaskRegistry, check_cancelled,
                                             current_task)
from elasticsearch_tpu.tracing.tracer import Span, Tracer

__all__ = [
    "Tracer", "Span", "TaskRegistry", "TaskCancelledException",
    "check_cancelled", "current_task", "wire_context",
    "adopt_wire_context",
]


def wire_context() -> Optional[dict]:
    """The active span + task as one wire-header dict (None when the
    current flow is untraced and untasked)."""
    out = {}
    trace = _tracer.trace_header()
    if trace:
        out["trace"] = trace
    task = _tasks.task_header()
    if task:
        out["task"] = task
    return out or None


@contextmanager
def adopt_wire_context(ctx: Optional[dict]) -> Iterator[None]:
    """Adopt a received wire context for the duration of a handler:
    spans join the sender's trace, registered tasks become children of
    the sender's task."""
    if not ctx:
        yield
        return
    with _tracer.adopt(ctx.get("trace")):
        with _tasks.adopt_parent(ctx.get("task")):
            yield
