"""Process-global retrace auditor hookup for the search profiler.

The profiler splits device time into COMPILE vs EXECUTE by watching
``jax.jit`` trace counts around each device call: a call whose trace
count moved paid tracing+compilation; a steady call ran a cached
program. The counter is tools.tpulint.trace_audit's auditor — the same
instrument tools/tpu_ab.py uses for ``retraces_timed`` — installed
process-wide.

Install-order constraint (see trace_audit's module docstring): the
codebase binds ``jax.jit`` at import time, so the auditor must patch
``jax.jit`` first. The ``__init__`` of every jit-binding package
(``ops/``, ``models/``, ``parallel/``) calls :func:`ensure_installed` —
parent packages initialize before their submodules, so the patch lands
before any ``@jax.jit`` binds, while the ROOT package import stays
jax-free (a Client-only import pays nothing). ``ESTPU_NO_TRACE_AUDIT=1``
opts out — then profiles report ``retraces: null`` and bench deltas
``jit_compiles: null`` (unavailable as a typed absence; the in-process
``traces_since`` sentinel stays -1 for cheap comparisons, but it must
never leak into a serialized envelope or a sum).
"""
from __future__ import annotations

import os
import threading
from typing import Optional

_LOCK = threading.Lock()
_AUDITOR = None
_TRIED = False


def ensure_installed():
    """Install the global auditor once; None when unavailable (no jax,
    no tools package, or explicitly disabled)."""
    global _AUDITOR, _TRIED
    with _LOCK:
        if _TRIED:
            return _AUDITOR
        _TRIED = True
        if os.environ.get("ESTPU_NO_TRACE_AUDIT"):
            return None
        try:
            from tools.tpulint import trace_audit

            _AUDITOR = trace_audit.install()
            # device-program observatory feed: every (re)trace reports
            # the traced callable's identity + abstract arg shapes into
            # monitor/programs.py, so compiles are attributed to stable
            # (program, shapes, backend) keys instead of only bumping a
            # per-thread counter. The `#seq` construction suffix is
            # stripped: it depends on import order, the qualname does not
            # (the census's cross-process stability contract).
            _AUDITOR.set_reporter(_report_trace)
        except Exception:
            # tools/ not importable (installed-package context) or jax
            # missing: the profiler degrades to retraces unknown
            _AUDITOR = None
        return _AUDITOR


def _report_trace(key: str, args: tuple, kwargs: dict) -> None:
    """Trace-auditor reporter → program registry (lazy import: the
    registry pulls monitor/metrics, which this module must not load for
    auditor-less processes)."""
    from elasticsearch_tpu.monitor import programs

    program = key.rpartition("#")[0] or key
    programs.REGISTRY.record_compile(program,
                                     programs.shape_sig(args, kwargs))


def auditor():
    """The installed auditor, or None (never installs as a side effect —
    a late install would miss every import-time-bound program and report
    a misleading 0)."""
    return _AUDITOR


def snapshot() -> Optional[int]:
    """Per-THREAD trace count at this instant (tracing runs
    synchronously on the calling thread, so thread attribution is
    exact). A global count would misclassify: a neighbor request's
    first-call compile on another thread must not turn this thread's
    cached execution into device_compile."""
    a = _AUDITOR
    return a.thread_total() if a is not None else None


def traces_since(snap: Optional[int]) -> int:
    """New traces ON THIS THREAD since ``snap``; -1 when the auditor is
    unavailable (unknown must stay distinguishable from zero)."""
    a = _AUDITOR
    if a is None or snap is None:
        return -1
    return a.thread_total() - snap
