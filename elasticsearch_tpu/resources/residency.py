"""Tiered HBM residency: one choke point for device-resident allocations.

The reference keeps fielddata in an IndicesFieldDataCache whose entries
load lazily, count against the fielddata breaker, and evict under
pressure (org/elasticsearch/index/fielddata/ + indices/fielddata/cache/).
Here the device-resident structures play that role: doc-value columns,
vector slabs and dense impact blocks are *evictable* — the registry keeps
the host mirror, drops the device copy LRU-first when a reservation
can't fit, and transparently rehydrates on the next touch (a
``tpu.rehydrate`` tracer span + profiler phase, so the latency cost of
running over-HBM is visible, never silent).

Three entry points, one accounting surface:

- :meth:`ResidencyRegistry.put_array` — an EVICTABLE device copy of a
  host array (handle keeps the mirror; ``handle.get()`` returns the
  device array, rehydrating if evicted). Charges the tier's breaker;
  under pressure evicts LRU handles before tripping.
- :meth:`ResidencyRegistry.track` — a pinned charge for device memory
  owned elsewhere (executor data/prepared-query caches, IVF device
  lists): force-charged (never trips — the owners have their own LRU
  caps) and released when the token dies with its cache entry.
- :meth:`ResidencyRegistry.device_put` — the accounting wrapper around
  ``jax.device_put`` for always-resident placements (postings, live
  masks, nested-join arrays). Counts placements/bytes per tier so
  ``/_nodes`` shows where HBM goes; admission control for these is the
  engine's per-segment ``segments``-breaker charge at freeze.

tpulint R008 flags raw ``jax.device_put`` in ``elasticsearch_tpu/`` that
bypasses these entry points (``# tpulint: offbudget`` is the justified
escape hatch for transient per-call uploads).

Fault point ``resources.reserve`` (utils/faults.py) fires before every
breaker reservation — the chaos suite uses it to prove a tripped
fielddata breaker degrades to partial shard results.
"""
from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.resources.breakers import CircuitBreakerService
from elasticsearch_tpu.utils.errors import CircuitBreakingException
from elasticsearch_tpu.utils.faults import FAULTS

#: residency tiers — each maps to the breaker of the same name
TIERS = ("fielddata", "segments", "request")


def _jax_device_put(x, *args, **kw):
    import jax

    return jax.device_put(x, *args, **kw)


class ResidentArray:
    """Handle for one evictable device-resident array.

    ``get()`` is the only consumer API: it returns the device array,
    touching LRU recency, and rehydrates (reserve → device_put → span)
    when the device copy was evicted. The host mirror is authoritative
    and immutable (segments are frozen), so evict→rehydrate is exact.

    Note eviction drops the REGISTRY's reference; XLA frees the buffer
    once in-flight consumers drop theirs too (normal refcounting — same
    lifecycle as a merged-away segment's arrays).
    """

    def __init__(self, registry: "ResidencyRegistry", host: np.ndarray,
                 label: str, tier: str, dtype: Any = None):
        try:  # device dtype decides the footprint (bf16 halves it)
            itemsize = (np.dtype(dtype).itemsize if dtype is not None
                        else host.dtype.itemsize)
        except TypeError:
            itemsize = host.dtype.itemsize
        self.label = label
        self.tier = tier
        self.nbytes = int(host.size * itemsize)
        self.evictions = 0
        self.rehydrations = 0
        self._host = host
        self._dtype = dtype
        self._dev: Any = None
        self._lock = threading.Lock()
        self._registry = registry
        # shared state cell: the weakref.finalize callback releases the
        # breaker charge for a handle GC'd while resident (segment
        # merged away / index closed) without resurrecting the handle
        self._cell = {"resident": False, "nbytes": self.nbytes,
                      "tier": tier, "key": id(self)}
        registry._adopt(self)

    @property
    def resident(self) -> bool:
        return self._dev is not None

    def _place(self):
        if self._dtype is not None:
            import jax.numpy as jnp

            return jnp.asarray(self._host, dtype=self._dtype)
        return _jax_device_put(self._host)

    def get(self):
        with self._lock:
            dev = self._dev
        if dev is not None:
            self._registry._touch(self)
            return dev
        return self._rehydrate()

    def _rehydrate(self):
        reg = self._registry
        t0 = time.perf_counter()
        reg._reserve(self.nbytes, self.tier, self.label, exclude=self)
        try:
            tracer = reg._tracer
            if tracer is not None:
                with tracer.span("tpu.rehydrate", label=self.label,
                                 tier=self.tier, bytes=self.nbytes):
                    dev = self._place()
            else:
                dev = self._place()
        except Exception:
            # the reservation must not leak when the placement itself
            # fails (device OOM / transfer error) — repeated transient
            # failures would otherwise ratchet `used` into permanent
            # spurious trips
            reg._release(self.nbytes, self.tier)
            raise
        ns = int((time.perf_counter() - t0) * 1e9)
        with self._lock:
            if self._dev is None:
                self._dev = dev
                fresh = True
            else:  # lost a rehydrate race: keep the winner's copy
                dev = self._dev
                fresh = False
        if fresh:
            self.rehydrations += 1
            self._cell["resident"] = True
            reg._on_rehydrated(self, ns)
        else:
            reg._release(self.nbytes, self.tier)
        return dev

    def evict(self) -> bool:
        """Drop the device copy (host mirror retained); False when
        already evicted. Next ``get()`` rehydrates."""
        with self._lock:
            if self._dev is None:
                return False
            self._dev = None
        self.evictions += 1
        self._cell["resident"] = False
        self._registry._on_evicted(self)
        return True


class PinnedToken:
    """A pinned byte charge tied to a cache entry's lifetime: close()
    (or GC) releases it."""

    def __init__(self, registry: "ResidencyRegistry", nbytes: int,
                 label: str, tier: str):
        self.nbytes = int(nbytes)
        self.label = label
        self.tier = tier
        self._registry = registry
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._registry._untrack(self)

    def __del__(self):  # cache entry dropped without explicit close
        try:
            self.close()
        except Exception:
            pass


class ResidencyRegistry:
    """Per-node registry of device-resident allocations (one per
    process by default — the device is process-shared, so admission
    control must be too; in-process multi-node tests share it the same
    way they share the device)."""

    def __init__(self, breakers: CircuitBreakerService):
        self.breakers = breakers
        self._lock = threading.Lock()
        # id(handle) -> weakref; insertion order IS the LRU order
        self._lru: "OrderedDict[int, weakref.ref]" = OrderedDict()
        self._tracer = None
        self._tiers: Dict[str, Dict[str, int]] = {
            t: {"resident_bytes": 0, "handles": 0, "loads": 0,
                "evictions": 0, "rehydrations": 0,
                "rehydrate_time_in_nanos": 0}
            for t in TIERS}
        self._pinned_bytes = 0
        self._pinned_tokens = 0
        self._placements = 0
        self._placed_bytes_total = 0

    def set_tracer(self, tracer) -> None:
        """Adopt a node's tracer so rehydration spans land in its ring
        (in-process multi-node: last registration wins — rehydrates are
        process-wide events, same note as the shared registry)."""
        self._tracer = tracer

    # -- evictable handles --------------------------------------------------

    def put_array(self, host: np.ndarray, *, label: str,
                  tier: str = "fielddata", dtype: Any = None,
                  best_effort: bool = False) -> Optional[ResidentArray]:
        """Register ``host`` and place its device copy, charging the
        tier's breaker (evicting LRU peers under pressure). Raises
        CircuitBreakingException when nothing evictable covers the
        reservation — or returns None with ``best_effort=True`` (for
        pure accelerations like dense impact blocks, where the caller
        has a slower but correct path)."""
        handle = ResidentArray(self, host, label, tier, dtype=dtype)
        try:
            self._reserve(handle.nbytes, tier, label, exclude=handle)
        except CircuitBreakingException:
            self._drop(handle)
            if best_effort:
                return None
            raise
        try:
            dev = handle._place()
        except Exception:
            # reservation-leak guard, same as _rehydrate: a failed
            # allocation must release its breaker charge
            self._release(handle.nbytes, tier)
            self._drop(handle)
            raise
        with handle._lock:
            handle._dev = dev
        handle._cell["resident"] = True
        with self._lock:
            self._tiers[tier]["resident_bytes"] += handle.nbytes
            self._tiers[tier]["loads"] += 1
        return handle

    def _adopt(self, handle: ResidentArray) -> None:
        with self._lock:
            self._lru[id(handle)] = weakref.ref(handle)
            self._tiers[handle.tier]["handles"] += 1
        weakref.finalize(handle, self._on_gc, handle._cell)

    def _drop(self, handle: ResidentArray) -> None:
        # LRU removal only — the handle-count decrement stays with the
        # weakref.finalize callback (_on_gc), which fires exactly once
        with self._lock:
            self._lru.pop(handle._cell["key"], None)

    def _on_gc(self, cell: dict) -> None:
        with self._lock:
            self._lru.pop(cell["key"], None)
            t = self._tiers[cell["tier"]]
            t["handles"] -= 1
            if cell["resident"]:
                t["resident_bytes"] -= cell["nbytes"]
        if cell["resident"]:
            self.breakers.breaker(cell["tier"]).release(cell["nbytes"])

    def _touch(self, handle: ResidentArray) -> None:
        with self._lock:
            if id(handle) in self._lru:
                self._lru.move_to_end(id(handle))

    def _reserve(self, n: int, tier: str, label: str,
                 exclude: Optional[ResidentArray] = None) -> None:
        """Charge ``n`` against the tier's breaker, evicting LRU
        handles (any tier — they all share the parent) until it fits;
        raises the ES-shaped CircuitBreakingException when it can't."""
        FAULTS.check("resources.reserve", tier=tier, label=label, nbytes=n)
        br = self.breakers.breaker(tier)
        if br.reserve(n, count_trip=False):
            return
        for victim in self._victims(exclude):
            victim.evict()
            if br.reserve(n, count_trip=False):
                return
        br.break_or_reserve(n, label)  # counts the trip and raises

    def _victims(self, exclude: Optional[ResidentArray]) -> List[ResidentArray]:
        with self._lock:
            refs = list(self._lru.values())
        out = []
        for r in refs:  # oldest first
            h = r()
            if h is not None and h is not exclude and h.resident:
                out.append(h)
        return out

    def _release(self, n: int, tier: str) -> None:
        self.breakers.breaker(tier).release(n)

    def _on_evicted(self, handle: ResidentArray) -> None:
        self.breakers.breaker(handle.tier).release(handle.nbytes)
        with self._lock:
            t = self._tiers[handle.tier]
            t["resident_bytes"] -= handle.nbytes
            t["evictions"] += 1

    def _on_rehydrated(self, handle: ResidentArray, ns: int) -> None:
        with self._lock:
            t = self._tiers[handle.tier]
            t["resident_bytes"] += handle.nbytes
            t["rehydrations"] += 1
            t["rehydrate_time_in_nanos"] += ns
        from elasticsearch_tpu.tracing import profiler

        profiler.record_rehydrate(ns)

    def evict_all(self, tier: Optional[str] = None) -> int:
        """Force-evict every evictable handle (of ``tier``, or all) —
        operational pressure valve + the evict/rehydrate parity tests."""
        n = 0
        for h in self._victims(None):
            if tier is None or h.tier == tier:
                n += bool(h.evict())
        return n

    # -- pinned charges -----------------------------------------------------

    def track(self, nbytes: int, label: str,
              tier: str = "request") -> PinnedToken:
        self.breakers.breaker(tier).force(int(nbytes))
        tok = PinnedToken(self, nbytes, label, tier)
        with self._lock:
            self._pinned_bytes += tok.nbytes
            self._pinned_tokens += 1
        return tok

    def _untrack(self, tok: PinnedToken) -> None:
        self.breakers.breaker(tok.tier).release(tok.nbytes)
        with self._lock:
            self._pinned_bytes -= tok.nbytes
            self._pinned_tokens -= 1

    # -- accounted placement choke point ------------------------------------

    def device_put(self, x, *args, label: str = "", tier: str = "segments",
                   **kw):
        """``jax.device_put`` with placement accounting (cumulative —
        these arrays live exactly as long as their owners; the byte
        ceiling for them is the engine's per-segment breaker charge)."""
        dev = _jax_device_put(x, *args, **kw)
        n = int(getattr(dev, "nbytes", getattr(x, "nbytes", 0)) or 0)
        with self._lock:
            self._placements += 1
            self._placed_bytes_total += n
        return dev

    # -- stats --------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "tiers": {t: dict(c) for t, c in self._tiers.items()},
                "pinned": {"bytes": self._pinned_bytes,
                           "tokens": self._pinned_tokens},
                "device_put": {"placements": self._placements,
                               "bytes_total": self._placed_bytes_total},
            }
