"""Hierarchical circuit breakers for device memory (HBM).

Reference: org/elasticsearch/common/breaker/CircuitBreaker.java +
indices/breaker/HierarchyCircuitBreakerService.java — a parent breaker
caps the sum of its children (``fielddata``, ``request``,
``in_flight_requests``); each child has a dynamically-updatable
``limit`` and ``overhead`` (``indices.breaker.*`` settings), and
exceeding a limit fails the REQUEST with a typed
``CircuitBreakingException`` instead of OOMing the node.

TPU adaptation: the budgeted resource is device HBM, not JVM heap.
Percent limits resolve against ``ESTPU_HBM_BYTES`` (default 16 GiB —
deliberately static so the breaker works identically on CPU tier-1 runs
and real chips). One accelerator-extra child joins the ES trio:

  ``segments``  frozen-segment baseline structures (postings, live
                masks) charged at refresh/merge by the engine — the
                successor of the old ad-hoc ``SEGMENT_HBM_BUDGET``.

The ``fielddata`` child accounts every *lazily-loaded evictable* device
copy (doc-value columns, vector slabs, dense impact blocks) through
resources/residency.py, which evicts LRU copies under pressure before
letting the breaker trip.

Thread safety: one service-level RLock orders every child/parent check —
searches and refreshes charge concurrently under the threading REST
server.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from elasticsearch_tpu.utils.errors import CircuitBreakingException


def hbm_capacity() -> int:
    """The byte base percent limits resolve against. Env-pinned rather
    than read from the device so limits are deterministic across
    CPU/TPU and across restarts (the reference resolves against -Xmx,
    which is equally static)."""
    env = os.environ.get("ESTPU_HBM_BYTES")
    if env:
        return int(env)
    return 16 << 30


def parse_limit(v, capacity: Optional[int] = None) -> int:
    """ES byte-size grammar → bytes: int, "512mb", "2gb", "60%", -1
    (= unlimited, like the reference's -1 parent limit)."""
    if v is None:
        raise ValueError("limit must not be None")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return int(v)
    s = str(v).strip().lower()
    if s in ("-1", "none", "unbounded"):
        return -1
    if s.endswith("%"):
        pct = float(s[:-1])
        if not 0 <= pct <= 100:
            raise ValueError(f"percent limit out of range [{v}]")
        return int((capacity if capacity is not None else hbm_capacity())
                   * pct / 100.0)
    for suf, mul in (("pb", 1 << 50), ("tb", 1 << 40), ("gb", 1 << 30),
                     ("mb", 1 << 20), ("kb", 1 << 10), ("b", 1)):
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mul)
    return int(float(s))


def human_bytes(n: int) -> str:
    """ES-style byte rendering ("512.0kb") — shared by breaker stats and
    the serving QoS layer's "Data too large" messages."""
    if n < 0:
        return "-1b"
    f = float(n)
    for suf in ("b", "kb", "mb", "gb", "tb"):
        if f < 1024 or suf == "tb":
            return f"{f:.1f}{suf}" if suf != "b" else f"{int(f)}b"
        f /= 1024
    return f"{int(n)}b"


_human = human_bytes  # module-internal call sites predate the public name


class CircuitBreaker:
    """One named byte budget. Usable standalone (the old ``HbmBudget``
    contract: reserve/force/release/used/total) or registered in a
    :class:`CircuitBreakerService`, where every reservation also checks
    the parent's combined limit."""

    def __init__(self, name: str, limit: int, overhead: float = 1.0,
                 service: Optional["CircuitBreakerService"] = None):
        self.name = name
        self.limit = int(limit)
        self.overhead = float(overhead)
        self.used = 0
        self.trip_count = 0
        self._service = service
        self._lock = service._lock if service is not None \
            else threading.RLock()

    # -- HbmBudget-compatible surface ---------------------------------------

    @property
    def total(self) -> int:
        return self.limit

    def remaining(self) -> int:
        with self._lock:
            if self.limit < 0:
                return 1 << 62
            return max(0, int(self.limit / max(self.overhead, 1e-9))
                       - self.used)

    def _would_trip(self, n: int) -> bool:
        return self.limit >= 0 and (self.used + n) * self.overhead > self.limit

    def reserve(self, n: int, count_trip: bool = True) -> bool:
        """Charge ``n`` bytes; False (and a ``tripped`` tick) when this
        breaker's or the parent's limit would be exceeded."""
        parent = False
        with self._lock:
            if self._would_trip(n):
                if count_trip:
                    self.trip_count += 1
            elif self._service is not None \
                    and self._service._parent_would_trip(n):
                if count_trip:
                    self._service.parent_tripped += 1
                    self.trip_count += 1
                parent = True
            else:
                self.used += n
                return True
            used, limit = self.used, self.limit
        # flight-recorder entry OUTSIDE the breaker lock (no new
        # lock-order edges, R013): a trip is an admission anomaly worth
        # black-box evidence even when the caller degrades gracefully
        if count_trip:
            try:
                from elasticsearch_tpu.monitor import flight

                flight.record("breaker_trips", breaker=self.name,
                              parent=parent, bytes_wanted=used + n,
                              bytes_limit=limit)
            except Exception:  # tpulint: allow[R006] — recording must
                pass           # never turn a clean denial into an error
        return False

    def break_or_reserve(self, n: int, label: str = "<unknown>") -> None:
        """reserve() or raise the ES-shaped CircuitBreakingException."""
        if self.reserve(n):
            return
        with self._lock:
            used, limit = self.used, self.limit
        raise CircuitBreakingException(
            f"[{self.name}] Data too large, data for [{label}] would be "
            f"[{used + n}/{_human(used + n)}] bytes, which is larger than "
            f"the limit of [{limit}/{_human(limit)}]",
            bytes_wanted=used + n, bytes_limit=limit)

    def force(self, n: int) -> None:
        """Unconditional charge — for paths that net-release memory and
        must never fail on transient accounting order (merges, tracked
        executor caches)."""
        with self._lock:
            self.used += n

    def release(self, n: int) -> None:
        with self._lock:
            self.used = max(0, self.used - n)

    def stats(self) -> dict:
        with self._lock:
            return {
                "limit_size_in_bytes": self.limit,
                "limit_size": _human(self.limit),
                "estimated_size_in_bytes": self.used,
                "estimated_size": _human(self.used),
                "overhead": self.overhead,
                "tripped": self.trip_count,
            }


class HbmBudget(CircuitBreaker):
    """Back-compat constructor for the pre-resources ad-hoc budget
    (tests and embedders build ``HbmBudget(total_bytes=...)``)."""

    def __init__(self, total_bytes: int = 2 << 30):
        super().__init__("adhoc", total_bytes)


#: (child name, default limit spec, default overhead, settings key prefix)
_DEFAULTS = (
    ("fielddata", "60%", 1.03, "indices.breaker.fielddata."),
    ("request", "40%", 1.0, "indices.breaker.request."),
    ("in_flight_requests", "100%", 1.0,
     "network.breaker.inflight_requests."),
    ("segments", None, 1.0, "indices.breaker.segments."),
)


def _segments_default() -> int:
    # honors the pre-resources env knob so existing deployments keep
    # their configured segment budget
    return int(os.environ.get("ESTPU_SEGMENT_BUDGET_BYTES", 8 << 30))


class CircuitBreakerService:
    """The breaker hierarchy: parent + named children, ES-shaped stats,
    dynamic ``indices.breaker.*`` / ``network.breaker.*`` settings."""

    PARENT_KEY = "indices.breaker.total.limit"

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.RLock()
        self.capacity = capacity if capacity is not None else hbm_capacity()
        self.parent_limit = parse_limit("70%", self.capacity)
        self.parent_tripped = 0
        self._children: Dict[str, CircuitBreaker] = {}
        for name, limit, overhead, _prefix in _DEFAULTS:
            lb = (_segments_default() if limit is None
                  else parse_limit(limit, self.capacity))
            self._children[name] = CircuitBreaker(name, lb, overhead,
                                                  service=self)

    def breaker(self, name: str) -> CircuitBreaker:
        return self._children[name]

    def _parent_would_trip(self, n: int) -> bool:
        # caller holds self._lock (children share it)
        if self.parent_limit < 0:
            return False
        return sum(c.used for c in self._children.values()) + n \
            > self.parent_limit

    def apply_cluster_settings(self, flat: Dict[str, object]) -> None:
        """Apply the MERGED persistent+transient cluster settings map:
        a present key sets, an absent key resets to the default —
        idempotent from the full map, so setting deletion (PUT with
        null) needs no special casing at the call site."""
        with self._lock:
            v = flat.get(self.PARENT_KEY)
            self.parent_limit = (parse_limit(v, self.capacity)
                                 if v is not None
                                 else parse_limit("70%", self.capacity))
            for name, limit, overhead, prefix in _DEFAULTS:
                br = self._children[name]
                lv = flat.get(prefix + "limit")
                if lv is not None:
                    br.limit = parse_limit(lv, self.capacity)
                else:
                    br.limit = (_segments_default() if limit is None
                                else parse_limit(limit, self.capacity))
                ov = flat.get(prefix + "overhead")
                br.overhead = float(ov) if ov is not None else overhead

    def hbm_usage(self) -> "tuple[int, int]":
        """``(used_bytes, capacity_bytes)`` snapshot for watermark
        reads: the parent's combined child bytes (which already include
        device-resident residency charges) over the capacity limits
        resolve against. One locked sum instead of the full ``stats()``
        render — the allocator probes this on every usage refresh and
        the disk-watermark deciders compare it against the
        ``cluster.routing.allocation.disk.watermark.*`` thresholds."""
        with self._lock:
            return (sum(c.used for c in self._children.values()),
                    self.capacity)

    def stats(self) -> dict:
        """``/_nodes/stats/breaker`` section (reference:
        AllCircuitBreakerStats.toXContent shape)."""
        with self._lock:
            out = {name: br.stats() for name, br in self._children.items()}
            est = sum(br.used for br in self._children.values())
            out["parent"] = {
                "limit_size_in_bytes": self.parent_limit,
                "limit_size": _human(self.parent_limit),
                "estimated_size_in_bytes": est,
                "estimated_size": _human(est),
                "overhead": 1.0,
                "tripped": self.parent_tripped,
            }
            return out
