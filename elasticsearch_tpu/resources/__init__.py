"""Device-memory resource management: breakers + tiered residency.

One subsystem, two halves (see docs/RESOURCES.md):

- :mod:`breakers` — ES-shaped hierarchical circuit breakers (parent,
  fielddata, request, in_flight_requests + the accelerator-extra
  ``segments``), dynamically updatable via ``indices.breaker.*`` /
  ``network.breaker.*`` cluster settings, surfaced at
  ``/_nodes/stats/breaker``.
- :mod:`residency` — the per-node registry accounting every
  device-resident allocation through one choke point, with LRU
  eviction + transparent rehydration for the lazily-loaded tier.

``BREAKERS``/``RESIDENCY`` are the process singletons (the device is
process-shared, so admission control is too). Always access them as
``resources.BREAKERS`` attributes — tests swap them for isolated
instances.

Import cost: no jax at import time (jax loads lazily on first device
placement), so the transport/tooling layers can import this freely.
"""
from __future__ import annotations

from elasticsearch_tpu.resources.breakers import (CircuitBreaker,
                                                  CircuitBreakerService,
                                                  HbmBudget, hbm_capacity,
                                                  parse_limit)
from elasticsearch_tpu.resources.residency import (PinnedToken,
                                                   ResidencyRegistry,
                                                   ResidentArray)

__all__ = [
    "BREAKERS", "RESIDENCY", "CircuitBreaker", "CircuitBreakerService",
    "HbmBudget", "PinnedToken", "ResidencyRegistry", "ResidentArray",
    "hbm_capacity", "parse_limit", "apply_cluster_settings",
]

#: process-global breaker hierarchy + residency registry
BREAKERS = CircuitBreakerService()
RESIDENCY = ResidencyRegistry(BREAKERS)


def apply_cluster_settings(flat: dict) -> None:
    """Apply the merged cluster-settings map to the LIVE service (the
    attribute, not the import-time binding — tests swap BREAKERS)."""
    import elasticsearch_tpu.resources as _self

    _self.BREAKERS.apply_cluster_settings(flat)
