"""Per-index device-program key census: persistence + replay.

The program observatory (monitor/programs.py) learns, per index, exactly
which (program, shapes, field) keys its traffic exercises — the padded
shape classes the pow2 discipline bounds. This module persists that set
through the content-addressed blob cache's durable tier (beside the
IVF/PQ artifacts, ``<key>.census`` files in every registered data
directory), so a restarted node can know, before serving a single
request, the complete program universe its index needs.

That is the pre-warm contract ROADMAP #6 (zero-warmup serving) consumes:
replay the census against a persistent compiled-program cache and the
first request after a restart/relocation pays zero compiles. Until that
cache exists, :func:`replay` already answers the operational question —
which census keys are warm in the live registry and which would compile
on first touch — and the acceptance tests use it to prove a served
key set round-trips exactly.

Format: ``sha1-hex\\n{json}`` — the digest makes corruption (torn write,
disk bitrot) a *detected* miss: a bad blob is deleted and the caller
falls back to cold-start, never to a crash or a silently wrong key set.
The payload carries the backend fingerprint, so a census captured on one
chip generation is never replayed against another.

Import cost: no jax at import time (resources/ package contract).
"""
from __future__ import annotations

import hashlib
from typing import List, Optional

_EXT = "census"
VERSION = 1


def census_key(index_name: str) -> str:
    """Blob-cache key for an index's census (name-addressed: unlike the
    IVF/PQ slabs there is no content to address — the census IS the
    content, validated by its embedded digest)."""
    return "census_" + hashlib.sha1(index_name.encode("utf-8")).hexdigest()


def store_census(index_name: str,
                 keys: Optional[List[dict]] = None) -> Optional[bytes]:
    """Persist ``index_name``'s observed key set (default: the live
    registry's census). Returns the encoded blob, or None when the index
    has no observed keys (nothing to pre-warm — don't overwrite a
    previous census with emptiness on an idle restart)."""
    from elasticsearch_tpu.index import ivf_cache
    from elasticsearch_tpu.monitor import programs

    if keys is None:
        keys = programs.REGISTRY.census(index_name)
    if not keys:
        return None
    payload = {
        "version": VERSION,
        "index": index_name,
        "backend": programs.backend_fingerprint(),
        "keys": keys,
    }
    # the generic tier's shared digest frame (ivf_cache.frame_blob) —
    # census and incident blobs stay format-identical by construction
    blob = ivf_cache.frame_blob(payload)
    ivf_cache.store_blob(census_key(index_name), blob, _EXT)
    return blob


def load_census(index_name: str) -> Optional[dict]:
    """The persisted census payload for ``index_name`` or None. A
    corrupt blob (digest mismatch, bad JSON, wrong shape) is deleted and
    treated as a miss — the observatory re-learns the keys from traffic
    and the next store replaces it."""
    from elasticsearch_tpu.index import ivf_cache

    key = census_key(index_name)
    blob = ivf_cache.load_blob(key, _EXT)
    if blob is None:
        return None
    payload = ivf_cache.unframe_blob(blob)
    if (payload is None
            or payload.get("version") != VERSION
            or payload.get("index") != index_name
            or not isinstance(payload.get("keys"), list)):
        ivf_cache.delete_blob(key, _EXT)
        return None
    return payload


def replay(index_name: str) -> dict:
    """Replay the persisted census against the LIVE program registry:
    which keys are already warm (present in the registry — their
    programs exist in this process's jit caches) and which are missing
    (would compile on first touch). ``missing`` is exactly the pre-warm
    work list ROADMAP #6's compiled-program cache will consume; today it
    is the restart-cliff report."""
    from elasticsearch_tpu.monitor import programs

    payload = load_census(index_name)
    if payload is None:
        return {"found": False, "index": index_name}
    live = {(r["program"], r["shapes"])
            for r in programs.REGISTRY.snapshot()}
    missing = [k for k in payload["keys"]
               if (k.get("program"), k.get("shapes")) not in live]
    fp = programs.backend_fingerprint()
    return {
        "found": True,
        "index": index_name,
        "backend": payload.get("backend"),
        "backend_matches": payload.get("backend") == fp,
        "total": len(payload["keys"]),
        "warm": len(payload["keys"]) - len(missing),
        "missing": missing,
    }
