"""Per-index device-program key census: persistence + replay.

The program observatory (monitor/programs.py) learns, per index, exactly
which (program, shapes, field) keys its traffic exercises — the padded
shape classes the pow2 discipline bounds — and, since ISSUE 14, how HOT
each key is and which canonical search bodies drove them. This module
persists that set through the content-addressed blob cache's durable
tier (beside the IVF/PQ artifacts, ``<key>.census`` files in every
registered data directory), so a restarted node can know, before serving
a single request, the complete program universe its index needs — and
replay it.

That is the pre-warm contract ROADMAP #6 (zero-warmup serving) consumes
(serving/warmup.py): replay the census bodies through the real search
path — which drives the real executor program factories and the AOT
executable cache (parallel/aot.py) — hottest first, and the first
request after a restart/relocation pays zero compiles. :func:`replay`
answers the verification question: which census keys are warm in the
live registry and which would still compile on first touch.

Format v2: ``sha1-hex\\n{json}`` with ``keys`` rows carrying per-key
``hits`` (warmup ordering) and a bounded ``bodies`` list of canonical
request bodies with their own hit counts (the replayable half — a
compiled DSL tree cannot be rebuilt from arg shapes alone). v1 blobs
(PR 11) still load: their keys get ``hits: 1`` and no bodies. The digest
makes corruption (torn write, disk bitrot) a *detected* miss: a bad blob
is deleted and the caller falls back to cold-start, never to a crash or
a silently wrong key set. The payload carries the backend fingerprint,
so a census captured on one chip generation is never replayed against
another.

Durability (ISSUE 14 satellite): :func:`store_census` MERGES with the
persisted census (key/body union, per-entry ``max`` of hit counts — max,
not sum, so repeated periodic flushes never double-count) and is called
from three places: the watchdog tick (crash durability — a kill no
longer loses the work list), shard assignment/recovery graduation, and
``Node.close()``.

Import cost: no jax at import time (resources/ package contract).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

_EXT = "census"
VERSION = 2

#: persisted-blob caps, mirroring the in-memory registry caps
#: (programs.ProgramRegistry._CENSUS_CAP / _BODY_CAP): merge-on-store
#: would otherwise grow the blob by up to one process's worth of new
#: entries per generation, forever — the hottest rows survive the cut,
#: which is exactly the set warmup consumes
KEY_CAP = 1024
BODY_CAP = 64


def census_key(index_name: str) -> str:
    """Blob-cache key for an index's census (name-addressed: unlike the
    IVF/PQ slabs there is no content to address — the census IS the
    content, validated by its embedded digest)."""
    return "census_" + hashlib.sha1(index_name.encode("utf-8")).hexdigest()


def _key_id(row: dict) -> Tuple[str, str, str]:
    return (str(row.get("program", "")), str(row.get("shapes", "")),
            str(row.get("field", "")))


#: indices whose persisted census this process has already decayed once
#: (the decay is per RESTART, not per periodic flush — within one
#: process, live counts are cumulative and plain max is correct)
_DECAYED: set = set()


def _merge_rows(persisted: List[dict], live: List[dict],
                ident, decay: bool = False) -> List[dict]:
    """Union by identity, ``hits`` = max(persisted, live): monotone under
    repeated flushes (a periodic flush must never double-count the hits
    the previous flush already persisted) and never forgets a key the
    current process simply hasn't served yet.

    ``decay`` (set on the first merge of each process): persisted rows
    NOT reinforced by live traffic halve their hits. Without it, a
    workload that shifted would be pinned forever — old maxima always
    out-rank a fresh process's young counts, so the hottest-first cap
    cut would keep evicting the NEW workload and pre-warm would replay
    obsolete queries on every restart. Halving per restart lets a
    genuinely dead body fall out of the capped set in a handful of
    generations while one idle restart barely dents a hot one."""
    merged: Dict[object, dict] = {}
    for row in persisted:
        r = dict(row)
        r["hits"] = int(r.get("hits", 1))
        merged[ident(r)] = r
    live_ids = set()
    for row in live:
        r = dict(row)
        r["hits"] = int(r.get("hits", 1))
        live_ids.add(ident(r))
        prev = merged.get(ident(r))
        if prev is None or r["hits"] > prev.get("hits", 1):
            merged[ident(r)] = r
    if decay:
        for key, r in merged.items():
            if key not in live_ids:
                r["hits"] = max(1, r["hits"] // 2)
    return sorted(merged.values(),
                  key=lambda r: (-r.get("hits", 1), str(sorted(r.items()))))


def store_census(index_name: str,
                 keys: Optional[List[dict]] = None,
                 bodies: Optional[List[dict]] = None,
                 merge: bool = True) -> Optional[bytes]:
    """Persist ``index_name``'s observed key set + replayable bodies
    (default: the live registry's census). Returns the encoded blob, or
    None when there is nothing to persist (nothing to pre-warm — don't
    overwrite a previous census with emptiness on an idle restart).
    ``merge`` folds the previously persisted census in (see module
    docstring); explicit-keys callers can pass ``merge=False`` for the
    overwrite semantics tests rely on."""
    from elasticsearch_tpu.index import ivf_cache
    from elasticsearch_tpu.monitor import programs

    if keys is None:
        keys = programs.REGISTRY.census(index_name)
    if bodies is None:
        bodies = programs.REGISTRY.bodies(index_name)
    if merge:
        prev = load_census(index_name)
        if prev is not None:
            decay = index_name not in _DECAYED
            _DECAYED.add(index_name)
            keys = _merge_rows(prev.get("keys", []), keys, _key_id,
                               decay=decay)
            bodies = _merge_rows(prev.get("bodies", []), bodies,
                                 lambda r: r.get("body"), decay=decay)
    # bound the persisted union (hottest-first order from _merge_rows):
    # without the cut, N restarts of a shifting workload grow the blob
    # O(N·cap) while warmup only ever reads the top rows
    keys = keys[:KEY_CAP]
    bodies = bodies[:BODY_CAP]
    if not keys and not bodies:
        return None
    payload = {
        "version": VERSION,
        "index": index_name,
        "backend": programs.backend_fingerprint(),
        "keys": keys,
        "bodies": bodies,
    }
    # the generic tier's shared digest frame (ivf_cache.frame_blob) —
    # census and incident blobs stay format-identical by construction
    blob = ivf_cache.frame_blob(payload)
    ivf_cache.store_blob(census_key(index_name), blob, _EXT)
    return blob


def export_census(index_name: str) -> Optional[dict]:
    """The census payload for SHIPPING (shard-relocation streams, PR 14's
    stated residual): the persisted census merged with the live
    registry's, capped like a stored blob — but with no store, no decay
    bookkeeping, and no digest frame (the transport layer owns transfer
    integrity). None when there is nothing worth shipping."""
    from elasticsearch_tpu.monitor import programs

    keys = programs.REGISTRY.census(index_name)
    bodies = programs.REGISTRY.bodies(index_name)
    prev = load_census(index_name)
    if prev is not None:
        keys = _merge_rows(prev.get("keys", []), keys, _key_id)
        bodies = _merge_rows(prev.get("bodies", []), bodies,
                             lambda r: r.get("body"))
    keys = keys[:KEY_CAP]
    bodies = bodies[:BODY_CAP]
    if not keys and not bodies:
        return None
    return {
        "version": VERSION,
        "index": index_name,
        "backend": programs.backend_fingerprint(),
        "keys": keys,
        "bodies": bodies,
    }


def adopt_census(index_name: str, payload) -> bool:
    """Adopt a census shipped beside a shard-relocation stream: validate
    the payload shape, refuse a foreign backend fingerprint (the same
    honesty rule warmup applies at replay time — a census captured on
    another chip generation must not be persisted as this node's), and
    MERGE it into the locally persisted census so the relocation target
    can pre-warm before its first request. Returns True when adopted."""
    from elasticsearch_tpu.monitor import programs

    if not isinstance(payload, dict) \
            or payload.get("index") != index_name \
            or payload.get("version") not in (1, VERSION):
        return False
    keys = payload.get("keys")
    bodies = payload.get("bodies", [])
    if not isinstance(keys, list) or not isinstance(bodies, list):
        return False
    if payload.get("backend") != programs.backend_fingerprint():
        return False

    def _rows(rows, need=None):
        # per-row defensive coercion: one malformed row from a skewed
        # source (hits: null, "1.5") is SKIPPED, never raised — a raise
        # here would collaterally cancel the caller's census flush and
        # pre-warm kick for the whole shard graduation
        out = []
        for r in rows:
            if not isinstance(r, dict) or (need and not r.get(need)):
                continue
            try:
                out.append(dict(r, hits=int(r.get("hits", 1))))
            except (TypeError, ValueError):
                continue
        return out

    keys = _rows(keys)
    bodies = _rows(bodies, need="body")
    if not keys and not bodies:
        return False
    store_census(index_name, keys=keys, bodies=bodies, merge=True)
    return True


def load_census(index_name: str) -> Optional[dict]:
    """The persisted census payload for ``index_name`` or None. A
    corrupt blob (digest mismatch, bad JSON, wrong shape) is deleted and
    treated as a miss — the observatory re-learns the keys from traffic
    and the next store replaces it. v1 payloads (PR 11) normalize to the
    v2 shape (hits=1, no bodies)."""
    from elasticsearch_tpu.index import ivf_cache

    key = census_key(index_name)
    blob = ivf_cache.load_blob(key, _EXT)
    if blob is None:
        return None
    payload = ivf_cache.unframe_blob(blob)
    if (payload is None
            or payload.get("version") not in (1, VERSION)
            or payload.get("index") != index_name
            or not isinstance(payload.get("keys"), list)
            or not isinstance(payload.get("bodies", []), list)):
        ivf_cache.delete_blob(key, _EXT)
        return None
    if payload.get("version") == 1:
        payload = dict(payload, version=VERSION, bodies=[],
                       keys=[dict(k, hits=int(k.get("hits", 1)))
                             for k in payload["keys"]])
    else:
        payload.setdefault("bodies", [])
    return payload


def replay(index_name: str) -> dict:
    """Replay the persisted census against the LIVE program registry:
    which keys are already warm (present in the registry — their
    programs exist in this process's jit caches or resolved through the
    AOT executable cache) and which are missing (would compile on first
    touch). ``missing`` is the warmup verification list; ``bodies`` is
    the replayable work list serving/warmup.py consumes, hottest
    first."""
    from elasticsearch_tpu.monitor import programs

    payload = load_census(index_name)
    if payload is None:
        return {"found": False, "index": index_name}
    live = {(r["program"], r["shapes"])
            for r in programs.REGISTRY.snapshot()}
    missing = [k for k in payload["keys"]
               if (k.get("program"), k.get("shapes")) not in live]
    fp = programs.backend_fingerprint()
    return {
        "found": True,
        "index": index_name,
        "backend": payload.get("backend"),
        "backend_matches": payload.get("backend") == fp,
        "total": len(payload["keys"]),
        "warm": len(payload["keys"]) - len(missing),
        "missing": missing,
        "bodies": payload.get("bodies", []),
    }
