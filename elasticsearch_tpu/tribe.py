"""Tribe node — documented stub (SURVEY §2.11).

Reference: org/elasticsearch/tribe/TribeService.java — a tribe node joins
MULTIPLE clusters as a read-only member and merges their cluster states
into one view. This rebuild's multi-host layer (cluster/bootstrap.py) is a
single-cluster control plane; federating several of them is out of scope
and this module says so explicitly instead of half-working.

What exists today: `TribeNode.search_remote` fans a search out to a list
of remote REST endpoints with the plain HTTP client and merges hit lists
client-side — the read-only core of the tribe use case — while cluster
state federation (the hard part: conflicting index names, routing merge)
raises NotImplementedError with the reference pointer.
"""
from __future__ import annotations

from typing import Dict, List

from elasticsearch_tpu.client import Client


class TribeNode:
    def __init__(self, endpoints: List[str]):
        self.clients = [Client(url=url) for url in endpoints]

    def search_remote(self, index: str, body: dict, size: int = 10) -> dict:
        """Scatter a search to every remote cluster, merge by _score. Each
        remote is asked for the full merged window — a cluster's 11th-best
        hit may be the tribe's 3rd."""
        hits: List[dict] = []
        total = 0
        # one window everywhere: what we ask each remote for is what the
        # caller gets back (size param or body size, whichever is larger)
        size = max(size, int(body.get("size", 10)))
        remote_body = {**body, "size": size}
        for c in self.clients:
            r = c.search(index=index, body=remote_body)
            total += r["hits"]["total"]
            hits.extend(r["hits"]["hits"])
        hits.sort(key=lambda h: -(h.get("_score") or 0.0))
        return {"hits": {"total": total, "hits": hits[:size]}}

    def merged_cluster_state(self) -> Dict:
        raise NotImplementedError(
            "tribe cluster-state federation is not implemented (reference: "
            "tribe/TribeService.java — on-conflict index preference, merged "
            "routing); use search_remote for the read-only fan-out")
