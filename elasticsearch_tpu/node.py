"""Node: the top-level runtime holding indices, cluster state, templates.

Reference: org/elasticsearch/node/Node.java + node/internal/InternalNode.java
(service wiring), action/admin/indices/create/TransportCreateIndexAction.java
(template application order), action/bulk/TransportBulkAction.java (bulk
fan-out), action/search/TransportMultiSearchAction.java.
"""
from __future__ import annotations

import fnmatch
import json
import os
import re
import uuid
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.cluster.state import ClusterState, DiscoveryNode, IndexMetadata
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.utils.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
    IndexAlreadyExistsException,
    IndexNotFoundException,
)
from elasticsearch_tpu import __version__


class Node:
    def __init__(self, name: str = "node-1", data_path: Optional[str] = None,
                 cluster_name: str = "elasticsearch_tpu"):
        self.node_id = uuid.uuid4().hex[:12]
        self.name = name
        self.data_path = data_path
        self.indices: Dict[str, IndexService] = {}
        # stored search templates (reference keeps these in the .scripts
        # index; node-local registry here)
        self.search_templates: Dict[str, Any] = {}
        self.search_template_versions: Dict[str, int] = {}
        # snapshot repositories (reference: RepositoriesService)
        self.repositories: Dict[str, Any] = {}
        # dynamic cluster settings (reference: ClusterUpdateSettingsRequest
        # persistent/transient maps); stored keys are surfaced via
        # GET /_cluster/settings
        self.cluster_settings: Dict[str, Dict[str, Any]] = {
            "persistent": {}, "transient": {}}
        self.cluster_state = ClusterState(cluster_name)
        self.cluster_state.add_node(DiscoveryNode(self.node_id, name), master=True)
        # observability: span tracer + task registry (reference: the
        # TaskManager every TransportService carries; tracing/__init__.py)
        from elasticsearch_tpu.tracing import TaskRegistry, Tracer

        self.tasks = TaskRegistry(self.node_id)
        self.tracer = Tracer(self.node_id)
        # continuous metrics (monitor/metrics.py): a per-NODE registry —
        # REST latency, span histograms, indexing — plus scrape-time
        # collectors over the process-shared subsystems; every finished
        # span feeds a latency histogram via the tracer sink, so PR 4's
        # instrumentation became time-series without new call sites
        from elasticsearch_tpu.monitor.metrics import (MetricsRegistry,
                                                       span_sink)

        self.metrics = MetricsRegistry(include_shared=True)
        self.tracer.set_sink(span_sink(self.metrics))
        self._register_metric_collectors()
        # flight recorder + stall watchdog (monitor/flight.py,
        # monitor/watchdog.py): the recorder is registered with the
        # process fan so node-less subsystems (breakers, engines) reach
        # it; the watchdog's tick thread is lazy — serving entry points
        # (RestServer.start, cluster bootstrap) call ensure_started()
        from elasticsearch_tpu.monitor import flight as flight_mod
        from elasticsearch_tpu.monitor.watchdog import WatchdogService

        self.flight = flight_mod.FlightRecorder(self.node_id, name)
        flight_mod.register(self.flight)
        self.watchdog = WatchdogService(self)
        # serving front-end: cross-request micro-batching + per-tenant
        # QoS (serving/). Cheap to build — the drain thread is lazy, so
        # library-embedded Nodes that never coalesce don't pay for it.
        from elasticsearch_tpu.serving import ServingFrontend

        self.serving = ServingFrontend(self)
        # resource management: rehydration spans (tpu.rehydrate) land in
        # this node's tracer ring (process-shared registry — the device
        # is process-shared too; last in-process node wins)
        from elasticsearch_tpu import resources

        resources.RESIDENCY.set_tracer(self.tracer)
        # lazy: pools spin worker threads, so library-embedded Nodes that
        # never serve REST traffic don't pay for them
        self._thread_pool = None
        self._tp_lock = __import__("threading").Lock()
        self._ivf_dir = None
        if data_path:
            # durable ANN tier must be visible BEFORE replay freezes
            # segments, or recovery pays the k-means the cache holds
            from elasticsearch_tpu.index import ivf_cache

            self._ivf_dir = os.path.join(data_path, "_ivf")
            ivf_cache.register(self._ivf_dir)
            self._gateway_recover()

    @property
    def thread_pool(self):
        """Named request pools (reference: threadpool/ThreadPool.java).
        Double-checked under a lock — concurrent first REST requests must
        not each spin a registry of worker threads."""
        if self._thread_pool is None:
            from elasticsearch_tpu.utils.threadpool import ThreadPool

            with self._tp_lock:
                if self._thread_pool is None:
                    self._thread_pool = ThreadPool()
        return self._thread_pool

    def _register_metric_collectors(self) -> None:
        """Scrape-time gauge/counter families over state that is already
        counted elsewhere — threadpool queues, breaker bytes, residency
        tiers, kernel dispatch, jit traces. Re-counting these on every
        record would double-lock hot paths; reading them at scrape time
        costs one request per scrape instead."""
        m = self.metrics

        def _pools():
            tp = self._thread_pool
            return tp.stats().items() if tp is not None else ()

        m.collector("estpu_threadpool_queue_depth",
                    "Queued work items per named thread pool", ("pool",),
                    lambda: [((n,), st["queue"]) for n, st in _pools()])
        m.collector("estpu_threadpool_active",
                    "Active workers per named thread pool", ("pool",),
                    lambda: [((n,), st["active"]) for n, st in _pools()])
        m.collector("estpu_threadpool_rejected_total",
                    "Work rejected by a full queue, per pool", ("pool",),
                    lambda: [((n,), st["rejected"]) for n, st in _pools()],
                    kind="counter")
        m.collector("estpu_threadpool_completed_total",
                    "Work completed per named thread pool", ("pool",),
                    lambda: [((n,), st["completed"]) for n, st in _pools()],
                    kind="counter")

        def _breakers():
            from elasticsearch_tpu import resources

            return resources.BREAKERS.stats().items()

        m.collector("estpu_breaker_used_bytes",
                    "Estimated bytes held per circuit breaker",
                    ("breaker",),
                    lambda: [((n,), br["estimated_size_in_bytes"])
                             for n, br in _breakers()])
        m.collector("estpu_breaker_limit_bytes",
                    "Configured byte limit per circuit breaker",
                    ("breaker",),
                    lambda: [((n,), br["limit_size_in_bytes"])
                             for n, br in _breakers()])
        m.collector("estpu_breaker_tripped_total",
                    "Trips per circuit breaker", ("breaker",),
                    lambda: [((n,), br["tripped"]) for n, br in _breakers()],
                    kind="counter")

        def _tiers():
            from elasticsearch_tpu import resources

            return resources.RESIDENCY.stats()["tiers"].items()

        m.collector("estpu_residency_tier_bytes",
                    "Device-resident bytes per residency tier", ("tier",),
                    lambda: [((t,), st["resident_bytes"])
                             for t, st in _tiers()])
        m.collector("estpu_residency_evictions_total",
                    "Device-copy evictions per residency tier", ("tier",),
                    lambda: [((t,), st["evictions"]) for t, st in _tiers()],
                    kind="counter")
        m.collector("estpu_residency_rehydrations_total",
                    "Evicted-copy rehydrations per residency tier",
                    ("tier",),
                    lambda: [((t,), st["rehydrations"])
                             for t, st in _tiers()],
                    kind="counter")

        def _kernels():
            from elasticsearch_tpu.monitor import kernels

            return kernels.snapshot().items()

        m.collector("estpu_kernel_dispatch_total",
                    "Requests served per device kernel / dispatch "
                    "decision (monitor/kernels.py names)", ("kernel",),
                    lambda: [((k,), v) for k, v in _kernels()],
                    kind="counter")

        def _jit_traces():
            from elasticsearch_tpu.tracing import retrace

            a = retrace.auditor()
            # 0 when the auditor never installed: the exposition needs a
            # stable family; /_nodes profiles keep the honest -1 sentinel
            return [((), a.total() if a is not None else 0)]

        m.collector("estpu_jit_traces_total",
                    "jax.jit traces (compilations) recorded by the "
                    "trace auditor since process start", (),
                    _jit_traces, kind="counter")

        # device-program observatory (monitor/programs.py): per-key
        # compile/execute attribution. Cardinality is bounded by the
        # registry's own key cap (pow2 padding keeps the real universe
        # small; overflow collapses into the reserved _other_ row), so
        # these scrape-time families inherit the cap. The counters view
        # skips percentile math — the full snapshot() is for the REST
        # table, not a 15s-interval scrape — and a short memo lets ONE
        # registry walk serve all three families of a scrape (the three
        # collect() calls land within one render; counters may lag a
        # fraction of a second, which a 15s scrape cannot observe).
        _prog_memo = {"t": float("-inf"), "rows": ()}

        def _programs():
            import time as _time

            from elasticsearch_tpu.monitor import programs

            now = _time.monotonic()
            if now - _prog_memo["t"] > 0.2:
                _prog_memo["rows"] = programs.REGISTRY.counters_snapshot()
                _prog_memo["t"] = now
            return _prog_memo["rows"]

        m.collector("estpu_program_compiles_total",
                    "jit compiles per (program, shapes, backend) key",
                    ("program", "shapes", "backend"),
                    lambda: [((p, s, b), compiles)
                             for p, s, b, compiles, _cs, _es
                             in _programs()],
                    kind="counter")
        m.collector("estpu_program_compile_seconds",
                    "Wall seconds spent in calls that paid tracing + "
                    "compilation, per program key",
                    ("program", "shapes", "backend"),
                    lambda: [((p, s, b), cs)
                             for p, s, b, _c, cs, _es in _programs()],
                    kind="counter")
        m.collector("estpu_program_execute_seconds",
                    "Wall seconds spent executing cached programs, per "
                    "program key",
                    ("program", "shapes", "backend"),
                    lambda: [((p, s, b), es)
                             for p, s, b, _c, _cs, es in _programs()],
                    kind="counter")

        # AOT executable cache (parallel/aot.py via the jax-free counter
        # store monitor/compile_cache.py): per-source resolution counts —
        # aot_hit (deserialized blob, the zero-warmup path), xla_dir_hit
        # (fresh compile served by the persistent XLA dir), fresh (full
        # price), and the detected-miss/fallback taxonomy — plus phase
        # seconds. Fixed label vocabulary, cardinality bounded by
        # construction.
        def _cc_events():
            from elasticsearch_tpu.monitor import compile_cache

            return [((s,), v)
                    for s, v in compile_cache.events_snapshot().items()]

        def _cc_seconds():
            from elasticsearch_tpu.monitor import compile_cache

            return [((ph,), v)
                    for ph, v in compile_cache.seconds_snapshot().items()]

        m.collector("estpu_compile_cache_events_total",
                    "AOT executable-cache resolutions by source "
                    "(parallel/aot.py): aot_hit / xla_dir_hit / fresh, "
                    "plus detected corrupt/mismatch misses, store "
                    "outcomes, and call fallbacks", ("source",),
                    _cc_events, kind="counter")
        m.collector("estpu_compile_cache_seconds_total",
                    "Wall seconds in AOT cache phases: deserialize "
                    "(blob hit), compile (fresh lower+compile), "
                    "serialize (store)", ("phase",),
                    _cc_seconds, kind="counter")

    # -- gateway ---------------------------------------------------------------

    def _index_meta_path(self, name: str) -> str:
        return os.path.join(self.data_path, name, "_meta.json")

    def _persist_index_meta(self, name: str) -> None:
        """Durable index metadata (reference: gateway stores the cluster
        MetaData on disk — without it, translogs are orphans on restart)."""
        if not self.data_path or name not in self.indices:
            return
        svc = self.indices[name]
        path = self._index_meta_path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"settings": svc.settings,
                       "mappings": svc.mappings.to_json(),
                       "aliases": svc.aliases,
                       "closed": bool(svc.closed)}, f)
        os.replace(tmp, path)

    def _gateway_recover(self) -> None:
        """Re-open every index found under data_path (reference:
        GatewayService + LocalGatewayMetaState on node start); each
        IndexService then replays its shards' translogs."""
        if not os.path.isdir(self.data_path):
            return
        for name in sorted(os.listdir(self.data_path)):
            meta_path = self._index_meta_path(name)
            if not os.path.isfile(meta_path):
                continue
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
                svc = IndexService(
                    name, meta.get("settings"),
                    {"properties": {}} if not meta.get("mappings") else meta["mappings"],
                    data_path=self.data_path,
                    # a pre-validation index with a broken-but-unused
                    # analysis component must still re-open (lazy
                    # resolution, the behavior it was created under)
                    validate_analysis=False)
            except Exception:
                # one unrecoverable index (bad meta, failing replay) must
                # not stop the node from booting — it just stays absent
                # (red), reference: per-index recovery failures
                continue
            svc.aliases = dict(meta.get("aliases", {}))
            svc.closed = bool(meta.get("closed", False))
            svc._node = self  # foreign-index doc lookups (terms lookup)
            self.indices[name] = svc
            self.cluster_state.add_index(
                IndexMetadata(name, svc.settings, meta.get("mappings", {}),
                              svc.aliases),
                svc.num_shards, self.node_id)
            if svc.closed:
                m = self.cluster_state.indices.get(name)
                if m is not None:
                    m.state = "close"

    # -- index admin -----------------------------------------------------------

    def create_index(self, name: str, body: Optional[dict] = None) -> dict:
        if name in self.indices:
            raise IndexAlreadyExistsException(name)
        _validate_index_name(name)
        body = body or {}
        settings = dict(body.get("settings", {}))
        mappings = dict(body.get("mappings", {}))
        aliases = dict(body.get("aliases", {}))
        # apply matching templates, lowest order first (CreateIndexService)
        tmpls = sorted(
            (t for t in self.cluster_state.templates.values()
             if any(fnmatch.fnmatch(name, pat) for pat in t.get("index_patterns", [t.get("template", "")]))),
            key=lambda t: t.get("order", 0),
        )
        merged_settings: dict = {}
        merged_mappings: dict = {}
        for t in tmpls:
            _deep_merge(merged_settings, t.get("settings", {}))
            _deep_merge(merged_mappings, t.get("mappings", {}))
            aliases.update(t.get("aliases", {}))
        _deep_merge(merged_settings, settings)
        _deep_merge(merged_mappings, mappings)
        svc = IndexService(name, merged_settings, merged_mappings, data_path=self.data_path)
        svc._node = self  # foreign-index doc lookups (terms lookup)
        # aliases with `routing` fan it into index/search routing, like
        # IndicesAliasesRequest does
        for spec in aliases.values():
            if isinstance(spec, dict) and "routing" in spec:
                r = spec.pop("routing")
                spec.setdefault("index_routing", r)
                spec.setdefault("search_routing", r)
        svc.aliases = aliases
        for wname, wspec in dict(body.get("warmers", {})).items():
            svc.warmers[wname] = (wspec.get("source", wspec)
                                  if isinstance(wspec, dict) else wspec)
        self.indices[name] = svc
        self.cluster_state.add_index(
            IndexMetadata(name, merged_settings, merged_mappings, aliases),
            svc.num_shards, self.node_id,
        )
        self._persist_index_meta(name)
        return {"acknowledged": True, "shards_acknowledged": True, "index": name}

    def delete_index(self, name: str) -> dict:
        found = self.resolve_indices(name)
        if not found:
            raise IndexNotFoundException(name)
        mh = getattr(self, "multihost", None)
        for n in found:
            if mh is not None and n in mh.dist_indices:
                # cluster-wide: drop from the published metadata so peers
                # remove their copies (a local-only delete would be
                # resurrected by the next publish)
                mh.data.delete_index(n)
            else:
                self._delete_local_index(n)
        return {"acknowledged": True}

    def _delete_local_index(self, n: str) -> None:
        self.indices.pop(n).close()
        self.cluster_state.remove_index(n)
        if self.data_path:
            import shutil

            shutil.rmtree(os.path.join(self.data_path, n), ignore_errors=True)

    def index_exists(self, name: str) -> bool:
        return name in self.indices or bool(self._alias_targets(name))

    def resolve_indices(self, expr: Optional[str]) -> List[str]:
        """Resolve a name/alias/wildcard/csv expression to index names."""
        if expr in (None, "", "_all", "*"):
            return list(self.indices)
        out: List[str] = []
        for part in str(expr).split(","):
            part = part.strip()
            if "*" in part or "?" in part:
                out.extend(n for n in self.indices if fnmatch.fnmatch(n, part))
            elif part in self.indices:
                out.append(part)
            else:
                out.extend(self._alias_targets(part))
        seen = set()
        uniq = []
        for n in out:
            if n not in seen:
                seen.add(n)
                uniq.append(n)
        return uniq

    def _alias_targets(self, alias: str) -> List[str]:
        return [n for n, svc in self.indices.items() if alias in svc.aliases]

    def get_index(self, name: str) -> IndexService:
        names = self.resolve_indices(name)
        if not names:
            raise IndexNotFoundException(name)
        if len(names) > 1:
            raise ElasticsearchTpuException(
                f"alias/expression [{name}] resolves to multiple indices for a single-index op"
            )
        return self.indices[names[0]]

    def put_mapping(self, index: str, body: dict) -> dict:
        import copy

        names = self.resolve_indices(index)
        # validate the merged result on copies first: a rejected update must
        # leave every index untouched (all-or-nothing, like the reference's
        # MetaDataMappingService cluster-state update)
        for n in names:
            svc = self.indices[n]
            trial = copy.deepcopy(svc.mappings)
            trial.merge(body)
            svc._validate_analyzers(trial)
        for n in names:
            self.indices[n].mappings.merge(body)
            self._persist_index_meta(n)
        return {"acknowledged": True}

    def get_mapping(self, index: Optional[str] = None) -> dict:
        out = {}
        for n in self.resolve_indices(index):
            m = self.indices[n].mappings
            mj = m.to_json()
            # typed-mapping echo: indices that declared 2.0 type blocks
            # read back keyed by those names (single-type model underneath)
            out[n] = {"mappings": ({t: mj for t in m.type_names}
                                   if m.type_names else mj)}
        return out

    def update_aliases(self, actions: List[dict]) -> dict:
        mh = getattr(self, "multihost", None)
        if mh is not None:
            # alias changes are metadata: a headless node must fail them
            # typed 503 up front, not apply-and-ack a change the quorum's
            # master will overwrite on the next adopt
            mh.ensure_not_blocked("metadata_write")
        if mh is not None and not mh.is_master:
            # alias changes touching distributed indices are cluster state:
            # the master owns them (they ride the published metadata, so a
            # local-only change would be resurrected by the next publish).
            # SPLIT the batch at the per-INDEX level: expressions resolve
            # HERE (the master's index set differs), each resolved name
            # becomes an explicit single-index action, and only the
            # dist-index ones forward — so a wildcard spanning a local
            # and a distributed index updates both
            fwd: List[dict] = []
            local: List[dict] = []
            for action in actions:
                for op, spec in action.items():
                    for nm in (self.resolve_indices(
                            spec.get("index", spec.get("indices"))) or []):
                        single = {k: v for k, v in spec.items()
                                  if k not in ("index", "indices")}
                        single["index"] = nm
                        (fwd if nm in mh.dist_indices
                         else local).append({op: single})
            if fwd:
                from elasticsearch_tpu.cluster.search_action import \
                    ACTION_ALIASES

                mh.transport.send_remote(
                    mh.master_addr, ACTION_ALIASES, {"actions": fwd})
                actions = local
                if not actions:
                    return {"acknowledged": True}
        touched: List[str] = []
        for action in actions:
            for op, spec in action.items():
                idx_names = self.resolve_indices(spec.get("index", spec.get("indices")))
                alias = spec["alias"]
                for n in idx_names:
                    if op == "add":
                        meta = {k: v for k, v in spec.items()
                                if k not in ("index", "indices", "alias")}
                        if "routing" in meta:  # fans into both routings
                            r = str(meta.pop("routing"))
                            meta.setdefault("index_routing", r)
                            meta.setdefault("search_routing", r)
                        for rk in ("index_routing", "search_routing"):
                            if rk in meta:  # Settings are string maps
                                meta[rk] = str(meta[rk])
                        self.indices[n].aliases[alias] = meta
                    elif op == "remove":
                        self.indices[n].aliases.pop(alias, None)
                    self._persist_index_meta(n)
                    touched.append(n)
        if mh is not None and mh.is_master:
            # master: fold the new alias maps into the published dist
            # metadata (authoritative once present — _adopt_indices
            # REPLACES peers' local maps with it, so removals propagate
            # instead of being resurrected by the next publish)
            dist_touched = [n for n in touched if n in mh.dist_indices]
            if dist_touched:
                with mh._indices_lock:
                    prior = {n: dict(mh.dist_indices[n].get("aliases")
                                     or {}) for n in dist_touched}
                    for n in dist_touched:
                        mh.dist_indices[n]["aliases"] = dict(
                            self.indices[n].aliases)
                try:
                    mh.publish_indices()
                except Exception:
                    # not committed: restore BOTH halves (published map +
                    # local alias state) so this node doesn't diverge
                    # from what the quorum's master republishes, then
                    # fail the client typed
                    with mh._indices_lock:
                        for n, aliases in prior.items():
                            if n in mh.dist_indices:
                                mh.dist_indices[n]["aliases"] = \
                                    dict(aliases)
                            if n in self.indices:
                                self.indices[n].aliases = dict(aliases)
                                self._persist_index_meta(n)
                        mh._persist_dist_meta()
                    raise
        return {"acknowledged": True}

    def put_template(self, name: str, body: dict,
                     create: bool = False) -> dict:
        if create and name in self.cluster_state.templates:
            raise IndexAlreadyExistsException(name)
        body = dict(body)
        aliases = dict(body.get("aliases") or {})
        for spec in aliases.values():  # same routing fan-out as create
            if isinstance(spec, dict) and "routing" in spec:
                r = str(spec.pop("routing"))
                spec.setdefault("index_routing", r)
                spec.setdefault("search_routing", r)
        if aliases:
            body["aliases"] = aliases
        self.cluster_state.templates[name] = body
        return {"acknowledged": True}

    def delete_template(self, name: str) -> dict:
        if self.cluster_state.templates.pop(name, None) is None:
            raise IndexNotFoundException(name)
        return {"acknowledged": True}

    # -- documents -------------------------------------------------------------

    def bulk(self, operations: List[dict]) -> dict:
        """operations: parsed NDJSON pairs [{action}, {source}?, ...]."""
        items = []
        errors = False
        i = 0
        while i < len(operations):
            action_line = operations[i]
            (op, meta), = action_line.items()
            i += 1
            source = None
            if op in ("index", "create", "update"):
                source = operations[i]
                i += 1
            index_name = meta.get("_index")
            doc_id = meta.get("_id")
            parent = meta.get("parent", meta.get("_parent"))
            routing = meta.get("routing", meta.get("_routing")) or parent
            doc_type = meta.get("_type")
            try:
                # distributed index: every op hash-routes to its shard's
                # owner process (TransportBulkAction shard-bulk routing)
                mh = getattr(self, "multihost", None)
                data = (mh.data if mh is not None
                        and index_name in mh.dist_indices else None)
                svc = data or self.get_or_autocreate(index_name)
                args = (index_name,) if data is not None else ()
                if op in ("index", "create"):
                    kw = {}
                    if doc_type and doc_type != "_doc":
                        kw["doc_type"] = doc_type
                    if parent:
                        kw["parent"] = parent
                    r = svc.index_doc(*args, doc_id, source, routing=routing,
                                      op_type="create" if op == "create" else "index",
                                      **kw)
                    status = 201 if r.get("created") else 200
                elif op == "update":
                    r = svc.update_doc(*args, doc_id, source, routing=routing)
                    status = 200
                elif op == "delete":
                    r = svc.delete_doc(*args, doc_id, routing=routing)
                    status = 200
                else:
                    raise ElasticsearchTpuException(f"unknown bulk op [{op}]")
                items.append({op: {**r, "status": status}})
            except ElasticsearchTpuException as e:
                errors = True
                items.append({op: {
                    "_index": index_name, "_id": doc_id, "status": e.status,
                    "error": {"type": e.error_type, "reason": str(e)},
                }})
        return {"took": 0, "errors": errors, "items": items}

    def get_or_autocreate(self, name: str) -> IndexService:
        names = self.resolve_indices(name)
        if names:
            if len(names) == 1:
                return self.indices[names[0]]
            raise ElasticsearchTpuException(f"[{name}] resolves to multiple indices for a write")
        self.create_index(name)
        return self.indices[name]

    # -- search ----------------------------------------------------------------

    def search(self, index: Optional[str], body: dict,
               preference: Optional[str] = None) -> dict:
        mh = getattr(self, "multihost", None)
        if mh is not None and index is not None:
            rname = mh.data.resolve_index(index)
            if rname in mh.dist_indices:
                # a distributed index (by name or alias) scatters
                # cross-host; multi-index expressions mixing local +
                # distributed stay local-scoped. Pass the RESOLVED name so
                # the data plane doesn't re-resolve.
                return mh.data.search(rname, body or {})
        if mh is not None and index in (None, "", "_all", "*"):
            # the all-indices spelling must ride the dist plane too: the
            # local-scoped fallback silently under-reports acked docs on
            # any member whose local copy of a shard is empty (a bare
            # GET /_search on a non-owner saw only its own shards)
            open_names = [nm for nm in self.resolve_indices(index)
                          if not self.indices[nm].closed]
            dist = [nm for nm in open_names if nm in mh.dist_indices]
            if len(dist) == 1 and len(open_names) == 1:
                return mh.data.search(dist[0], body or {})
            if dist:
                # multiple distributed indices, or distributed mixed
                # with local-only: a loud typed refusal beats the old
                # silently-local-scoped (under-reporting) answer
                from elasticsearch_tpu.utils.errors import \
                    IllegalArgumentException

                raise IllegalArgumentException(
                    "all-indices search over multiple (or mixed "
                    "local/distributed) indices is not supported in "
                    "coordinator mode; name one index (distributed "
                    f"here: {sorted(dist)})")
        names = self.resolve_indices(index)
        if not names and index not in (None, "", "_all", "*"):
            raise IndexNotFoundException(str(index))
        searchers = []
        alias_filters = []
        from elasticsearch_tpu.cluster.metadata import check_open

        # wildcard/_all expansion SKIPS closed indices; an explicitly named
        # closed index (directly or via an alias) is an error (reference:
        # IndicesOptions wildcard expansion defaults to open-only)
        explicit = set()
        for part in str(index or "").split(","):
            part = part.strip()
            if part and not any(c in part for c in "*?") and part not in ("_all",):
                explicit.update(self.resolve_indices(part) or [part])
        searched_names: List[str] = []
        for n in names:
            svc = self.indices[n]
            if svc.closed and n not in explicit:
                continue
            check_open(svc, op="read")
            searched_names.append(n)
        search_type = (body or {}).get("search_type")
        if len(searched_names) == 1:
            # single-index: delegate to the index service BEFORE building
            # searchers (reader() advances replica round-robin; calling it
            # twice per request would defeat replica rotation). The service
            # runs the mesh executor as the default product path.
            svc = self.indices[searched_names[0]]
            dfs = search_type == "dfs_query_then_fetch"

            def _run():
                return svc.search(body or {}, dfs=dfs,
                                  preference=preference)

            if not dfs and preference is None:
                # serving coalescer: eligible bodies of CONCURRENT
                # requests park briefly and execute as one fused batch
                # (serving/coalescer.py); lone requests and ineligible
                # bodies run the normal path unchanged
                out = self.serving.coalescer.execute(svc, body or {}, _run)
                if out is not None:
                    return out
            return _run()
        if (body or {}).get("query"):
            from elasticsearch_tpu.search.queries import rewrite_mlt_in_body

            def _lookup(doc_id, routing=None, index=None):
                # mlt_source's own index check handles aliases AND
                # delegates foreign names through the node, so one call
                # covers explicit-_index references; an explicitly-named
                # index never falls back to a different index's same-id
                # document
                if index:
                    return self.indices[searched_names[0]].mlt_source(
                        doc_id, routing=routing, index=index)
                for nm in searched_names:
                    src = self.indices[nm].mlt_source(doc_id,
                                                      routing=routing)
                    if src is not None:
                        return src
                return None

            q2 = rewrite_mlt_in_body(body["query"], _lookup)
            if q2 is not body["query"]:
                body = dict(body, query=q2)
        for n in searched_names:
            svc = self.indices[n]
            searchers.extend(g.reader(preference).searcher for g in svc.groups)
        if not searchers:
            return {
                "took": 0, "timed_out": False,
                "_shards": {"total": 0, "successful": 0, "failed": 0},
                "hits": {"total": 0, "max_score": None, "hits": []},
            }
        from elasticsearch_tpu.search.service import search_shards

        # NOTE: searcher.shard_ord is NOT renumbered here — search_shards
        # stamps candidates with positional ordinals itself, so persistent
        # searcher state stays untouched across multi-index searches
        gs = None
        if search_type == "dfs_query_then_fetch":
            # merge per-index dfs term stats so idf is consistent across
            # EVERY searched index (reference: search/dfs/DfsPhase collects
            # over all participating shards, not one index)
            from elasticsearch_tpu.search.context import GlobalStats

            num_docs: Dict[str, int] = {}
            df: Dict[Any, int] = {}
            for n2 in searched_names:
                g2 = self.indices[n2].global_stats(body)
                for k2, v2 in g2.num_docs.items():
                    num_docs[k2] = num_docs.get(k2, 0) + v2
                for k2, v2 in g2.df.items():
                    df[k2] = df.get(k2, 0) + v2
            gs = GlobalStats(num_docs=num_docs, df=df)
        resp = search_shards(searchers, body or {}, index_name=",".join(names), global_stats=gs)
        # hits already carry per-hit owning index (fetch_phase uses the
        # searcher's own index_name)
        return resp

    def msearch(self, pairs: List[tuple]) -> dict:
        # batched fast path: the ELIGIBLE SUBSET of a single-concrete-
        # index batch executes as ONE fused kernel per segment
        # (search/batch.py partial batching); ineligible items (aggs,
        # sort, off-shape queries) ride the sequential loop below, and
        # typed malformed-query items become per-item failures
        pre: List[Optional[dict]] = [None] * len(pairs)
        if len(pairs) >= 2:
            # index may be a list (valid msearch header syntax) — those and
            # mixed-index batches take the sequential path
            names = {h.get("index") if isinstance(h.get("index"), str)
                     else None for h, _ in pairs}
            if len(names) == 1 and None not in names:
                try:
                    resolved = self.resolve_indices(next(iter(names)))
                except ElasticsearchTpuException:
                    resolved = []
                mh = getattr(self, "multihost", None)
                if len(resolved) == 1 and not (
                        mh is not None
                        and resolved[0] in mh.dist_indices):
                    # a distributed index's LOCAL service holds only the
                    # locally-owned shards — the fused batch would return
                    # partial results; the sequential loop below routes
                    # each request through the cross-host data plane
                    from elasticsearch_tpu.cluster.metadata import check_open
                    from elasticsearch_tpu.search.batch import try_batched_msearch

                    svc = self.indices[resolved[0]]
                    try:
                        check_open(svc, op="read")  # closed/blocked → sequential
                        out = try_batched_msearch(svc, [b for _, b in pairs])
                    except Exception:
                        out = None  # sequential path is always correct
                    if out is not None:
                        pre = out
        from elasticsearch_tpu.search.batch import msearch_error_entry

        responses = []
        for (header, body), served in zip(pairs, pre):
            if served is not None:
                # fused-batch response, or a typed per-item failure the
                # partial-batch split already shaped (2.0 msearch error
                # strings like "IndexMissingException[no such index]")
                responses.append(served)
                continue
            try:
                responses.append(self.search(header.get("index"), body))
            except ElasticsearchTpuException as e:
                responses.append(msearch_error_entry(e))
        return {"responses": responses}

    def nodes_stats(self) -> dict:
        from elasticsearch_tpu.monitor.stats import (TRANSLOG_RECOVERY,
                                                     aggregate_recovery,
                                                     aggregate_slowlog,
                                                     device_stats, os_stats,
                                                     process_stats)

        from elasticsearch_tpu.monitor.stats import SearchStats

        # seed keys from SearchStats itself: one source of truth
        search = {k: 0 for k in SearchStats().to_json()}
        indexing = {"index_total": 0, "delete_total": 0, "index_time_in_millis": 0}
        seg_count = seg_mem = 0
        fd_mem = fd_ev = 0
        tl_frames = tl_bytes = 0
        for svc in self.indices.values():
            for g in svc.groups:
                for shard in g.copies:
                    ss = shard.searcher.stats.to_json()
                    for k in search:
                        search[k] += ss.get(k, 0)
                    # per-shard write/segment stats come from the shard's own
                    # stats() — single source of truth (index/shard.py)
                    st = shard.stats()
                    for k in indexing:
                        indexing[k] += st["indexing"][k]
                    seg_count += st["segments"]["count"]
                    seg_mem += st["segments"]["memory_in_bytes"]
                    fd_mem += st["fielddata"]["memory_size_in_bytes"]
                    fd_ev += st["fielddata"]["evictions"]
                    tl_frames += st["translog"].get("corrupt_tail_events", 0)
                    tl_bytes += st["translog"].get(
                        "corrupt_tail_bytes_dropped", 0)
        from elasticsearch_tpu.monitor import kernels

        # node-wide kernel dispatch counters (which device program served
        # each query component) + mesh-vs-host routing counts
        snap = kernels.snapshot()
        search["kernels"] = snap
        # first-class fallback gauges (r4 verdict weak #5): a product query
        # class silently living on the host-fallback path must be visible
        # without digging through the kernels map
        search["mesh_fallback_total"] = snap.get("mesh_fallback_total", 0)
        search["span_clause_truncated"] = snap.get("span_clause_truncated", 0)
        search["mesh_host_by_design"] = snap.get("mesh_host_by_design", 0)
        proc = process_stats()
        return {
            "cluster_name": self.cluster_state.cluster_name,
            "nodes": {
                self.node_id: {
                    "name": self.name,
                    "indices": {
                        "docs": {"count": sum(s.num_docs for s in self.indices.values())},
                        "search": search,
                        "indexing": indexing,
                        "segments": {"count": seg_count,
                                     "memory_in_bytes": seg_mem},
                        # resident fielddata + the once-zero eviction
                        # counter, real since columns became evictable
                        "fielddata": {"memory_size_in_bytes": fd_mem,
                                      "evictions": fd_ev},
                        # translog replay damage accounting, aggregated
                        # from THIS node's own shards (the process-global
                        # event log with per-path detail lives in
                        # monitor/stats.py::TRANSLOG_RECOVERY)
                        "translog_recovery": {
                            "corrupt_tail_frames_skipped": tl_frames,
                            "corrupt_tail_bytes_dropped": tl_bytes,
                            "events": [
                                e for e in
                                TRANSLOG_RECOVERY.to_json()["events"]
                                if self._owns_translog_path(e["path"])],
                        },
                        # recovery accounting: incremental (ops-replay)
                        # vs full-copy streams, from this node's own
                        # RecoveryRegistry entries
                        "recovery": aggregate_recovery(
                            self.indices.values()),
                    },
                    "process": proc,
                    "os": os_stats(),
                    # ES response-shape parity: dashboards read jvm.mem.*;
                    # the honest numbers are the Python process's
                    "jvm": {"mem": {"heap_used_in_bytes":
                                    proc["mem"]["resident_in_bytes"]}},
                    # don't force pool creation just to report stats — the
                    # section is empty until REST traffic spins the pools
                    "thread_pool": (self._thread_pool.stats()
                                    if self._thread_pool is not None else {}),
                    "breakers": self._breaker_stats(),
                    # residency tiers: resident bytes + evict/rehydrate
                    # counters + the device-put accounting choke point
                    "resources": self._residency_stats(),
                    # transport info (reference: NodeInfo transport section;
                    # profiles {} = no extra transport profiles configured)
                    "transport": self._transport_info(),
                    # observability: in-flight/completed tasks + span ring
                    # + per-NODE slow-op counters (this node's indices
                    # only — in-process multi-node setups must not bleed
                    # counts across nodes)
                    "tasks": self.tasks.stats(),
                    "tracing": self.tracer.stats(),
                    # continuous metrics: histogram percentile summaries
                    # + counter totals — the JSON view of the same
                    # numbers GET /_prometheus/metrics exposes
                    "metrics": self.metrics.summaries(),
                    # serving front-end: coalescer queue depth/config +
                    # per-tenant QoS shares (serving/)
                    "serving": self.serving.stats(),
                    "slowlog": aggregate_slowlog(self.indices.values()),
                    # device-program observatory totals (key count,
                    # compiles, compile/execute seconds); the per-key
                    # table lives at /_nodes/_local/xla/programs and
                    # /_cat/programs (monitor/programs.py)
                    "programs": self._program_stats(),
                    # flight recorder ring counts + watchdog trip totals;
                    # the full rings live at /_nodes/_local/flight and in
                    # the /_cluster/diagnostics bundle
                    "flight": self.flight.stats(),
                    "watchdog": self.watchdog.stats(),
                    # TPU-native extra: device kind + HBM usage
                    "accelerator": device_stats(),
                }
            },
        }

    def _transport_info(self) -> dict:
        """Transport section of node info/stats (reference:
        transport/TransportInfo.java): addresses + configured profiles
        (always {} here — profiles are a netty-transport concept; the
        multi-host TCP transport has a single default binding)."""
        mh = getattr(self, "multihost", None)
        addr = "local[in-process]"
        if mh is not None:
            local = getattr(mh, "local", None)
            addr = getattr(local, "transport_address", None) or addr
        return {"bound_address": [addr], "publish_address": addr,
                "profiles": {}}

    def _owns_translog_path(self, path: str) -> bool:
        """True when a recovery event's translog path lives under THIS
        node's data_path — keeps per-node stats per-node when several
        in-process nodes share the global event log."""
        if not self.data_path:
            return False
        return os.path.abspath(path).startswith(
            os.path.abspath(self.data_path) + os.sep)

    @staticmethod
    def _residency_stats() -> dict:
        from elasticsearch_tpu import resources

        return resources.RESIDENCY.stats()

    @staticmethod
    def _program_stats() -> dict:
        from elasticsearch_tpu.monitor import programs

        return programs.REGISTRY.stats()

    @staticmethod
    def _breaker_stats() -> dict:
        """ES-shaped `/_nodes/stats/breaker`: parent + fielddata/request/
        in_flight_requests (+ the accelerator-extra `segments`), real
        estimated/tripped numbers (resources/breakers.py)."""
        from elasticsearch_tpu import resources

        return resources.BREAKERS.stats()

    def info(self) -> dict:
        import jax

        return {
            "name": self.name,
            "cluster_name": self.cluster_state.cluster_name,
            "version": {
                "number": __version__,
                "build_flavor": "tpu",
                "lucene_version": "n/a (device-resident segments)",
            },
            "tagline": "You Know, for Search — on TPU",
            "devices": [str(d) for d in jax.devices()],
        }

    def close(self):
        # stop the watchdog tick thread and leave the process fan before
        # teardown: a detector must not race the indices closing under it
        watchdog = getattr(self, "watchdog", None)
        if watchdog is not None:
            watchdog.close()
        flight_rec = getattr(self, "flight", None)
        if flight_rec is not None:
            from elasticsearch_tpu.monitor import flight as flight_mod

            flight_mod.unregister(flight_rec)
        # drain the serving coalescer FIRST: parked requests must resolve
        # (sequentially) before the indices they target close
        serving = getattr(self, "serving", None)
        if serving is not None:
            serving.close()
        for svc in self.indices.values():
            svc.close()
        if self._ivf_dir is not None:
            # persist each index's observed program-key census into the
            # durable blob tier BEFORE unregistering it, so the next
            # process over this data_path can read the exact program
            # universe this one served (resources/census.py; pre-warm
            # input for ROADMAP #6)
            from elasticsearch_tpu.resources import census

            for name in self.indices:
                try:
                    census.store_census(name)
                except Exception:
                    pass  # census persistence is best-effort: a failed
                    # write costs the next process a warmup, never a close
            from elasticsearch_tpu.index import ivf_cache

            ivf_cache.unregister(self._ivf_dir)
            self._ivf_dir = None
        if self._thread_pool is not None:
            self._thread_pool.shutdown()
            self._thread_pool = None


_INVALID_NAME = re.compile(r'[\\/*?"<>| ,#:A-Z]')


def _validate_index_name(name: str):
    if not name or name.startswith(("_", "-", "+")) or _INVALID_NAME.search(name):
        raise IllegalArgumentException(f"invalid index name [{name}]")


def _deep_merge(dst: dict, src: dict):
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
