"""Python client facade.

Reference: org/elasticsearch/client/Client.java (and support/
AbstractClient.java): prepareIndex/prepareSearch/prepareGet/... — here a
pythonic facade over an in-process Node (the common embedding) or a remote
REST endpoint (http mode), mirroring the elasticsearch-py surface users
migrate from.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.utils.errors import ElasticsearchTpuException


class ApiError(ElasticsearchTpuException):
    """An HTTP-mode error with the server's error TYPE and status intact,
    so callers can branch on `e.error_type == "engine_failed_exception"`
    (a failed-closed engine, 503) vs a routing 404 the same way in-process
    embedders catch typed exceptions. Note partial shard failures are NOT
    errors: a degraded `_search` returns HTTP 200 with `_shards.failed>0`
    and `_shards.failures[]` — inspect the response, nothing raises."""

    def __init__(self, msg: str, error_type: str, status: int):
        super().__init__(msg)
        self._remote_type = error_type
        self.status = status

    @property
    def error_type(self) -> str:  # the base derives it from the class name
        return self._remote_type


class Client:
    def __init__(self, node: Optional[Node] = None, url: Optional[str] = None):
        if node is None and url is None:
            node = Node()
        self.node = node
        self.url = url.rstrip("/") if url else None
        self.indices = IndicesClient(self)
        self.cluster = ClusterClient(self)

    # -- transport -------------------------------------------------------------

    def _http(self, method: str, path: str, body=None, ndjson: Optional[str] = None):
        import urllib.request

        data = None
        headers = {"Content-Type": "application/json"}
        if ndjson is not None:
            data = ndjson.encode()
            headers["Content-Type"] = "application/x-ndjson"
        elif body is not None:
            data = json.dumps(body).encode()
        req = urllib.request.Request(self.url + path, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else None
        except urllib.error.HTTPError as e:
            payload = e.read()
            err = json.loads(payload) if payload else {"status": e.code}
            detail = err.get("error", err)
            err_type = (detail.get("type", "exception")
                        if isinstance(detail, dict) else "exception")
            raise ApiError(json.dumps(detail), err_type, e.code)

    # -- document APIs ---------------------------------------------------------

    def index(self, index: str, body: dict, id: Optional[str] = None,
              refresh: bool = False, **kw) -> dict:
        if self.url:
            path = f"/{index}/_doc/{id}" if id is not None else f"/{index}/_doc"
            path += "?refresh=true" if refresh else ""
            return self._http("PUT" if id is not None else "POST", path, body)
        svc = self.node.get_or_autocreate(index)
        r = svc.index_doc(id, body, **kw)
        if refresh:
            svc.refresh()
        return r

    def get(self, index: str, id: str) -> dict:
        if self.url:
            return self._http("GET", f"/{index}/_doc/{id}")
        return self.node.get_index(index).get_doc(id)

    def exists(self, index: str, id: str) -> bool:
        r = self.get(index, id)
        return bool(r.get("found"))

    def delete(self, index: str, id: str, refresh: bool = False) -> dict:
        if self.url:
            return self._http("DELETE", f"/{index}/_doc/{id}" + ("?refresh=true" if refresh else ""))
        svc = self.node.get_index(index)
        r = svc.delete_doc(id)
        if refresh:
            svc.refresh()
        return r

    def update(self, index: str, id: str, body: dict, refresh: bool = False) -> dict:
        if self.url:
            return self._http("POST", f"/{index}/_update/{id}" + ("?refresh=true" if refresh else ""), body)
        svc = self.node.get_index(index)
        r = svc.update_doc(id, body)
        if refresh:
            svc.refresh()
        return r

    def mget(self, index: str, ids: List[str]) -> dict:
        if self.url:
            return self._http("POST", f"/{index}/_mget", {"ids": ids})
        return self.node.get_index(index).mget(ids)

    def bulk(self, operations: List[dict], refresh: bool = False) -> dict:
        if self.url:
            nd = "\n".join(json.dumps(o) for o in operations) + "\n"
            return self._http("POST", "/_bulk" + ("?refresh=true" if refresh else ""), ndjson=nd)
        r = self.node.bulk(operations)
        if refresh:
            for svc in self.node.indices.values():
                svc.refresh()
        return r

    # -- search APIs -----------------------------------------------------------

    def search(self, index: Optional[str] = None, body: Optional[dict] = None) -> dict:
        if self.url:
            path = f"/{index}/_search" if index else "/_search"
            return self._http("POST", path, body or {})
        return self.node.search(index, body or {})

    def count(self, index: str, body: Optional[dict] = None) -> dict:
        if self.url:
            return self._http("POST", f"/{index}/_count", body or {})
        names = self.node.resolve_indices(index)
        total = sum(self.node.indices[nm].count(body or {})["count"] for nm in names)
        return {"count": total}

    def msearch(self, searches: List[tuple]) -> dict:
        if self.url:
            lines = []
            for header, body in searches:
                lines.append(json.dumps(header))
                lines.append(json.dumps(body))
            return self._http("POST", "/_msearch", ndjson="\n".join(lines) + "\n")
        return self.node.msearch(searches)

    def scroll(self, scroll_id: str) -> dict:
        if self.url:
            return self._http("POST", "/_search/scroll", {"scroll_id": scroll_id})
        from elasticsearch_tpu.search.service import scroll_next

        return scroll_next(scroll_id)

    def info(self) -> dict:
        if self.url:
            return self._http("GET", "/")
        return self.node.info()


class IndicesClient:
    def __init__(self, client: Client):
        self.c = client

    def create(self, index: str, body: Optional[dict] = None) -> dict:
        if self.c.url:
            return self.c._http("PUT", f"/{index}", body or {})
        return self.c.node.create_index(index, body)

    def delete(self, index: str) -> dict:
        if self.c.url:
            return self.c._http("DELETE", f"/{index}")
        return self.c.node.delete_index(index)

    def exists(self, index: str) -> bool:
        if self.c.url:
            try:
                self.c._http("GET", f"/{index}/_settings")
                return True
            except Exception:
                return False
        return self.c.node.index_exists(index)

    def refresh(self, index: str) -> dict:
        if self.c.url:
            return self.c._http("POST", f"/{index}/_refresh")
        for n in self.c.node.resolve_indices(index):
            self.c.node.indices[n].refresh()
        return {"_shards": {"successful": 1}}

    def flush(self, index: str) -> dict:
        if self.c.url:
            return self.c._http("POST", f"/{index}/_flush")
        for n in self.c.node.resolve_indices(index):
            self.c.node.indices[n].flush()
        return {"_shards": {"successful": 1}}

    def forcemerge(self, index: str, max_num_segments: int = 1) -> dict:
        if self.c.url:
            return self.c._http("POST", f"/{index}/_forcemerge?max_num_segments={max_num_segments}")
        for n in self.c.node.resolve_indices(index):
            self.c.node.indices[n].force_merge(max_num_segments)
        return {"_shards": {"successful": 1}}

    def put_mapping(self, index: str, body: dict) -> dict:
        if self.c.url:
            return self.c._http("PUT", f"/{index}/_mapping", body)
        return self.c.node.put_mapping(index, body)

    def get_mapping(self, index: str) -> dict:
        if self.c.url:
            return self.c._http("GET", f"/{index}/_mapping")
        return self.c.node.get_mapping(index)

    def put_alias(self, index: str, alias: str) -> dict:
        return self.update_aliases([{"add": {"index": index, "alias": alias}}])

    def update_aliases(self, actions: List[dict]) -> dict:
        if self.c.url:
            return self.c._http("POST", "/_aliases", {"actions": actions})
        return self.c.node.update_aliases(actions)

    def put_template(self, name: str, body: dict) -> dict:
        if self.c.url:
            return self.c._http("PUT", f"/_template/{name}", body)
        return self.c.node.put_template(name, body)

    def stats(self, index: str) -> dict:
        if self.c.url:
            return self.c._http("GET", f"/{index}/_stats")
        return self.c.node.get_index(index).stats()

    def analyze(self, index: Optional[str] = None, body: Optional[dict] = None) -> dict:
        if self.c.url:
            path = f"/{index}/_analyze" if index else "/_analyze"
            return self.c._http("POST", path, body or {})
        from elasticsearch_tpu.rest.server import _do_analyze
        from elasticsearch_tpu.analysis.registry import AnalysisRegistry

        if index:
            svc = self.c.node.get_index(index)
            return _do_analyze(svc.analysis, body or {}, svc)
        return _do_analyze(AnalysisRegistry(), body or {})


class ClusterClient:
    def __init__(self, client: Client):
        self.c = client

    def health(self) -> dict:
        if self.c.url:
            return self.c._http("GET", "/_cluster/health")
        return self.c.node.cluster_state.health()

    def state(self) -> dict:
        if self.c.url:
            return self.c._http("GET", "/_cluster/state")
        return self.c.node.cluster_state.to_json()

    def stats(self) -> dict:
        if self.c.url:
            return self.c._http("GET", "/_cluster/stats")
        from elasticsearch_tpu.rest.server import _cluster_stats

        return _cluster_stats(self.c.node, {}, b"")[1]
