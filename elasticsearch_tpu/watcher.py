"""Resource watcher: mtime-polled file-change notifications.

Reference: org/elasticsearch/watcher/ — ResourceWatcherService.java +
FileWatcher.java (ES polls registered files/directories on an interval and
fires listeners on create/change/delete; used for config reload, e.g.
synonym files and the scripts directory). This is a REAL implementation of
that contract (not a stub): register paths with listeners, `check_now()`
runs one poll round, `start()` polls on a daemon thread.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

Listener = Callable[[str, str], None]  # (path, event: created|changed|deleted)


class ResourceWatcherService:
    def __init__(self, interval: float = 5.0):
        self.interval = interval
        self._watched: Dict[str, Tuple[Optional[float], List[Listener]]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _mtime(path: str) -> Optional[float]:
        try:
            return os.stat(path).st_mtime
        except OSError:
            return None

    def add(self, path: str, listener: Listener) -> None:
        with self._lock:
            mt, listeners = self._watched.get(path, (self._mtime(path), []))
            listeners.append(listener)
            self._watched[path] = (mt, listeners)

    def remove(self, path: str) -> None:
        with self._lock:
            self._watched.pop(path, None)

    def check_now(self) -> int:
        """One poll round; returns how many events fired."""
        fired = 0
        with self._lock:
            items = list(self._watched.items())
        for path, (old_mt, listeners) in items:
            new_mt = self._mtime(path)
            event = None
            if old_mt is None and new_mt is not None:
                event = "created"
            elif old_mt is not None and new_mt is None:
                event = "deleted"
            elif old_mt is not None and new_mt is not None and new_mt != old_mt:
                event = "changed"
            if event:
                with self._lock:
                    # re-read the CURRENT listener list under the lock:
                    # writing back the snapshot's list would revert a
                    # concurrent remove()+add() cycle to the stale list
                    # and silently drop its listeners (check-then-act
                    # window found by tpulint R016)
                    cur = self._watched.get(path)
                    if cur is not None:
                        self._watched[path] = (new_mt, cur[1])
                for fn in listeners:
                    try:
                        fn(path, event)
                        fired += 1
                    except Exception:
                        pass  # a broken listener must not stop the watcher
        return fired

    def start(self) -> None:
        if self._thread is not None:
            return
        # per-start stop event: an old poller that outlived a timed-out
        # join keeps ITS event (forever set) and exits at its next wait —
        # clearing a shared event could revive it alongside the new poller
        stop = threading.Event()
        self._stop = stop
        self._thread = threading.Thread(target=self._loop, args=(stop,),
                                        name="resource-watcher", daemon=True)
        self._thread.start()

    def _loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval):
            self.check_now()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.interval + 1.0)
        self._thread = None
