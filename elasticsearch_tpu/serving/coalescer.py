"""Cross-request adaptive micro-batching: the query coalescer.

Reference: there is no coalescer in ES 2.x — searches execute one
program each. Here the engine's single biggest measured lever is
batching (an explicit ``_msearch`` body runs its whole batch as one
vmapped device program, search/batch.py), so the serving front-end
converts *concurrent independent* single-search requests into the same
amortized shape: each eligible request parks briefly in a micro-batch
queue keyed by ``(index, query-shape bucket)``; a drain thread flushes
the bucket as ONE fused batch (``execute_batch``) and fans each
request's top-k back to its parked thread. Hybrid retrieval bodies
(search/hybrid.py) coalesce too, under their own
``(fusion method, lexical field, vector field)`` bucket — per-request
fusion weights ride as traced batch rows, so weight diversity never
fragments the bucket (the solo-bypass contract is unchanged).

Blocking discipline: tpulint R010 forbids unbounded waits while holding
a lock in this package, and R013 generalizes the same hazard — plus
lock-order cycle detection — to every module interprocedurally; waits
here are timeout-bounded and parking happens OUTSIDE the coalescer
lock.

Drain policy (adaptive):

- **solo bypass** — when no other eligible search is in flight and no
  batch is forming, the request runs the normal path untouched: a lone
  request pays ~zero added latency (``mode=adaptive``, the default).
- **full** — a bucket reaching ``max_batch`` flushes immediately.
- **deadline** — a forming batch flushes ``wait window`` after its
  first entry; the window adapts to the observed arrival rate (EWMA of
  inter-arrival gaps, clamped to ``max_wait``) so dense bursts hold
  just long enough to fill.
- **idle** — no new arrivals for ``idle_gap`` flushes early: the burst
  is over, waiting out the deadline would only add latency.

Integration with the production substrate (PRs 3–7):

- queue-wait is a ``serving.queue_wait`` tracer span (child of the REST
  search span), and a ``coalescer`` section under ``?profile=true``;
- every parked request registers a *pending* TaskRegistry child task —
  ``POST /_tasks/{id}/_cancel`` evicts it from the queue before it ever
  reaches the device;
- ``estpu_coalescer_*`` metric families (batch-size histogram,
  queue-wait histogram, flush-reason / bypass-reason counters) ride the
  node registry;
- admission happens upstream in REST dispatch through the per-tenant
  QoS layer (serving/qos.py) over the ``in_flight_requests`` breaker.

Ineligible bodies (aggs, sort, scroll, scripts, non-uniform query
shapes) bypass the queue unchanged.

Lock discipline (tpulint R010): every ``Condition.wait``/``Event.wait``
in this module is timeout-bounded — an unbounded wait while holding a
lock would wedge the drain path behind one lost notify.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: body keys a parked request may carry; `profile` parks too (its queue
#: wait must be attributed honestly) but executes sequentially at flush
PARK_KEYS = frozenset({"query", "size", "from", "_source", "profile"})

#: sentinel result: the waiter executes its own body on its own thread
#: (sequential remainder of a flush — profile bodies, fused-tier refusals)
RUN_SELF = object()


class _Entry:
    """One parked request."""

    __slots__ = ("svc", "body", "query", "claimed", "done",
                 "result", "error", "task", "enqueued", "claimed_at",
                 "batch_size", "flush_reason")

    def __init__(self, svc, body: dict, query):
        self.svc = svc
        self.body = body
        self.query = query
        self.claimed = threading.Event()  # left the queue (exec started)
        self.done = threading.Event()     # result/error available
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.task = None
        self.enqueued = time.perf_counter()
        self.claimed_at: Optional[float] = None
        self.batch_size = 0
        self.flush_reason = ""

    def resolve(self, result: Any = None,
                error: Optional[BaseException] = None) -> None:
        self.result = result
        self.error = error
        if self.claimed_at is None:
            self.claimed_at = time.perf_counter()
        self.claimed.set()
        self.done.set()


def _parse_duration_s(v, default: float) -> float:
    if v is None:
        return default
    from elasticsearch_tpu.search.service import _parse_timeout

    out = _parse_timeout(v)
    return default if out is None else float(out)


class QueryCoalescer:
    """Micro-batch queue between REST dispatch and the search executor."""

    #: EWMA smoothing for the inter-arrival gap estimate
    _ALPHA = 0.2
    #: wait window = this many estimated gaps (room for several joiners)
    _GAP_FACTOR = 4.0
    #: floor so a dense burst still holds long enough to fill a batch
    _MIN_WINDOW_S = 2e-4

    def __init__(self, node):
        self.node = node
        self._cv = threading.Condition()
        # (index name, shape bucket) -> forming batch
        self._queues: Dict[Tuple[str, str], List[_Entry]] = {}
        self._flush_at: Dict[Tuple[str, str], float] = {}
        self._last_arrival: Optional[float] = None
        self._ewma_gap: Optional[float] = None
        self._active = 0  # bypassed eligible searches currently executing
        self._outstanding = 0  # parked entries not yet fully served
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # -- config (env default + dynamic serving.coalescer.* settings)
        env = os.environ.get("ESTPU_COALESCER", "1").lower()
        self.enabled = env not in ("0", "false", "off")
        self.mode = "adaptive"  # adaptive | always | off
        self.max_batch = 256
        self.max_wait_s = 0.004
        self.idle_gap_s = 0.001
        # -- metrics (node registry; estpu_coalescer_* families)
        m = node.metrics
        self._m_batch = m.histogram(
            "estpu_coalescer_batch_size",
            "Requests per coalesced device batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        self._m_wait = m.histogram(
            "estpu_coalescer_queue_wait_seconds",
            "Time a request spent parked in the micro-batch queue")
        self._m_flush = m.counter(
            "estpu_coalescer_flush_total",
            "Batch flushes by drain reason (full/deadline/idle/close)",
            ("reason",))
        self._m_bypass = m.counter(
            "estpu_coalescer_bypass_total",
            "Searches that bypassed the queue, by reason", ("reason",))

    # -- settings ------------------------------------------------------------

    def apply_cluster_settings(self, flat: Dict[str, object]) -> None:
        """Idempotent from the merged map (absent key = default) — the
        breaker-settings discipline."""
        with self._cv:
            v = flat.get("serving.coalescer.enabled")
            env = os.environ.get("ESTPU_COALESCER", "1").lower()
            self.enabled = (str(v).lower() not in ("false", "0", "off")
                            if v is not None
                            else env not in ("0", "false", "off"))
            v = flat.get("serving.coalescer.mode")
            self.mode = (str(v) if v in ("adaptive", "always", "off")
                         else "adaptive")
            v = flat.get("serving.coalescer.max_batch")
            self.max_batch = max(2, int(v)) if v is not None else 256
            self.max_wait_s = _parse_duration_s(
                flat.get("serving.coalescer.max_wait"), 0.004)
            self.idle_gap_s = _parse_duration_s(
                flat.get("serving.coalescer.idle_gap"), 0.001)
            self._cv.notify_all()

    # -- submission ----------------------------------------------------------

    def execute(self, svc, body: dict, run) -> Optional[dict]:
        """The serving front door for one single-index search. Returns
        the response (coalesced or via ``run()``, the caller's normal
        sequential path), or None when the body is ineligible and the
        caller must run its own path (parse errors keep their typed
        surface there)."""
        if (not self.enabled or self.mode == "off" or self._closed
                or not isinstance(body, dict) or set(body) - PARK_KEYS):
            return None
        try:
            frm, size = int(body.get("from", 0)), int(body.get("size", 10))
        except (TypeError, ValueError):
            return None
        if frm + size < 1 or frm + size > 10_000:
            return None
        now = time.perf_counter()
        with self._cv:
            window = self._note_arrival(now)
            park = (self.mode == "always" or self._active > 0
                    or bool(self._queues))
            if not park:
                # solo: the normal path untouched — a lone request pays
                # zero added latency; _active marks the overlap window
                # so a concurrent burst starts coalescing immediately
                self._active += 1
        if not park:
            try:
                self._m_bypass.labels("solo").inc()
                return run()
            finally:
                with self._cv:
                    self._active -= 1
                    self._cv.notify_all()  # close() may be draining
        # coalescing is warranted: now pay for shape analysis
        made = self._make_entry(svc, body)
        if made is None:
            self._m_bypass.labels("shape").inc()
            return None
        entry, field = made
        return self._park(entry, field, window, run)

    def _make_entry(self, svc,
                    body: dict) -> Optional[Tuple[_Entry, str]]:
        from elasticsearch_tpu.search.batch import batch_field
        from elasticsearch_tpu.search.queries import parse_query

        try:
            query = parse_query(body.get("query"))
        except Exception:
            return None  # the normal path reports the typed error
        field = batch_field(svc, query)
        if field is None:
            return None
        return _Entry(svc, body, query), field

    def _park(self, entry: _Entry, field: str, window: float, run) -> dict:
        key = (entry.svc.name, field)
        with self._cv:
            self._outstanding += 1
        # pending child task: visible in /_tasks, cancellable while
        # parked — on_cancel evicts before the device ever sees it
        entry.task = self.node.tasks.register(
            "indices:data/read/search[coalesced]",
            description=f"indices[{entry.svc.name}] queued[{field}]",
            status="pending",
            on_cancel=lambda t, e=entry: self._evict(e))
        try:
            with self._cv:
                if entry.error is None:  # not born-cancelled
                    q = self._queues.get(key)
                    if q is None:
                        q = self._queues[key] = []
                        self._flush_at[key] = entry.enqueued + window
                    q.append(entry)
                    self._ensure_thread()
                    self._cv.notify_all()
            # queue wait as a span: child of the REST search span (same
            # thread of execution), closed at CLAIM — execution time is
            # the executor's, not the queue's
            with self.node.tracer.span("serving.queue_wait",
                                       index=entry.svc.name, bucket=field):
                while not entry.claimed.wait(timeout=0.05):
                    with self._cv:
                        dead = (self._thread is None
                                or not self._thread.is_alive())
                    if dead and self._reclaim(entry, key):
                        break
            while not entry.done.wait(timeout=0.05):
                pass
            queue_s = ((entry.claimed_at or entry.enqueued)
                       - entry.enqueued)
            self._m_wait.observe(queue_s)
            if entry.error is not None:
                raise entry.error
            if entry.result is RUN_SELF:
                resp = run()
            else:
                resp = entry.result
            if isinstance(resp, dict):
                queue_ms = int(queue_s * 1000)
                if "took" in resp:
                    resp["took"] = int(resp["took"]) + queue_ms
                if "profile" in resp and isinstance(resp["profile"], dict):
                    resp["profile"]["coalescer"] = {
                        "queue_wait_nanos": int(queue_s * 1e9),
                        "batch_size": entry.batch_size,
                        "flush_reason": entry.flush_reason or "self",
                    }
            return resp
        finally:
            self.node.tasks.unregister(entry.task)
            with self._cv:
                self._outstanding -= 1
                self._cv.notify_all()  # close() may be draining

    def _note_arrival(self, now: float) -> float:
        """Caller holds _cv. Update the EWMA inter-arrival estimate and
        return the adaptive wait window for a batch formed now."""
        if self._last_arrival is not None:
            gap = min(now - self._last_arrival, 1.0)
            self._ewma_gap = (gap if self._ewma_gap is None
                              else (1 - self._ALPHA) * self._ewma_gap
                              + self._ALPHA * gap)
        self._last_arrival = now
        if self.mode == "always":
            return self.max_wait_s
        if self._ewma_gap is None:
            return self._MIN_WINDOW_S
        return min(self.max_wait_s,
                   max(self._ewma_gap * self._GAP_FACTOR,
                       self._MIN_WINDOW_S))

    # -- eviction / reclaim --------------------------------------------------

    def _evict(self, entry: _Entry) -> None:
        """on_cancel hook (cancelling thread): remove a still-parked
        entry from its queue and fail it with the task's typed error —
        it never reaches the device. A claimed entry is past eviction;
        its flush resolves it normally."""
        from elasticsearch_tpu.tracing import TaskCancelledException

        with self._cv:
            for key, q in list(self._queues.items()):
                if entry in q:
                    q.remove(entry)
                    if not q:
                        self._queues.pop(key, None)
                        self._flush_at.pop(key, None)
                    break
            if not entry.claimed.is_set():
                task = entry.task
                reason = (task.cancel_reason if task is not None
                          else None) or "by user request"
                tid = task.tagged_id if task is not None else "?"
                entry.resolve(error=TaskCancelledException(
                    f"task [{tid}] (indices:data/read/search[coalesced]) "
                    f"was cancelled [{reason}] while queued"))
            self._cv.notify_all()

    def _reclaim(self, entry: _Entry, key) -> bool:
        """Dead drain thread: pull the entry back and run it ourselves
        (never wedge a client on a crashed drain loop)."""
        with self._cv:
            q = self._queues.get(key)
            if q is not None and entry in q:
                q.remove(entry)
                if not q:
                    self._queues.pop(key, None)
                    self._flush_at.pop(key, None)
                entry.resolve(result=RUN_SELF)
                return True
            return entry.done.is_set()

    # -- drain thread --------------------------------------------------------

    def _ensure_thread(self) -> None:
        """Caller holds _cv. Lazy drain thread (library-embedded Nodes
        that never coalesce don't pay for one)."""
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._drain_loop, name="estpu-coalescer",
                daemon=True)
            self._thread.start()

    def _due(self, now: float) -> Optional[Tuple[Tuple[str, str], str]]:
        """Caller holds _cv. The first bucket due to flush, with reason."""
        for key, q in self._queues.items():
            if not q:
                continue
            if len(q) >= self.max_batch:
                return key, "full"
            if now >= self._flush_at.get(key, now):
                return key, "deadline"
            if (self._last_arrival is not None
                    and now - self._last_arrival >= self.idle_gap_s):
                return key, "idle"
        return None

    def _next_wakeup(self, now: float) -> float:
        """Caller holds _cv. Seconds until the earliest possible flush."""
        t = 0.5  # idle heartbeat: re-check config/close periodically
        if self._queues:
            for key in self._queues:
                t = min(t, self._flush_at.get(key, now) - now)
            if self._last_arrival is not None:
                t = min(t, self._last_arrival + self.idle_gap_s - now)
        return max(t, 1e-4)

    def _drain_loop(self) -> None:
        while True:
            batch: List[_Entry] = []
            reason = ""
            with self._cv:
                while True:
                    if self._closed:
                        for q in self._queues.values():
                            for e in q:
                                e.resolve(result=RUN_SELF)
                        self._queues.clear()
                        self._flush_at.clear()
                        return
                    now = time.perf_counter()
                    due = self._due(now)
                    if due is not None:
                        key, reason = due
                        q = self._queues.pop(key, [])
                        self._flush_at.pop(key, None)
                        batch = q[: self.max_batch]
                        rest = q[self.max_batch:]
                        if rest:
                            self._queues[key] = rest
                            self._flush_at[key] = now
                        break
                    self._cv.wait(timeout=self._next_wakeup(now))
            if batch:
                try:
                    self._flush(batch, reason)
                except Exception:
                    # the sequential path is always correct — a drain bug
                    # must degrade to per-request execution, not wedge
                    # parked clients (counted, never silent)
                    self._m_bypass.labels("drain_error").inc()
                    for e in batch:
                        if not e.done.is_set():
                            e.resolve(result=RUN_SELF)

    def _flush(self, batch: List[_Entry], reason: str) -> None:
        from elasticsearch_tpu.search.batch import execute_batch

        # cancelled-while-claiming entries resolve with their typed error
        live: List[_Entry] = []
        for e in batch:
            if e.done.is_set():
                continue
            if e.task is not None and e.task.cancelled:
                self._evict(e)
                continue
            live.append(e)
        if not live:
            return
        self._m_flush.labels(reason).inc()
        # profile bodies pay the queue wait like everyone (that is the
        # honest number) but execute sequentially: a fused batch cannot
        # attribute per-phase device time to one request
        fused = [e for e in live if "profile" not in e.body]
        rest = [e for e in live if "profile" in e.body]
        now = time.perf_counter()
        for e in live:
            e.claimed_at = now
            e.batch_size = len(fused) if e in fused else 1
            e.flush_reason = reason
            e.claimed.set()
        # the sequential remainder has no dependency on the fused batch:
        # release those waiters BEFORE the device execution, not after —
        # they run on their own threads in parallel with the batch
        for e in rest:
            e.resolve(result=RUN_SELF)
        responses = None
        if len(fused) >= 2:
            svc = fused[0].svc
            try:
                responses = execute_batch(
                    svc, [e.body for e in fused],
                    queries=[e.query for e in fused], pad_pow2=True)
            except Exception:
                responses = None  # sequential fallback below
                self._m_bypass.labels("batch_error").inc()
        if responses is not None:
            self._m_batch.observe(len(fused))
            q_ms = (time.perf_counter() - now) * 1000
            for e, r in zip(fused, responses):
                try:  # slow log sees coalesced searches too (honest cost:
                    # this request's share is queue wait + batch execute)
                    e.svc.slowlog.on_search(
                        q_ms + (e.claimed_at - e.enqueued) * 1000,
                        e.body, r)
                except Exception:
                    pass  # logging must never fail the batch
                e.resolve(result=r)
        else:
            for e in fused:
                e.resolve(result=RUN_SELF)

    # -- lifecycle -----------------------------------------------------------

    def oldest_queue_age(self) -> Optional[float]:
        """Age in seconds of the oldest still-PARKED request across
        every forming bucket, or None when nothing is parked. Normal
        waits are sub-millisecond (the adaptive window); an age orders
        of magnitude past ``max_wait`` means the drain thread is wedged
        or dead — the watchdog's coalescer_drain signal."""
        with self._cv:
            oldest = min((e.enqueued for q in self._queues.values()
                          for e in q), default=None)
        if oldest is None:
            return None
        return time.perf_counter() - oldest

    def stats(self) -> dict:
        with self._cv:
            return {
                "enabled": self.enabled,
                "mode": self.mode,
                "queued": sum(len(q) for q in self._queues.values()),
                "buckets": len(self._queues),
                "max_batch": self.max_batch,
                "max_wait_ms": self.max_wait_s * 1000,
            }

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            th = self._thread
        if th is not None and th.is_alive():
            th.join(timeout=2.0)
        # parked waiters resolved RUN_SELF (and solo bypasses) still
        # EXECUTE on their own threads — wait them out (bounded) so the
        # caller can tear indices down without racing live searches
        deadline = time.perf_counter() + 5.0
        with self._cv:
            while (self._outstanding > 0 or self._active > 0) \
                    and time.perf_counter() < deadline:
                self._cv.wait(timeout=0.05)
