"""Per-tenant QoS admission: weighted shares of the in-flight budget.

Reference: there is no tenant concept in ES 2.x — the nearest ancestor
is the netty-level in-flight-requests circuit breaker this layer rides
on (org/elasticsearch/http/netty/NettyHttpServerTransport.java request
accounting + indices/breaker/HierarchyCircuitBreakerService.java).

Model: every search-family request names a tenant (``X-Tenant-Id``
header or ``?tenant=`` param; absent → ``_default``). Each tenant owns a
*weighted share* of the ``in_flight_requests`` breaker's byte limit:

    share(t) = max(MIN_CHARGE, limit * weight(t) / Σ weight(active ∪ configured))

A request charges ``max(body_bytes, MIN_CHARGE)`` — the floor makes
admission behave like weighted concurrency slots even for empty GET
bodies — first against the tenant's share, then against the real
breaker (the global cap). Exceeding either raises the breaker's typed
``CircuitBreakingException`` ("Data too large", HTTP 429), so a greedy
tenant starves *itself* while other tenants' shares stay serveable.

Weights are dynamic cluster settings (``serving.qos.tenant.<id>.weight``,
``serving.qos.default_weight``, ``serving.qos.enabled``) applied through
the same idempotent full-map path the breaker limits use.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from elasticsearch_tpu.utils.errors import CircuitBreakingException


def _human(n: int) -> str:
    from elasticsearch_tpu.resources.breakers import human_bytes

    return human_bytes(n)


class TenantAdmission:
    """Weighted per-tenant admission over the in_flight_requests breaker."""

    #: byte floor per admitted request: empty search bodies still consume
    #: share, so admission degenerates to weighted concurrency slots
    MIN_CHARGE = 4096
    DEFAULT_TENANT = "_default"

    def __init__(self, metrics=None):
        self._lock = threading.Lock()
        self.enabled = True
        self.default_weight = 1.0
        self.weights: Dict[str, float] = {}
        self._used: Dict[str, int] = {}  # in-flight charged bytes by tenant
        self._m_admitted = self._m_rejected = None
        if metrics is not None:
            self._m_admitted = metrics.counter(
                "estpu_coalescer_tenant_admitted_total",
                "Search requests admitted per tenant (QoS layer)",
                ("tenant",))
            self._m_rejected = metrics.counter(
                "estpu_coalescer_tenant_rejected_total",
                "Search requests rejected 429 per tenant (share or "
                "breaker exceeded)", ("tenant",))

    # -- settings ------------------------------------------------------------

    def apply_cluster_settings(self, flat: Dict[str, object]) -> None:
        """Idempotent from the MERGED settings map (absent key = default),
        the breaker-service discipline — null deletion needs no special
        casing at the call site."""
        prefix = "serving.qos.tenant."
        with self._lock:
            v = flat.get("serving.qos.enabled")
            self.enabled = (str(v).lower() not in ("false", "0", "off")
                            if v is not None else True)
            v = flat.get("serving.qos.default_weight")
            self.default_weight = float(v) if v is not None else 1.0
            weights: Dict[str, float] = {}
            for k, val in flat.items():
                if k.startswith(prefix) and k.endswith(".weight"):
                    tenant = k[len(prefix): -len(".weight")]
                    if tenant:
                        weights[tenant] = max(float(val), 0.0)
            self.weights = weights

    # -- admission -----------------------------------------------------------

    def _share(self, tenant: str, limit: int) -> int:
        """Caller holds self._lock. The tenant's byte share of `limit`."""
        if limit < 0:
            return 1 << 62
        known = set(self.weights) | set(self._used) | {tenant}
        total = sum(self.weights.get(t, self.default_weight) for t in known)
        w = self.weights.get(tenant, self.default_weight)
        if total <= 0 or w <= 0:
            return 0
        return max(self.MIN_CHARGE, int(limit * w / total))

    def admit(self, tenant: Optional[str],
              nbytes: int) -> Tuple[str, int]:
        """Admit one request; returns the (tenant, charge) token for
        :meth:`release`. Raises the typed ``CircuitBreakingException``
        (429) when the tenant's share or the global breaker trips."""
        from elasticsearch_tpu import resources

        breaker = resources.BREAKERS.breaker("in_flight_requests")
        tenant = (str(tenant).strip() or self.DEFAULT_TENANT) if tenant \
            else self.DEFAULT_TENANT
        if not self.enabled:
            # QoS off: the seed behavior — raw body bytes, no floor
            breaker.break_or_reserve(nbytes, "<http_request>")
            return (self.DEFAULT_TENANT, -nbytes - 1)  # marker: raw charge
        charge = max(int(nbytes), self.MIN_CHARGE)
        with self._lock:
            used = self._used.get(tenant, 0)
            share = self._share(tenant, breaker.limit)
            if used + charge > share:
                if self._m_rejected is not None:
                    self._m_rejected.labels(tenant).inc()
                w = self.weights.get(tenant, self.default_weight)
                raise CircuitBreakingException(
                    f"[in_flight_requests] Data too large, data for "
                    f"[tenant:{tenant}] would be [{used + charge}/"
                    f"{_human(used + charge)}], which is larger than the "
                    f"tenant share of [{share}/{_human(share)}] "
                    f"(weight [{w}])",
                    bytes_wanted=used + charge, bytes_limit=share)
            # reserve the tenant slot BEFORE the breaker call: two racing
            # admits for one tenant must not both pass the share check
            self._used[tenant] = used + charge
        try:
            breaker.break_or_reserve(charge, f"<tenant:{tenant}>")
        except CircuitBreakingException:
            with self._lock:
                left = self._used.get(tenant, 0) - charge
                if left > 0:
                    self._used[tenant] = left
                else:
                    self._used.pop(tenant, None)
            if self._m_rejected is not None:
                self._m_rejected.labels(tenant).inc()
            raise
        if self._m_admitted is not None:
            self._m_admitted.labels(tenant).inc()
        return (tenant, charge)

    def release(self, token: Tuple[str, int]) -> None:
        from elasticsearch_tpu import resources

        tenant, charge = token
        breaker = resources.BREAKERS.breaker("in_flight_requests")
        if charge < 0:  # raw-charge marker from the disabled path
            breaker.release(-charge - 1)
            return
        breaker.release(charge)
        with self._lock:
            left = self._used.get(tenant, 0) - charge
            if left > 0:
                self._used[tenant] = left
            else:
                self._used.pop(tenant, None)

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "default_weight": self.default_weight,
                    "weights": dict(self.weights),
                    "in_flight_bytes": dict(self._used)}
