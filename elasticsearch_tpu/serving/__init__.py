"""Serving front-end: cross-request micro-batching + per-tenant QoS.

One subsystem, two halves (see docs/SERVING.md):

- :mod:`coalescer` — the adaptive micro-batch queue between REST
  dispatch and the search executor: concurrent independent searches
  coalesce into one vmapped device program per (index, query-shape)
  bucket and fan their top-k back out.
- :mod:`qos` — weighted per-tenant admission over the
  ``in_flight_requests`` breaker: a greedy tenant 429s against its own
  share while other tenants keep serving.

Each :class:`~elasticsearch_tpu.node.Node` owns one
:class:`ServingFrontend` (``node.serving``); REST dispatch admits
through ``serving.qos`` and ``Node.search`` routes eligible
single-index bodies through ``serving.coalescer``.

Import cost: no jax at import time — the device work happens inside
search/batch.py at flush time.
"""
from __future__ import annotations

from typing import Dict

from elasticsearch_tpu.serving.coalescer import QueryCoalescer
from elasticsearch_tpu.serving.qos import TenantAdmission

__all__ = ["QueryCoalescer", "TenantAdmission", "ServingFrontend"]


class ServingFrontend:
    """Per-node serving layer: coalescer + QoS, one settings surface."""

    def __init__(self, node):
        self.coalescer = QueryCoalescer(node)
        self.qos = TenantAdmission(node.metrics)

    def apply_cluster_settings(self, flat: Dict[str, object]) -> None:
        self.coalescer.apply_cluster_settings(flat)
        self.qos.apply_cluster_settings(flat)

    def stats(self) -> dict:
        return {"coalescer": self.coalescer.stats(),
                "qos": self.qos.stats()}

    def close(self) -> None:
        self.coalescer.close()
