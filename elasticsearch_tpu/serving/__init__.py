"""Serving front-end: cross-request micro-batching + per-tenant QoS.

One subsystem, three halves (see docs/SERVING.md):

- :mod:`coalescer` — the adaptive micro-batch queue between REST
  dispatch and the search executor: concurrent independent searches
  coalesce into one vmapped device program per (index, query-shape)
  bucket and fan their top-k back out.
- :mod:`qos` — weighted per-tenant admission over the
  ``in_flight_requests`` breaker: a greedy tenant 429s against its own
  share while other tenants keep serving.
- :mod:`warmup` — the census-driven pre-warm pipeline (ROADMAP #6):
  on boot/index-open/recovery-graduation, replay the index's persisted
  census bodies through the real search path on a cancellable
  background task, hottest first, breaker-charged and cooldown-guarded,
  so a restarted node's first page of requests pays zero compiles.

Each :class:`~elasticsearch_tpu.node.Node` owns one
:class:`ServingFrontend` (``node.serving``); REST dispatch admits
through ``serving.qos`` and ``Node.search`` routes eligible
single-index bodies through ``serving.coalescer``.

Import cost: no jax at import time — the device work happens inside
search/batch.py at flush time.
"""
from __future__ import annotations

from typing import Dict

from elasticsearch_tpu.serving.coalescer import QueryCoalescer
from elasticsearch_tpu.serving.qos import TenantAdmission
from elasticsearch_tpu.serving.warmup import WarmupService

__all__ = ["QueryCoalescer", "TenantAdmission", "WarmupService",
           "ServingFrontend"]


class ServingFrontend:
    """Per-node serving layer: coalescer + QoS + pre-warm, one settings
    surface."""

    def __init__(self, node):
        self.coalescer = QueryCoalescer(node)
        self.qos = TenantAdmission(node.metrics)
        self.warmup = WarmupService(node)

    def apply_cluster_settings(self, flat: Dict[str, object]) -> None:
        self.coalescer.apply_cluster_settings(flat)
        self.qos.apply_cluster_settings(flat)
        self.warmup.apply_cluster_settings(flat)

    def stats(self) -> dict:
        return {"coalescer": self.coalescer.stats(),
                "qos": self.qos.stats(),
                "warmup": self.warmup.stats()}

    def close(self) -> None:
        # warmup first: its worker drives searches through the coalescer
        # path — stop producing before draining
        self.warmup.close()
        self.coalescer.close()
