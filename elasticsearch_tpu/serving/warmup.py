"""Pre-warm service: replay the persisted census before traffic arrives.

The zero-warmup pipeline's active half (ISSUE 14 / ROADMAP #6). The AOT
executable cache (parallel/aot.py) makes a restarted node's first touch
of each program a deserialize instead of a compile; this service moves
even that cost out of the first request: on node boot (RestServer.start),
index open, and shard-recovery graduation it replays the index's
persisted census — the canonical search bodies the previous process
actually served, hottest first — through the REAL search path, which
drives the real executor program factories, the AOT blob lookups, and
the device-data uploads exactly as live traffic would.

Discipline (the issue's contract):

- **background, low priority** — one daemon worker thread (tpulint R011:
  daemon + stop-Event-gated loop), replaying one body at a time; live
  traffic never queues behind warmup.
- **cancellable** — each index replay runs as a ``cluster:admin/warmup``
  parent task: visible in ``GET /_tasks``, and ``POST /_tasks/{id}/_cancel``
  stops the replay at the next body boundary with the registry left
  consistent (a replayed body is a completed search; an unreplayed one
  is simply still cold).
- **breaker-charged** — every body charges ``charge_bytes`` against the
  ``request`` breaker before executing and releases after; a denial
  retries briefly, then DEFERS the run (status ``deferred``) without
  failing any foreground search — under memory pressure warmup yields.
- **cooldown-guarded** — a completed index re-warms only after
  ``cooldown_s``; steady-state kicks (an index re-opened twice, repeated
  shard syncs) are recorded as ``cooldown`` no-ops, so warmup can never
  become a recurring background tax.
- **backend-honest** — a census captured on another backend fingerprint
  is refused (``backend_mismatch``), never replayed against this chip.

Replays run under the :func:`in_prewarm` flag: IndexService labels their
latency samples ``warmup=prewarm`` (not ``true``/``false`` — warmup's own
compiles must not pollute the cold-start acceptance series) and skips
census body re-recording (warmup must not inflate its own work list).
"""
from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

_PREWARM: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "estpu-prewarm", default=False)


def in_prewarm() -> bool:
    """True on flows executing a warmup replay (IndexService reads this
    for the metric label + census suppression)."""
    return _PREWARM.get()


class WarmupService:
    """Per-node pre-warm worker. Construction is cheap (no thread); the
    worker spins lazily on the first :meth:`kick`."""

    DEFAULTS: Dict[str, float] = {
        "cooldown_s": 300.0,     # a completed index re-warms only after
        "charge_bytes": float(1 << 20),  # request-breaker charge per body
        "defer_retries": 3.0,    # breaker-denial retries before deferring
        "defer_wait_s": 0.05,    # stop-gated wait between retries
        "max_bodies": 64.0,      # per-run replay ceiling
    }

    def __init__(self, node, **overrides: float):
        self.node = node
        self.config: Dict[str, float] = dict(self.DEFAULTS)
        for k, v in overrides.items():
            if k not in self.config:
                raise ValueError(f"unknown warmup option [{k}]")
            self.config[k] = float(v)
        self._enabled_setting: Optional[bool] = None  # cluster override
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._queue: "deque[tuple]" = deque()
        self._queued: set = set()
        self._active: Optional[str] = None
        #: per-index last run result (bounded: one entry per index name)
        self.runs: Dict[str, dict] = {}
        self._last_complete: Dict[str, float] = {}
        m = node.metrics
        self._m_runs = m.counter(
            "estpu_warmup_runs_total",
            "Pre-warm runs by terminal status "
            "(complete/deferred/canceled/no_census/backend_mismatch/"
            "cooldown/error)", ("status",))
        self._m_replayed = m.counter(
            "estpu_warmup_replayed_total",
            "Census bodies replayed through the real search path by the "
            "pre-warm service")

    # -- config ---------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        if self._enabled_setting is not None:
            return self._enabled_setting
        return os.environ.get("ESTPU_WARMUP", "1").lower() not in (
            "0", "false", "off")

    def apply_cluster_settings(self, flat: Dict[str, object]) -> None:
        v = flat.get("serving.warmup.enabled")
        self._enabled_setting = (None if v is None
                                 else str(v).lower() in ("1", "true"))
        cd = flat.get("serving.warmup.cooldown_seconds")
        if cd is not None:
            try:
                self.config["cooldown_s"] = float(cd)
            except (TypeError, ValueError):
                pass
        elif "cooldown_s" in self.DEFAULTS:
            self.config["cooldown_s"] = self.DEFAULTS["cooldown_s"]

    # -- kick / queue ---------------------------------------------------------

    def kick(self, reason: str, indices: Optional[List[str]] = None
             ) -> List[str]:
        """Queue warmup for ``indices`` (default: every open local
        index). Returns the names actually queued; cooldown-guarded
        indices are skipped here AND re-checked at run time (a kick can
        sit queued while a previous run completes)."""
        if not self.enabled or self._stop.is_set():
            return []
        names = indices if indices is not None else sorted(
            self.node.indices)
        queued: List[str] = []
        now = time.monotonic()
        with self._lock:
            for name in names:
                svc = self.node.indices.get(name)
                if svc is None or getattr(svc, "closed", False):
                    continue
                last = self._last_complete.get(name)
                if last is not None \
                        and now - last < self.config["cooldown_s"]:
                    self._note_cooldown_locked(name, reason)
                    continue
                if name in self._queued or name == self._active:
                    continue
                self._queue.append((name, reason))
                self._queued.add(name)
                queued.append(name)
        if queued:
            self._ensure_thread()
        return queued

    def _note_cooldown_locked(self, index: str, reason: str) -> None:
        """Record a cooldown skip WITHOUT destroying the last
        substantive run's diagnostics (an operator checking whether
        pre-warm ran must still see replayed/took_ms — a routine
        shard-sync kick inside the window must not blank them).
        Caller holds self._lock."""
        prev = self.runs.get(index)
        if prev is not None and prev.get("status") != "cooldown":
            prev["cooldown_skips"] = prev.get("cooldown_skips", 0) + 1
            prev["last_skip_reason"] = reason
        else:
            self.runs[index] = {"index": index, "reason": reason,
                                "status": "cooldown"}
        self._m_runs.labels("cooldown").inc()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._loop, name="estpu-warmup", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                if not self._queue:
                    # exit when the queue drains, DEATH ANNOUNCED UNDER
                    # THE LOCK (a racing kick's _ensure_thread sees
                    # _thread None and respawns — no lost job, no
                    # forever-polling idle thread for a service that
                    # typically runs once per boot)
                    self._thread = None
                    return
                job = self._queue.popleft()
                self._queued.discard(job[0])
                self._active = job[0]
            try:
                self.run_index(job[0], job[1])
            except Exception:
                pass  # a broken replay must never kill the worker
            finally:
                with self._lock:
                    self._active = None

    # -- one index ------------------------------------------------------------

    def run_index(self, index: str, reason: str) -> dict:
        """Replay one index's persisted census synchronously (the worker
        calls this; tests and the bench call it directly for
        determinism). Returns and records the run result."""
        from elasticsearch_tpu.resources import census
        from elasticsearch_tpu.tracing.tasks import TaskCancelledException

        t0 = time.perf_counter()
        result = {"index": index, "reason": reason, "status": "error",
                  "replayed": 0, "errors": 0, "deferrals": 0}

        def _finish(status: str) -> dict:
            result["status"] = status
            result["took_ms"] = round(
                (time.perf_counter() - t0) * 1000.0, 3)
            with self._lock:
                self.runs[index] = result
                if status == "complete":
                    self._last_complete[index] = time.monotonic()
            self._m_runs.labels(status).inc()
            return result

        svc = self.node.indices.get(index)
        if svc is None or getattr(svc, "closed", False):
            return _finish("skipped")
        # run-time cooldown re-check (kick's contract): a kick can sit
        # queued while another trigger's run completes — replaying again
        # seconds later is exactly the steady-state tax the guard exists
        # to prevent. Returned (not stored) as the result: the stored
        # record keeps the completed run's diagnostics.
        with self._lock:
            last = self._last_complete.get(index)
            if last is not None and time.monotonic() - last \
                    < self.config["cooldown_s"]:
                self._note_cooldown_locked(index, reason)
                result["status"] = "cooldown"
                return result
        rep = census.replay(index)
        if not rep.get("found"):
            return _finish("no_census")
        if not rep.get("backend_matches"):
            result["census_backend"] = rep.get("backend")
            return _finish("backend_mismatch")
        result["keys_total"] = rep.get("total", 0)
        result["keys_warm_before"] = rep.get("warm", 0)
        bodies = rep.get("bodies", [])[: int(self.config["max_bodies"])]
        if not bodies:
            # keys-only census (pre-v2, or traffic that bypassed the
            # body recorder): nothing replayable — complete, so the
            # cooldown still guards repeated no-op kicks
            return _finish("complete")
        from elasticsearch_tpu import resources

        breaker = resources.BREAKERS.breaker("request")
        charge = int(self.config["charge_bytes"])
        try:
            with self.node.tasks.task(
                    "cluster:admin/warmup",
                    description=f"pre-warm [{index}] "
                                f"({reason}, {len(bodies)} bodies)"
            ) as task:
                for row in bodies:
                    task.check_cancelled()
                    if self._stop.is_set():
                        return _finish("stopped")
                    # admission: warmup yields to live traffic. A denial
                    # is EXPECTED under pressure — no trip counted, no
                    # flight entry; a brief stop-gated retry, then defer.
                    admitted = False
                    for _ in range(int(self.config["defer_retries"])):
                        if breaker.reserve(charge, count_trip=False):
                            admitted = True
                            break
                        result["deferrals"] += 1
                        if self._stop.wait(self.config["defer_wait_s"]):
                            return _finish("stopped")
                    if not admitted:
                        return _finish("deferred")
                    tok = _PREWARM.set(True)
                    try:
                        body = json.loads(row.get("body") or "{}")
                        svc.search(body)
                        result["replayed"] += 1
                        self._m_replayed.inc()
                    except TaskCancelledException:
                        raise
                    except Exception:
                        # one stale body (mapping changed, field gone)
                        # must not stop the rest of the work list
                        result["errors"] += 1
                    finally:
                        _PREWARM.reset(tok)
                        breaker.release(charge)
        except TaskCancelledException:
            return _finish("canceled")
        rep2 = census.replay(index)
        result["keys_warm_after"] = rep2.get("warm", 0)
        return _finish("complete")

    # -- views / lifecycle ----------------------------------------------------

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the queue drains and no run is active (bench and
        tests; bounded — never wedges a caller)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                idle = not self._queue and self._active is None
            if idle:
                return True
            if self._stop.wait(0.02):
                return True
        return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "queued": [name for name, _ in self._queue],
                "active": self._active,
                "runs": {k: dict(v) for k, v in sorted(self.runs.items())},
            }

    def close(self) -> None:
        self._stop.set()
        th = self._thread
        if th is not None and th.is_alive():
            th.join(timeout=2.0)
