"""elasticsearch_tpu — a TPU-native distributed search & analytics engine.

A from-scratch rebuild of the capabilities of Elasticsearch (reference:
org.elasticsearch, ES 2.0 / Lucene 5.2) designed for TPU hardware:

- Immutable, device-resident columnar segments (padded CSR postings, doc
  values, dense-vector slabs) instead of Lucene's on-disk codecs.
- Queries compile to whole-segment dense scoring programs executed under
  ``jax.jit`` (segment-at-a-time, impact-style BM25), instead of Lucene's
  doc-at-a-time Weight/Scorer iterator trees.
- kNN vector search as bf16 matmuls on the MXU.
- Shards laid out across a ``jax.sharding.Mesh``; per-shard top-k merged
  with XLA collectives instead of transport-layer scatter/gather.

Public entry points:
    from elasticsearch_tpu import Node, Client
"""

__version__ = "0.1.0"

__all__ = ["Node", "Client", "__version__"]

# NOTE: the jit retrace auditor the search profiler reads
# (tracing/retrace.py) installs from the __init__ of each jit-binding
# package (ops/, models/, parallel/) — parent packages initialize before
# their submodules, so the patch lands before any `@jax.jit` binds,
# WITHOUT making this root import pull in jax (a Client-only import
# stays light, see __getattr__ below).


def __getattr__(name):  # lazy: submodules pull in jax; keep import light
    if name == "Node":
        from elasticsearch_tpu.node import Node

        return Node
    if name == "Client":
        from elasticsearch_tpu.client import Client

        return Client
    raise AttributeError(name)
