"""Launcher: ``python -m elasticsearch_tpu.server`` ≈ ``bin/elasticsearch``.

Reference: org/elasticsearch/bootstrap/Bootstrap.java + bin/elasticsearch.
"""
from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="elasticsearch_tpu")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--name", default="node-1")
    ap.add_argument("--cluster-name", default="elasticsearch_tpu")
    ap.add_argument("--data-path", default=None, help="directory for translog durability")
    args = ap.parse_args(argv)

    from elasticsearch_tpu.utils.platform import ensure_cpu_if_requested

    ensure_cpu_if_requested()

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.server import RestServer

    node = Node(name=args.name, data_path=args.data_path, cluster_name=args.cluster_name)
    server = RestServer(node, host=args.host, port=args.port)
    print(f"[{args.name}] listening on http://{server.host}:{server.port}", flush=True)

    def _stop(*_):
        print("shutting down", flush=True)
        server.stop()
        node.close()
        sys.exit(0)

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    server.start(background=False)


if __name__ == "__main__":
    main()
