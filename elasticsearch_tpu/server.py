"""Launcher: ``python -m elasticsearch_tpu.server`` ≈ ``bin/elasticsearch``.

Reference: org/elasticsearch/bootstrap/Bootstrap.java + bin/elasticsearch.
"""
from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(prog="elasticsearch_tpu")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9200)
    ap.add_argument("--name", default="node-1")
    ap.add_argument("--cluster-name", default="elasticsearch_tpu")
    ap.add_argument("--data-path", default=None, help="directory for translog durability")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of the jax.distributed coordinator "
                         "(process 0); enables the multi-host control plane "
                         "with rank-0 master over the TCP transport")
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--transport-port", type=int, default=9300,
                    help="TCP control-plane port (rank 0 binds it; other "
                         "ranks dial the coordinator host on it)")
    ap.add_argument("--minimum-master-nodes", type=int, default=None,
                    help="election/publish quorum; default: majority of "
                         "the master-eligible voting configuration")
    args = ap.parse_args(argv)

    from elasticsearch_tpu.utils.platform import (enable_compilation_cache,
                                                   ensure_cpu_if_requested)

    ensure_cpu_if_requested()
    enable_compilation_cache()  # persistent XLA cache: warm-start restarts

    cluster = None
    if args.coordinator:
        from elasticsearch_tpu.cluster.bootstrap import initialize_distributed

        initialize_distributed(args.coordinator, args.num_processes,
                               args.process_id)

    from elasticsearch_tpu.node import Node
    from elasticsearch_tpu.rest.server import RestServer

    node = Node(name=args.name, data_path=args.data_path, cluster_name=args.cluster_name)
    if args.coordinator:
        from elasticsearch_tpu.cluster.bootstrap import MultiHostCluster

        cluster = MultiHostCluster(
            node, args.process_id, args.num_processes,
            bind_host=args.host, transport_port=args.transport_port,
            master_host=args.coordinator.split(":")[0],
            minimum_master_nodes=args.minimum_master_nodes)
        role = "master" if cluster.is_master else "data"
        print(f"[{args.name}] joined cluster as {role} "
              f"(rank {args.process_id}/{args.num_processes})", flush=True)
    server = RestServer(node, host=args.host, port=args.port)
    print(f"[{args.name}] listening on http://{server.host}:{server.port}", flush=True)

    def _stop(*_):
        print("shutting down", flush=True)
        if cluster is not None:
            cluster.close()
        # close the node IN the handler, stop the listener from a helper
        # thread: this handler interrupted serve_forever on THIS thread,
        # so a same-thread httpd.shutdown() waits forever for the loop it
        # suspended — the old sequence deadlocked here and node.close()
        # (translog flush, program-census persistence) never ran
        import threading

        threading.Thread(target=server.stop, daemon=True).start()
        node.close()
        sys.exit(0)  # unwinds serve_forever; the stopper thread's
        # server_close then runs against an already-exited loop

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    server.start(background=False)


if __name__ == "__main__":
    main()
