"""Device-program observatory: per-key compile/execute attribution.

Before this module the node knew *that* jit retraces happened
(tools.tpulint.trace_audit counts them) but not *which* program compiled,
with *which* padded shapes, or what it cost — the "(program, shapes,
backend fingerprint)" census ROADMAP #6 (persistent compiled-program
cache + pre-warming) and #3 (metric-driven shard allocation) both need.
This registry closes that gap with ONE process-global table of
:class:`ProgramEntry` rows keyed by

    (program, shapes, backend)

where ``program`` is a stable logical name (a dispatch-point name like
``mesh_dsl``/``batch_bm25_fused``, or a jitted callable's qualname as
reported by the trace auditor), ``shapes`` is the canonical padded
arg-shape/dtype signature (:func:`shape_sig` / :func:`static_sig` — the
pow2 padding discipline makes this a small, stable universe), and
``backend`` is :func:`backend_fingerprint` (platform + device kind), so
a census captured on one chip is never replayed against another.

Two feeds, two granularities:

- **Compiles** arrive from the trace auditor's reporter hook
  (tracing/retrace.py installs it): every jit (re)trace reports the
  traced callable's identity and its abstract arg shapes — exact, even
  for programs no dispatch wrapper knows about. These census-level rows
  carry compile *counts*; their wall time is attributed below.
- **Wall time** arrives from :meth:`ProgramRegistry.timed` wrappers at
  the host dispatch points (parallel/executor.py, search/batch.py fused
  paths, ops/ivf.py): a call whose per-THREAD trace count moved paid
  tracing+compilation (``compile_seconds``); a steady call ran a cached
  program (``calls``/``execute_seconds`` + the PR-7 log-bucket
  Histogram for p50/p99). The same thread-attribution trick the search
  profiler uses keeps concurrent requests honest.

A dispatch-level key therefore aggregates the inner jit programs it
drives: its ``compiles`` counts *calls that paid compilation*, while the
trace-level rows underneath count each inner program's traces — read
``_cat/programs`` with that two-level shape in mind.

Cardinality: the key universe is bounded by pow2 padding, but a bug
(R001 territory) could explode it — past ``_MAX_KEYS`` new keys collapse
into the reserved ``_other_`` row (monitor/metrics.py's overflow
discipline: counts are never lost, they lose attribution). The
``estpu_program_*`` metric families read this registry at scrape time,
so the same cap bounds the exposition.

Census: while an index's search runs inside :func:`index_scope`, every
recorded key also lands in that index's (program, shapes, field) census
set — persisted beside IVF/PQ artifacts via resources/census.py and
replayable later for pre-warming (ROADMAP #6).

Clock discipline (tpulint R007): durations come from
``time.perf_counter()`` deltas; ``last_used_at`` is a display-only epoch
timestamp that never feeds a subtraction.
"""
from __future__ import annotations

import contextvars
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from elasticsearch_tpu.monitor.metrics import (DEFAULT_LATENCY_BUCKETS,
                                               OVERFLOW_LABEL, Histogram)

#: the index whose search is currently executing on this logical flow —
#: set by IndexService.search / the fused batch path so dispatch-point
#: records can accrue into the per-index census without threading an
#: index name through every layer
_ACTIVE_INDEX: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("estpu-program-index", default=None)

#: the (program, shapes) key of the dispatch wrapper currently timing a
#: device call on this flow — set by :meth:`ProgramRegistry.timed` so the
#: AOT layer (parallel/aot.py) can attribute its cache-source events to
#: the SAME observatory key the wall time lands on (the AOT layer only
#: sees raw arg signatures, which differ from dispatch-point static sigs)
_ACTIVE_PROG_KEY: contextvars.ContextVar[Optional[Tuple[str, str]]] = \
    contextvars.ContextVar("estpu-program-key", default=None)


@contextmanager
def index_scope(index_name: Optional[str]):
    """Scope ``index_name`` as the census target for program records made
    below (None = record without census attribution)."""
    tok = _ACTIVE_INDEX.set(index_name)
    try:
        yield
    finally:
        _ACTIVE_INDEX.reset(tok)


# ---------------------------------------------------------------------------
# key components
# ---------------------------------------------------------------------------

def _one_sig(a: Any) -> str:
    """One argument's shape/dtype signature. Works on np/jax arrays AND
    abstract tracers (both expose .shape/.dtype); non-array leaves render
    as their type name so a static python arg still perturbs the key."""
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        dims = ",".join(str(int(d)) for d in shape)
        return f"{_short_dtype(str(dtype))}[{dims}]"
    if isinstance(a, (list, tuple)):
        return "(" + "+".join(_one_sig(x) for x in a) + ")"
    if isinstance(a, (bool, int, float, str)):
        return repr(a)
    return type(a).__name__


_DTYPE_SHORT = {"float32": "f32", "float64": "f64", "bfloat16": "bf16",
                "float16": "f16", "int32": "i32", "int64": "i64",
                "int8": "i8", "uint8": "u8", "uint32": "u32", "bool": "b1"}


def _short_dtype(name: str) -> str:
    return _DTYPE_SHORT.get(name, name)


def shape_sig(args: Iterable[Any] = (), kwargs: Optional[dict] = None) -> str:
    """Canonical padded-shape signature of a call's arguments:
    ``f32[8,1024]|i32[8,16]``. Deterministic in shapes/dtypes only — no
    object ids, no ordering surprises — so the same query shape produces
    the same key in every process (the census replay contract)."""
    parts = [_one_sig(a) for a in args]
    for k in sorted(kwargs or {}):
        parts.append(f"{k}={_one_sig(kwargs[k])}")
    return "|".join(parts)


def static_sig(**dims: Any) -> str:
    """Signature from the static shape-class dims a dispatch point keys
    its own program cache on (``Q=8|D=1024|k=10``) — equivalent to the
    padded array shapes but free to compute."""
    return "|".join(f"{k}={dims[k]}" for k in sorted(dims))


_FP_LOCK = threading.Lock()
_FP: Optional[str] = None


def backend_fingerprint() -> str:
    """``platform/device-kind`` of the default backend (``cpu/cpu`` on
    the host fallback). Cached after first resolution; ``unknown`` when
    jax is unavailable — never raises, never blocks a record."""
    global _FP
    if _FP is not None:
        return _FP
    with _FP_LOCK:
        if _FP is not None:
            return _FP
        try:
            import jax

            platform = jax.default_backend()
            kind = getattr(jax.devices()[0], "device_kind", platform)
            fp = f"{platform}/{kind}".replace(" ", "_")
        except Exception:
            return "unknown"  # don't cache: jax may appear later
        _FP = fp
        return _FP


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class ProgramEntry:
    """Counters for one (program, shapes, backend) key."""

    __slots__ = ("program", "shapes", "backend", "compiles",
                 "compile_seconds", "calls", "execute_seconds", "hist",
                 "fields", "last_used_at", "cache_sources")

    _FIELD_CAP = 8  # bounded per-entry field set (census attribution)

    def __init__(self, program: str, shapes: str, backend: str):
        self.program = program
        self.shapes = shapes
        self.backend = backend
        self.compiles = 0
        self.compile_seconds = 0.0
        self.calls = 0
        self.execute_seconds = 0.0
        self.hist = Histogram(DEFAULT_LATENCY_BUCKETS)
        self.fields: Set[str] = set()
        self.last_used_at = 0.0  # epoch, display only (no subtraction)
        # per-source resolution counts from the AOT executable cache
        # (aot_hit / xla_dir_hit / fresh — parallel/aot.py): the honest
        # "where did this program come from" ledger behind the `cache`
        # column of _cat/programs. Bounded by construction: the source
        # vocabulary is fixed.
        self.cache_sources: Dict[str, int] = {}

    @property
    def cold(self) -> bool:
        """True until the key serves its first CACHED execution in this
        process — a restarted node's whole table starts cold, which is
        exactly the warmup cliff ROADMAP #6 wants to see and then
        eliminate. Trace-census rows with no dispatch wrapper stay cold
        by construction."""
        return self.calls == 0

    def to_json(self) -> dict:
        return {
            "program": self.program,
            "shapes": self.shapes,
            "backend": self.backend,
            "compiles": self.compiles,
            "compile_seconds": round(self.compile_seconds, 6),
            "calls": self.calls,
            "execute_seconds": round(self.execute_seconds, 6),
            "execute_p50_seconds": round(self.hist.percentile(50), 6),
            "execute_p99_seconds": round(self.hist.percentile(99), 6),
            "cold": self.cold,
            "fields": sorted(self.fields),
            "last_used_at": self.last_used_at,
            "cache_sources": dict(sorted(self.cache_sources.items())),
        }


class ProgramRegistry:
    """Thread-safe (program, shapes, backend) → :class:`ProgramEntry`
    table with per-index census sets. Process-global singleton
    (:data:`REGISTRY`): the device — and its compiled-program cache —
    is process-shared, so attribution is too."""

    _MAX_KEYS = 512          # key cap; overflow collapses, never grows
    _CENSUS_CAP = 1024       # per-index census key cap
    _BODY_CAP = 64           # per-index replayable-body cap

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str, str], ProgramEntry] = {}
        # per-index (program, shapes, field) → hit count: the count makes
        # warmup hottest-first instead of alphabetical (ISSUE 14)
        self._census: Dict[str, Dict[Tuple[str, str, str], int]] = {}
        # per-index canonical search bodies → hit count: the REPLAYABLE
        # half of the census. Keys alone can't rebuild a compiled DSL
        # tree (mesh_dsl program structure isn't derivable from arg
        # shapes), so warmup replays the observed bodies through the
        # real search path — which drives the real program factories —
        # and the keys verify coverage (census.replay warm/missing).
        self._bodies: Dict[str, Dict[str, int]] = {}
        # monotone census/bodies mutation counters: the watchdog's
        # periodic flush skips the blob write when nothing moved —
        # per INDEX, so one busy index can't force idle siblings'
        # censuses through a load+merge+rewrite every interval
        self._census_gen = 0
        self._census_gens: Dict[str, int] = {}
        # in-flight dispatches on the shared age-board primitive
        # (monitor/flight.py::OpBoard — the watchdog's publish tracking
        # rides the same class): the program-stall detector reads ages
        # from here, because a dispatch that never returns is invisible
        # to every completion-fed counter above.
        from elasticsearch_tpu.monitor.flight import OpBoard

        self._inflight = OpBoard()

    # -- entry resolution ----------------------------------------------------

    def _entry(self, program: str, shapes: str,
               field: Optional[str], census: bool = True) -> ProgramEntry:
        """Get-or-create under the lock; past the cap the reserved
        overflow row absorbs new keys (counts survive, attribution
        doesn't — the metrics.py discipline). ``census=False`` skips the
        per-index census side effect (cache-source accounting resolves
        entries without knowing the field — recording would plant a
        spurious field-less duplicate beside the real dispatch row)."""
        backend = backend_fingerprint()
        key = (program, shapes, backend)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                if len(self._entries) >= self._MAX_KEYS:
                    key = (OVERFLOW_LABEL, OVERFLOW_LABEL, backend)
                    e = self._entries.get(key)
                if e is None:
                    e = ProgramEntry(*key)
                    self._entries[key] = e
            if field and len(e.fields) < ProgramEntry._FIELD_CAP:
                e.fields.add(field)
            index = _ACTIVE_INDEX.get()
            if census and index is not None and key[0] != OVERFLOW_LABEL:
                c = self._census.setdefault(index, {})
                ck = (program, shapes, field or "")
                if ck in c:
                    c[ck] += 1
                    self._bump_census_gen_locked(index)
                elif len(c) < self._CENSUS_CAP:
                    c[ck] = 1
                    self._bump_census_gen_locked(index)
        return e

    def _bump_census_gen_locked(self, index: str) -> None:
        self._census_gen += 1
        self._census_gens[index] = self._census_gens.get(index, 0) + 1

    # -- recording -----------------------------------------------------------

    def record_compile(self, program: str, shapes: str, n: int = 1,
                       seconds: float = 0.0,
                       field: Optional[str] = None) -> None:
        """A (re)trace of ``program`` at ``shapes`` — fed by the trace
        auditor's reporter for every jit program in the process."""
        e = self._entry(program, shapes, field)
        with self._lock:
            e.compiles += n
            e.compile_seconds += float(seconds)
            e.last_used_at = time.time()
        # flight recorder: compile events are rare by construction (the
        # pow2 discipline bounds the program universe) and each one is a
        # latency cliff worth a black-box entry
        try:
            from elasticsearch_tpu.monitor import flight

            flight.record("compiles", program=program, shapes=shapes,
                          seconds=round(float(seconds), 6))
        except Exception:
            pass  # recording must never fail the compile feed

    def record_execute(self, program: str, shapes: str, seconds: float,
                       field: Optional[str] = None) -> None:
        """A cached-program execution of ``seconds`` wall time."""
        e = self._entry(program, shapes, field)
        e.hist.observe(float(seconds))  # own lock; plain host float (R009)
        with self._lock:
            e.calls += 1
            e.execute_seconds += float(seconds)
            e.last_used_at = time.time()

    def record_call(self, program: str, shapes: str, seconds: float,
                    trace_delta: int, field: Optional[str] = None) -> None:
        """One dispatch of ``seconds`` wall time, classified by the
        caller's per-thread trace delta (``retrace.traces_since``). For
        call sites that can only decide AFTER the call whether it served
        a real program (the fused-batch tiers return None on refusal) —
        :meth:`timed` is the same thing as a context manager.

        ``trace_delta < 0`` means the auditor is unavailable — then the
        call records NOTHING: classifying blind would file seconds of
        tracing+compilation as a cached execute (a fake known), the
        exact -1-sentinel leak the warmup label reports as ``unknown``.
        Without the auditor the observatory honestly degrades to empty.
        """
        if trace_delta < 0:
            return
        if trace_delta > 0:
            self.record_compile(program, shapes, n=1, seconds=seconds,
                                field=field)
        else:
            self.record_execute(program, shapes, seconds, field=field)

    def record_cache_source(self, source: str,
                            fallback_program: str = "",
                            fallback_shapes: str = "") -> None:
        """One AOT-cache resolution (aot_hit / xla_dir_hit / fresh,
        parallel/aot.py) attributed to the observatory key of the
        dispatch wrapper currently timing this flow — the contextvar
        :meth:`timed` sets — so the `cache` column of _cat/programs
        lines up with the wall-time rows. Resolutions outside any timed
        block (direct factory use) land on the caller-supplied
        fallback key."""
        active = _ACTIVE_PROG_KEY.get()
        program, shapes = active if active is not None else (
            fallback_program, fallback_shapes)
        if not program:
            return
        # census=False: the dispatch wrapper's own record carries the
        # field — a second, field-less census row here would be a
        # phantom key in every persisted census
        e = self._entry(program, shapes, None, census=False)
        with self._lock:
            e.cache_sources[source] = e.cache_sources.get(source, 0) + 1

    def record_body(self, index: str, body_key: str, n: int = 1) -> None:
        """One eligible canonical search body observed for ``index`` —
        the replayable census half (IndexService.search feeds this;
        pre-warm replays suppress themselves so warmup traffic never
        inflates its own work list). Bounded per index; hit counts make
        replay hottest-first. ``n`` > 1 when the caller samples (each
        recorded observation stands for n requests)."""
        n = max(1, int(n))
        with self._lock:
            b = self._bodies.setdefault(index, {})
            if body_key in b:
                b[body_key] += n
            elif len(b) < self._BODY_CAP:
                b[body_key] = n
            else:
                # lossy-counting probation at the cap: decay the coldest
                # entry; once it bottoms out the newcomer takes its slot.
                # A workload that SHIFTS to new hot bodies therefore
                # displaces stale early ones (first-come-forever would
                # freeze the replay set at boot-time traffic), while a
                # churn of one-off queries only nibbles at the floor —
                # hot entries' counts dwarf the decay.
                # decay/insert by n, not 1: in the sampled regime each
                # observation stands for n requests — unit steps would
                # displace stale entries n× slower than the model above
                cold = min(b, key=b.get)
                if b[cold] <= n:
                    del b[cold]
                    b[body_key] = n
                else:
                    b[cold] -= n
            self._bump_census_gen_locked(index)

    # -- in-flight dispatch tracking (watchdog feed) -------------------------

    def begin_dispatch(self, program: str, shapes: str) -> int:
        """Mark one dispatch in flight; returns the token
        :meth:`end_dispatch` retires. Cost: one dict insert under the
        board's own small lock — the only hot-path addition the
        watchdog needs (the registry lock is never touched)."""
        return self._inflight.begin(program, shapes=shapes)

    def end_dispatch(self, token: int) -> None:
        self._inflight.end(token)

    def inflight_snapshot(self) -> List[dict]:
        """Every dispatch currently in flight, with its age."""
        return [{"program": r["kind"], "shapes": r.get("shapes", ""),
                 "age_seconds": r["age_seconds"]}
                for r in self._inflight.snapshot()]

    def execute_p99(self, program: str, shapes: str) -> Tuple[float, int]:
        """(execute p99 seconds, cached-call count) for one key under
        the current backend — the watchdog derives its adaptive stall
        bound from the key's OWN history, not a blanket constant."""
        key = (program, shapes, backend_fingerprint())
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return 0.0, 0
            calls = e.calls
        return e.hist.percentile(99), calls

    @contextmanager
    def timed(self, program: str, shapes: str,
              field: Optional[str] = None):
        """Time one device dispatch and attribute it: the per-THREAD jit
        trace count moving inside the block means this call paid
        tracing+compilation (the profiler's exact trick — a neighbor
        request's compile on another thread can't misclassify this one).
        Nothing records when the block raises: a failed dispatch (e.g.
        the Pallas→XLA retry) must not pollute the execute histogram.
        The dispatch IS visible to the watchdog while in flight either
        way (begin/end_dispatch) — a hang records nothing but ages."""
        from elasticsearch_tpu.tracing import retrace

        snap = retrace.snapshot()
        tok = self.begin_dispatch(program, shapes)
        # the AOT layer resolving a program INSIDE this block attributes
        # its cache source to this key (record_cache_source)
        ptok = _ACTIVE_PROG_KEY.set((program, shapes))
        t0 = time.perf_counter()
        try:
            yield
        finally:
            _ACTIVE_PROG_KEY.reset(ptok)
            self.end_dispatch(tok)
        self.record_call(program, shapes, time.perf_counter() - t0,
                         retrace.traces_since(snap), field=field)

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> List[dict]:
        """Per-key rows, sorted by (program, shapes, backend). Rows are
        rendered UNDER the registry lock: a concurrent ``_entry()`` adds
        to ``e.fields`` under the same lock, and an unlocked
        ``sorted(fields)`` mid-mutation is a RuntimeError that would
        500 a scrape. (Histogram percentiles take only the histogram's
        own lock, never the registry lock — no ordering cycle.)"""
        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: (e.program, e.shapes, e.backend))
            return [e.to_json() for e in entries]

    def counters_snapshot(self) -> List[Tuple[str, str, str, int, float,
                                              float]]:
        """(program, shapes, backend, compiles, compile_seconds,
        execute_seconds) rows — the cheap view for scrape-time
        collectors and the bench counter map: no percentile math, one
        lock acquisition for all three metric families."""
        with self._lock:
            return sorted(
                (e.program, e.shapes, e.backend, e.compiles,
                 e.compile_seconds, e.execute_seconds)
                for e in self._entries.values())

    def stats(self) -> dict:
        """Aggregate totals for the ``programs`` section of
        ``/_nodes/stats`` (note the two-level counting: dispatch keys
        aggregate the trace-level programs they drive)."""
        with self._lock:
            entries = list(self._entries.values())
        return {
            "keys": len(entries),
            "compiles": sum(e.compiles for e in entries),
            "compile_seconds": round(
                sum(e.compile_seconds for e in entries), 6),
            "calls": sum(e.calls for e in entries),
            "execute_seconds": round(
                sum(e.execute_seconds for e in entries), 6),
        }

    def census(self, index: str) -> List[dict]:
        """The observed (program, shapes, field) key set for ``index``
        with per-key hit counts, sorted — the persistable pre-warm
        census (resources/census.py)."""
        with self._lock:
            keys = sorted(self._census.get(index, {}).items())
        return [{"program": p, "shapes": s, "field": f, "hits": n}
                for (p, s, f), n in keys]

    def bodies(self, index: str) -> List[dict]:
        """The observed replayable bodies for ``index``, hottest first —
        the warmup work list (serving/warmup.py replays these through
        the real search path, hottest keys first)."""
        with self._lock:
            items = sorted(self._bodies.get(index, {}).items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return [{"body": b, "hits": n} for b, n in items]

    def census_generation(self) -> int:
        """Monotone census/bodies mutation counter — the watchdog's
        periodic durability flush short-circuits when this is still."""
        with self._lock:
            return self._census_gen

    def census_generations(self) -> Dict[str, int]:
        """Per-index mutation counters — the flush writes only the
        indices that actually moved."""
        with self._lock:
            return dict(self._census_gens)

    def census_indices(self) -> List[str]:
        with self._lock:
            return sorted(set(self._census) | set(self._bodies))

    def counter_values(self) -> Dict[str, float]:
        """Flat per-key counter map for the bench before/after delta
        (``programs.<program>|<shapes>.{compiles,...}``). Reads the
        cheap counters view — no percentile math per snapshot."""
        out: Dict[str, float] = {}
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            base = f"programs.{e.program}|{e.shapes}"
            out[f"{base}.compiles"] = float(e.compiles)
            out[f"{base}.compile_seconds"] = float(e.compile_seconds)
            out[f"{base}.calls"] = float(e.calls)
            out[f"{base}.execute_seconds"] = float(e.execute_seconds)
        return out

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self._entries.clear()
            self._census.clear()
            self._bodies.clear()
            self._census_gen = 0
            self._census_gens.clear()
        self._inflight.clear()


#: the process singleton every feed records into
REGISTRY = ProgramRegistry()
