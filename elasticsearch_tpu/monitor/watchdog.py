"""Stall watchdogs: detectors that turn hangs into incidents.

Reference: ES itself has no watchdog in 2.x (operators got one in 7.x as
the ThreadWatchdog for the A2A transport and much later as the
StuckThreadDetector); production ES deployments lean on external
monitors. Here the runtime watches itself: a background service ticks
every ``interval`` seconds and evaluates a fixed detector set against
state the PRs before this one already account:

====================  ======================================================
detector              trips when
====================  ======================================================
``program_stall``     a device-program dispatch has been in flight longer
                      than an ADAPTIVE bound derived from that key's own
                      execute-latency history in the ProgramRegistry
                      (``mult × p99``, floored; keys with no history get
                      the absolute default) — the "one stalled chip stalls
                      the whole mesh" failure shard_map collectives make
                      possible, caught at the host dispatch point.
``threadpool_starve`` a named pool's oldest queued work item is older than
                      the bound while EVERY worker is busy — requests are
                      aging behind wedged workers, not just bursting.
``translog_fsync``    fsync observations since the last tick average over
                      the bound, or the lifetime max grew past it — a
                      pathological disk under durability=request.
``publish_stall``     a two-phase cluster-state publish has been in flight
                      longer than the bound, or a publish aborted inside
                      the commit window (the ``publish.commit`` fault
                      domain: quorum acked phase 1, commit fan-out never
                      ran — followers hold parked state).
``coalescer_drain``   the serving coalescer's oldest parked request has
                      waited orders of magnitude past the micro-batch
                      window — the drain thread is wedged or dead.
``relocation_stall``  an allocator-driven shard relocation has been in
                      flight longer than the bound — the recovery stream
                      to the target is wedged (``relocation.stream``
                      fault, dead target, hung transport). The trip also
                      ACTS: it cancels the move through the allocator
                      (releasing its throttle slot) and reschedules it
                      on a different target with the wedged one banned.
====================  ======================================================

A trip increments ``estpu_watchdog_trips_total{detector}``, records a
tracer event and a flight-ring entry, and — outside the per-detector
cooldown — captures an **incident dump**: the flight rings, a one-shot
hot-threads stack snapshot, the program table (with in-flight
dispatches), and the task list, persisted through the generic blob
helpers (monitor/flight.py::IncidentStore) so it survives restart.
Within the cooldown the observation still lands in the ``slow_ops``
flight ring — evidence accrues, dumps don't spam.

Fault injection: ``FAULTS.check("watchdog.program_stall")`` fires inside
the program detector's scan — an armed fault makes the detector treat
every in-flight dispatch (or, with none, a synthetic key) as stalled,
driving the full trip → incident → persistence pipeline without a real
hang; the age math itself is tested by planting in-flight entries.

Thread discipline (tpulint R011, extended to monitor/ by this PR): the
tick thread is ``daemon=True`` and its loop is gated on a stop Event
(``while not self._stop.wait(interval)``). Clock discipline (R007):
ages and bounds use ``time.monotonic()``/``perf_counter`` deltas only.
"""
from __future__ import annotations

import itertools
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.monitor import flight
from elasticsearch_tpu.utils.faults import FAULTS

#: detector names — the stable label set of estpu_watchdog_trips_total
DETECTORS = ("program_stall", "threadpool_starve", "translog_fsync",
             "publish_stall", "coalescer_drain", "relocation_stall")


def hot_threads_snapshot(limit: int = 32) -> List[dict]:
    """One-shot stack capture of every live thread — the incident-dump
    variant of ``/_nodes/hot_threads``: no sampling sleep (the watchdog
    must never add latency to the anomaly it is recording), just the
    exact stacks at capture time, capped at ``limit`` threads."""
    out: List[dict] = []
    frames = sys._current_frames()
    me = threading.get_ident()
    for t in threading.enumerate():
        if len(out) >= limit:
            break
        fr = frames.get(t.ident)
        if fr is None:
            continue
        # unlike the sampling endpoint, the CAPTURING thread is kept
        # (marked): when a request thread trips a detector inline, its
        # own stack is part of the evidence
        out.append({
            "name": t.name,
            "ident": t.ident,
            "daemon": t.daemon,
            "sampler": t.ident == me,
            "stack": [f"{f.filename}:{f.lineno} {f.name}"
                      for f in traceback.extract_stack(fr)],
        })
    return out


class WatchdogService:
    """Per-node watchdog: detector evaluation + incident capture.

    Construction is cheap (no thread); serving entry points call
    :meth:`ensure_started`. Tests drive :meth:`run_once` directly for
    deterministic single ticks. ``ESTPU_WATCHDOG=0`` disables the
    background thread entirely (run_once still works)."""

    #: default bounds — constructor overrides for tests; generous enough
    #: that a healthy node under load never trips
    DEFAULTS: Dict[str, float] = {
        "interval_s": 1.0,
        # program_stall: bound = clamp(p99_mult × key p99, floor, none);
        # keys with < min_calls history use the absolute default
        "program_floor_s": 1.0,
        "program_p99_mult": 8.0,
        "program_default_bound_s": 30.0,
        "program_min_calls": 8,
        "threadpool_age_bound_s": 5.0,
        "fsync_bound_s": 1.0,
        "publish_bound_s": 10.0,
        "coalescer_bound_s": 2.0,
        # relocation_stall: a healthy stream finishes in seconds even
        # for big shards (ops ride one transport round); a minute of
        # flight means the stream is wedged, not slow
        "relocation_bound_s": 60.0,
        # per-detector incident cooldown: within it a trip still counts
        # and records, but no new dump is captured
        "cooldown_s": 30.0,
        # census durability (ISSUE 14): the pre-warm work list used to
        # persist only on clean Node.close() — a crash or kill lost it.
        # The tick thread flushes it on this cadence when it changed.
        "census_flush_every_s": 60.0,
    }

    def __init__(self, node, **overrides: float):
        self.node = node
        self.config: Dict[str, float] = dict(self.DEFAULTS)
        for k, v in overrides.items():
            if k not in self.config:
                raise ValueError(f"unknown watchdog option [{k}]")
            self.config[k] = v
        self.board = flight.OpBoard()
        self.incidents = flight.IncidentStore()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self.ticks = 0
        self.trips: Dict[str, int] = {}
        self.incidents_captured = 0
        # per-detector monotonic time of the last incident capture
        self._last_incident: Dict[str, float] = {}
        # incremental-scan cursors; fsync seeds from the LIVE histogram
        # on the first tick — it is process-shared and may already hold
        # history this watchdog must not attribute to its first tick
        self._last_counters: Optional[Dict[str, float]] = None
        self._fsync_seen: Optional[Tuple[int, float, List[int]]] = None
        self._cluster_scan_ts = time.monotonic()
        # census-flush cursors: last flushed PER-INDEX generations +
        # last flush monotonic — flush only the indices that moved, and
        # only at the cadence, so a busy index pays one blob write per
        # interval and its idle siblings pay nothing
        self._census_flushed_gens: Dict[str, int] = {}
        self._census_flush_ts = time.monotonic()
        self._m_trips = node.metrics.counter(
            "estpu_watchdog_trips_total",
            "Watchdog detector trips, by detector", ("detector",))

    # -- lifecycle -----------------------------------------------------------

    def ensure_started(self) -> None:
        """Start the tick thread (idempotent). Called by the serving
        entry points (RestServer, cluster bootstrap) — library-embedded
        Nodes that never serve don't pay for a polling thread."""
        if os.environ.get("ESTPU_WATCHDOG", "1").lower() in (
                "0", "false", "off"):
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="estpu-watchdog", daemon=True)
            self._thread.start()

    def close(self) -> None:
        self._stop.set()
        th = self._thread
        if th is not None and th.is_alive():
            th.join(timeout=2.0)

    @property
    def running(self) -> bool:
        th = self._thread
        return th is not None and th.is_alive() and not self._stop.is_set()

    def _loop(self) -> None:
        while not self._stop.wait(self.config["interval_s"]):
            try:
                self.run_once()
            except Exception:
                pass  # a detector bug must never kill the watchdog loop

    # -- one tick ------------------------------------------------------------

    def run_once(self) -> List[dict]:
        """Evaluate every detector once; returns the trips (tests read
        them directly, production discards — everything observable went
        through metrics/flight/incidents)."""
        self.ticks += 1
        self._sample_metrics()
        try:
            self._flush_census()
        except Exception:
            pass  # durability is best-effort; detectors still run
        trips: List[dict] = []
        for check in (self._check_programs, self._check_threadpools,
                      self._check_fsync, self._check_publish,
                      self._check_coalescer, self._check_relocations):
            try:
                trips.extend(check())
            except Exception:
                pass  # one broken detector must not silence the others
        return trips

    def _sample_metrics(self) -> None:
        """Metric-delta snapshot into the flight ring: which counters
        moved since the last tick (bounded at 32 keys — the ring is a
        black box, not a TSDB; /_prometheus/metrics is the full view)."""
        from elasticsearch_tpu.monitor.metrics import process_counters

        try:
            now_counters = process_counters()
        except Exception:
            return
        prev = self._last_counters
        self._last_counters = now_counters
        if prev is None:
            return
        delta = {}
        for k, v in now_counters.items():
            d = v - prev.get(k, 0.0)
            if d > 0 and v >= 0 and prev.get(k, 0.0) >= 0:
                delta[k] = int(d) if d == int(d) else d
                if len(delta) >= 32:
                    break
        if delta:
            self.node.flight.record("metrics", delta=delta)

    def _flush_census(self) -> None:
        """Census durability (ISSUE 14 satellite): persist this node's
        per-index program census + replayable bodies on the tick cadence
        whenever the registry moved since the last flush — a kill -9 now
        costs at most one interval of census, not the whole pre-warm
        work list. Scoped to THIS node's indices (the registry is
        process-global; a sibling in-process node flushes its own)."""
        from elasticsearch_tpu.monitor import programs
        from elasticsearch_tpu.resources import census

        gens = programs.REGISTRY.census_generations()
        dirty = [name for name in set(gens) & set(self.node.indices)
                 if gens[name] != self._census_flushed_gens.get(name)]
        if not dirty:
            return
        now = time.monotonic()
        if now - self._census_flush_ts < self.config["census_flush_every_s"]:
            return
        # the TIME cursor advances now (failed stores retry at the
        # cadence, not every tick); each index's GENERATION cursor
        # advances only when ITS store succeeded — a transient disk
        # error on an idle-afterwards node must not mark unflushed
        # census data flushed forever
        self._census_flush_ts = now
        for name in dirty:
            try:
                census.store_census(name)
            except Exception:
                continue  # one index's failed write must not starve
                # the rest — and must keep ITS generation dirty
            self._census_flushed_gens[name] = gens[name]

    # -- detectors -----------------------------------------------------------

    def _program_bound(self, program: str, shapes: str) -> float:
        """The adaptive bound for one key: ``mult × its own execute
        p99`` (floored) once the key has history, else the absolute
        default — a key that normally runs in 2ms is stalled at 16ms×…
        long before a 30s blanket bound would notice."""
        from elasticsearch_tpu.monitor import programs

        p99, calls = programs.REGISTRY.execute_p99(program, shapes)
        if calls >= self.config["program_min_calls"] and p99 > 0:
            return max(self.config["program_floor_s"],
                       self.config["program_p99_mult"] * p99)
        return self.config["program_default_bound_s"]

    def _check_programs(self) -> List[dict]:
        from elasticsearch_tpu.monitor import programs

        inflight = programs.REGISTRY.inflight_snapshot()
        injected = False
        try:
            FAULTS.check("watchdog.program_stall", inflight=len(inflight))
        except Exception:
            # the armed fault simulates the stall: every in-flight
            # dispatch is treated as past its bound, driving the full
            # trip → incident → persistence pipeline deterministically
            injected = True
        trips = []
        for row in inflight:
            bound = self._program_bound(row["program"], row["shapes"])
            detail = dict(row, bound_seconds=round(bound, 6),
                          injected=injected)
            if injected or row["age_seconds"] > bound:
                trips.append(self._trip(
                    "program_stall",
                    f"device program [{row['program']}|{row['shapes']}] "
                    f"in flight {row['age_seconds']:.3f}s "
                    f"(bound {bound:.3f}s)", detail))
            elif row["age_seconds"] > bound / 2.0:
                self.node.flight.record("slow_ops", detector="program_stall",
                                        **detail)
        if injected and not inflight:
            trips.append(self._trip(
                "program_stall", "injected stall (no dispatch in flight)",
                {"program": "<injected>", "shapes": "", "injected": True}))
        return trips

    def _check_threadpools(self) -> List[dict]:
        tp = self.node._thread_pool
        if tp is None:
            return []
        trips = []
        bound = self.config["threadpool_age_bound_s"]
        for name, pool in tp.pools.items():
            age = pool.oldest_queue_age()
            if age is None:
                continue
            st = pool.stats()
            detail = {"pool": name, "oldest_age_seconds": round(age, 3),
                      "active": st["active"], "threads": st["threads"],
                      "queue": st["queue"]}
            if age > bound and st["active"] >= st["threads"]:
                trips.append(self._trip(
                    "threadpool_starve",
                    f"pool [{name}] oldest queued work is {age:.1f}s old "
                    f"with all {st['threads']} workers busy", detail))
            elif age > bound / 2.0:
                self.node.flight.record("slow_ops",
                                        detector="threadpool_starve",
                                        **detail)
        return trips

    def _check_fsync(self) -> List[dict]:
        from elasticsearch_tpu.monitor.metrics import SHARED

        h = SHARED.histogram(
            "estpu_translog_fsync_duration_seconds",
            "Translog flush+fsync latency").labels()
        with h._lock:
            count, total = h.count, h.sum
            counts = list(h.counts)
        last = self._fsync_seen
        self._fsync_seen = (count, total, counts)
        if last is None:
            return []  # first tick: baseline only, history isn't news
        last_count, last_sum, last_counts = last
        bound = self.config["fsync_bound_s"]
        dc, ds = count - last_count, total - last_sum
        if dc <= 0:
            return []
        avg = ds / dc
        # per-WINDOW max lower bound from the bucket deltas: the highest
        # bucket that gained an observation this tick guarantees at
        # least one fsync above its lower edge. The average alone
        # dilutes one 5s stall among 50 fast ops, and the lifetime max
        # saturates after the first outlier — either path alone goes
        # blind to a sustained one-slow-fsync-per-tick disk.
        window_floor = 0.0
        for i, (c, lc) in enumerate(zip(counts, last_counts)):
            if c > lc:
                window_floor = h.bounds[i - 1] if i > 0 else 0.0
        detail = {"observations": dc, "avg_seconds": round(avg, 6),
                  "window_max_at_least_seconds": round(window_floor, 6)}
        if avg > bound or window_floor > bound:
            return [self._trip(
                "translog_fsync",
                f"translog fsync latency over bound ({bound:.3f}s): "
                f"{avg:.3f}s avg over {dc} ops, slowest this window "
                f">= {window_floor:.3f}s", detail)]
        if avg > bound / 2.0 or window_floor > bound / 2.0:
            self.node.flight.record("slow_ops", detector="translog_fsync",
                                    **detail)
        return []

    def _check_publish(self) -> List[dict]:
        trips = []
        bound = self.config["publish_bound_s"]
        for op in self.board.snapshot():
            if op["kind"] != "publish_commit":
                continue
            if op["age_seconds"] > bound:
                trips.append(self._trip(
                    "publish_stall",
                    f"cluster-state publish in flight "
                    f"{op['age_seconds']:.1f}s (bound {bound:.1f}s)",
                    dict(op, age_seconds=round(op["age_seconds"], 3))))
            elif op["age_seconds"] > bound / 2.0:
                self.node.flight.record("slow_ops", detector="publish_stall",
                                        **op)
        # a publish that aborted inside the commit window (the
        # publish.commit fault domain) left followers holding parked
        # uncommitted state — trip on the flight event bootstrap records.
        # The cursor advances to the newest event actually SCANNED (not
        # to now()): an event recorded between a now() read and the scan
        # would otherwise be returned twice and double-trip.
        cursor = self._cluster_scan_ts
        events = self.node.flight.events_since("cluster", cursor)
        if events:
            self._cluster_scan_ts = max(e["ts_monotonic"] for e in events)
        for ev in events:
            if ev.get("event") == "publish_commit_window_fault":
                trips.append(self._trip(
                    "publish_stall",
                    "publish aborted in the commit window (term "
                    f"{ev.get('term')}, version {ev.get('version')}) — "
                    "followers hold parked uncommitted state",
                    {k: ev.get(k) for k in ("event", "term", "version")}))
        return trips

    def _check_coalescer(self) -> List[dict]:
        serving = getattr(self.node, "serving", None)
        co = getattr(serving, "coalescer", None)
        if co is None:
            return []
        age = co.oldest_queue_age()
        if age is None:
            return []
        bound = self.config["coalescer_bound_s"]
        detail = {"oldest_age_seconds": round(age, 3), **co.stats()}
        if age > bound:
            return [self._trip(
                "coalescer_drain",
                f"coalescer's oldest parked request has waited {age:.2f}s "
                f"(bound {bound:.2f}s) — drain stalled", detail)]
        if age > bound / 2.0:
            self.node.flight.record("slow_ops", detector="coalescer_drain",
                                    **detail)
        return []

    def _check_relocations(self) -> List[dict]:
        """Stuck-relocation detector (master-side: only the master's
        allocator holds in-flight moves): a move whose stream has been
        in flight past the bound is cancelled AND rescheduled onto a
        different target — the one detector that acts, because a wedged
        relocation holds a throttle slot that starves every later move
        (drains would never converge)."""
        alloc = getattr(getattr(self.node, "multihost", None),
                        "allocator", None)
        if alloc is None:
            return []
        bound = self.config["relocation_bound_s"]
        trips = []
        for mv in alloc.inflight_snapshot():
            if mv.get("cancelled"):
                continue  # already being torn down; don't double-trip
            age = mv["age_seconds"]
            detail = dict(mv, age_seconds=round(age, 3),
                          bound_seconds=bound)
            if age > bound:
                trips.append(self._trip(
                    "relocation_stall",
                    f"relocation [{mv['index']}][{mv['shard']}] "
                    f"{mv['source']}->{mv['target']} in flight "
                    f"{age:.1f}s (bound {bound:.1f}s) — cancelling and "
                    f"rescheduling", detail))
                try:
                    alloc.cancel_relocation(
                        (mv["index"], mv["shard"], mv["target"]),
                        reschedule=True, reason="watchdog trip")
                except Exception:
                    pass  # the trip evidence stands even if the
                    # cancel races the stream finishing
            elif age > bound / 2.0:
                self.node.flight.record("slow_ops",
                                        detector="relocation_stall",
                                        **detail)
        return trips

    # -- trip → incident -----------------------------------------------------

    def _trip(self, detector: str, reason: str, detail: dict) -> dict:
        """One detector trip: counter + tracer event + flight entry, and
        an incident dump unless the detector is inside its cooldown."""
        with self._lock:
            self.trips[detector] = self.trips.get(detector, 0) + 1
        self._m_trips.labels(detector).inc()
        flight.note_trip(detector)
        self.node.flight.record("trips", detector=detector, reason=reason,
                                detail=detail)
        try:
            with self.node.tracer.span("watchdog.trip", detector=detector):
                pass
        except Exception:
            pass  # tracer trouble must not suppress the incident
        incident_id = None
        now = time.monotonic()
        last = self._last_incident.get(detector)
        if last is None or now - last > self.config["cooldown_s"]:
            self._last_incident[detector] = now
            incident_id = self._capture(detector, reason, detail)
        return {"detector": detector, "reason": reason, "detail": detail,
                "incident_id": incident_id}

    def _capture(self, detector: str, reason: str, detail: dict) -> str:
        """Assemble and persist one incident dump."""
        from elasticsearch_tpu.monitor import programs

        node = self.node
        incident_id = f"{node.node_id}:{next(self._seq)}"
        payload = {
            "version": flight.INCIDENT_VERSION,
            "id": incident_id,
            "node": node.node_id,
            "node_name": node.name,
            "detector": detector,
            "reason": reason,
            "detail": detail,
            "timestamp_ms": int(time.time() * 1000),
            "flight": node.flight.snapshot(),
            "hot_threads": hot_threads_snapshot(),
            "programs": {
                "totals": programs.REGISTRY.stats(),
                "inflight": programs.REGISTRY.inflight_snapshot(),
                "table": programs.REGISTRY.snapshot()[:64],
            },
            "tasks": [t.to_json() for t in node.tasks.list_tasks()][:128],
        }
        self.incidents.save(payload)
        with self._lock:
            self.incidents_captured += 1
        flight.note_incident()
        return incident_id

    # -- views ---------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            trips = dict(self.trips)
            captured = self.incidents_captured
        return {
            "running": self.running,
            "ticks": self.ticks,
            "trips": trips,
            "incidents_captured": captured,
            "inflight_ops": self.board.snapshot(),
            "config": {k: self.config[k] for k in sorted(self.config)},
        }
