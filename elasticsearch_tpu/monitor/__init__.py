from elasticsearch_tpu.monitor.metrics import MetricsRegistry, SHARED
from elasticsearch_tpu.monitor.stats import SearchStats, os_stats, process_stats

__all__ = ["MetricsRegistry", "SHARED", "SearchStats", "os_stats",
           "process_stats"]
