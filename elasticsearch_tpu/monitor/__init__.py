from elasticsearch_tpu.monitor.stats import SearchStats, os_stats, process_stats

__all__ = ["SearchStats", "os_stats", "process_stats"]
