from elasticsearch_tpu.monitor.metrics import MetricsRegistry, SHARED
from elasticsearch_tpu.monitor.stats import SearchStats, os_stats, process_stats

__all__ = ["MetricsRegistry", "SHARED", "SearchStats", "os_stats",
           "process_stats"]

# NOTE: monitor.programs (the device-program observatory) is imported
# lazily by its feeds (tracing/retrace reporter, executor dispatch
# wrappers) — not re-exported here, so `from elasticsearch_tpu.monitor
# import kernels`-style light imports stay light.
