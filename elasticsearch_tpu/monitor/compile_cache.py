"""Compile-cache counters: the honest ledger of the AOT executable cache.

The AOT layer (parallel/aot.py) resolves every executor device program
through a three-step lookup — in-process memo → serialized-executable
blob deserialize → fresh XLA compile — and each resolution must be
attributable, or "zero-warmup" becomes an unverifiable claim. This
module is the process-global counter store those resolutions record
into, kept OUTSIDE parallel/ so monitor/metrics.py::process_counters and
the per-node ``estpu_compile_cache_*`` collectors can read it without
importing the jit-binding packages (importing parallel/ pulls jax — a
metrics scrape on a jax-less embedder must stay cheap and safe).

Event names (the ``source`` label of ``estpu_compile_cache_events_total``):

  aot_hit          executable deserialized from the blob cache — no trace,
                   no XLA compile, the zero-warmup path
  xla_dir_hit      fresh lower+compile whose XLA work was served by the
                   persistent compilation-cache directory (jax's own
                   ``/jax/compilation_cache/cache_hits`` event fired
                   during THIS thread's compile)
  fresh            full price paid: traced + XLA-compiled from nothing
  corrupt_miss     blob failed its digest/unpickle — deleted, detected miss
  mismatch_miss    blob was valid but for another backend/jax version/host
                   — deleted, detected miss
  deserialize_error  a structurally-valid blob failed deserialize_and_load
                   — deleted, fell through to fresh compile
  store            serialized executable persisted to the blob tier
  store_skipped    dir-served compile NOT serialized on purpose — an
                   XLA-dir-loaded executable lacks the object code
                   serialize_executable needs and its blob would fail
                   deserialize ("Symbols not found") in every later
                   process; the dir cache already covers this machine
  store_error      serialization/persist failed (cache stays cold, the
                   compiled program still serves)
  call_fallback    a resolved executable rejected its arguments at call
                   time — dropped from the memo, the plain jit path served

Phase seconds (``estpu_compile_cache_seconds_total``): ``deserialize``,
``compile``, ``serialize``.

Availability: ``enabled_state()`` is None until the AOT layer first
resolves whether it is enabled — process_counters maps that to the -1
unknown sentinel so bench deltas render ``null`` (the jit_compiles
discipline: unavailable never mixes into arithmetic as a fake 0).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

EVENTS = ("aot_hit", "xla_dir_hit", "fresh", "corrupt_miss",
          "mismatch_miss", "deserialize_error", "store", "store_skipped",
          "store_error", "call_fallback")
PHASES = ("deserialize", "compile", "serialize")

_LOCK = threading.Lock()
_EVENTS: Dict[str, int] = {}
_SECONDS: Dict[str, float] = {}
#: None = the AOT layer never ran (unknown); True/False once resolved
_ENABLED: Optional[bool] = None


def note_enabled(flag: bool) -> None:
    global _ENABLED
    with _LOCK:
        _ENABLED = bool(flag)


def enabled_state() -> Optional[bool]:
    with _LOCK:
        return _ENABLED


def event(name: str, n: int = 1) -> None:
    with _LOCK:
        _EVENTS[name] = _EVENTS.get(name, 0) + n


def seconds(phase: str, s: float) -> None:
    with _LOCK:
        _SECONDS[phase] = _SECONDS.get(phase, 0.0) + float(s)


def events_snapshot() -> Dict[str, int]:
    """Every event name, zero-filled — collectors need the stable label
    set, not just the names that happened to fire."""
    with _LOCK:
        return {name: _EVENTS.get(name, 0) for name in EVENTS}


def seconds_snapshot() -> Dict[str, float]:
    with _LOCK:
        return {p: _SECONDS.get(p, 0.0) for p in PHASES}


def counter_values() -> Dict[str, float]:
    """Flat ``compile_cache.*`` keys for process_counters / bench deltas.
    While the AOT layer has never resolved (enabled_state() is None)
    every value is the -1 unknown sentinel, which counters_delta renders
    as a typed null — never a fake 0."""
    with _LOCK:
        unknown = _ENABLED is None
        out: Dict[str, float] = {}
        for name in EVENTS:
            out[f"compile_cache.{name}"] = \
                -1.0 if unknown else float(_EVENTS.get(name, 0))
        for p in PHASES:
            out[f"compile_cache.{p}_seconds"] = \
                -1.0 if unknown else round(_SECONDS.get(p, 0.0), 6)
        return out


def reset() -> None:
    """Test isolation only."""
    global _ENABLED
    with _LOCK:
        _EVENTS.clear()
        _SECONDS.clear()
        _ENABLED = None
