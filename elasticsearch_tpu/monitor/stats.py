"""Monitoring: process/OS/device stats and search-phase counters.

Reference: org/elasticsearch/monitor/ — process/ProcessService.java,
os/OsService.java, jvm/JvmService.java feeding _nodes/stats, and
index/search/stats/SearchStats.java (query/fetch counts + cumulative
times per shard).

TPU adaptation: the "jvm" section maps to the Python process + the jax
device (HBM bytes in use via device memory stats when the backend exposes
them); search stats count compiled-program executions rather than Lucene
collector invocations, but the response shape matches the reference so
dashboards keep working.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional


class SearchStats:
    """Per-shard-ish search counters (reference: SearchStats.Stats)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.query_total = 0
        self.query_time_ms = 0.0
        self.fetch_total = 0
        self.fetch_time_ms = 0.0
        self.suggest_total = 0
        self.scroll_total = 0
        # per-group counters for requests tagged with body `stats: [...]`
        # (reference: SearchStats groupStats / the `groups` scope of _stats)
        self.groups: Dict[str, Dict[str, int]] = {}

    def _group(self, g: str) -> Dict[str, int]:
        return self.groups.setdefault(g, {
            "query_total": 0, "query_time_in_millis": 0,
            "fetch_total": 0, "fetch_time_in_millis": 0})

    def on_query(self, ms: float, n: int = 1, groups=None):
        """n > 1: a batched execution serving n requests at once (msearch
        fast path) — counters must match the sequential path's totals."""
        with self._lock:
            self.query_total += n
            self.query_time_ms += ms
            for g in groups or ():
                gs = self._group(str(g))
                gs["query_total"] += n
                gs["query_time_in_millis"] += int(ms)

    def on_fetch(self, ms: float, n: int = 1, groups=None):
        with self._lock:
            self.fetch_total += n
            self.fetch_time_ms += ms
            for g in groups or ():
                gs = self._group(str(g))
                gs["fetch_total"] += n
                gs["fetch_time_in_millis"] += int(ms)

    def on_suggest(self):
        with self._lock:
            self.suggest_total += 1

    def on_scroll(self):
        with self._lock:
            self.scroll_total += 1

    def to_json(self) -> dict:
        out = {
            "query_total": self.query_total,
            "query_time_in_millis": int(self.query_time_ms),
            "fetch_total": self.fetch_total,
            "fetch_time_in_millis": int(self.fetch_time_ms),
            "suggest_total": self.suggest_total,
            "scroll_total": self.scroll_total,
        }
        if self.groups:
            out["groups"] = {g: dict(gs) for g, gs in self.groups.items()}
        return out


class TranslogRecoveryStats:
    """Process-wide accounting of translog replay damage: every corrupt
    tail a replay stopped at (reference: the recovery stats surfaced by
    TranslogService + the TranslogCorruptedException logging — here the
    frames/bytes dropped are COUNTED so operators see data loss instead
    of inferring it from doc counts)."""

    def __init__(self, max_events: int = 64):
        from collections import deque

        self._lock = threading.Lock()
        self.frames_skipped = 0
        self.bytes_dropped = 0
        # counters stay exact; the per-event detail ring is bounded so a
        # node that keeps reopening damaged translogs can't grow its own
        # monitoring payload without limit
        self.events = deque(maxlen=max_events)

    def record(self, path: str, bytes_dropped: int, reason: str) -> None:
        with self._lock:
            self.frames_skipped += 1
            self.bytes_dropped += int(bytes_dropped)
            self.events.append({
                "path": path,
                "bytes_dropped": int(bytes_dropped),
                "reason": reason,
                "timestamp": int(time.time() * 1000),
            })

    def reset(self) -> None:
        with self._lock:
            self.frames_skipped = 0
            self.bytes_dropped = 0
            self.events.clear()

    def to_json(self) -> dict:
        with self._lock:
            return {
                "corrupt_tail_frames_skipped": self.frames_skipped,
                "corrupt_tail_bytes_dropped": self.bytes_dropped,
                "events": list(self.events),
            }


#: process-global sink — translog replay (index/translog.py) reports here
TRANSLOG_RECOVERY = TranslogRecoveryStats()


def record_corrupt_tail(path: str, bytes_dropped: int, reason: str) -> None:
    TRANSLOG_RECOVERY.record(path, bytes_dropped, reason)


def aggregate_slowlog(index_services) -> dict:
    """Node-wide slow-operation gauge for ``/_nodes``, aggregated from
    THIS node's own indices' slow-log rings (tracing/slowlog.py). NOT a
    process-global singleton: several in-process nodes (the multi-host
    test harness, embedded setups) must each report only their own slow
    ops — the same per-node discipline translog_recovery follows. The
    per-entry detail (source, took, level) stays in the per-index
    rings; this is the one-glance number a dashboard polls to notice an
    index going slow before digging into which one."""
    search_total = indexing_total = 0
    for svc in index_services:
        sl = getattr(svc, "slowlog", None)
        if sl is None:
            continue
        search_total += sl.query.total
        indexing_total += sl.index.total
    return {"search_slow_total": search_total,
            "indexing_slow_total": indexing_total}


def aggregate_recovery(index_services) -> dict:
    """Per-NODE recovery gauges aggregated from the node's own indices'
    RecoveryRegistry entries (index/recovery.py) — the same per-node
    discipline translog_recovery and slowlog follow. ``incremental``
    counts ops-mode (checkpoint-based) recoveries; ``full_copies`` the
    fallback streams — the ratio is the replication-safety win made
    visible (reference: RecoveryStats current_as_source/target)."""
    out = {"current_as_source": 0, "current_as_target": 0,
           "total": 0, "incremental": 0, "full_copies": 0,
           "ops_replayed": 0, "docs_copied": 0}
    for svc in index_services:
        reg = getattr(svc, "recoveries", None)
        if reg is None:
            continue
        out["current_as_source"] += getattr(reg, "source_active", 0)
        for e in reg.entries():
            out["total"] += 1
            if e["stage"] not in ("done", "failed"):
                out["current_as_target"] += 1
            if e.get("mode") == "ops":
                out["incremental"] += 1
            elif e.get("mode") == "full":
                out["full_copies"] += 1
            out["ops_replayed"] += e.get("ops_replayed", 0)
            out["docs_copied"] += e.get("docs_copied", 0)
    return out


def process_stats() -> dict:
    """Process-level stats (reference: ProcessService → _nodes/stats.process)."""
    import resource

    ru = resource.getrusage(resource.RUSAGE_SELF)
    out: Dict[str, Any] = {
        "timestamp": int(time.time() * 1000),
        "open_file_descriptors": _count_fds(),
        "cpu": {"total_in_millis": int((ru.ru_utime + ru.ru_stime) * 1000)},
        "mem": {
            # CURRENT resident set (dashboards treat this as live memory);
            # peak kept under its honest name
            "resident_in_bytes": _current_rss() or ru.ru_maxrss * 1024,
            "peak_resident_in_bytes": ru.ru_maxrss * 1024,
        },
    }
    return out


def _current_rss() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def _count_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def os_stats() -> dict:
    """Host stats (reference: OsService → _nodes/stats.os)."""
    out: Dict[str, Any] = {"timestamp": int(time.time() * 1000)}
    try:
        load1, load5, load15 = os.getloadavg()
        out["cpu"] = {"load_average": {"1m": load1, "5m": load5, "15m": load15}}
    except OSError:
        pass
    try:
        with open("/proc/meminfo") as f:
            mem = {}
            for line in f:
                parts = line.split()
                if parts[0] in ("MemTotal:", "MemFree:", "MemAvailable:"):
                    mem[parts[0][:-1]] = int(parts[1]) * 1024
        out["mem"] = {
            "total_in_bytes": mem.get("MemTotal", 0),
            "free_in_bytes": mem.get("MemFree", 0),
            "available_in_bytes": mem.get("MemAvailable", 0),
        }
    except OSError:
        pass
    return out


def device_stats() -> dict:
    """Accelerator stats — the TPU-native analogue of the reference's JVM
    heap section: device kind + HBM usage when the backend exposes it."""
    out: Dict[str, Any] = {}
    try:
        import jax

        dev = jax.devices()[0]
        out["platform"] = dev.platform
        out["device_kind"] = getattr(dev, "device_kind", "unknown")
        ms = getattr(dev, "memory_stats", None)
        if callable(ms):
            stats = ms() or {}
            out["hbm"] = {
                "bytes_in_use": stats.get("bytes_in_use", 0),
                "bytes_limit": stats.get("bytes_limit", 0),
            }
    except Exception:
        out["platform"] = "unavailable"
    return out
