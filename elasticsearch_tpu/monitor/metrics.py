"""Continuous metrics substrate: counters, gauges, log-bucketed histograms.

Reference: there is no metrics registry in ES 2.x — the closest ancestors
are the per-section counters NodeStats/ClusterStats aggregate on demand
and the community prometheus-exporter plugin that scraped them. This
module is the continuous view PR 4's per-request observability lacked:
every request updates cheap in-process counters/histograms, and
`GET /_prometheus/metrics` exposes them in text exposition format 0.0.4
(stdlib only), so latency percentiles, cache hit rates, breaker pressure
and compile counts are visible *between* bench rounds, not only when
someone passes ``?profile=true``.

Design constraints, in order:

- **Lock-cheap record.** ``Counter.inc`` / ``Histogram.observe`` take one
  short per-child lock around integer adds; bucket search is a bisect
  over a ~20-entry tuple. No allocation on the steady path (children are
  memoized per label-set).
- **Bounded label cardinality.** Each family caps its label-sets
  (``max_series``); overflow collapses into a reserved ``_other_``
  series instead of growing without bound OR silently dropping counts.
- **Device discipline (tpulint R009).** Recording a metric must never
  touch a device value: no ``observe``/``inc`` inside jit-traced code,
  no device-array arguments — pull the scalar to host first, then
  record the plain float. The static rule enforces both directions.
- **Percentiles from buckets.** Histograms are log-bucketed
  (factor-2 bounds, 100µs … ~100s for latency); p50/p90/p99 are
  estimated by linear interpolation within the covering bucket, and the
  exact observed ``max`` is kept alongside so the estimate's ceiling is
  honest.

Node scoping: each ``Node`` owns a ``MetricsRegistry`` (REST latency,
span histograms, indexing) so in-process multi-node harnesses keep
per-node numbers per-node — the slowlog/translog_recovery discipline.
Subsystems with no node affinity (translog fsync, executor caches via
monitor/kernels) record into the process-shared ``SHARED`` registry,
which every node's exposition includes — the same "the device is
process-shared too" rule residency.py follows.

Clock discipline (tpulint R007): durations observed here must come from
``time.perf_counter()`` at the call site; this module never reads a
clock itself.
"""
from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# factor-2 log buckets, 100µs .. ~104s — wide enough for a device-compile
# outlier, fine enough that p50 interpolation on a ~ms latency is useful
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    1e-4 * (2.0 ** i) for i in range(21))

# the reserved label value absorbing overflow past a family's series cap:
# counts are never lost, they just lose per-label attribution
OVERFLOW_LABEL = "_other_"


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats via repr
    (exposition format accepts scientific notation)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def escape_label_value(v: str) -> str:
    """Text-format label escaping: backslash, double-quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{escape_label_value(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class Counter:
    """One monotonically-increasing series."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """One settable series (current value, not a rate)."""

    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self.value -= n


class Histogram:
    """One log-bucketed series: cumulative-on-render bucket counts, sum,
    count, and the exact max (estimation honesty: a percentile clamped
    to a bucket bound can overshoot reality; ``max`` bounds it)."""

    __slots__ = ("_lock", "bounds", "counts", "sum", "count", "max")

    def __init__(self, bounds: Sequence[float]):
        self._lock = threading.Lock()
        self.bounds: Tuple[float, ...] = tuple(bounds)
        # one slot per finite bound + the +Inf overflow slot
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.max = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if v > self.max:
                self.max = v

    def percentile(self, p: float) -> float:
        """Estimate the p-th percentile (0..100) from the buckets:
        linear interpolation within the covering bucket, clamped to the
        exact observed max so a sparse top bucket can't overshoot."""
        with self._lock:
            total = self.count
            if total == 0:
                return 0.0
            rank = (p / 100.0) * total
            cum = 0
            for i, c in enumerate(self.counts):
                if c == 0:
                    continue
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) else self.max
                prev = cum
                cum += c
                if cum >= rank:
                    frac = (rank - prev) / c
                    est = lo + (max(hi, lo) - lo) * frac
                    # unconditional: with count > 0 the exact max is
                    # valid even at 0.0 (all-zero observations must not
                    # interpolate past it)
                    return min(est, self.max)
            return self.max

    def summary(self) -> dict:
        with self._lock:
            count, total, mx = self.count, self.sum, self.max
        return {
            "count": count,
            "sum_seconds": round(total, 6),
            "p50_seconds": round(self.percentile(50), 6),
            "p90_seconds": round(self.percentile(90), 6),
            "p99_seconds": round(self.percentile(99), 6),
            "max_seconds": round(mx, 6),
        }


class _Family:
    """One named metric with a fixed label-name tuple and memoized
    per-label-set children."""

    def __init__(self, name: str, help_: str, labelnames: Sequence[str],
                 kind: str, child_factory: Callable[[], Any],
                 max_series: int):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self._factory = child_factory
        self._max_series = max_series
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}
        if not self.labelnames:
            self._children[()] = child_factory()

    def labels(self, *values: Any):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes labels {self.labelnames}, "
                f"got {key}")
        child = self._children.get(key)
        if child is not None:
            return child
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self._max_series:
                    # cardinality cap: collapse, never grow unbounded
                    key = tuple(OVERFLOW_LABEL for _ in key)
                    child = self._children.get(key)
                    if child is not None:
                        return child
                child = self._factory()
                self._children[key] = child
        return child

    # unlabeled-family conveniences
    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def series(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())


class _CallbackFamily:
    """A family whose values are computed at scrape time (queue depths,
    breaker bytes, trace-audit totals): ``collect()`` returns
    ``[(labelvalues_tuple, value), ...]``. ``kind`` may be "counter" for
    monotonic sources owned elsewhere (threadpool rejected totals)."""

    def __init__(self, name: str, help_: str, labelnames: Sequence[str],
                 kind: str, collect: Callable[[], Iterable[Tuple[Tuple, float]]]):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.kind = kind
        self._collect = collect

    def series(self) -> List[Tuple[Tuple[str, ...], float]]:
        try:
            return [(tuple(str(x) for x in k), float(v))
                    for k, v in self._collect()]
        except Exception:
            # a scrape must degrade to a missing section, never a 500
            return []


class MetricsRegistry:
    """Node-wide registry: named families, text exposition, summaries.

    ``include_shared`` folds the process-wide ``SHARED`` registry's
    families into this registry's exposition/summaries (node registries
    do; SHARED itself must not recurse).
    """

    def __init__(self, include_shared: bool = False):
        self._lock = threading.Lock()
        self._families: Dict[str, Any] = {}
        self._include_shared = include_shared

    # -- family constructors (get-or-create; idempotent by name) ------------

    def _family(self, name: str, help_: str, labelnames, kind, factory,
                max_series: int):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, help_, labelnames, kind, factory,
                              max_series)
                self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "",
                labelnames: Sequence[str] = (),
                max_series: int = 256) -> _Family:
        return self._family(name, help_, labelnames, "counter", Counter,
                            max_series)

    def gauge(self, name: str, help_: str = "",
              labelnames: Sequence[str] = (),
              max_series: int = 256) -> _Family:
        return self._family(name, help_, labelnames, "gauge", Gauge,
                            max_series)

    def histogram(self, name: str, help_: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  max_series: int = 128) -> _Family:
        bounds = tuple(buckets)
        return self._family(name, help_, labelnames, "histogram",
                            lambda: Histogram(bounds), max_series)

    def collector(self, name: str, help_: str, labelnames: Sequence[str],
                  collect: Callable[[], Iterable[Tuple[Tuple, float]]],
                  kind: str = "gauge") -> None:
        """Register a scrape-time family (breaker bytes, queue depths —
        values already counted elsewhere; re-counting them on record
        would double-lock the hot path for no benefit)."""
        with self._lock:
            self._families[name] = _CallbackFamily(name, help_, labelnames,
                                                   kind, collect)

    # -- render --------------------------------------------------------------

    def _all_families(self) -> List[Any]:
        with self._lock:
            fams = list(self._families.values())
        if self._include_shared and self is not SHARED:
            with SHARED._lock:
                fams.extend(SHARED._families.values())
        return sorted(fams, key=lambda f: f.name)

    def expose(self) -> str:
        """Text exposition format 0.0.4 (the format every Prometheus
        scraper and promtool reads)."""
        out: List[str] = []
        for fam in self._all_families():
            out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            if fam.kind == "histogram":
                for lv, h in fam.series():
                    base = list(zip(fam.labelnames, lv))
                    cum = 0
                    with h._lock:
                        counts = list(h.counts)
                        hsum, hcount = h.sum, h.count
                    for bound, c in zip(h.bounds, counts):
                        cum += c
                        ls = _label_str(
                            [n for n, _ in base] + ["le"],
                            [v for _, v in base] + [_fmt(bound)])
                        out.append(f"{fam.name}_bucket{ls} {cum}")
                    ls = _label_str([n for n, _ in base] + ["le"],
                                    [v for _, v in base] + ["+Inf"])
                    out.append(f"{fam.name}_bucket{ls} {hcount}")
                    ls = _label_str(fam.labelnames, lv)
                    out.append(f"{fam.name}_sum{ls} {_fmt(hsum)}")
                    out.append(f"{fam.name}_count{ls} {hcount}")
            else:
                for lv, child in fam.series():
                    v = child.value if hasattr(child, "value") else child
                    ls = _label_str(fam.labelnames, lv)
                    out.append(f"{fam.name}{ls} {_fmt(v)}")
        return "\n".join(out) + "\n"

    def summaries(self) -> dict:
        """Histogram percentile summaries + counter totals for the
        ``metrics`` section of ``/_nodes/stats`` — the JSON view of the
        same numbers the exposition carries."""
        out: Dict[str, Any] = {}
        for fam in self._all_families():
            if fam.kind == "histogram":
                out[fam.name] = [
                    {"labels": dict(zip(fam.labelnames, lv)), **h.summary()}
                    for lv, h in fam.series()]
            elif isinstance(fam, _Family):
                out[fam.name] = [
                    {"labels": dict(zip(fam.labelnames, lv)),
                     "value": child.value}
                    for lv, child in fam.series()]
        return out

    def counter_values(self) -> Dict[str, float]:
        """Flat ``name{a=b}`` → value map of counter families (the bench
        before/after delta reads this)."""
        out: Dict[str, float] = {}
        for fam in self._all_families():
            if fam.kind != "counter" or not isinstance(fam, _Family):
                continue
            for lv, child in fam.series():
                out[fam.name + _label_str(fam.labelnames, lv)] = child.value
        return out


#: process-shared registry for subsystems with no node affinity
#: (translog fsync, transport frames from non-bootstrap embedders);
#: node registries fold it into their exposition
SHARED = MetricsRegistry(include_shared=False)


def span_sink(registry: MetricsRegistry) -> Callable[[Any], None]:
    """Tracer-sink adapter: every finished span lands in a latency
    histogram labeled by span name (bounded: span names are
    instrumentation-defined, not data-derived), plus an error counter —
    the whole span substrate becomes time-series without re-instrumenting
    a single call site. Install via ``Tracer.set_sink``."""
    hist = registry.histogram(
        "estpu_span_duration_seconds",
        "Latency of every finished tracer span, by span name",
        ("span",))
    errs = registry.counter(
        "estpu_span_errors_total",
        "Spans that finished with an error, by span name", ("span",))

    def sink(span) -> None:
        hist.labels(span.name).observe(span.duration)
        if span.error:
            errs.labels(span.name).inc()

    return sink


# -- process-wide counter snapshot (bench before/after delta) ---------------

def process_counters() -> Dict[str, float]:
    """One flat map of the process-wide monotonic counters a bench run
    moves: kernel dispatch + executor cache hits/misses
    (monitor/kernels.py), jit traces (tools.tpulint trace_audit, -1 when
    the auditor is not installed — the unknown sentinel stays
    distinguishable from zero in this snapshot map and renders as a
    typed ``None`` once it flows through :func:`counters_delta`),
    residency evictions/rehydrations, breaker trips, and the
    SHARED registry's counters. ``bench.py`` snapshots this before/after
    a run and emits the delta as ``metrics_delta``."""
    out: Dict[str, float] = {}
    from elasticsearch_tpu.monitor import kernels

    for k, v in kernels.snapshot().items():
        out[f"kernels.{k}"] = float(v)
    out.setdefault("kernels.executor_prep_hit", 0.0)
    out.setdefault("kernels.executor_prep_miss", 0.0)
    out.setdefault("kernels.executor_data_hit", 0.0)
    out.setdefault("kernels.executor_data_miss", 0.0)
    try:
        from elasticsearch_tpu.tracing import retrace

        a = retrace.auditor()
        out["jit.traces_total"] = float(a.total()) if a is not None else -1.0
    except Exception:
        out["jit.traces_total"] = -1.0
    try:
        # AOT executable-cache ledger (monitor/compile_cache.py, jax-free
        # import): -1 unknown sentinels until the AOT layer first
        # resolves, so bench deltas render null — the jit_compiles
        # discipline, never a fake 0
        from elasticsearch_tpu.monitor import compile_cache

        out.update(compile_cache.counter_values())
    except Exception:
        pass
    try:
        from elasticsearch_tpu import resources

        st = resources.RESIDENCY.stats()
        ev = rh = 0
        for t in st.get("tiers", {}).values():
            ev += t.get("evictions", 0)
            rh += t.get("rehydrations", 0)
        out["residency.evictions"] = float(ev)
        out["residency.rehydrations"] = float(rh)
        for name, br in resources.BREAKERS.stats().items():
            out[f"breakers.{name}.tripped"] = float(br.get("tripped", 0))
    except Exception:
        pass
    # device-program observatory: per-key compile/execute counters
    # (``programs.<program>|<shapes>.<counter>``) so the bench delta
    # carries which programs a run compiled and what they cost
    try:
        from elasticsearch_tpu.monitor import programs as _programs

        out.update(_programs.REGISTRY.counter_values())
    except Exception:
        pass
    # watchdog trips + incident captures (``watchdog.trips[.<detector>]``,
    # ``watchdog.incidents``): a stall during a bench round must be
    # visible in the artifact's metrics_delta, not only in the logs
    try:
        from elasticsearch_tpu.monitor import flight as _flight

        out.update(_flight.trip_counters())
    except Exception:
        pass
    out.update(SHARED.counter_values())
    return out


def counters_delta(before: Dict[str, float],
                   after: Dict[str, float]) -> Dict[str, Optional[float]]:
    """after - before, keeping every key either side saw. A counter that
    was UNKNOWN on either side (the trace auditor's -1 snapshot sentinel,
    or an explicit None) deltas to ``None`` — the typed absence JSON
    renders as null, so consumers can't mix it into arithmetic the way
    the old -1 leaked into sums (never a fake 0 either)."""
    out: Dict[str, Optional[float]] = {}
    for k in sorted(set(before) | set(after)):
        b, a = before.get(k, 0.0), after.get(k, 0.0)
        if b is None or a is None or b < 0 or a < 0:
            out[k] = None
        else:
            v = a - b
            out[k] = int(v) if v == int(v) else v
    return out
