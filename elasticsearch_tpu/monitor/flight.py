"""Flight recorder: always-on bounded rings of runtime anomalies.

Reference: there is no flight recorder in ES 2.x — the closest ancestors
are the JVM's own JFR (which ES operators lean on for exactly this) and
the hot-threads / pending-tasks endpoints that answer "what is it doing
RIGHT NOW". Everything this stack exposed so far is *pull*-observable
(spans, tasks, metrics, the program observatory): a hung collective or
a wedged drain shows up only if an operator scrapes at the right moment,
and the evidence dies with the process. This module is the push half —
a node-wide, lock-cheap black box every anomaly source appends into:

- periodic metric-delta snapshots (the watchdog's tick sampler),
- slow-op events (detector observations below trip threshold),
- breaker trips (resources/breakers.py),
- device-program compile events (monitor/programs.py reporter feed),
- election / publish transitions (cluster/bootstrap.py),
- engine failures (index/engine.py tragic events),
- watchdog trips (monitor/watchdog.py).

Every entry is monotonic-timestamped (ordering/age math) plus a
display-only epoch timestamp, and carries the active trace id when one
exists — an incident dump can be joined against the span ring.

Node scoping follows the tracer/metrics discipline: each ``Node`` owns a
:class:`FlightRecorder` (``node.flight``) and registers it with this
module; subsystems with no node back-reference (breakers, engines,
translog) record through the module-level :func:`record`, which fans to
every live recorder — the "device is process-shared" rule the SHARED
metrics registry follows. Node-scoped sources (bootstrap, watchdog)
record into their node's recorder directly.

Hot-path cost: one short lock around a deque append. Nothing here
serializes, allocates rings per event, or touches a device value; the
steady-state search path is untouched unless something anomalous fires.

Clock discipline (tpulint R007): ring ordering and age math use
``time.monotonic()``; ``time.time()`` appears only as the display
timestamp and never feeds a subtraction.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: ring name -> bounded capacity. Capacities are part of the diagnostics
#: bundle's schema contract (the tier-1 gate asserts snapshots never
#: exceed them): counters stay exact forever, per-event detail is last-N.
RING_CAPS: Dict[str, int] = {
    "metrics": 128,          # watchdog tick delta snapshots
    "slow_ops": 256,         # below-threshold detector observations
    "breaker_trips": 256,    # CircuitBreakingException admissions denials
    "compiles": 256,         # device-program (re)traces
    "cluster": 256,          # election / publish / step-down transitions
    "engine_failures": 64,   # tragic engine events
    "trips": 128,            # watchdog detector trips
}


class FlightRecorder:
    """One node's black box: a bounded deque per ring + exact counters."""

    def __init__(self, node_id: str = "", node_name: str = ""):
        self.node_id = node_id
        self.node_name = node_name
        self._lock = threading.Lock()
        self._rings: Dict[str, deque] = {
            name: deque(maxlen=cap) for name, cap in RING_CAPS.items()}
        self._counts: Dict[str, int] = {name: 0 for name in RING_CAPS}

    def record(self, ring: str, **fields: Any) -> None:
        """Append one event. Unknown ring names raise (a typo'd source
        would otherwise record into the void forever). The active trace
        id is attached when this flow runs under a span, so incident
        dumps join against the tracer ring."""
        entry: Dict[str, Any] = {
            "ts_monotonic": time.monotonic(),
            "timestamp_ms": int(time.time() * 1000),  # display only
        }
        try:
            from elasticsearch_tpu.tracing.tracer import current_context

            ctx = current_context()
            if ctx is not None:
                entry["trace_id"] = ctx.trace_id
        except Exception:
            pass  # tracing must never fail a recording
        entry.update(fields)
        with self._lock:
            self._rings[ring].append(entry)
            self._counts[ring] += 1

    def ring(self, name: str) -> List[dict]:
        with self._lock:
            return list(self._rings[name])

    def events_since(self, ring: str, ts_monotonic: float) -> List[dict]:
        """Events recorded after ``ts_monotonic`` — the watchdog's
        incremental scan over rings fed by other threads."""
        with self._lock:
            return [e for e in self._rings[ring]
                    if e["ts_monotonic"] > ts_monotonic]

    def snapshot(self) -> dict:
        """The whole box: every ring's retained events + exact lifetime
        counts + the capacity contract. This is the ``flight`` section
        of an incident dump and of ``GET /_nodes/_local/flight``."""
        with self._lock:
            return {
                "node": self.node_id,
                "rings": {name: list(ring)
                          for name, ring in self._rings.items()},
                "counts": dict(self._counts),
                "ring_caps": dict(RING_CAPS),
            }

    def stats(self) -> dict:
        with self._lock:
            return {"counts": dict(self._counts),
                    "retained": {name: len(ring)
                                 for name, ring in self._rings.items()}}


class OpBoard:
    """In-flight named operations: ``begin`` returns a token, ``end``
    retires it, ``snapshot`` reports ages. The ONE age-board behind both
    the watchdog's publish tracking and the ProgramRegistry's in-flight
    dispatch table — a hang records nothing in any completion-fed
    counter, which is exactly the gap this closes. Monotonic clock; one
    short lock; begin/end are the only hot-path cost."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seq = 0
        self._ops: Dict[int, tuple] = {}

    def begin(self, kind: str, **detail: Any) -> int:
        with self._lock:
            self._seq += 1
            tok = self._seq
            self._ops[tok] = (kind, detail, time.monotonic())
        return tok

    def end(self, token: int) -> None:
        with self._lock:
            self._ops.pop(token, None)

    def snapshot(self) -> List[dict]:
        now = time.monotonic()
        with self._lock:
            items = list(self._ops.values())
        return [{"kind": kind, "age_seconds": now - t0, **detail}
                for kind, detail, t0 in items]

    def clear(self) -> None:
        with self._lock:
            self._ops.clear()


# ---------------------------------------------------------------------------
# process-level fan: sources with no node back-reference
# ---------------------------------------------------------------------------

_REG_LOCK = threading.Lock()
_RECORDERS: List[FlightRecorder] = []


def register(rec: FlightRecorder) -> None:
    """Add a node's recorder to the process fan (Node.__init__)."""
    with _REG_LOCK:
        if rec not in _RECORDERS:
            _RECORDERS.append(rec)


def unregister(rec: FlightRecorder) -> None:
    with _REG_LOCK:
        try:
            _RECORDERS.remove(rec)
        except ValueError:
            pass


def record(ring: str, **fields: Any) -> None:
    """Record a process-shared event (breaker trip, engine failure,
    compile) into EVERY live node's ring — the SHARED-metrics discipline:
    a process-shared subsystem's anomaly happened to every node embedded
    in this process. Near-free when no node is live (import-time code,
    bare-library embedders)."""
    with _REG_LOCK:
        recs = list(_RECORDERS)
    for rec in recs:
        try:
            rec.record(ring, **fields)
        except Exception:
            pass  # recording must never fail the recording source


# ---------------------------------------------------------------------------
# process-wide trip/incident counters (bench before/after delta)
# ---------------------------------------------------------------------------

_TRIP_LOCK = threading.Lock()
_TRIPS: Dict[str, int] = {}
_INCIDENTS_TOTAL = 0


def note_trip(detector: str) -> None:
    with _TRIP_LOCK:
        _TRIPS[detector] = _TRIPS.get(detector, 0) + 1


def note_incident() -> None:
    global _INCIDENTS_TOTAL
    with _TRIP_LOCK:
        _INCIDENTS_TOTAL += 1


def trip_counters() -> Dict[str, float]:
    """Flat counter map for monitor.metrics.process_counters(): a stall
    during a bench round shows up in the artifact's metrics_delta."""
    with _TRIP_LOCK:
        out = {f"watchdog.trips.{d}": float(v) for d, v in _TRIPS.items()}
        out["watchdog.trips"] = float(sum(_TRIPS.values()))
        out["watchdog.incidents"] = float(_INCIDENTS_TOTAL)
    return out


# ---------------------------------------------------------------------------
# incident persistence (PR 11 generic blob helpers)
# ---------------------------------------------------------------------------

INCIDENT_VERSION = 1
_EXT = "incident"
_INDEX_KEY = "incident_index"
_INDEX_CAP = 64  # persisted incident index entries (oldest evicted)
_STORE_LOCK = threading.Lock()  # serializes index read-modify-write


def incident_key(incident_id: str) -> str:
    """Blob-cache key for one incident (filename-safe: ids carry ':')."""
    return "incident_" + hashlib.sha1(
        incident_id.encode("utf-8")).hexdigest()


class IncidentStore:
    """Bounded in-memory incident list + durable-blob persistence.

    Each saved incident becomes one digest-framed blob beside the
    IVF/PQ/census artifacts, and an entry in a shared index blob so a
    restarted process can list (and load) what the previous one
    captured. The index is process-shared like the blob cache itself —
    entries carry their origin node and dedup by incident id."""

    _MEM_CAP = 32  # full payloads retained in memory per store

    def __init__(self):
        self._lock = threading.Lock()
        self._payloads: "deque[dict]" = deque(maxlen=self._MEM_CAP)

    # -- save ----------------------------------------------------------------

    def save(self, incident: dict) -> str:
        """Persist one incident dump; returns its blob key. Persistence
        is best-effort (a failed disk write still leaves the in-memory
        copy and the process-shared memory blob)."""
        key = incident_key(str(incident["id"]))
        incident = dict(incident, blob_key=key)
        with self._lock:
            self._payloads.append(incident)
        try:
            from elasticsearch_tpu.index import ivf_cache

            ivf_cache.store_blob(key, ivf_cache.frame_blob(incident), _EXT)
            meta = {k: incident.get(k)
                    for k in ("id", "node", "node_name", "detector",
                              "reason", "timestamp_ms", "blob_key")}
            with _STORE_LOCK:
                entries = self._load_index()
                entries = [e for e in entries if e.get("id") != meta["id"]]
                entries.append(meta)
                evicted, entries = entries[:-_INDEX_CAP], \
                    entries[-_INDEX_CAP:]
                ivf_cache.store_blob(
                    _INDEX_KEY,
                    ivf_cache.frame_blob({"version": INCIDENT_VERSION,
                                          "entries": entries}), _EXT)
            # an index entry rolling off takes its payload blob with it:
            # an unlistable incident must not leak disk forever
            for e in evicted:
                if e.get("blob_key"):
                    ivf_cache.delete_blob(e["blob_key"], _EXT)
        except Exception:
            pass  # an incident must never fail the tripping thread
        return key

    # -- list / load ---------------------------------------------------------

    @staticmethod
    def _load_index() -> List[dict]:
        from elasticsearch_tpu.index import ivf_cache

        blob = ivf_cache.load_blob(_INDEX_KEY, _EXT)
        if blob is None:
            return []
        payload = ivf_cache.unframe_blob(blob)
        if payload is None or not isinstance(payload.get("entries"), list):
            ivf_cache.delete_blob(_INDEX_KEY, _EXT)  # corrupt: clean miss
            return []
        return payload["entries"]

    def list(self, include_persisted: bool = True) -> List[dict]:
        """Incident metadata, newest last: this store's live captures
        plus (by default) everything the persisted index remembers —
        dedup'd by id so a live incident isn't listed twice."""
        with self._lock:
            live = [
                {k: inc.get(k)
                 for k in ("id", "node", "node_name", "detector", "reason",
                           "timestamp_ms", "blob_key")}
                for inc in self._payloads]
        if not include_persisted:
            return live
        seen = {e["id"] for e in live}
        persisted = []
        try:
            for e in self._load_index():
                if e.get("id") not in seen:
                    persisted.append(dict(e, persisted=True))
        except Exception:
            pass
        return persisted + live

    def load(self, incident_id: str) -> Optional[dict]:
        """One incident's full payload: the in-memory copy, else the
        persisted blob (digest-verified; corruption deletes the blob and
        reads as a miss)."""
        with self._lock:
            for inc in reversed(self._payloads):
                if str(inc.get("id")) == str(incident_id):
                    return inc
        try:
            from elasticsearch_tpu.index import ivf_cache

            key = incident_key(str(incident_id))
            blob = ivf_cache.load_blob(key, _EXT)
            if blob is None:
                return None
            payload = ivf_cache.unframe_blob(blob)
            if payload is None:
                ivf_cache.delete_blob(key, _EXT)
                return None
            return payload
        except Exception:
            return None

    def recent(self, n: int) -> List[dict]:
        """The last ``n`` full payloads held in memory (the diagnostics
        bundle ships these; older incidents stay fetchable by id)."""
        with self._lock:
            items = list(self._payloads)
        return items[-max(0, int(n)):]
