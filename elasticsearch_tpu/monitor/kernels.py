"""Kernel-dispatch counters: which device program served each query.

Round-2 verdict asked for an observable record of the kernel behind every
search ("a profile or stats counter shows which kernel served each query").
Dispatch decisions happen in HOST code (query execution / prim build /
mesh_service routing) — never inside traced programs, where a counter would
only tick at compile time — so each `record()` call site marks one served
request component. Surfaced under `indices.search.kernels` in
`_nodes/stats` (reference: the per-phase counters ES exposes via
org/elasticsearch/index/search/stats/SearchStats.java:1-120).

Names:
  bm25_scatter        pure scatter-add postings scoring (host or mesh)
  bm25_hybrid         dense-impact MXU matmul + scatter tail
  bm25_fused_topk     Pallas streaming dense top-k (no [Q, D] intermediate)
  bm25_postings_sharded  oversized field scored via the cross-device
                      postings split + psum merge (parallel/postings_shard)
  knn_fused_topk      fused scores+mask+topk (Pallas on TPU, XLA elsewhere);
                      subsumed the r3 `knn_full` [D]-row path in r4 (filters
                      now fold into the fused candidate mask)
  knn_ivf             IVF-flat probe + exact candidate scoring
  knn_ivf_pq          IVF probe + ADC coarse rank over PQ codes + exact
                      fine re-rank of the top survivors (ops/pq.py)
  knn_maxsim          multi-vector MaxSim query served by the fused
                      per-token sweep + device scatter-max merge
  knn_fused_batch     kNN/MaxSim request served by the fused BATCH tier
                      (search/batch.knn_topk_fused_batch — msearch or
                      the serving coalescer); one count per request
  adc_pallas          PQ coarse rank ran the Pallas tiled ADC kernel
  adc_xla             PQ coarse rank ran the XLA gather table-sum
  adc_pallas_failed   ADC kernel attempt failed (latch bookkeeping —
                      ops/pallas_kernels.note_adc_failure)
  ivf_build           IVF quantizer built via k-means at segment freeze
  ivf_cache_hit       IVF quantizer reloaded from the persisted blob cache
                      (index/ivf_cache.py) instead of rebuilt
  pq_build            PQ codebooks trained + slab encoded at freeze
  pq_cache_hit        PQ tier reloaded from the persisted blob cache
  mesh_search         request served by the mesh product path
  mesh_fallback_total request fell back to the host per-shard loop
  mesh_host_by_design request routed to the host loop ON PURPOSE (IVF
                      probing) — not a fallback, excluded from the budget
  span_clause_truncated  a deeply-nested span clause exceeded
                      MAX_SPANS_PER_CLAUSE on the host walk (search/spans)
  executor_prep_hit   a search round reused a prepared-query memo entry
                      (compiled program + device inputs, no rebuild)
  executor_prep_miss  a memoizable round built programs/inputs fresh
  executor_data_hit   a segment-round device-data group was reused
  executor_data_miss  a segment-round device-data group was built+uploaded

The executor cache counters feed bench.py's ``metrics_delta`` and the
``estpu_kernel_dispatch_total`` Prometheus family (monitor/metrics.py).
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict

_LOCK = threading.Lock()
_COUNTS: Dict[str, int] = defaultdict(int)


def record(name: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTS[name] += n


def snapshot() -> Dict[str, int]:
    with _LOCK:
        return dict(_COUNTS)


def reset() -> None:
    """Test isolation only."""
    with _LOCK:
        _COUNTS.clear()
