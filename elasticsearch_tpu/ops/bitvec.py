"""Packed bit-vector algebra for candidate sets and kNN pre-filters.

"Efficient Multi-Vector Dense Retrieval Using Bit Vectors" (arXiv:
2404.02805) carries ANN candidate sets as packed bit vectors so that
filter intersection is a handful of word-wise ANDs instead of a dense
bool walk. Our filter algebra is already device bool[D] masks; this
module supplies the packed uint32[D/32] form those masks compress into
(32x smaller, so a query's pre-filter ships to the IVF/PQ program as a
few KB instead of a full bool row) plus the word-wise ops that compose
with them:

  * ``pack_mask`` / ``unpack_mask`` — bool[D] <-> uint32[D/32]
    (max_docs is always pow2 >= 64, so D % 32 == 0 holds by
    construction — utils/shapes.pow2_bucket minimum).
  * ``test_bits`` — membership probe for a gathered id vector:
    ``(words[id >> 5] >> (id & 31)) & 1``. This is how the IVF+PQ
    program pre-filters probed candidates BEFORE the coarse rank, so a
    selective filter no longer starves the fine stage (the old path
    intersected after selection — ES applies the kNN filter during the
    search, not after).
  * ``popcount`` — SWAR per-word popcount, summed; the starvation
    floor check (enough filtered matches to cover k) without a bool
    reduction over D.

All ops are pure jnp (trace-safe); none allocate persistent device
state, so there is nothing to account against the residency registry.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def pack_mask(mask):
    """bool[D] -> uint32[D // 32] little-endian bit packing (bit i of
    word w is doc w * 32 + i). D must be a multiple of 32 — true for
    every segment (max_docs is pow2-padded with minimum 64)."""
    D = mask.shape[0]
    assert D % 32 == 0, "mask length must be a multiple of 32"
    bits = mask.reshape(D // 32, 32).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, :]
    return jnp.sum(bits * weights, axis=1, dtype=jnp.uint32)


@jax.jit
def unpack_mask(words):
    """uint32[W] -> bool[W * 32] (inverse of pack_mask)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, :]
    bits = (words[:, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(-1).astype(bool)


@jax.jit
def test_bits(words, ids):
    """Membership of each int32 id in the packed set: bool[len(ids)].

    Callers pass CLAMPED ids (0 <= id < 32 * len(words)) — the IVF
    program's padded candidates are masked separately by its own
    validity lane, so an out-of-range sentinel never reaches here.
    """
    word = words[ids >> 5]
    bit = (ids & 31).astype(jnp.uint32)
    return ((word >> bit) & jnp.uint32(1)) != 0


@jax.jit
def popcount(words):
    """Total set bits across the packed vector (int32 scalar) — the
    classic SWAR reduction, no 256-entry lookup table to keep resident."""
    x = words
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    per_word = (x * jnp.uint32(0x01010101)) >> 24
    return jnp.sum(per_word.astype(jnp.int32))


@jax.jit
def bitvec_and(a, b):
    return a & b


@jax.jit
def bitvec_or(a, b):
    return a | b


@jax.jit
def bitvec_andnot(a, b):
    """a & ~b — e.g. candidate set minus a deletion set."""
    return a & ~b
