"""Product quantization: PQ-coded vector slabs + asymmetric distance.

BENCH_r05 measured the IVF cliff (389.5 -> 73.3 -> 12.6 qps as
num_candidates grows 1k -> 16k) because the fine-rank stage gathers and
re-scores full-precision f32 vectors for EVERY probed candidate — a
memory-bandwidth wall, exactly what TileMaxSim (arXiv:2606.26439)
attacks with tiled scoring over fused product quantization. The fix is
the classical PQ/ADC split:

  * BUILD (host/offline, at segment freeze beside the IVF quantizer):
    split dims into M subspaces of dsub dims, k-means K centroids per
    subspace (reusing ops/ivf.kmeans — device matmuls, host in/out),
    then encode every slab row into M uint8 codes. The code array is
    dims*4/M times smaller than the f32 slab (128d, M=32 -> 16x).
  * QUERY (asymmetric distance computation, ADC): one M x K lookup
    table of partial similarities between the UNQUANTIZED query and
    every codeword, then each candidate's coarse score is a table-sum
    over its M codes — a uint8 gather + add, no f32 vector gather, no
    matmul over the candidate set. Cost per candidate is O(M) bytes
    instead of O(dims) floats, so the coarse rank no longer scales
    with num_candidates in any way that hurts.
  * The fine stage re-scores only the top ~4k ADC survivors in exact
    f32 (ops/knn.exact metrics), restoring exact ES score semantics.

Metric mapping (coarse scores are MONOTONE PROXIES — ranking-only;
the fine stage emits the real ES-shaped scores):

  cosine       slab rows are l2-normalized before training/encoding;
               LUT = normalized-query-subvector . codeword, so the
               table-sum approximates cos(q, v).
  dot_product  LUT = query-subvector . codeword (vectors unit-norm by
               ES contract).
  l2_norm      LUT = 2 q_m.c - ||c||^2 (the norm expansion of
               -||q_m - c||^2 with the constant ||q_m||^2 dropped) —
               monotone in negative squared distance.

Residency: code arrays register as EVICTABLE fielddata-tier
ResidentArray handles (resources/residency.py) — pressure evicts them
LRU-first and the next query rehydrates bit-exactly from the host
mirror; a breaker denial at placement is best-effort (the caller keeps
the exact f32 fine-rank path — same contract as dense impact blocks).
Codebooks are tiny (M*K*dsub f32 = the slab's footprint / D) and place
through the accounted RESIDENCY.device_put choke point beside the IVF
centroids; both persist via the content-addressed blob cache
(index/ivf_cache.py) so restarts and snapshot restores skip the
k-means.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np

#: encode-time chunk: bounds the [chunk, M, K] argmax intermediate so a
#: million-row slab never materializes an N x M x K affinity tensor
_ENCODE_CHUNK = 16384


def _jax():
    import jax

    return jax


def pq_layout(dims: int) -> Tuple[int, int]:
    """(M subspaces, dsub dims each) for a vector field.

    Targets dsub >= 4 with M capped at 32 (LUT stays M*K f32 <= 32 KB —
    VMEM-resident for the Pallas ADC kernel); tiny dims degrade to
    dsub 2, then to a single-subspace VQ.
    """
    for M in (32, 16, 8, 4, 2):
        if dims % M == 0 and dims // M >= 4:
            return M, dims // M
    for M in (16, 8, 4, 2):
        if dims % M == 0 and dims // M >= 2:
            return M, dims // M
    return 1, dims


def pq_codebook_size(n_train: int) -> int:
    """K for a training set of n_train live vectors: 256 when the slab
    affords it, else the largest power of two that keeps >= 8 training
    vectors per codeword."""
    if n_train >= 2048:
        return 256
    k = 1 << max(int(np.floor(np.log2(max(n_train // 8, 1)))), 0)
    return max(min(k, 256), 1)


@dataclass
class PqHostParts:
    """Host-side build output — placement (and its breaker accounting)
    stays with the caller so a denial can retry later."""

    codebooks: np.ndarray  # f32[M, K, dsub]
    codes: np.ndarray  # uint8[max_docs, M]
    M: int
    K: int
    dsub: int
    dims: int
    metric: str


@dataclass
class PqIndex:
    """Device-resident PQ tier for one (immutable) vector slab."""

    codebooks: Any  # f32[M, K, dsub] (device, accounted)
    codes: Any  # ResidentArray handle (evictable) or device array
    M: int
    K: int
    dsub: int
    dims: int
    metric: str
    codebooks_host: Optional[np.ndarray] = None
    codes_host: Optional[np.ndarray] = None

    def codes_dev(self):
        """The device code array, rehydrating an evicted handle."""
        from elasticsearch_tpu.resources.residency import ResidentArray

        if isinstance(self.codes, ResidentArray):
            return self.codes.get()
        return self.codes


def _normalize_rows(x: np.ndarray) -> np.ndarray:
    n = np.linalg.norm(x, axis=-1, keepdims=True)
    return x / np.maximum(n, 1e-12)


_ENCODE_PROGRAMS: dict = {}


def _encode_program(M: int, dsub: int):
    """Compiled chunk encoder for one (M, dsub) shape class: nearest
    codeword per subspace via the norm expansion (argmin ||x - c||^2 ==
    argmax x.c - ||c||^2 / 2) — one einsum on the MXU per chunk."""
    key = (M, dsub)
    prog = _ENCODE_PROGRAMS.get(key)
    if prog is not None:
        return prog
    jax = _jax()
    import jax.numpy as jnp

    @jax.jit
    def run(chunk, codebooks):
        x = chunk.reshape(chunk.shape[0], M, dsub)
        aff = jnp.einsum("nmd,mkd->nmk", x, codebooks,
                         preferred_element_type=jnp.float32)
        # codebooks are repeat-padded when training was tiny (train_pq
        # tiles codewords to K): duplicate codewords are argmax-neutral,
        # and the norm sum runs over the full dsub axis  # tpulint: masked
        aff = aff - 0.5 * jnp.sum(codebooks * codebooks, axis=-1)[None, :, :]
        return jnp.argmax(aff, axis=2).astype(jnp.uint8)

    # factory-key discipline (ROADMAP #6): the encoder rides the AOT
    # blob cache so a restarted node re-encodes without recompiling
    from elasticsearch_tpu.parallel import aot

    run = aot.wrap(run, "pq_encode", key)
    _ENCODE_PROGRAMS[key] = run
    return run


def train_pq(train: np.ndarray, M: int, K: int, iters: int = 6,
             metric: str = "cosine") -> np.ndarray:
    """Per-subspace k-means codebooks f32[M, K, dsub] over live training
    rows (already normalized for cosine). Subspace clustering is ALWAYS
    squared-l2 (standard PQ — the reconstruction objective), regardless
    of the field similarity; the similarity shapes the LUT instead."""
    from elasticsearch_tpu.ops.ivf import kmeans

    n, dims = train.shape
    dsub = dims // M
    books = np.empty((M, K, dsub), np.float32)
    for m in range(M):
        sub = np.ascontiguousarray(train[:, m * dsub:(m + 1) * dsub])
        cents, _ = kmeans(sub, K, iters=iters, metric="l2")
        if cents.shape[0] < K:  # tiny training set: repeat-pad codewords
            reps = int(np.ceil(K / cents.shape[0]))
            cents = np.tile(cents, (reps, 1))[:K]
        books[m] = cents
    return books


def pq_encode(vecs: np.ndarray, codebooks: np.ndarray) -> np.ndarray:
    """uint8[N, M] codes for every slab row (chunked device encode)."""
    jax = _jax()

    M, _K, dsub = codebooks.shape
    # (M, dsub) is the pq_layout shape class for the field's dims — a
    # config-bounded universe, one program per layout  # tpulint: bucketed
    prog = _encode_program(M, dsub)
    # offbudget: build-time temporaries, freed when the encode returns
    d_books = jax.device_put(codebooks)  # tpulint: offbudget
    N = vecs.shape[0]
    out = np.empty((N, M), np.uint8)
    step = _ENCODE_CHUNK
    for s in range(0, N, step):
        chunk = vecs[s:s + step]
        if chunk.shape[0] < step and N > step:
            pad = np.zeros((step - chunk.shape[0], vecs.shape[1]),
                           np.float32)
            enc = prog(jax.device_put(  # tpulint: offbudget
                np.concatenate([chunk, pad])), d_books)
            out[s:s + chunk.shape[0]] = np.asarray(enc)[: chunk.shape[0]]
        else:
            enc = prog(jax.device_put(chunk), d_books)  # tpulint: offbudget
            out[s:s + chunk.shape[0]] = np.asarray(enc)
    return out


def build_pq(vecs_np: np.ndarray, exists_np: np.ndarray, metric: str,
             M: Optional[int] = None, K: Optional[int] = None,
             iters: int = 6, min_train: int = 128) -> Optional[PqHostParts]:
    """Train + encode the PQ tier for one frozen slab (host in, host
    out — placement is the caller's). None = declined (too few live
    vectors for a codebook worth having; exact scoring wins there)."""
    # host-side BUILD path (freeze-time, never traced)
    ids = np.nonzero(exists_np)[0]  # tpulint: host
    n = ids.size
    if n < min_train:
        return None
    dims = vecs_np.shape[1]
    if M is None:
        M, dsub = pq_layout(dims)
    else:
        if dims % M:
            raise ValueError(f"pq subspaces [{M}] must divide dims [{dims}]")
        dsub = dims // M
    if K is None:
        K = pq_codebook_size(n)
    slab = vecs_np.astype(np.float32, copy=False)
    if metric == "cosine":
        # encode the DIRECTIONS: the ADC table-sum then approximates
        # cos(q, v) directly (query side normalizes in the LUT build)
        slab = _normalize_rows(slab)
        train = slab[ids]
    else:
        train = slab[ids]
    books = train_pq(train, M, K, iters=iters, metric=metric)
    codes = pq_encode(slab, books)
    return PqHostParts(codebooks=books, codes=codes, M=M, K=K, dsub=dsub,
                       dims=dims, metric=metric)


def place_pq(parts: PqHostParts, label: str = "pq") -> Optional[PqIndex]:
    """Place a built PQ tier on device. Codebooks go through the
    accounted RESIDENCY.device_put choke point (tiny, always-resident,
    owned by the column like IVF centroids); the code array registers
    as an EVICTABLE fielddata-tier handle. best_effort: a breaker
    denial returns None — PQ is a pure acceleration, the caller keeps
    the exact fine-rank path and retries on a later query."""
    from elasticsearch_tpu import resources

    handle = resources.RESIDENCY.put_array(
        parts.codes, label=f"{label}.codes", tier="fielddata",
        best_effort=True)
    if handle is None:
        return None
    try:
        books = resources.RESIDENCY.device_put(parts.codebooks,
                                               label=f"{label}.codebooks")
    except Exception:
        # a codebook breaker denial must not strand the codes handle's
        # fielddata charge — evict it before propagating
        handle.evict()
        raise
    return PqIndex(codebooks=books, codes=handle, M=parts.M, K=parts.K,
                   dsub=parts.dsub, dims=parts.dims, metric=parts.metric,
                   codebooks_host=parts.codebooks, codes_host=parts.codes)


# ---------------------------------------------------------------------------
# traced ADC pieces (inlined into the IVF coarse->fine program)
# ---------------------------------------------------------------------------

def adc_lut(jnp, query, codebooks, metric: str):
    """[M, K] partial-similarity lookup table for one query (traced).

    Higher is better for every metric; values are ranking proxies, not
    calibrated ES scores (the fine stage re-scores survivors exactly).
    """
    if metric == "cosine":
        q = query / jnp.maximum(jnp.linalg.norm(query), 1e-12)
    else:
        q = query
    M, _K, dsub = codebooks.shape
    qs = q.reshape(M, dsub)
    lut = jnp.einsum("md,mkd->mk", qs, codebooks,
                     preferred_element_type=jnp.float32)
    if metric in ("l2_norm", "l2"):
        # monotone in -||q_m - c||^2 (constant ||q_m||^2 dropped)
        lut = 2.0 * lut - jnp.sum(codebooks * codebooks, axis=-1)
    return lut


def adc_sum(jnp, codes, lut):
    """Table-sum coarse scores f32[W] for codes [W, M] (traced XLA
    form — a [W, M] gather + row sum; the Pallas variant lives in
    ops/pallas_kernels.adc_scores_pallas)."""
    M = lut.shape[0]
    idx = codes.astype(jnp.int32)
    return jnp.sum(lut[jnp.arange(M)[None, :], idx], axis=1)
