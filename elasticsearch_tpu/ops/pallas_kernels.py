"""Pallas TPU kernels for the kNN hot path.

The XLA path (ops/knn.py) materializes the full [Q, D] similarity matrix in
HBM before top-k — at SIFT scale (D=1M, Q=64) that is a 256 MB round trip
per batch. This kernel streams corpus tiles HBM→VMEM, runs the MXU matmul
per tile, applies the metric transform + live-doc mask on the VPU, and
maintains the running top-k in the output block across sequential grid
steps — the [Q, D] intermediate never exists.

Top-k merge strategy: k is small (ES size/num_candidates, ≤64 here) so each
tile does k iterations of (row-max, argmax, knock-out) over the fused
[Q, TILE+K] candidate block — pure VPU reductions, no sort network needed.

Falls back to interpret mode on CPU (tests) and to the XLA path for shapes
the kernel doesn't cover; both produce identical results (modulo fp
reduction order), asserted in tests/unit/test_pallas_kernels.py.
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = float("-inf")  # python scalar: jnp constants would be captured consts in pallas


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@partial(jax.jit, static_argnames=("k", "metric", "tile", "interpret",
                                   "precise"))
def knn_topk_pallas(queries, vecs, mask, *, k: int, metric: str = "cosine",
                    tile: int = 2048, interpret: bool = False,
                    precise: bool = False):
    """Fused scores + mask + running top-k over corpus tiles.

    queries: f32[Q, dims] (Q, dims small enough for VMEM residency)
    vecs:    f32[D, dims], D % tile == 0 (caller pads; padded rows masked)
    mask:    bool[D] live-doc mask
    precise: score in f32 (multi-pass on the MXU, ~3x the matmul cost) —
             for exact-kNN recall on corpora whose neighbor gaps are below
             bf16 resolution; default bf16 for throughput.
    Returns ([Q, k] scores, [Q, k] int32 doc ids), same contract as
    ops.knn.knn_topk.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if metric not in ("cosine", "dot_product", "dot", "l2_norm", "l2"):
        raise ValueError(f"unknown knn metric [{metric}]")  # match ops.knn
    Q, dims = queries.shape
    D = vecs.shape[0]
    assert D % tile == 0, "corpus must be padded to a tile multiple"
    n_tiles = D // tile

    if metric == "cosine":
        qn = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=-1, keepdims=True), 1e-12)
    else:
        qn = queries
    qh = qn.astype(jnp.float32 if precise else jnp.bfloat16)

    def kernel(q_ref, v_ref, m_ref, out_v_ref, out_i_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_v_ref[:] = jnp.full((Q, k), NEG_INF, dtype=jnp.float32)
            out_i_ref[:] = jnp.zeros((Q, k), dtype=jnp.int32)

        v = v_ref[:]  # [tile, dims] f32
        if metric == "cosine":
            norm = jnp.sqrt(jnp.sum(v * v, axis=-1, keepdims=True))
            v = v / jnp.maximum(norm, 1e-12)
        s = jax.lax.dot_general(
            q_ref[:], v if precise else v.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST if precise else None,
        )  # [Q, tile]
        if metric in ("cosine", "dot_product", "dot"):
            s = (1.0 + s) * 0.5
        else:  # l2_norm via norm expansion
            q2 = jnp.sum(q_ref[:].astype(jnp.float32) ** 2, axis=-1,
                         keepdims=True)
            v2 = jnp.sum(v.astype(jnp.float32) ** 2, axis=-1)[None, :]
            s = 1.0 / (1.0 + jnp.maximum(q2 - 2.0 * s + v2, 0.0))
        s = jnp.where(m_ref[:][None, :], s, NEG_INF)

        base = step * tile
        tile_ids = base + jax.lax.broadcasted_iota(jnp.int32, (Q, tile), 1)

        # fused candidates: previous best (k) + this tile
        cand_v = jnp.concatenate([out_v_ref[:], s], axis=1)  # [Q, k+tile]
        cand_i = jnp.concatenate([out_i_ref[:], tile_ids], axis=1)

        # k iterations of extract-max (VPU row reductions). No gathers —
        # Mosaic lowers mask-reduce, not take_along_axis: the picked id is
        # recovered by masking the id matrix with the argmax column.
        def extract(j, carry):
            cv, ci, bv, bi = carry
            m = jnp.max(cv, axis=1)  # [Q]
            am = jnp.argmax(cv, axis=1)  # [Q]
            width = cv.shape[1]
            knock = jax.lax.broadcasted_iota(jnp.int32, (Q, width), 1) == am[:, None]
            picked_i = jnp.max(jnp.where(knock, ci, jnp.int32(-1)), axis=1)
            # column-j store via iota mask (dynamic .at[] would be a scatter)
            col_j = jax.lax.broadcasted_iota(jnp.int32, (Q, k), 1) == j
            bv = jnp.where(col_j, m[:, None], bv)
            bi = jnp.where(col_j, picked_i[:, None], bi)
            cv = jnp.where(knock, NEG_INF, cv)  # knock out the chosen column
            return cv, ci, bv, bi

        bv0 = jnp.full((Q, k), NEG_INF, dtype=jnp.float32)
        bi0 = jnp.zeros((Q, k), dtype=jnp.int32)
        _, _, bv, bi = jax.lax.fori_loop(
            0, k, extract, (cand_v, cand_i, bv0, bi0))
        out_v_ref[:] = bv
        out_i_ref[:] = bi

    out_v, out_i = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((Q, dims), lambda i: (0, 0)),          # queries: resident
            pl.BlockSpec((tile, dims), lambda i: (i, 0)),       # corpus tile
            pl.BlockSpec((tile,), lambda i: (i,)),              # mask tile
        ],
        out_specs=[
            pl.BlockSpec((Q, k), lambda i: (0, 0)),             # running top-k
            pl.BlockSpec((Q, k), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Q, k), jnp.float32),
            jax.ShapeDtypeStruct((Q, k), jnp.int32),
        ],
        interpret=interpret,
    )(qh, vecs, mask)
    return out_v, out_i


@partial(jax.jit, static_argnames=("k", "tile", "q_tile", "interpret"))
def bm25_dense_topk_pallas(qw, impact, mask, *, k: int, tile: int = 2048,
                           q_tile: int = 256, interpret: bool = False):
    """Fused batched BM25 over the dense impact block with in-kernel top-k.

    The XLA hybrid path (ops/scoring.bm25_score_hybrid_batch) materializes
    the full [Q, D] score matrix in HBM and runs a separate top-k pass —
    at bench scale (Q=2048, D=1M) that is an 8 GB round trip. This kernel
    streams impact[F, tile] tiles HBM→VMEM, runs the qw @ tile matmul on
    the MXU, applies the live mask on the VPU, and maintains the running
    top-k in the output block across grid steps — [Q, D] never exists.

    qw:     f32[Q, F]  idf*boost per dense term per query (0 = absent)
    impact: f32[F, D]  index-time impact block (idf folded at query time
                       via qw; rows are tfnorm impacts)
    mask:   bool[D]    live-doc mask
    Returns ([Q, k] scores, [Q, k] int32 doc ids) — same contract as
    topk_batch(bm25_score_hybrid_batch(...)).

    Scoring matches the XLA path modulo bf16 matmul rounding (the XLA
    hybrid uses f32-HIGHEST; tests assert top-1 agreement).
    """
    from jax.experimental import pallas as pl

    Q, F = qw.shape
    D = impact.shape[1]
    assert D % tile == 0, "impact block must be padded to a tile multiple"
    assert Q % q_tile == 0, "queries must be padded to a q_tile multiple"
    n_tiles = D // tile
    n_q = Q // q_tile
    qh = qw.astype(jnp.bfloat16)
    QT = q_tile

    def kernel(q_ref, imp_ref, m_ref, out_v_ref, out_i_ref):
        step = pl.program_id(1)  # d-tile sweep is the inner grid axis

        @pl.when(step == 0)
        def _init():
            out_v_ref[:] = jnp.full((QT, k), NEG_INF, dtype=jnp.float32)
            out_i_ref[:] = jnp.zeros((QT, k), dtype=jnp.int32)

        s = jax.lax.dot_general(
            q_ref[:], imp_ref[:].astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [QT, tile]
        s = jnp.where(m_ref[:], s, NEG_INF)  # mask block is [1, tile]
        base = step * tile
        tile_ids = base + jax.lax.broadcasted_iota(jnp.int32, (QT, tile), 1)

        # Early-exit selection: the running top-k lives UNSORTED in the
        # output refs; each pass extracts the tile's per-row max and
        # replaces the row's current minimum where it improves, looping
        # only while SOME row can still improve. In the steady state a
        # tile improves ~0-1 entries per row (top-k insertions over a
        # random-order sweep total ~k·ln(D/k) per query), so this runs
        # ~1 pass where the old fixed fori_loop always paid k — the
        # kernel's dominant VPU cost at large Q. A tile can contribute at
        # most k entries per row, so k iterations bound the loop. Tie
        # discipline: equal scores never displace an incumbent (m > rmin
        # strict), and within a tile argmax picks the lowest doc id; the
        # host-side wrapper re-sorts the unsorted buffer with an explicit
        # (-value, doc id) key to match lax.top_k tie order exactly.
        # the tile max `m` rides in the carry: cond/body can't CSE across
        # a while_loop, and the [QT, tile] reductions ARE the kernel's
        # dominant VPU cost — the non-improving steady state must pay
        # exactly ONE full-width pass (the pre-loop max) per tile
        def cond(carry):
            cv, bv, bi, m, it = carry
            return (it < k) & jnp.any(m > jnp.min(bv, axis=1))

        def body(carry):
            cv, bv, bi, m, it = carry
            am = jnp.argmax(cv, axis=1)
            knock = (jax.lax.broadcasted_iota(jnp.int32, (QT, tile), 1)
                     == am[:, None])
            picked_i = jnp.max(jnp.where(knock, tile_ids, jnp.int32(-1)),
                               axis=1)
            rmin = jnp.min(bv, axis=1)
            amin = jnp.argmin(bv, axis=1)
            improve = m > rmin
            upd = improve[:, None] & (
                jax.lax.broadcasted_iota(jnp.int32, (QT, k), 1)
                == amin[:, None])
            bv = jnp.where(upd, m[:, None], bv)
            bi = jnp.where(upd, picked_i[:, None], bi)
            cv = jnp.where(knock, NEG_INF, cv)
            return cv, bv, bi, jnp.max(cv, axis=1), it + 1

        _, bv, bi, _, _ = jax.lax.while_loop(
            cond, body,
            (s, out_v_ref[:], out_i_ref[:], jnp.max(s, axis=1), 0))
        out_v_ref[:] = bv
        out_i_ref[:] = bi

    out_v, out_i = pl.pallas_call(
        kernel,
        grid=(n_q, n_tiles),
        in_specs=[
            pl.BlockSpec((QT, F), lambda qi, di: (qi, 0)),     # query block
            pl.BlockSpec((F, tile), lambda qi, di: (0, di)),   # impact tile
            # mask rides as [1, D] — 1-D i32 blocks can hit XLA/Mosaic
            # layout mismatches at small tiles (T(1024) vs T(tile))
            pl.BlockSpec((1, tile), lambda qi, di: (0, di)),
        ],
        out_specs=[
            pl.BlockSpec((QT, k), lambda qi, di: (qi, 0)),
            pl.BlockSpec((QT, k), lambda qi, di: (qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Q, k), jnp.float32),
            jax.ShapeDtypeStruct((Q, k), jnp.int32),
        ],
        interpret=interpret,
    )(qh, impact, mask[None, :])
    # the kernel's buffer is unsorted: order by (-value, doc id) — id
    # ascending FIRST, then a stable value top_k, so equal scores rank by
    # lowest doc id exactly like lax.top_k over the dense score row
    order = jnp.argsort(out_i, axis=1)
    v2 = jnp.take_along_axis(out_v, order, axis=1)
    i2 = jnp.take_along_axis(out_i, order, axis=1)
    vals, pos = jax.lax.top_k(v2, k)
    ids = jnp.take_along_axis(i2, pos, axis=1)
    return vals, ids


def bm25_dense_tiles_for(Q: int, F: int, D: int):
    """(q_tile, tile) keeping the working set under the VMEM budget:
    qw block (bf16) + impact tile (f32) + ~3 live [q_tile, tile] f32
    intermediates (scores + candidate copies) ≤ ~10 MB."""
    budget = 10 * 1024 * 1024
    for q_tile in (512, 256, 128, 64, 32, 16, 8):
        if Q % q_tile:
            continue
        for tile in (4096, 2048, 1024, 512):
            if D % tile:
                continue
            est = q_tile * F * 2 + F * tile * 4 + 3 * q_tile * tile * 4
            if est <= budget:
                return q_tile, tile
    return 0, 0


# sticky failure latch for the fused BM25 kernel (list so the traced-free
# eager dispatcher can flip it in place). Latches ONLY on deterministic
# compile/lowering failures — a transient runtime error (momentary device
# OOM, transfer hiccup) falls back per-call and the kernel retries, up to
# a bounded run of consecutive failures so a persistently-broken device
# can't pay a fresh kernel attempt on every batch until restart.
_BM25_PALLAS_BROKEN = [False]
_BM25_TRANSIENT_FAILS = [0]
_BM25_TRANSIENT_LIMIT = 8

# error shapes that mean "this kernel will NEVER compile/lower here" —
# deterministic, so one failure latches. Everything else is treated as
# transient (RESOURCE_EXHAUSTED, cancelled transfers, backend restarts).
_COMPILE_ERR_MARKERS = ("mosaic", "lowering", "unsupported", "unimplemented",
                        "compilation", "cannot lower")


def _is_compile_error(e: BaseException) -> bool:
    if isinstance(e, NotImplementedError):
        return True
    text = f"{type(e).__name__}: {e}".lower()
    return any(m in text for m in _COMPILE_ERR_MARKERS)


def bm25_dense_topk_auto(qw, impact, mask, *, k: int):
    """Dispatch: fused Pallas kernel on TPU when static shape gates hold,
    XLA hybrid matmul + topk_batch otherwise (same gate discipline as
    knn_topk_auto — no runtime fallback illusions).

    Q below the sublane multiple (a single REST query is Q=1) pads up to 8
    with zero query rows and slices the result — without this no single
    query could ever pass the q_tile gate and every request would fall to
    the XLA path that materializes the [Q, D] row this kernel avoids (the
    same regression knn_topk_auto documents from round 1)."""
    Q, F = qw.shape
    D = impact.shape[1]
    qpad = ((Q + 7) // 8) * 8
    q_tile, tile = bm25_dense_tiles_for(qpad, F, D)
    # ESTPU_BM25_BATCH_KERNEL: auto (default) | pallas | xla — the A/B
    # knob for the large-Q batch path (the kernel's in-kernel selection is
    # VPU-bound at k passes per tile; XLA's chunked matmul+top_k rides the
    # MXU + its tuned sort). Read eagerly here, like the other knobs.
    pref = os.environ.get("ESTPU_BM25_BATCH_KERNEL", "auto").lower()
    gates_ok = (not _BM25_PALLAS_BROKEN[0] and _on_tpu() and k <= 64
                and F % 8 == 0 and q_tile and D >= 2 * tile)
    if pref == "pallas" and not gates_ok:
        # a forced-pallas A/B must never SILENTLY measure the XLA side
        import warnings

        warnings.warn("ESTPU_BM25_BATCH_KERNEL=pallas but the kernel's "
                      "shape gates reject this call "
                      f"(on_tpu={_on_tpu()}, k={k}, F={F}, q_tile={q_tile},"
                      f" D={D}, tile={tile}) — falling back to XLA")
    if pref != "xla" and gates_ok:
        # this dispatcher runs EAGERLY, so a Mosaic lowering/compile
        # failure (first real-TPU run of the early-exit selection) is
        # catchable here — fall through to the XLA path with a warning
        # instead of failing the batch
        try:
            if qpad != Q:
                qp = jnp.concatenate(
                    [qw, jnp.zeros((qpad - Q, F), qw.dtype)], axis=0)
                vals, idx = bm25_dense_topk_pallas(qp, impact, mask, k=k,
                                                   tile=tile, q_tile=q_tile)
                _BM25_TRANSIENT_FAILS[0] = 0
                return vals[:Q], idx[:Q]
            out = bm25_dense_topk_pallas(qw, impact, mask, k=k, tile=tile,
                                         q_tile=q_tile)
            _BM25_TRANSIENT_FAILS[0] = 0
            return out
        except Exception as e:
            import warnings

            from elasticsearch_tpu.monitor import kernels

            kernels.record("bm25_pallas_failed")
            if _is_compile_error(e):
                # sticky: a deterministic Mosaic lowering failure must not
                # pay a fresh trace/compile attempt on every batch
                _BM25_PALLAS_BROKEN[0] = True
                warnings.warn(f"fused BM25 kernel failed ({type(e).__name__}"
                              f": {str(e)[:200]}); serving via the XLA path "
                              f"from now on")
            else:
                # transient (device OOM mid-burst, transfer error): fall
                # back for THIS call only; a bounded run of consecutive
                # failures latches anyway (every retry costs a batch)
                _BM25_TRANSIENT_FAILS[0] += 1
                if _BM25_TRANSIENT_FAILS[0] >= _BM25_TRANSIENT_LIMIT:
                    _BM25_PALLAS_BROKEN[0] = True
                    warnings.warn(
                        f"fused BM25 kernel failed {_BM25_TRANSIENT_FAILS[0]}"
                        f" consecutive times ({type(e).__name__}: "
                        f"{str(e)[:200]}); latching to the XLA path")
                else:
                    warnings.warn(
                        f"fused BM25 kernel transient failure "
                        f"({type(e).__name__}: {str(e)[:200]}); XLA "
                        f"fallback for this batch")
    from elasticsearch_tpu.ops.scoring import (impact_precision, topk_auto,
                                               topk_block_config)

    # XLA fallback, Q-chunked: one unchunked [Q, D] score matrix at msearch
    # batch scale (Q=2048, D=1M) would be an 8 GB intermediate. This
    # dispatcher runs EAGERLY, so reading the configs here is safe.
    outs = []
    step = min(Q, 256)
    blk = topk_block_config()
    prec = impact_precision()  # jax canonicalizes the precision string
    for q0 in range(0, Q, step):
        scores = jnp.dot(qw[q0:q0 + step], impact, precision=prec)
        masked = jnp.where(mask[None, :], scores, NEG_INF)
        outs.append(topk_auto(masked, k, blk))
    vals = jnp.concatenate([v for v, _ in outs], axis=0)
    idx = jnp.concatenate([i for _, i in outs], axis=0)
    return vals, idx.astype(jnp.int32)


# ---------------------------------------------------------------------------
# ADC (PQ table-sum) kernel — the coarse stage of the IVF coarse->fine rank
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("tile", "interpret"))
def adc_scores_pallas(codes, lut, *, tile: int = 2048,
                      interpret: bool = False):
    """Tiled asymmetric-distance table-sum: codes i32[W, M], lut
    f32[M, K] -> f32[W] coarse scores.

    Mosaic doesn't lower general gathers, so the per-subspace table
    lookup is phrased as a one-hot [tile, K] compare + matvec against
    the LUT row — an M-step static unroll of VPU compare + MXU matvec,
    with the LUT (<= 32 KB) resident in VMEM across the whole sweep.
    This is the TileMaxSim shape: candidate tiles stream HBM->VMEM as
    uint8-sized codes (M bytes/candidate), never as f32 vectors.
    """
    from jax.experimental import pallas as pl

    W, M = codes.shape
    K = lut.shape[1]
    assert W % tile == 0, "candidate set must be padded to a tile multiple"
    n_tiles = W // tile

    def kernel(c_ref, lut_ref, out_ref):
        c = c_ref[:]  # [tile, M] int32
        acc = jnp.zeros((tile,), jnp.float32)
        for m in range(M):  # static unroll, M <= 32
            onehot = (jax.lax.broadcasted_iota(jnp.int32, (tile, K), 1)
                      == c[:, m][:, None]).astype(jnp.float32)
            acc = acc + jax.lax.dot_general(
                onehot, lut_ref[m, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        out_ref[0, :] = acc

    out = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile, M), lambda i: (i, 0)),   # code tile
            pl.BlockSpec((M, K), lambda i: (0, 0)),      # LUT: resident
        ],
        # 1-D i32/f32 blocks can hit XLA/Mosaic layout mismatches at
        # small tiles (same note as the BM25 mask input) — ride as [1, W]
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, W), jnp.float32),
        interpret=interpret,
    )(codes, lut)
    return out[0]


# sticky failure latch for the ADC kernel — same discipline as the fused
# BM25 kernel above: deterministic compile/lowering failures latch on the
# first hit; transients fall back per-call up to a bounded run.
_ADC_PALLAS_BROKEN = [False]
_ADC_TRANSIENT_FAILS = [0]
_ADC_TRANSIENT_LIMIT = 8


def adc_pallas_tile(W: int, M: int, K: int) -> int:
    """Largest candidate tile the ADC kernel may use (0 = use the XLA
    gather form). Static shape gates only — the dispatch site runs
    EAGERLY (ops/ivf.ivf_candidate_scores), so a first-call Mosaic
    failure is catchable there and flips the latch."""
    if _ADC_PALLAS_BROKEN[0] or not _on_tpu():
        return 0
    if K % 128 != 0 or M > 32:
        return 0  # lane-aligned LUT rows; M bounds the unroll
    budget = 8 * 1024 * 1024
    for tile in (4096, 2048, 1024, 512):
        if W % tile:
            continue
        est = tile * M * 4 + M * K * 4 + 2 * tile * K * 4
        if est <= budget:
            return tile
    return 0


def note_adc_failure(e: BaseException) -> bool:
    """Record one ADC kernel failure (called from the eager dispatch in
    ops/ivf.py). Returns True when the latch is now set — the caller
    rebuilds its program without the Pallas ADC from then on; False
    means transient, fall back for this call only."""
    import warnings

    from elasticsearch_tpu.monitor import kernels

    kernels.record("adc_pallas_failed")
    if _is_compile_error(e):
        _ADC_PALLAS_BROKEN[0] = True
        warnings.warn(f"ADC kernel failed ({type(e).__name__}: "
                      f"{str(e)[:200]}); serving PQ coarse rank via the "
                      f"XLA gather path from now on")
        return True
    _ADC_TRANSIENT_FAILS[0] += 1
    if _ADC_TRANSIENT_FAILS[0] >= _ADC_TRANSIENT_LIMIT:
        _ADC_PALLAS_BROKEN[0] = True
        warnings.warn(f"ADC kernel failed {_ADC_TRANSIENT_FAILS[0]} "
                      f"consecutive times ({type(e).__name__}: "
                      f"{str(e)[:200]}); latching to the XLA path")
        return True
    warnings.warn(f"ADC kernel transient failure ({type(e).__name__}: "
                  f"{str(e)[:200]}); XLA fallback for this call")
    return False


def note_adc_success() -> None:
    """A served Pallas ADC call clears the transient-failure run."""
    _ADC_TRANSIENT_FAILS[0] = 0


def _knn_tile_for(Q: int, dims: int, k: int, D: int) -> int:
    """Largest corpus tile keeping the kernel's VMEM working set in budget:
    query block + corpus tile + ~3 live [Q, tile+k] candidate copies. A
    Q-blind tile (r4 regression: Q=256 x tile=8192 = 17 MB stack) OOMs
    scoped vmem at batch sizes the executor actually sends."""
    budget = 12 * 1024 * 1024
    qpad = ((Q + 7) // 8) * 8
    for tile in (8192, 4096, 2048, 1024, 512):
        if D % tile:
            continue
        est = qpad * dims * 4 + tile * dims * 4 + 3 * qpad * (tile + k) * 4
        if est <= budget:
            return tile
    return 0


def knn_topk_auto(queries, vecs, mask, *, k: int, metric: str = "cosine",
                  precise: bool = False):
    """Dispatch: Pallas fused kernel on TPU when shapes fit, XLA otherwise.

    precise=True scores in f32 end to end (Pallas multi-pass / XLA
    use_bf16=False) — exact-kNN recall parity for latency-path queries;
    batched throughput callers keep bf16 and follow with
    ops.knn.exact_rescore_topk on the candidates.

    Dispatch is decided purely from STATIC shape gates — no try/except:
    this is routinely called inside an outer jit/shard_map trace, where
    Mosaic lowering errors surface at outer-compile time (after any except
    block here has exited), so a runtime fallback would be an illusion.
    The gates mirror what the kernel is validated for on hardware: Q a
    sublane multiple, lane-aligned dims, small k, tile-divisible corpus.

    Q below the sublane multiple (a single REST knn query is Q=1) pads up
    to 8 with zero queries and slices the result — round 1 sent every
    single-query request down the XLA path that materializes the [Q, D]
    matrix this kernel exists to avoid."""
    from elasticsearch_tpu.ops.knn import knn_topk

    Q, dims = queries.shape
    D = vecs.shape[0]
    tile = _knn_tile_for(Q, dims, k, D)
    if (_on_tpu() and k <= 64 and dims % 128 == 0
            and tile and D >= 2 * tile):
        if Q % 8 != 0:
            qpad = ((Q + 7) // 8) * 8
            queries = jnp.concatenate(
                [queries, jnp.zeros((qpad - Q, dims), queries.dtype)], axis=0)
            vals, idx = knn_topk_pallas(queries, vecs, mask, k=k,
                                        metric=metric, tile=tile,
                                        precise=precise)
            return vals[:Q], idx[:Q]
        return knn_topk_pallas(queries, vecs, mask, k=k, metric=metric,
                               tile=tile, precise=precise)
    from elasticsearch_tpu.ops.scoring import topk_block_config

    return knn_topk(queries, vecs, mask, k=k, metric=metric,
                    use_bf16=not precise, topk_block=topk_block_config())


# ---------------------------------------------------------------------------
# MaxSim kernel — tiled multi-vector re-rank with fused PQ ADC decode
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("t_real", "tile", "interpret"))
def maxsim_adc_pallas(codes, luts, *, t_real: int, tile: int = 2048,
                      interpret: bool = False):
    """Tiled MaxSim over PQ codes: codes i32[W, M], luts f32[M, K, Tp]
    -> f32[W] per-candidate MaxSim scores (max over query tokens of the
    token's ADC table-sum).

    The ADC kernel above (`adc_scores_pallas`) is the single-token
    warm-up act: one one-hot compare + matvec per subspace. Here the
    matvec widens to a matmul against ALL token LUT columns at once —
    onehot [tile, K] @ luts[m] [K, Tp] accumulates the per-token partial
    sums [tile, Tp] across the M-step static unroll, and the token max
    collapses on the VPU at the end. Candidate tiles stream HBM->VMEM as
    M-byte code rows, never as f32 vectors — the TileMaxSim shape
    (dimension-tiled over the candidate axis, PQ decode fused into the
    interaction matmul, no [T, W] similarity intermediate in HBM).

    ``t_real`` <= Tp masks LUT pad columns out of the max (callers pad
    the token axis to a sublane multiple; a zero pad column would win
    the max whenever every real table-sum is negative, e.g. l2 LUTs).
    """
    from jax.experimental import pallas as pl

    W, M = codes.shape
    K, Tp = luts.shape[1], luts.shape[2]
    assert W % tile == 0, "candidate set must be padded to a tile multiple"
    n_tiles = W // tile

    def kernel(c_ref, lut_ref, out_ref):
        c = c_ref[:]  # [tile, M] int32
        acc = jnp.zeros((tile, Tp), jnp.float32)
        for m in range(M):  # static unroll, M <= 32
            onehot = (jax.lax.broadcasted_iota(jnp.int32, (tile, K), 1)
                      == c[:, m][:, None]).astype(jnp.float32)
            acc = acc + jax.lax.dot_general(
                onehot, lut_ref[m], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        tok = jax.lax.broadcasted_iota(jnp.int32, (tile, Tp), 1)
        acc = jnp.where(tok < t_real, acc, NEG_INF)
        out_ref[0, :] = jnp.max(acc, axis=1)

    out = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile, M), lambda i: (i, 0)),    # code tile
            pl.BlockSpec((M, K, Tp), lambda i: (0, 0, 0)),  # LUTs: resident
        ],
        # 1-D outputs ride as [1, W] (same layout note as the ADC kernel)
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, W), jnp.float32),
        interpret=interpret,
    )(codes, luts)
    return out[0]


# sticky failure latch — same discipline as the BM25/ADC kernels above:
# deterministic compile/lowering failures latch on the first hit;
# transients fall back per-call up to a bounded run.
_MAXSIM_PALLAS_BROKEN = [False]
_MAXSIM_TRANSIENT_FAILS = [0]
_MAXSIM_TRANSIENT_LIMIT = 8


def maxsim_adc_tile(W: int, M: int, K: int, Tp: int) -> int:
    """Largest candidate tile the MaxSim-ADC kernel may use (0 = use the
    XLA gather form). Static shape gates only — the dispatch below runs
    EAGERLY, so a first-call Mosaic failure is catchable there."""
    if _MAXSIM_PALLAS_BROKEN[0] or not _on_tpu():
        return 0
    if K % 128 != 0 or M > 32 or Tp > 64:
        return 0  # lane-aligned LUT rows; M bounds the unroll
    budget = 8 * 1024 * 1024
    for tile in (4096, 2048, 1024, 512):
        if W % tile:
            continue
        est = (tile * M * 4 + M * K * Tp * 4 + tile * K * 4
               + 2 * tile * Tp * 4)
        if est <= budget:
            return tile
    return 0


def _note_maxsim_failure(e: BaseException) -> None:
    import warnings

    from elasticsearch_tpu.monitor import kernels

    kernels.record("maxsim_pallas_failed")
    if _is_compile_error(e):
        _MAXSIM_PALLAS_BROKEN[0] = True
        warnings.warn(f"MaxSim-ADC kernel failed ({type(e).__name__}: "
                      f"{str(e)[:200]}); serving the re-rank stage via "
                      f"the XLA gather path from now on")
        return
    _MAXSIM_TRANSIENT_FAILS[0] += 1
    if _MAXSIM_TRANSIENT_FAILS[0] >= _MAXSIM_TRANSIENT_LIMIT:
        _MAXSIM_PALLAS_BROKEN[0] = True
        warnings.warn(f"MaxSim-ADC kernel failed {_MAXSIM_TRANSIENT_FAILS[0]}"
                      f" consecutive times ({type(e).__name__}: "
                      f"{str(e)[:200]}); latching to the XLA path")
        return
    warnings.warn(f"MaxSim-ADC kernel transient failure ({type(e).__name__}"
                  f": {str(e)[:200]}); XLA fallback for this call")


@jax.jit
def _maxsim_adc_xla(codes, luts):
    """XLA reference form: per-token table-sum gather + token max.
    codes i32[W, M], luts f32[T, M, K] -> f32[W]."""
    M = luts.shape[1]
    idx = codes.astype(jnp.int32)  # [W, M]
    # [T, W, M] gather off the LUT tables, summed over subspaces
    per_tok = jnp.sum(luts[:, jnp.arange(M)[None, :], idx], axis=2)
    return jnp.max(per_tok, axis=0)


def maxsim_adc_auto(codes, luts):
    """Dispatch: fused Pallas MaxSim-ADC kernel on TPU when static shape
    gates hold, XLA gather form otherwise. Runs EAGERLY (same contract
    as bm25_dense_topk_auto — a Mosaic failure is catchable here).

    codes: i32[W, M] PQ code rows of the candidates (gathered upstream)
    luts:  f32[T, M, K] per-token ADC tables (ops.pq.adc_lut per token)
    Returns f32[W] MaxSim scores (max over tokens of the table-sum).

    ESTPU_MAXSIM_KERNEL: auto (default) | pallas | xla — the A/B knob
    for the re-rank stage, mirroring ESTPU_BM25_BATCH_KERNEL.
    """
    from elasticsearch_tpu.utils.shapes import round_up

    W, M = codes.shape
    T, _, K = luts.shape
    pref = os.environ.get("ESTPU_MAXSIM_KERNEL", "auto").lower()
    # sublane-align the token axis; Tp (not the raw token count) rides
    # the kernel's static key so a token-count sweep stays in-bucket
    Tp = round_up(T, 8)
    tile = maxsim_adc_tile(W if W % 512 == 0 else ((W + 511) // 512) * 512,
                           M, K, Tp)
    if pref == "pallas" and not tile:
        import warnings

        warnings.warn("ESTPU_MAXSIM_KERNEL=pallas but the kernel's shape "
                      f"gates reject this call (on_tpu={_on_tpu()}, W={W}, "
                      f"M={M}, K={K}, Tp={Tp}) — falling back to XLA")
    if pref != "xla" and tile:
        from elasticsearch_tpu.monitor import kernels

        try:
            Wp = ((W + tile - 1) // tile) * tile
            cp = codes
            if Wp != W:
                cp = jnp.concatenate(
                    [codes, jnp.zeros((Wp - W, M), codes.dtype)], axis=0)
            # [T, M, K] -> [M, K, Tp]: the kernel wants token columns.
            # Pad tokens with large-negative tables (finite: -inf would
            # NaN through the onehot matmul's 0*inf lanes) so pad
            # columns self-mask under the token max, and pass the
            # BUCKETED count as t_real — the static key then only sees
            # sublane multiples, never the raw per-query token count.
            lp = jnp.transpose(luts, (1, 2, 0))
            if Tp != T:
                lp = jnp.concatenate(
                    [lp, jnp.full((M, K, Tp - T), -1e30, lp.dtype)],
                    axis=2)
            out = maxsim_adc_pallas(cp, lp, t_real=Tp, tile=tile)
            _MAXSIM_TRANSIENT_FAILS[0] = 0
            kernels.record("maxsim_adc_pallas")
            return out[:W]
        except Exception as e:  # noqa: BLE001 — latch discipline
            _note_maxsim_failure(e)
    from elasticsearch_tpu.monitor import kernels

    kernels.record("maxsim_adc_xla")
    return _maxsim_adc_xla(codes, luts)
