"""Core TPU scoring programs.

These replace Lucene's Weight/Scorer doc-at-a-time iterator trees
(reference: Lucene BM25Similarity via org/elasticsearch/index/similarity/
BM25SimilarityProvider.java, and the per-segment search loop in
org/elasticsearch/search/query/QueryPhase.java) with whole-segment dense
programs:

- ``bm25_score_segment``: T query terms × P-wide postings slices →
  scatter-add into an f32[D] score vector. P and T are power-of-two
  buckets; terms with longer postings runs are pre-split into multiple
  (start, len) chunks by the executor, so one compiled program serves all
  queries in a shape class. Weights fold idf × boost; tf-normalization is
  precomputed per posting at index time (impact-style eager scoring).
- ``term_mask``: same slicing, but produces a bool[D] filter mask.
- ``topk_with_mask``: masked top-k (scores → (values, doc_ids)).
- range masks over numeric doc-value columns, including exact 64-bit
  comparison via (hi, lo) int32 pairs.

All functions are jitted with static shape arguments; callers bucket their
inputs (see utils.shapes.pow2_bucket).
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = jnp.float32(-jnp.inf)


# ---------------------------------------------------------------------------
# postings slicing
# ---------------------------------------------------------------------------

def _slice_postings(doc_ids, payload, start, length, P: int):
    """Slice a P-wide window of a term's postings run, handling the edge
    clamp: lax.dynamic_slice clamps start to nnz_pad - P, so compute the
    in-window shift and mask accordingly. Returns (docs[P], payload[P], valid[P]).
    """
    nnz_pad = doc_ids.shape[0]
    clamped = jnp.minimum(start, nnz_pad - P)
    shift = start - clamped
    docs = lax.dynamic_slice(doc_ids, (clamped,), (P,))
    pay = lax.dynamic_slice(payload, (clamped,), (P,))
    idx = jnp.arange(P, dtype=jnp.int32)
    valid = (idx >= shift) & (idx < shift + length)
    return docs, pay, valid


@partial(jax.jit, static_argnames=("P", "D"))
def bm25_score_segment(doc_ids, tfnorm, starts, lens, weights, *, P: int, D: int):
    """BM25 score vector for one segment.

    doc_ids: i32[nnz_pad] — postings doc ids (padded entries point at D
        sentinel and carry tfnorm 0, so they contribute nothing).
    tfnorm:  f32[nnz_pad] — precomputed tf*(k1+1)/(tf+k1*(1-b+b*dl/avg)).
    starts, lens: i32[T] — per-chunk postings runs (host-computed, bucketed).
    weights: f32[T] — idf * query boost per chunk.
    Returns f32[D] scores (0 for non-matching docs).
    """

    def per_chunk(start, length, w):
        docs, tfn, valid = _slice_postings(doc_ids, tfnorm, start, length, P)
        return docs, jnp.where(valid, tfn * w, 0.0)

    docs, contrib = jax.vmap(per_chunk)(starts, lens, weights)  # [T, P]
    scores = jnp.zeros(D, dtype=jnp.float32)
    scores = scores.at[docs.reshape(-1)].add(
        contrib.reshape(-1), mode="drop", indices_are_sorted=False
    )
    return scores


@partial(jax.jit, static_argnames=("P", "D"))
def bm25_score_batch(doc_ids, tfnorm, starts, lens, weights, *, P: int, D: int):
    """Batched queries: starts/lens/weights are [Q, T] → f32[Q, D]."""
    f = partial(bm25_score_segment, P=P, D=D)
    return jax.vmap(lambda s, l, w: f(doc_ids, tfnorm, s, l, w))(starts, lens, weights)


# ---------------------------------------------------------------------------
# hybrid dense/sparse scoring (frequent terms on the MXU, tail via scatter)
#
# Each hybrid op = one dense contribution (a matmul against the segment's
# impact[F, D] block, see index.segment.build_dense_impact) composed with the
# corresponding pure-scatter kernel for the short CSR tail. The scatter logic
# lives ONLY in the base kernels; hybrids never re-implement it.
# ---------------------------------------------------------------------------


def topk_block_config() -> int:
    """The blocked-top-k knob, read from ``ESTPU_BLOCKED_TOPK``: unset =
    platform default (8192 on TPU — the two-stage selection measured
    ~9 ms faster than one 1M-wide flat ``lax.top_k`` on a v5e, and
    ``exact_topk`` is tie-exact so there is no accuracy trade; 0 = flat
    elsewhere, where XLA:CPU's top_k is already fine); 0/false = flat;
    1/true = two-stage with the default 8192 block; an integer = that
    block size. MUST be read OUTSIDE jit (at call or program-build time)
    and plumbed through as a static argument, so the choice participates
    in jit/program cache keys — an env read inside traced code would be
    silently frozen by the first trace."""
    v = os.environ.get("ESTPU_BLOCKED_TOPK", "").lower()
    if not v:
        try:
            import jax

            on_tpu = jax.default_backend() == "tpu"
        except Exception:  # backend probe must never break scoring
            on_tpu = False
        return 8192 if on_tpu else 0
    if v in ("0", "false", "off"):
        return 0
    if v in ("1", "true", "on"):
        return 8192
    try:
        return int(v)
    except ValueError:
        # a typo'd knob must not crash every search request deep in the
        # scoring path — warn once and run the flat top_k
        global _TOPK_WARNED
        if not _TOPK_WARNED:
            import warnings

            warnings.warn(f"ESTPU_BLOCKED_TOPK={v!r} is not an integer; "
                          f"blocked top-k disabled")
            _TOPK_WARNED = True
        return 0


_TOPK_WARNED = False


def exact_topk(x, k: int, block: int = 8192):
    """Exact top-k over the last axis, two-stage: per-block top-k, then
    top-k over the concatenated block winners. Identical results to
    ``lax.top_k`` INCLUDING tie order (ties resolve to the lowest index:
    within a block top_k orders ties by index, and across blocks the
    winner list is block-ordered so the global pass picks the earlier
    block first). Falls back to the flat top_k when blocking can't help
    (small D, huge k, non-divisible shapes). Shapes a large-D top-k into
    row-sized sorts, which some backends execute far better than one
    D-wide selection."""
    D = x.shape[-1]
    if k >= block or D < 2 * block or D % block:
        return lax.top_k(x, k)
    nb = D // block
    xb = x.reshape(x.shape[:-1] + (nb, block))
    bv, bi = lax.top_k(xb, k)  # [..., nb, k]
    bi = bi + (jnp.arange(nb, dtype=bi.dtype) * block)[:, None]
    flatv = bv.reshape(x.shape[:-1] + (nb * k,))
    flati = bi.reshape(x.shape[:-1] + (nb * k,))
    gv, gp = lax.top_k(flatv, k)
    gi = jnp.take_along_axis(flati, gp, axis=-1)
    return gv, gi


def topk_auto(x, k: int, block: int = 0):
    """Product top-k dispatch: blocked two-stage when ``block`` > 0, else
    flat ``lax.top_k``. Pass ``topk_block_config()`` read OUTSIDE jit."""
    return exact_topk(x, k, block) if block else lax.top_k(x, k)


_PRECS = {"highest": lax.Precision.HIGHEST, "high": lax.Precision.HIGH,
          "default": lax.Precision.DEFAULT}
_PREC_WARNED = False


def impact_precision() -> str:
    """f32 impact-matmul precision knob (``ESTPU_IMPACT_PRECISION``):
    "highest" (default — exactness tests rely on it; on TPU it is the
    multi-pass f32 emulation), "high" (3-pass), or "default" (native
    bf16 MXU pass — fastest, ranking-grade). Read OUTSIDE jit and plumbed
    as a static arg / program-cache key, exactly like topk_block_config —
    an env read inside traced code would be frozen by the first trace."""
    v = os.environ.get("ESTPU_IMPACT_PRECISION", "highest").lower()
    if v in _PRECS:
        return v
    global _PREC_WARNED
    if not _PREC_WARNED:
        import warnings

        warnings.warn(f"ESTPU_IMPACT_PRECISION={v!r} is not one of "
                      f"{sorted(_PRECS)}; using 'highest'")
        _PREC_WARNED = True
    return "highest"


def _dense_dot(qw, dense_impact, prec: str = "highest"):
    """qw @ impact with dtype-aware MXU mapping: an f32 block multiplies at
    the configured precision (HIGHEST by default — exactness tests rely on
    it); a bf16 block (segment's ESTPU_IMPACT_BF16 storage) takes the
    native bf16 MXU path with f32 accumulation — no upcast copy of the
    block in HBM."""
    if dense_impact.dtype == jnp.bfloat16:
        return jnp.dot(qw.astype(jnp.bfloat16), dense_impact,
                       preferred_element_type=jnp.float32)
    return jnp.dot(qw, dense_impact,
                   precision=_PRECS.get(prec, lax.Precision.HIGHEST))


@partial(jax.jit, static_argnames=("P", "D", "prec"))
def bm25_score_hybrid(
    dense_impact, qw, doc_ids, tfnorm, starts, lens, weights, *, P: int,
    D: int, prec: str = "highest"
):
    """Single-query hybrid BM25: qw f32[F] (idf*boost per dense term) scores
    frequent terms via one matvec; starts/lens/weights i32/f32[T] are the
    short-run tail. Returns f32[D]."""
    dense = _dense_dot(qw, dense_impact, prec)
    return dense + bm25_score_segment(doc_ids, tfnorm, starts, lens, weights, P=P, D=D)


@partial(jax.jit, static_argnames=("P", "D"))
def bm25_score_hybrid_gather(dense_impact, qrows, qrw, doc_ids, tfnorm,
                             starts, lens, weights, *, P: int, D: int):
    """Single-query hybrid BM25 reading ONLY the query's dense rows.

    ``qrows`` i32[R] are the query's dense-row indices (-1 padding),
    ``qrw`` f32[R] the matching idf*boost weights (0 padding). The matmul
    form (`bm25_score_hybrid`) reads the WHOLE impact[F, D] block per
    query — ~1 GB at the 1M-doc bench shape — where this gathers R << F
    contiguous rows (~16 MB), a ~F/R traffic cut that measures ~14x
    end-to-end on the product's single-query path. Accumulation is f32
    over the gathered rows (at R <= F terms, at least as precise as the
    matvec's bf16-pass emulation), so scores agree with the matmul form
    to fp rounding. Row 0 stands in for padding via clamp; its weight is
    0 so it contributes nothing."""
    rows = dense_impact[jnp.maximum(qrows, 0)]  # [R, D]
    dense = jnp.einsum("r,rd->d", qrw, rows.astype(jnp.float32),
                       precision=lax.Precision.HIGHEST)
    return dense + bm25_score_segment(doc_ids, tfnorm, starts, lens,
                                      weights, P=P, D=D)


DENSE_ROW_PAD = 8  # kernel sublane multiple; pack_dense_rows pads R to it


def pack_dense_rows(row_w: dict):
    """(qrows i32[R], qrw f32[R]) from {dense_row: weight}: sorted rows,
    -1/0 padding, R = pow2(len) >= DENSE_ROW_PAD. ONE definition for the
    host path (context.hybrid_slices) and the mesh prim
    (compiler.HybridTGroupPrim) — the padding sentinel and alignment
    multiple must never diverge between them."""
    from elasticsearch_tpu.utils.shapes import pow2_bucket

    R = pow2_bucket(max(len(row_w), 1), minimum=DENSE_ROW_PAD)
    qrows = np.full(R, -1, np.int32)
    qrw = np.zeros(R, np.float32)
    for i, (row, w) in enumerate(sorted(row_w.items())):
        qrows[i] = row
        qrw[i] = w
    return qrows, qrw


@jax.jit
def gather_impact_rows(dense_impact, qrows):
    """(impact[qrows] [R, D], valid f32[R]) for feeding batched kernels a
    compact per-query block: padding rows (-1) clamp to row 0 and carry
    validity 0 so presence counts ignore them."""
    sub = dense_impact[jnp.maximum(qrows, 0)]
    return sub, (qrows >= 0).astype(jnp.float32)


@partial(jax.jit, static_argnames=("P", "D"))
def match_count_hybrid_gather(dense_impact, qrows, doc_ids, starts, lens,
                              *, P: int, D: int):
    """Matched-term count via gathered dense rows (row-gather analogue of
    match_count_hybrid; padding rows are masked by qrows >= 0)."""
    valid = (qrows >= 0)[:, None]
    present = (dense_impact[jnp.maximum(qrows, 0)] != 0) & valid  # [R, D]
    dcount = jnp.sum(present.astype(jnp.int32), axis=0)
    tail = match_count_segment(doc_ids, starts, lens, P=P, D=D)
    return dcount + tail


@partial(jax.jit, static_argnames=("P", "D"))
def term_mask_hybrid_gather(dense_impact, qrows, doc_ids, starts, lens,
                            *, P: int, D: int):
    """Any-term match mask via gathered dense rows (row-gather analogue
    of term_mask_hybrid)."""
    valid = (qrows >= 0)[:, None]
    dmask = jnp.any((dense_impact[jnp.maximum(qrows, 0)] != 0) & valid,
                    axis=0)
    return dmask | term_mask(doc_ids, starts, lens, P=P, D=D)


@partial(jax.jit, static_argnames=("P", "D", "prec"))
def bm25_score_hybrid_batch(
    dense_impact, qw, doc_ids, tfnorm, starts, lens, weights, *, P: int,
    D: int, prec: str = "highest"
):
    """Batched hybrid BM25: ONE MXU matmul ``qw[Q, F] @ impact[F, D]`` for
    frequent terms (replacing what would be millions of scatter-adds for long
    postings runs) + the scatter kernel on the [Q, T] tail. Returns f32[Q, D]."""
    dense = _dense_dot(qw, dense_impact, prec)
    return dense + bm25_score_batch(doc_ids, tfnorm, starts, lens, weights, P=P, D=D)


@partial(jax.jit, static_argnames=("P", "D", "k", "topk_block", "prec"))
def bm25_hybrid_topk_batch(dense_impact, qw, doc_ids, tfnorm, starts, lens,
                           weights, live, *, P: int, D: int, k: int,
                           topk_block: int = 0, prec: str = "highest"):
    """Batched hybrid top-k: scores via bm25_score_hybrid_batch, then the
    per-query masked top-k and exact totals in the SAME program, so the
    [Q, D] score block never leaves the device. For all-positive
    disjunctive term groups, score > 0 is exactly 'matched'. Returns
    (vals f32[Q, k], idx i32[Q, k], totals i32[Q])."""
    scores = bm25_score_hybrid_batch(dense_impact, qw, doc_ids, tfnorm,
                                     starts, lens, weights, P=P, D=D,
                                     prec=prec)
    m = (scores > 0) & live[None, :]
    masked = jnp.where(m, scores, NEG_INF)
    vals, idx = topk_auto(masked, k, topk_block)
    return vals, idx.astype(jnp.int32), jnp.sum(m.astype(jnp.int32), axis=1)


@partial(jax.jit, static_argnames=("P", "D"))
def match_count_hybrid(dense_impact, qind, doc_ids, starts, lens, *, P: int, D: int):
    """Matched-term count: qind f32[F] is the 1.0 indicator of dense query
    terms; dense count = qind @ (impact != 0). Only conjunctive queries
    (operator:and / minimum_should_match) pay for this second pass over the
    impact block — disjunctions derive their mask from scores directly."""
    present = (dense_impact != 0).astype(jnp.float32)
    dcount = jnp.dot(qind, present, precision=lax.Precision.HIGHEST)
    tail = match_count_segment(doc_ids, starts, lens, P=P, D=D)
    return jnp.rint(dcount).astype(jnp.int32) + tail


@partial(jax.jit, static_argnames=("P", "D"))
def term_mask_hybrid(dense_impact, qind, doc_ids, starts, lens, *, P: int, D: int):
    """bool[D] any-of mask across dense rows (qind indicator) + CSR tail."""
    present = (dense_impact != 0).astype(jnp.float32)
    dmask = jnp.dot(qind, present, precision=lax.Precision.DEFAULT) > 0
    return dmask | term_mask(doc_ids, starts, lens, P=P, D=D)


@jax.jit
def dense_presence_count(impact, qind, live):
    """Exact hit count for a pure-dense term group: docs where ANY dense
    query row (qind f32[1, F] indicator) has a non-zero impact, ANDed with
    the live mask. One [1, F] @ [F, D] matvec — the fused top-k fast path
    uses this for `hits.total` without materializing per-doc scores twice."""
    present = (impact != 0).astype(jnp.float32)
    m = (jnp.dot(qind, present, precision=lax.Precision.DEFAULT) > 0)[0] & live
    return jnp.sum(m.astype(jnp.int32))


@partial(jax.jit, static_argnames=("chunk",))
def dense_presence_count_batch(impact, qind, live, *, chunk: int):
    """Batched exact hit counts: i32[Q] docs where any dense query row has
    non-zero impact, ANDed with live. Sweeps D in `chunk`-wide slices so the
    [Q, D] presence matrix never materializes (Q=2048, D=1M would be 8 GB).
    Caller picks chunk with D % chunk == 0."""
    D = impact.shape[1]
    Q = qind.shape[0]

    def body(i, acc):
        sl = lax.dynamic_slice_in_dim(impact, i * chunk, chunk, axis=1)
        lv = lax.dynamic_slice_in_dim(live, i * chunk, chunk)
        pres = (jnp.dot(qind, (sl != 0).astype(jnp.float32),
                        precision=lax.Precision.DEFAULT) > 0) & lv[None, :]
        return acc + jnp.sum(pres.astype(jnp.int32), axis=1)

    return lax.fori_loop(0, D // chunk, body, jnp.zeros(Q, jnp.int32))


@partial(jax.jit, static_argnames=("P", "D"))
def match_count_segment(doc_ids, starts, lens, *, P: int, D: int):
    """Count of matching query *terms* per doc. Each doc id occurs at most
    once in a term's postings run, so even when a term is split into several
    (start, len) chunks a matching doc is counted exactly once for that term
    — the result equals the number of distinct matched terms. Executors
    compare against the number of distinct query terms (operator:and /
    minimum_should_match), NOT against T (the chunk count). Returns i32[D]."""
    ones = jnp.ones_like(starts, dtype=jnp.float32)

    def per_chunk(start, length, w):
        docs, _, valid = _slice_postings(doc_ids, doc_ids.astype(jnp.float32), start, length, P)
        return docs, jnp.where(valid, w, 0.0)

    docs, contrib = jax.vmap(per_chunk)(starts, lens, ones)
    counts = jnp.zeros(D, dtype=jnp.float32)
    counts = counts.at[docs.reshape(-1)].add(contrib.reshape(-1), mode="drop")
    return counts.astype(jnp.int32)


@partial(jax.jit, static_argnames=("P", "D"))
def term_mask(doc_ids, starts, lens, *, P: int, D: int):
    """bool[D] mask of docs containing ANY of the T postings chunks
    (a terms filter; a single term is T=1)."""

    def per_chunk(start, length):
        docs, _, valid = _slice_postings(doc_ids, doc_ids.astype(jnp.float32), start, length, P)
        return docs, valid

    docs, valid = jax.vmap(per_chunk)(starts, lens)
    mask = jnp.zeros(D, dtype=bool)
    mask = mask.at[docs.reshape(-1)].max(valid.reshape(-1), mode="drop")
    return mask


# ---------------------------------------------------------------------------
# scatter-free hybrid top-k (candidate-set tail)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("P", "D", "k", "topk_block"))
def bm25_hybrid_candidates_topk(dense_impact, qrows, qrw, doc_ids, tfnorm,
                                starts, lens, weights, live, *, P: int,
                                D: int, k: int, topk_block: int = 0):
    """Exact hybrid BM25 top-k with NO scatter anywhere.

    The [D]-vector tail construction (`bm25_score_segment`) is a
    scatter-add — on TPU, XLA lowers non-trivial scatters to a
    sequential read-modify-write loop (~2 µs/slot), so a T×P padded
    window costs tens of ms per query regardless of how few postings are
    real. This computes the same top-k Lucene-style instead: only the
    docs the tail actually TOUCHES are scored.

      1. dense[D] = qrw @ impact[qrows]   (row gather, no scatter)
      2. tail windows → (doc, contrib) pairs [W = T·P], sort by doc
         (vectorized bitonic), segment-sum equal-doc runs via cumsum
      3. tail candidates = run ends; their TOTAL score adds dense[doc]
         via a W-element gather
      4. merge with the dense-only blocked top-k; a doc in both sets
         keeps the tail entry (its total includes the dense part, the
         dense-only entry doesn't) — dedup by id-match mask
      5. exact totals = |dense>0 ∧ live| + |tail runs with dense==0 ∧
         live ∧ contrib>0|

    Tie order matches the scatter path's `lax.top_k` over the dense
    row: the final merge sorts by (-score, doc id). Returns
    (vals f32[k], idx i32[k], total i32).
    """
    # 1. dense scores (gather form), masked
    rows = dense_impact[jnp.maximum(qrows, 0)]
    dense = jnp.einsum("r,rd->d", qrw, rows.astype(jnp.float32),
                       precision=lax.Precision.HIGHEST)
    dense_m = jnp.where(live, dense, 0.0)

    # 2. tail windows → flat (doc, contrib); padding → doc D, contrib 0
    def per_chunk(start, length, w):
        docs, tfn, valid = _slice_postings(doc_ids, tfnorm, start, length, P)
        return jnp.where(valid, docs, D), jnp.where(valid, tfn * w, 0.0)

    T = starts.shape[0]
    dws, contrib = jax.vmap(per_chunk)(starts, lens, weights)
    dws = dws.reshape(-1)
    contrib = contrib.reshape(-1)
    # sort by doc id; padding (doc D) sorts to the tail
    dws, contrib = lax.sort((dws, contrib), num_keys=1)
    # segment-sum runs of equal doc, EXACTLY: a doc appears at most once
    # per tail term (chunk-split slices are disjoint), so run length <= T
    # (static) and T-1 shifted adds sum each run in-order in f32 — no
    # cumsum-difference cancellation across the 32k window
    totals_at = contrib
    for j in range(1, T):
        same = jnp.concatenate([jnp.zeros((j,), bool),
                                dws[j:] == dws[:-j]])
        totals_at = totals_at + jnp.where(
            same, jnp.concatenate([jnp.zeros((j,), contrib.dtype),
                                   contrib[:-j]]), 0.0)
    is_end = jnp.concatenate([dws[1:] != dws[:-1], jnp.ones((1,), bool)])
    valid_end = is_end & (dws < D)
    tail_total = jnp.where(valid_end, totals_at, 0.0)

    # 3. add the dense part + live mask at the touched docs
    docs_c = jnp.minimum(dws, D - 1)
    dense_at = dense_m[docs_c]
    live_at = live[docs_c]
    cand_score = jnp.where(valid_end & live_at, tail_total + dense_at,
                           NEG_INF)

    # 4. dense-only top-k (docs the tail may not touch)
    dmasked = jnp.where(live & (dense > 0), dense, NEG_INF)
    dv, di = topk_auto(dmasked, k, topk_block)
    # drop dense-only entries whose doc also appears as a tail candidate
    # (the tail entry holds the doc's FULL score)
    dup = jnp.any((di[:, None] == docs_c[None, :])
                  & valid_end[None, :], axis=1)
    dv = jnp.where(dup, NEG_INF, dv)
    all_v = jnp.concatenate([dv, cand_score])
    all_i = jnp.concatenate([di, docs_c])
    # positives only (score > 0 is the match contract); exact tie order:
    # sort candidates by id ascending, then a stable value top_k
    all_v = jnp.where(all_v > 0, all_v, NEG_INF)
    order = jnp.argsort(all_i)
    sv = all_v[order]
    si = all_i[order]
    vals, pos = lax.top_k(sv, k)
    idx = si[pos]

    # 5. exact totals
    n_dense = jnp.sum((dense_m > 0).astype(jnp.int32))
    tail_only = valid_end & live_at & (tail_total > 0) & (dense_at <= 0)
    total = n_dense + jnp.sum(tail_only.astype(jnp.int32))
    return vals, idx.astype(jnp.int32), total


# -- scatter-free [D]-vector tail (lookup form) ------------------------------
#
# For COMPOSED queries (bool/filter trees) the emit contract is a dense
# f32[D]/bool[D] — the candidate-set trick can't apply. This builds the
# same vectors without scatter: sort the (doc, contrib) window pairs,
# binary-search the D+1 bin boundaries (vectorized; the window table is
# VMEM-small), and sum each doc's <= T entries with T bounded gathers —
# exact, in-order f32. Counts and masks fall out of the boundary diffs
# directly (window docs are unique per term, so entries-per-doc IS the
# distinct matched-term count).

def _sorted_window_pairs(doc_ids, tfnorm, starts, lens, weights, P, D):
    def per_chunk(start, length, w):
        docs, tfn, valid = _slice_postings(doc_ids, tfnorm, start, length, P)
        return jnp.where(valid, docs, D), jnp.where(valid, tfn * w, 0.0)

    dws, contrib = jax.vmap(per_chunk)(starts, lens, weights)
    return lax.sort((dws.reshape(-1), contrib.reshape(-1)), num_keys=1)


def _tail_bounds(dws, D):
    bounds = jnp.searchsorted(dws, jnp.arange(D + 1, dtype=dws.dtype))
    return bounds[:-1], bounds[1:] - bounds[:-1]  # (lo [D], n [D])


@partial(jax.jit, static_argnames=("P", "D"))
def bm25_score_segment_lookup(doc_ids, tfnorm, starts, lens, weights, *,
                              P: int, D: int):
    """Scatter-free equivalent of bm25_score_segment (same f32[D])."""
    T = starts.shape[0]
    dws, contrib = _sorted_window_pairs(doc_ids, tfnorm, starts, lens,
                                        weights, P, D)
    lo, n = _tail_bounds(dws, D)
    W = dws.shape[0]
    score = jnp.zeros(D, jnp.float32)
    for t in range(T):  # run length <= T terms: exact in-order sums
        score = score + jnp.where(
            t < n, contrib[jnp.clip(lo + t, 0, W - 1)], 0.0)
    return score


def _sorted_window_docs(doc_ids, starts, lens, P, D):
    """Keys-only variant: the sorted window doc ids (no payload sort)."""
    def per_chunk(start, length):
        docs, _pay, valid = _slice_postings(doc_ids, doc_ids, start,
                                            length, P)
        return jnp.where(valid, docs, D)

    dws = jax.vmap(per_chunk)(starts, lens)
    return jnp.sort(dws.reshape(-1))


@partial(jax.jit, static_argnames=("P", "D"))
def match_count_segment_lookup(doc_ids, starts, lens, *, P: int, D: int):
    """Scatter-free distinct matched-term counts (i32[D]): window docs
    are unique per term, so entries-per-doc IS the distinct count."""
    dws = _sorted_window_docs(doc_ids, starts, lens, P, D)
    _, n = _tail_bounds(dws, D)
    return n.astype(jnp.int32)


@partial(jax.jit, static_argnames=("P", "D"))
def term_mask_lookup(doc_ids, starts, lens, *, P: int, D: int):
    """Scatter-free any-term mask (bool[D])."""
    return match_count_segment_lookup(doc_ids, starts, lens, P=P, D=D) > 0


@partial(jax.jit, static_argnames=("P", "D"))
def bm25_score_hybrid_lookup(dense_impact, qrows, qrw, doc_ids, tfnorm,
                             starts, lens, weights, *, P: int, D: int):
    """Row-gather dense + lookup tail (scatter-free hybrid scores)."""
    rows = dense_impact[jnp.maximum(qrows, 0)]
    dense = jnp.einsum("r,rd->d", qrw, rows.astype(jnp.float32),
                       precision=lax.Precision.HIGHEST)
    return dense + bm25_score_segment_lookup(doc_ids, tfnorm, starts,
                                             lens, weights, P=P, D=D)


@partial(jax.jit, static_argnames=("P", "D"))
def match_count_hybrid_lookup(dense_impact, qrows, doc_ids, starts, lens,
                              *, P: int, D: int):
    """Gathered dense presence + lookup tail counts (scatter-free)."""
    valid = (qrows >= 0)[:, None]
    present = (dense_impact[jnp.maximum(qrows, 0)] != 0) & valid
    return (jnp.sum(present.astype(jnp.int32), axis=0)
            + match_count_segment_lookup(doc_ids, starts, lens, P=P, D=D))


@partial(jax.jit, static_argnames=("P", "D"))
def term_mask_hybrid_lookup(dense_impact, qrows, doc_ids, starts, lens,
                            *, P: int, D: int):
    """Gathered dense presence | lookup tail mask (scatter-free)."""
    valid = (qrows >= 0)[:, None]
    dmask = jnp.any((dense_impact[jnp.maximum(qrows, 0)] != 0) & valid,
                    axis=0)
    return dmask | term_mask_lookup(doc_ids, starts, lens, P=P, D=D)


@partial(jax.jit, static_argnames=("P", "D", "k", "topk_block", "prec"))
def bm25_hybrid_candidates_topk_batch(dense_impact, qw, doc_ids, tfnorm,
                                      starts, lens, weights, live, *,
                                      P: int, D: int, k: int,
                                      topk_block: int = 0,
                                      prec: str = "highest"):
    """Batched hybrid top-k with a scatter-free tail (batch analogue of
    bm25_hybrid_candidates_topk; same contract as bm25_hybrid_topk_batch).

    Dense terms keep the ONE amortized matmul ``qw[Q, F] @ impact[F, D]``
    (a batch reads the block once — the row-gather trick is a
    single-query lever); the tail replaces the vmapped scatter-add —
    which XLA serializes per element on TPU, Q·T·P slots per batch —
    with per-row sort + bounded-window segment-sum + gathers, all
    vectorized. Returns (vals [Q, k], idx [Q, k], totals [Q]).
    """
    Q, T = starts.shape
    dense = _dense_dot(qw, dense_impact, prec)  # [Q, D]
    dense_m = jnp.where(live[None, :], dense, 0.0)

    def window(starts_q, lens_q, ws_q):
        def per_chunk(start, length, w):
            docs, tfn, valid = _slice_postings(doc_ids, tfnorm, start,
                                               length, P)
            return jnp.where(valid, docs, D), jnp.where(valid, tfn * w, 0.0)

        dws, contrib = jax.vmap(per_chunk)(starts_q, lens_q, ws_q)
        return dws.reshape(-1), contrib.reshape(-1)

    dws, contrib = jax.vmap(window)(starts, lens, weights)  # [Q, W]
    dws, contrib = lax.sort((dws, contrib), dimension=1, num_keys=1)
    totals_at = contrib
    for j in range(1, T):  # run length <= T: exact in-order f32 sums
        same = jnp.concatenate(
            [jnp.zeros((Q, j), bool), dws[:, j:] == dws[:, :-j]], axis=1)
        totals_at = totals_at + jnp.where(
            same, jnp.concatenate([jnp.zeros((Q, j), contrib.dtype),
                                   contrib[:, :-j]], axis=1), 0.0)
    is_end = jnp.concatenate([dws[:, 1:] != dws[:, :-1],
                              jnp.ones((Q, 1), bool)], axis=1)
    valid_end = is_end & (dws < D)
    tail_total = jnp.where(valid_end, totals_at, 0.0)
    docs_c = jnp.minimum(dws, D - 1)
    dense_at = jnp.take_along_axis(dense_m, docs_c, axis=1)  # [Q, W]
    live_at = live[docs_c]
    cand_score = jnp.where(valid_end & live_at, tail_total + dense_at,
                           NEG_INF)

    dmasked = jnp.where(live[None, :] & (dense > 0), dense, NEG_INF)
    dv, di = topk_auto(dmasked, k, topk_block)  # [Q, k]
    dup = jnp.any((di[:, :, None] == docs_c[:, None, :])
                  & valid_end[:, None, :], axis=2)
    dv = jnp.where(dup, NEG_INF, dv)
    all_v = jnp.concatenate([dv, cand_score], axis=1)
    all_i = jnp.concatenate([di, docs_c], axis=1)
    all_v = jnp.where(all_v > 0, all_v, NEG_INF)
    order = jnp.argsort(all_i, axis=1)
    sv = jnp.take_along_axis(all_v, order, axis=1)
    si = jnp.take_along_axis(all_i, order, axis=1)
    vals, pos = lax.top_k(sv, k)
    idx = jnp.take_along_axis(si, pos, axis=1)

    n_dense = jnp.sum((dense_m > 0).astype(jnp.int32), axis=1)
    tail_only = valid_end & live_at & (tail_total > 0) & (dense_at <= 0)
    totals = n_dense + jnp.sum(tail_only.astype(jnp.int32), axis=1)
    return vals, idx.astype(jnp.int32), totals


def tail_mode_batch() -> bool:
    """True when batch paths should use the scatter-free candidate tail
    (same ESTPU_TAIL_MODE knob/platform default as the DSL fast path).
    Read eagerly by callers and passed through static dispatch."""
    mode = os.environ.get("ESTPU_TAIL_MODE", "auto").lower()
    if mode in ("candidates", "scatter"):
        return mode == "candidates"
    try:
        import jax as _jax

        return _jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# doc-value masks
# ---------------------------------------------------------------------------

@jax.jit
def range_mask_f32(values, exists, lo, hi, include_lo, include_hi):
    """Range filter over an f32 column. lo/hi are f32 scalars (±inf for open)."""
    ge = jnp.where(include_lo, values >= lo, values > lo)
    le = jnp.where(include_hi, values <= hi, values < hi)
    return ge & le & exists


@jax.jit
def range_mask_i64pair(hi_col, lo_col, exists, lo_hi, lo_lo, hi_hi, hi_lo, include_lo, include_hi):
    """Exact 64-bit range over (hi, lo) int32 pair columns (lexicographic)."""
    def ge_pair(ah, al, bh, bl):
        return (ah > bh) | ((ah == bh) & (al >= bl))

    def gt_pair(ah, al, bh, bl):
        return (ah > bh) | ((ah == bh) & (al > bl))

    ge = jnp.where(include_lo, ge_pair(hi_col, lo_col, lo_hi, lo_lo), gt_pair(hi_col, lo_col, lo_hi, lo_lo))
    le = jnp.where(include_hi, ge_pair(hi_hi, hi_lo, hi_col, lo_col), gt_pair(hi_hi, hi_lo, hi_col, lo_col))
    return ge & le & exists


# ---------------------------------------------------------------------------
# top-k
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k", "topk_block"))
def _topk_with_mask_jit(scores, mask, *, k: int, topk_block: int):
    masked = jnp.where(mask, scores, NEG_INF)
    vals, idx = topk_auto(masked, k, topk_block)
    return vals, idx.astype(jnp.int32)


def topk_with_mask(scores, mask, *, k: int):
    """(values f32[k], indices i32[k]) of the top-k masked scores.
    Masked-out docs get -inf; callers treat -inf as 'no hit'. Eager
    wrapper: the blocked-top-k knob is read here, OUTSIDE jit, and enters
    the cache key as a static arg — callers need no plumbing."""
    return _topk_with_mask_jit(scores, mask, k=k,
                               topk_block=topk_block_config())


def topk_batch(scores, mask, *, k: int):
    """Batched: scores [Q, D], mask [D] or [Q, D] → ([Q,k], [Q,k])."""
    return _topk_with_mask_jit(scores, mask, k=k,
                               topk_block=topk_block_config())


@jax.jit
def count_mask(mask):
    return jnp.sum(mask.astype(jnp.int32))


@jax.jit
def pack_topk_result(vals, idx, total):
    """Pack (vals f32[k], idx i32[k], total i32) into ONE i32[2k+1] array.

    Device→host pulls pay a fixed per-ARRAY latency (network-attached
    chips: ~5-20 ms each); fetching three tiny arrays costs three round
    trips. Bitcasting the f32 scores into the i32 payload makes the whole
    query result one transfer; hosts un-bitcast with np.view (exact)."""
    return jnp.concatenate([
        lax.bitcast_convert_type(vals, jnp.int32),
        idx.astype(jnp.int32),
        jnp.asarray(total, jnp.int32)[None],
    ])


def unpack_topk_result(packed_np, k: int):
    """np i32[2k+1] → (vals f32[k], idx i32[k], total int)."""
    import numpy as np

    vals = packed_np[:k].view(np.float32)
    idx = packed_np[k: 2 * k]
    return vals, idx, int(packed_np[-1])


# ---------------------------------------------------------------------------
# per-field segment reductions (aggregation building blocks)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("num_buckets",))
def bucket_sum(values, bucket_ids, weights, *, num_buckets: int):
    """segment-sum of values*weights into num_buckets (ordinal reductions)."""
    contrib = values * weights
    out = jnp.zeros(num_buckets, dtype=jnp.float32)
    return out.at[bucket_ids].add(contrib, mode="drop")


@partial(jax.jit, static_argnames=("num_buckets", "scatter_free"))
def _bucket_count_jit(bucket_ids, mask, *, num_buckets: int,
                      scatter_free: bool):
    # `mask` is a 0/1 SELECTION mask, never fractional weights: the
    # scatter-free branch is a selected-id histogram (sort + boundary
    # diffs — the len(ids)-element scatter serializes on TPU) and would
    # silently diverge from the scatter-add branch for any other value.
    # Weighted reductions belong in bucket_sum.
    if scatter_free:
        ids = jnp.where(mask > 0, bucket_ids, num_buckets)
        sids = jnp.sort(ids)
        bounds = jnp.searchsorted(
            sids, jnp.arange(num_buckets + 1, dtype=sids.dtype))
        return (bounds[1:] - bounds[:-1]).astype(jnp.float32)
    out = jnp.zeros(num_buckets, dtype=jnp.float32)
    return out.at[bucket_ids].add(mask, mode="drop")


def bucket_count(bucket_ids, mask, *, num_buckets: int):
    """Selected-id histogram. ``mask`` MUST be a 0/1 selection mask —
    the parameter is named to make a fractional-weights call read wrong
    at the call site (ADVICE r5: the TPU scatter-free branch computes a
    histogram, not a weighted sum, so non-mask values diverge from the
    CPU branch with no error). Eager wrapper: reads the platform
    scatter-free switch outside jit."""
    return _bucket_count_jit(bucket_ids, mask, num_buckets=num_buckets,
                             scatter_free=tail_mode_batch())
