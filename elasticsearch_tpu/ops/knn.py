"""Dense-vector kNN ops — brute-force similarity on the MXU.

The reference ES 2.0 predates dense_vector; this implements the north-star
kNN path (BASELINE.json: SIFT1M exact-kNN at recall parity, ≥8× p50 vs CPU).
Design: the corpus slab is a [D, dims] f32 array in HBM; queries arrive as
[Q, dims]. Similarity = one bf16 matmul (cosine/dot) or a fused
norm-expansion (l2), producing [Q, D] scores tiled by XLA onto the MXU,
followed by masked top-k. For very large D the executor scans HBM chunks
with lax.map to bound the [Q, D] intermediate.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from elasticsearch_tpu.ops.scoring import topk_auto

NEG_INF = jnp.float32(-jnp.inf)


@partial(jax.jit, static_argnames=("metric", "use_bf16"))
def knn_scores(queries, vecs, *, metric: str = "cosine", use_bf16: bool = True):
    """Similarity scores [Q, D] between queries [Q, dims] and corpus [D, dims].

    Scoring matches ES dense_vector `similarity` semantics:
      cosine:      (1 + cos) / 2           (ES _score for cosine)
      dot_product: (1 + dot) / 2           (vectors assumed unit-norm)
      l2_norm:     1 / (1 + l2^2)
    """
    if use_bf16:
        q = queries.astype(jnp.bfloat16)
        v = vecs.astype(jnp.bfloat16)
        prec = None
    else:
        q = queries
        v = vecs
        prec = lax.Precision.HIGHEST
    if metric == "cosine":
        qn = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-12).astype(q.dtype)
        vn = v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-12).astype(v.dtype)
        sim = jnp.matmul(qn, vn.T, preferred_element_type=jnp.float32, precision=prec)
        return (1.0 + sim) * 0.5
    if metric in ("dot_product", "dot"):
        sim = jnp.matmul(q, v.T, preferred_element_type=jnp.float32, precision=prec)
        return (1.0 + sim) * 0.5
    if metric in ("l2_norm", "l2"):
        # ||q - v||^2 = ||q||^2 - 2 q.v + ||v||^2 — matmul-dominant expansion
        dots = jnp.matmul(q, v.T, preferred_element_type=jnp.float32, precision=prec)
        q2 = jnp.sum(queries.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
        v2 = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=-1)[None, :]
        d2 = jnp.maximum(q2 - 2.0 * dots + v2, 0.0)
        return 1.0 / (1.0 + d2)
    raise ValueError(f"unknown knn metric [{metric}]")


@partial(jax.jit, static_argnames=("k", "metric", "use_bf16", "topk_block"))
def knn_topk(queries, vecs, mask, *, k: int, metric: str = "cosine",
             use_bf16: bool = True, topk_block: int = 0):
    """Fused scores + masked top-k: ([Q, k] scores, [Q, k] doc ids)."""
    scores = knn_scores(queries, vecs, metric=metric, use_bf16=use_bf16)
    masked = jnp.where(mask[None, :], scores, NEG_INF)
    vals, idx = topk_auto(masked, k, topk_block)
    return vals, idx.astype(jnp.int32)


@partial(jax.jit, static_argnames=("metric",))
def exact_rescore_topk(queries, vecs, vals, idx, *, metric: str = "cosine"):
    """f32 re-rank of a bf16 candidate sweep — the FAISS-style two-stage
    refinement. The bf16 MXU pass selects candidates fast but its ~3-digit
    mantissa shuffles near-ties (clustered corpora: recall collapse);
    gathering the [Q, k] winners and rescoring with Precision.HIGHEST
    restores exact-kNN recall at the cost of one tiny gather+einsum.
    Invalid candidates (vals == -inf) stay -inf and keep sorting last."""
    cand = vecs[idx].astype(jnp.float32)  # [Q, k, dims]
    q = queries.astype(jnp.float32)
    hi = lax.Precision.HIGHEST
    if metric == "cosine":
        qn = q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
        cn = cand / jnp.maximum(
            jnp.linalg.norm(cand, axis=-1, keepdims=True), 1e-12)
        s = (1.0 + jnp.einsum("qd,qkd->qk", qn, cn, precision=hi)) * 0.5
    elif metric in ("dot_product", "dot"):
        s = (1.0 + jnp.einsum("qd,qkd->qk", q, cand, precision=hi)) * 0.5
    elif metric in ("l2_norm", "l2"):
        d2 = jnp.sum((q[:, None, :] - cand) ** 2, axis=-1)
        s = 1.0 / (1.0 + d2)
    else:
        raise ValueError(f"unknown knn metric [{metric}]")
    s = jnp.where(vals > NEG_INF, s, NEG_INF)
    new_v, pos = lax.top_k(s, s.shape[1])
    new_i = jnp.take_along_axis(idx, pos, axis=1)
    return new_v, new_i.astype(jnp.int32)


@partial(jax.jit, static_argnames=("k",))
def merge_candidate_topk(vals, ids, *, k: int):
    """Per-row dedup-by-max + top-k over candidate (score, id) pairs.

    vals f32[Q, N], ids i32[Q, N] (ids REPEAT when several query tokens
    surface the same doc; invalid slots carry -inf). Returns
    ([Q, k] vals, [Q, k] i32 ids, i32[Q] unique-valid counts).

    Device-friendly dedup: sort pairs by (id asc, score desc) — the
    first occurrence of each id is its max — mask non-first occurrences
    to -inf, then a stable top-k. Tie discipline matches lax.top_k over
    a dense score row: equal scores rank by ascending doc id (the id
    sort puts the lowest id first and top_k takes the first maximum).
    """
    width = vals.shape[1]
    if k > width:
        raise ValueError(f"k [{k}] exceeds candidate width [{width}]")
    sid, negv = lax.sort((ids, -vals), num_keys=2, dimension=1)
    sval = -negv
    first = jnp.concatenate(
        [jnp.ones((ids.shape[0], 1), bool), sid[:, 1:] != sid[:, :-1]],
        axis=1)
    valid = first & (sval > NEG_INF)
    n_unique = jnp.sum(valid.astype(jnp.int32), axis=1)
    sel = jnp.where(valid, sval, NEG_INF)
    best_v, pos = lax.top_k(sel, k)
    best_i = jnp.take_along_axis(sid, pos, axis=1)
    return best_v, best_i.astype(jnp.int32), n_unique


@partial(jax.jit, static_argnames=("k", "metric", "chunk", "use_bf16"))
def knn_topk_chunked(queries, vecs, mask, *, k: int, metric: str = "cosine",
                     chunk: int = 1 << 16, use_bf16: bool = True):
    """HBM-bounded scan over corpus chunks, merging running top-k.

    Keeps the intermediate at [Q, chunk] instead of [Q, D]; used when
    Q * D * 4 bytes would pressure HBM (large segments × query batches).
    """
    D = vecs.shape[0]
    if D % chunk != 0:
        raise ValueError("corpus rows must be padded to a multiple of chunk")
    n_chunks = D // chunk
    Q = queries.shape[0]

    def step(carry, i):
        best_v, best_i = carry
        v = lax.dynamic_slice_in_dim(vecs, i * chunk, chunk, axis=0)
        m = lax.dynamic_slice_in_dim(mask, i * chunk, chunk, axis=0)
        s = knn_scores(queries, v, metric=metric, use_bf16=use_bf16)
        s = jnp.where(m[None, :], s, NEG_INF)
        cand_v, cand_i = lax.top_k(s, min(k, chunk))
        cand_i = cand_i + i * chunk
        merged_v = jnp.concatenate([best_v, cand_v], axis=1)
        merged_i = jnp.concatenate([best_i, cand_i], axis=1)
        new_v, pos = lax.top_k(merged_v, k)
        new_i = jnp.take_along_axis(merged_i, pos, axis=1)
        return (new_v, new_i), None

    init = (jnp.full((Q, k), NEG_INF), jnp.zeros((Q, k), dtype=jnp.int32))
    (vals, idx), _ = lax.scan(step, init, jnp.arange(n_chunks))
    return vals, idx.astype(jnp.int32)
