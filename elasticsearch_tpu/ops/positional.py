"""Device positional programs: phrase / ordered-near matching on TPU.

Reference: Lucene ExactPhraseScorer / SloppyPhraseScorer semantics as used
by org/elasticsearch/index/query/MatchQueryBuilder.java (type=phrase) and
SpanNearQueryBuilder.java. Round-1 ran these host-side per candidate doc
(the latency-oriented pointer-chasing SURVEY §1 exists to kill); this is
the R2 replacement: whole-segment vectorized interval verification.

Execution model — "anchor entries + branchless binary search":

  * The positional CSR (segment.py: pos_offsets aligned with postings
    order, positions i32[total]) lives on device, plus a doc-per-position
    expansion (doc_per_pos). All immutable, cached per segment.
  * The FIRST query term's positional entries are the anchors: [A] pairs
    (doc, pos) sliced straight out of the global arrays (contiguous CSR).
  * For every other term j, each anchor does a vectorized lower_bound into
    the term's postings doc run (padded [R]), then a bounded lower_bound
    into the global positions array between that posting's pos_offsets —
    per-anchor [lo, hi) bounds, log-step fori-style loops, no gather lists.
  * Exact phrase (slop=0): hit iff position anchor+delta_j exists for all
    j. Sloppy (slop>0): greedy nearest-to-expected per term, matchLength =
    spread of (q_j - delta_j), weight 1/(1+matchLength) — Lucene's sloppy
    freq for the window each anchor selects. Deviation: Lucene explores
    alternative windows for repeated terms; the greedy program scores the
    nearest-window per anchor (oracle in tests/unit/test_positional.py
    mirrors this exactly, and equals Lucene on non-degenerate phrases).
  * Scatter-add of weights by anchor doc → phrase_freq f32[D]; the caller
    scores idf_sum * tfNorm(freq) like a single pseudo-term (what
    BM25Similarity does with phraseFreq).

Ordered span_near chains greedily instead: q_j = first position of clause
j at or after the previous match end (NearSpansOrdered's advance), width -
m <= slop.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _lower_bound(arr, target, lo, hi, steps: int):
    """Vectorized lower_bound of `target` [A] in sorted `arr` between
    per-element bounds [lo, hi). Runs `steps` fixed iterations."""
    n = arr.shape[0]
    for _ in range(steps):
        cond = lo < hi
        mid = (lo + hi) // 2
        v = arr[jnp.clip(mid, 0, n - 1)]
        less = (v < target) & cond
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(cond & ~less, mid, hi)
    return lo


def _steps(n: int) -> int:
    return max(int(np.ceil(np.log2(max(n, 2)))) + 1, 1)


def _freq_segmented(anchor_doc, match, w, *, D: int):
    """Scatter-free anchor→doc frequency rollup: sort (doc, w) pairs,
    segmented inclusive scan over the CONTIGUOUS equal-doc runs (the
    same-doc-at-distance-s guard is exact precisely because runs are
    contiguous after the sort), then one boundary search — run ends hold
    each doc's total. Replaces the A-element scatter-add, which XLA
    serializes per slot on TPU. Reassociates the per-run f32 sums in
    tree order (the scatter's accumulation order is unspecified too)."""
    from jax import lax

    A = anchor_doc.shape[0]
    dkey = jnp.where(match, anchor_doc, D)
    ds, tot = lax.sort((dkey, jnp.where(match, w, 0.0)), num_keys=1)
    s = 1
    while s < A:
        same = jnp.concatenate([jnp.zeros((s,), bool), ds[s:] == ds[:-s]])
        tot = tot + jnp.where(
            same, jnp.concatenate([jnp.zeros((s,), tot.dtype), tot[:-s]]),
            0.0)
        s *= 2
    bounds = jnp.searchsorted(ds, jnp.arange(D + 1, dtype=ds.dtype))
    hi = bounds[1:]
    n = hi - bounds[:-1]
    return jnp.where(n > 0, tot[jnp.clip(hi - 1, 0, A - 1)], 0.0)


@partial(jax.jit, static_argnames=("slop", "D", "ordered", "unordered",
                                   "scatter_free"))
def phrase_freq_program(anchor_doc, anchor_pos, anchor_valid,
                        doc_runs, run_starts, run_lens, deltas,
                        positions, pos_offsets, *,
                        slop: int, D: int, ordered: bool = False,
                        unordered: bool = False,
                        scatter_free: bool = False):
    """Phrase / ordered-near / unordered-near frequency vector f32[D].

    anchor_doc/pos/valid: [A] anchor positional entries (term 0).
    doc_runs:   i32[M, R] per-term postings doc ids, padded with D.
    run_starts: i32[M] postings entry base of each term's run.
    run_lens:   i32[M] true run lengths.
    deltas:     i32[M] expected position offset vs anchor (phrase mode).
    positions, pos_offsets: the segment's global positional CSR (device).
    ordered=True switches to span_near greedy chaining (deltas ignored
    except as minimum widths of 1 per clause). unordered=True is
    SpanNearQuery in_order=false over unit-width clauses: per anchor the
    greedy nearest position of every other term, match when the combined
    window minus the clause count fits the slop (NearSpansUnordered's
    condition; like the sloppy branch this explores the nearest window per
    anchor, not every combination — documented deviation).
    """
    A = anchor_doc.shape[0]
    M, R = doc_runs.shape
    doc_steps = _steps(R)
    pos_steps = _steps(int(positions.shape[0]))

    match = anchor_valid
    if unordered:
        # greedy nearest-to-anchor per clause (deltas are 0); window spread
        # minus M unit-width clauses must fit the slop
        adj_min = anchor_pos.astype(jnp.int32)
        adj_max = anchor_pos.astype(jnp.int32)
        npos = positions.shape[0]
        for j in range(M):
            e = _lower_bound(doc_runs[j], anchor_doc,
                             jnp.zeros(A, jnp.int32),
                             jnp.full(A, run_lens[j], jnp.int32), doc_steps)
            found = (e < run_lens[j]) & (doc_runs[j][jnp.clip(e, 0, R - 1)] == anchor_doc)
            entry = run_starts[j] + jnp.clip(e, 0, R - 1)
            lo = pos_offsets[entry]
            hi = pos_offsets[entry + 1]
            idx = _lower_bound(positions, anchor_pos, lo, hi, pos_steps)
            c1 = positions[jnp.clip(idx, 0, npos - 1)]
            c1_ok = idx < hi
            c0 = positions[jnp.clip(idx - 1, 0, npos - 1)]
            c0_ok = (idx - 1) >= lo
            d1 = jnp.where(c1_ok, jnp.abs(c1 - anchor_pos), 1 << 30)
            d0 = jnp.where(c0_ok, jnp.abs(c0 - anchor_pos), 1 << 30)
            q = jnp.where(d0 < d1, c0, c1)
            found = found & (c0_ok | c1_ok)
            adj_min = jnp.where(found, jnp.minimum(adj_min, q), adj_min)
            adj_max = jnp.where(found, jnp.maximum(adj_max, q), adj_max)
            match = match & found
        mlen = (adj_max - adj_min) - M  # (width - total clause length)
        match = match & (mlen <= slop)
        w = jnp.where(match,
                      1.0 / (1.0 + jnp.maximum(mlen, 0).astype(jnp.float32)),
                      0.0)
        if scatter_free:
            return _freq_segmented(anchor_doc, match, w, D=D)
        freq = jnp.zeros(D, jnp.float32).at[anchor_doc].add(
            jnp.where(match, w, 0.0), mode="drop")
        return freq
    if slop == 0 and not ordered:
        for j in range(M):
            e = _lower_bound(doc_runs[j], anchor_doc,
                             jnp.zeros(A, jnp.int32),
                             jnp.full(A, run_lens[j], jnp.int32), doc_steps)
            found = (e < run_lens[j]) & (doc_runs[j][jnp.clip(e, 0, R - 1)] == anchor_doc)
            entry = run_starts[j] + jnp.clip(e, 0, R - 1)
            lo = pos_offsets[entry]
            hi = pos_offsets[entry + 1]
            target = anchor_pos + deltas[j]
            idx = _lower_bound(positions, target, lo, hi, pos_steps)
            npos = positions.shape[0]
            hit = (idx < hi) & (positions[jnp.clip(idx, 0, npos - 1)] == target)
            match = match & found & hit
        w = jnp.where(match, 1.0, 0.0)
    elif not ordered:
        # greedy sloppy: nearest position to the expected slot per term
        adj_min = anchor_pos.astype(jnp.int32)
        adj_max = anchor_pos.astype(jnp.int32)
        npos = positions.shape[0]
        for j in range(M):
            e = _lower_bound(doc_runs[j], anchor_doc,
                             jnp.zeros(A, jnp.int32),
                             jnp.full(A, run_lens[j], jnp.int32), doc_steps)
            found = (e < run_lens[j]) & (doc_runs[j][jnp.clip(e, 0, R - 1)] == anchor_doc)
            entry = run_starts[j] + jnp.clip(e, 0, R - 1)
            lo = pos_offsets[entry]
            hi = pos_offsets[entry + 1]
            target = anchor_pos + deltas[j]
            idx = _lower_bound(positions, target, lo, hi, pos_steps)
            c1 = positions[jnp.clip(idx, 0, npos - 1)]
            c1_ok = idx < hi
            c0 = positions[jnp.clip(idx - 1, 0, npos - 1)]
            c0_ok = (idx - 1) >= lo
            d1 = jnp.where(c1_ok, jnp.abs(c1 - target), 1 << 30)
            d0 = jnp.where(c0_ok, jnp.abs(c0 - target), 1 << 30)
            q = jnp.where(d0 < d1, c0, c1)
            found = found & (c0_ok | c1_ok)
            adj = q - deltas[j]
            adj_min = jnp.where(found, jnp.minimum(adj_min, adj), adj_min)
            adj_max = jnp.where(found, jnp.maximum(adj_max, adj), adj_max)
            match = match & found
        mlen = adj_max - adj_min
        match = match & (mlen <= slop)
        w = jnp.where(match, 1.0 / (1.0 + mlen.astype(jnp.float32)), 0.0)
    else:
        # ordered near: chain each clause to the first position >= prev+1
        npos = positions.shape[0]
        prev = anchor_pos
        first = anchor_pos
        for j in range(M):
            e = _lower_bound(doc_runs[j], anchor_doc,
                             jnp.zeros(A, jnp.int32),
                             jnp.full(A, run_lens[j], jnp.int32), doc_steps)
            found = (e < run_lens[j]) & (doc_runs[j][jnp.clip(e, 0, R - 1)] == anchor_doc)
            entry = run_starts[j] + jnp.clip(e, 0, R - 1)
            lo = pos_offsets[entry]
            hi = pos_offsets[entry + 1]
            idx = _lower_bound(positions, prev + 1, lo, hi, pos_steps)
            ok = idx < hi
            q = positions[jnp.clip(idx, 0, npos - 1)]
            match = match & found & ok
            prev = jnp.where(ok, q, prev)
        width = prev - first + 1
        mlen = width - (M + 1)
        match = match & (mlen <= slop)
        w = jnp.where(match, 1.0 / (1.0 + jnp.maximum(mlen, 0).astype(jnp.float32)), 0.0)

    if scatter_free:
        return _freq_segmented(anchor_doc, match, w, D=D)
    freq = jnp.zeros(D, jnp.float32).at[anchor_doc].add(
        jnp.where(match, w, 0.0), mode="drop")
    return freq


@partial(jax.jit, static_argnames=("D",))
def phrase_score(freq, lengths, avg_len, idf_sum, *, D: int,
                 k1: float = 1.2, b: float = 0.75):
    """BM25 over the phrase pseudo-term: idf_sum * tfNorm(phraseFreq)."""
    norm = k1 * (1.0 - b + b * lengths / jnp.maximum(avg_len, 1e-9))
    tfn = freq * (k1 + 1.0) / (freq + norm)
    return jnp.where(freq > 0, idf_sum * tfn, 0.0)


@partial(jax.jit, static_argnames=("D", "scatter_free"))
def span_not_program(anchor_doc, anchor_pos, anchor_valid,
                     doc_runs, run_starts, run_lens,
                     positions, pos_offsets, pre, post, *, D: int,
                     scatter_free: bool = False):
    """Surviving-include-anchor count f32[D] for span_not: an include span
    at position p survives when NO exclude-term position lies inside
    [p - pre, p + post] (unit-width exclude spans overlap the padded
    include window exactly on that closed range). One vectorized pass —
    anchors are ALL include positions, exclusion via bounded lower_bound
    into the positional CSR (SpanNotQuery semantics, no per-doc walks)."""
    A = anchor_doc.shape[0]
    M, R = doc_runs.shape
    doc_steps = _steps(R)
    pos_steps = _steps(int(positions.shape[0]))
    npos = positions.shape[0]
    alive = anchor_valid
    for j in range(M):
        e = _lower_bound(doc_runs[j], anchor_doc,
                         jnp.zeros(A, jnp.int32),
                         jnp.full(A, run_lens[j], jnp.int32), doc_steps)
        found = (e < run_lens[j]) & (doc_runs[j][jnp.clip(e, 0, R - 1)] == anchor_doc)
        entry = run_starts[j] + jnp.clip(e, 0, R - 1)
        lo = pos_offsets[entry]
        hi = pos_offsets[entry + 1]
        idx = _lower_bound(positions, anchor_pos - pre, lo, hi, pos_steps)
        has = (found & (idx < hi)
               & (positions[jnp.clip(idx, 0, npos - 1)] <= anchor_pos + post))
        alive = alive & ~has
    if scatter_free:
        return _freq_segmented(anchor_doc, alive,
                               jnp.ones_like(anchor_pos, jnp.float32), D=D)
    return jnp.zeros(D, jnp.float32).at[anchor_doc].add(
        jnp.where(alive, 1.0, 0.0), mode="drop")


# ---------------------------------------------------------------------------
# host-side prep
# ---------------------------------------------------------------------------

def pow2(n: int) -> int:
    from elasticsearch_tpu.utils.shapes import pow2_bucket

    return pow2_bucket(max(n, 1))


def positional_device(inv):
    """Cached device copies of the positional CSR + doc-per-position
    expansion for one InvertedField (immutable once frozen). The HOST copy
    of doc_per_pos is cached alongside (``inv._pos_host_dpp``) — anchor
    builders slice it instead of re-running the O(total positions) repeat
    per query."""
    cached = getattr(inv, "_pos_dev", None)
    if cached is not None:
        return cached
    if inv.positions is None or inv.pos_offsets is None:
        return None
    # cached as long as the field: place through the residency choke
    # point so the positional CSR's HBM is accounted
    from elasticsearch_tpu import resources

    put = resources.RESIDENCY.device_put
    pos = put(np.asarray(inv.positions, np.int32), label="positions")
    offs = put(np.asarray(inv.pos_offsets, np.int32), label="pos_offsets")
    counts = np.diff(inv.pos_offsets).astype(np.int64)
    doc_per_pos = np.repeat(inv.doc_ids_host[:counts.shape[0]],
                            counts).astype(np.int32)
    dpp = put(doc_per_pos, label="doc_per_pos")
    inv._pos_host_dpp = doc_per_pos
    inv._pos_dev = (pos, offs, dpp)
    return inv._pos_dev


def build_union_anchor_inputs(inv, anchor_terms, other_terms, D: int):
    """Anchors = UNION of the anchor_terms' positional entries (for span
    trees whose first clause is a term disjunction) + padded run tables for
    other_terms. Vectorized host prep only — no per-doc loops. None when
    positions are missing or no anchor term occurs."""
    dev = positional_device(inv)
    if dev is None:
        return None
    positions, pos_offsets, _dpp = dev
    spans = []
    for t in anchor_terms:
        s, ln = inv.term_slice(t)
        if ln:
            spans.append((int(inv.pos_offsets[s]),
                          int(inv.pos_offsets[s + ln])))
    n_anchor = sum(h - l for l, h in spans)
    if n_anchor == 0:
        return None
    dpp = inv._pos_host_dpp  # cached by positional_device above
    pos_np = np.asarray(inv.positions)
    A = pow2(n_anchor)
    adoc = np.full(A, D, np.int32)
    apos = np.zeros(A, np.int32)
    k = 0
    for l, h in spans:
        adoc[k: k + h - l] = dpp[l:h]
        apos[k: k + h - l] = pos_np[l:h]
        k += h - l
    avalid = np.arange(A) < n_anchor
    M = len(other_terms)
    R = pow2(max((inv.term_slice(t)[1] for t in other_terms), default=1) or 1)
    doc_runs = np.full((max(M, 1), R), D, np.int32)
    run_starts = np.zeros(max(M, 1), np.int32)
    run_lens = np.zeros(max(M, 1), np.int32)
    for j, t in enumerate(other_terms):
        s, ln = inv.term_slice(t)
        if ln:
            doc_runs[j, :ln] = inv.doc_ids_host[s: s + ln]
            run_starts[j] = s
            run_lens[j] = ln
    return (jnp.asarray(adoc), jnp.asarray(apos), jnp.asarray(avalid),
            jnp.asarray(doc_runs), jnp.asarray(run_starts),
            jnp.asarray(run_lens), positions, pos_offsets)


def build_phrase_inputs(inv, terms, D: int):
    """(anchor arrays + per-term run tables) for phrase_freq_program, or
    None when any positional prerequisite is missing. Terms are (term,
    delta) pairs; the first is the anchor (delta folded so anchor delta=0).
    """
    dev = positional_device(inv)
    if dev is None:
        return None
    positions, pos_offsets, doc_per_pos = dev
    (t0, d0), rest = terms[0], terms[1:]
    s0, ln0 = inv.term_slice(t0)
    if ln0 == 0:
        return None
    p_lo = int(inv.pos_offsets[s0])
    p_hi = int(inv.pos_offsets[s0 + ln0])
    A = pow2(p_hi - p_lo)
    anchor_pos = jnp.zeros(A, jnp.int32)
    anchor_doc = jnp.full(A, D, jnp.int32)
    n_anchor = p_hi - p_lo
    anchor_pos = anchor_pos.at[:n_anchor].set(positions[p_lo:p_hi])
    anchor_doc = anchor_doc.at[:n_anchor].set(doc_per_pos[p_lo:p_hi])
    anchor_valid = jnp.arange(A) < n_anchor

    M = len(rest)
    if M == 0:
        return None
    R = pow2(max(inv.term_slice(t)[1] for t, _ in rest))
    doc_runs = np.full((M, R), D, np.int32)
    run_starts = np.zeros(M, np.int32)
    run_lens = np.zeros(M, np.int32)
    deltas = np.zeros(M, np.int32)
    for j, (t, d) in enumerate(rest):
        s, ln = inv.term_slice(t)
        if ln == 0:
            return None  # absent term → phrase can't match
        doc_runs[j, :ln] = inv.doc_ids_host[s: s + ln]
        run_starts[j] = s
        run_lens[j] = ln
        deltas[j] = d - d0
    return (anchor_doc, anchor_pos, anchor_valid,
            jnp.asarray(doc_runs), jnp.asarray(run_starts),
            jnp.asarray(run_lens), jnp.asarray(deltas),
            positions, pos_offsets)
