# patch jax.jit with the retrace auditor BEFORE the imports below bind
# `@jax.jit` decorators — the search profiler's device compile/execute
# split depends on it (tracing/retrace.py); this package pulls in jax
# anyway, so the root elasticsearch_tpu import stays light
from elasticsearch_tpu.tracing import retrace as _retrace

_retrace.ensure_installed()

from elasticsearch_tpu.ops.scoring import (
    bm25_score_segment,
    bm25_score_batch,
    bm25_score_hybrid,
    bm25_score_hybrid_batch,
    match_count_hybrid,
    term_mask,
    term_mask_hybrid,
    topk_with_mask,
    range_mask_f32,
    range_mask_i64pair,
)
from elasticsearch_tpu.ops.knn import knn_scores, knn_topk

__all__ = [
    "bm25_score_segment",
    "bm25_score_batch",
    "bm25_score_hybrid",
    "bm25_score_hybrid_batch",
    "match_count_hybrid",
    "term_mask",
    "term_mask_hybrid",
    "topk_with_mask",
    "range_mask_f32",
    "range_mask_i64pair",
    "knn_scores",
    "knn_topk",
]
