"""IVF-flat approximate kNN — coarse k-means quantizer + inverted lists.

No ES 2.0 counterpart (the reference predates vector search); the north-star
plan (SURVEY §2.4 knn row, BASELINE configs[3]) calls for an ANN path beside
the brute-force MXU matmul. The classical IVF recipe (train a coarse
quantizer, bucket vectors by nearest centroid, probe the closest nprobe
lists at query time) maps exceptionally well to TPU:

  * k-means training IS batched matmuls: assignment = argmax(vecs @ cᵀ),
    update = segment-sum — both MXU/VPU-shaped, no pointer chasing.
  * inverted lists become a PADDED [C, Lmax] id matrix (static shapes —
    no ragged CSR walks); probing = one gather + one small matmul.
  * probe selection, candidate scoring, and top-k fuse into one XLA
    program; `num_candidates` tunes nprobe.

Recall/latency contract mirrors FAISS IVF-flat: with C ≈ 4√N lists and
nprobe sized so probed lists cover ≥ num_candidates vectors, recall@10 on
clustered data ≥ 0.95 at a fraction of brute-force FLOPs.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import numpy as np

from elasticsearch_tpu.utils.shapes import pow2_bucket


def _jax():
    import jax

    return jax


# ---------------------------------------------------------------------------
# k-means (device)
# ---------------------------------------------------------------------------

def _quantizer_affinity(jnp, vecs, cents, metric: str):
    """[N, C] affinity used for BOTH k-means assignment and query-time
    probing — argmax row-wise picks the nearest centroid under the field's
    similarity. l2_norm uses the norm expansion (argmin ||v-c||^2 ==
    argmax v.c - ||c||^2/2); cosine/dot normalize centroids (dot against a
    unit-norm direction — standard spherical k-means for MIPS/cosine)."""
    if metric in ("l2_norm", "l2"):
        vc = jnp.matmul(vecs, cents.T, preferred_element_type=jnp.float32)
        return vc - 0.5 * jnp.sum(cents * cents, axis=-1)[None, :]
    cn = cents / jnp.maximum(
        jnp.linalg.norm(cents, axis=-1, keepdims=True), 1e-12)
    return jnp.matmul(vecs, cn.T, preferred_element_type=jnp.float32)


def kmeans(vecs_np: np.ndarray, C: int, iters: int = 8, seed: int = 1234,
           metric: str = "cosine"):
    """Train C centroids over vecs [N, dims] (host in, host out).

    Deterministic: init = evenly strided sample of the corpus (stable across
    runs — no RNG in the build path, mirroring how segment freezes must be
    reproducible for recovery). Empty clusters re-seed from the farthest
    vectors of the biggest cluster's assignment pass.

    The assignment metric follows the field's similarity (advisor r2):
    l2_norm fields cluster/probe by squared-l2, cosine/dot by normalized
    dot — so the inverted lists agree with query-time probing. Returns
    (centroids, assign) where `assign` is ONE FINAL assignment pass against
    the FINAL centroids (not the stale pre-update assignment), keeping the
    lists consistent with the quantizer actually probed at query time.
    """
    jax = _jax()
    import jax.numpy as jnp

    N, dims = vecs_np.shape
    C = min(C, N)
    stride = max(N // C, 1)
    cents = vecs_np[:: stride][:C].astype(np.float32).copy()

    @partial(jax.jit, static_argnames=("nc", "metric"))
    def step(vecs, cents, *, nc, metric):
        # one [N, C] matmul on the MXU
        sim = _quantizer_affinity(jnp, vecs, cents, metric)
        assign = jnp.argmax(sim, axis=1)
        one = jnp.zeros((nc,), jnp.float32).at[assign].add(1.0)
        sums = jnp.zeros((nc, vecs.shape[1]), jnp.float32).at[assign].add(vecs)
        new = sums / jnp.maximum(one[:, None], 1.0)
        # keep old centroid where a cluster went empty
        new = jnp.where(one[:, None] > 0, new, cents)
        return new, assign

    @partial(jax.jit, static_argnames=("metric",))
    def assign_only(vecs, cents, *, metric):
        return jnp.argmax(_quantizer_affinity(jnp, vecs, cents, metric), axis=1)

    # offbudget: k-means build temporaries — freed when the build returns
    d_vecs = jax.device_put(vecs_np.astype(np.float32))  # tpulint: offbudget
    d_cents = jax.device_put(cents)  # tpulint: offbudget
    for _ in range(iters):
        d_cents, _ = step(d_vecs, d_cents, nc=C, metric=metric)
    assign = assign_only(d_vecs, d_cents, metric=metric)
    return np.asarray(d_cents), np.asarray(assign)


# ---------------------------------------------------------------------------
# index
# ---------------------------------------------------------------------------

@dataclass
class IvfIndex:
    centroids: Any  # f32[C, dims] (device)
    lists: Any  # i32[C, Lmax] doc ids, padded with `sentinel` (device)
    list_lens: Any  # i32[C] (device)
    C: int
    Lmax: int
    sentinel: int  # = max_docs of the owning segment
    avg_len: float
    metric: str = "cosine"  # quantizer metric (follows the field similarity)

    def nprobe_for(self, num_candidates: int) -> int:
        n = int(np.ceil(num_candidates / max(self.avg_len, 1.0)))
        return max(1, min(n, self.C))


def build_ivf(vecs_np: np.ndarray, exists_np: np.ndarray, max_docs: int,
              C: Optional[int] = None, iters: int = 8,
              metric: str = "cosine") -> Optional[IvfIndex]:
    """Build an IVF index over the live vectors of one segment slab."""
    jax = _jax()

    # host-side BUILD path (freeze-time, never traced): the ragged live-id
    # set is exactly what the padded [C, Lmax] device lists exist to absorb
    ids = np.nonzero(exists_np)[0].astype(np.int32)  # tpulint: host
    n = ids.size
    if n < 64:
        return None  # brute force is strictly better at this scale
    live = vecs_np[ids]
    if C is None:
        C = int(max(8, min(4 * np.sqrt(n), n // 8)))
    cents, assign = kmeans(live, C, iters=iters, metric=metric)
    C = cents.shape[0]
    counts = np.bincount(assign, minlength=C)
    Lmax = pow2_bucket(int(counts.max()) if counts.size else 1)
    lists = np.full((C, Lmax), max_docs, np.int32)
    fill = np.zeros(C, np.int64)
    for i, a in zip(ids, assign):
        lists[a, fill[a]] = i
        fill[a] += 1
    # IVF device caches live as long as the owning VectorColumn — place
    # through the residency choke point so their HBM is accounted
    from elasticsearch_tpu import resources

    put = resources.RESIDENCY.device_put
    return IvfIndex(
        centroids=put(cents, label="ivf.centroids"),
        lists=put(lists, label="ivf.lists"),
        list_lens=put(counts.astype(np.int32), label="ivf.list_lens"),
        C=C, Lmax=Lmax, sentinel=max_docs,
        avg_len=float(n) / C, metric=metric,
    )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

_PROGRAMS: dict = {}


def ivf_candidate_scores(index: IvfIndex, vecs, query_np: np.ndarray,
                         num_candidates: int, metric: str, D: int):
    """Scatter ANN candidate scores into a whole-segment [D] score vector.

    Probes the nprobe closest lists (nprobe sized so probed lists cover
    ≈ num_candidates vectors), gathers their vectors from the slab, scores
    with the exact metric, and scatters into dense f32[D] (−inf elsewhere)
    + bool[D] mask — the same (scores, mask) contract every other query
    program has, so IVF composes with filters/bool/rescore unchanged.
    """
    jax = _jax()

    from elasticsearch_tpu.ops.scoring import tail_mode_batch

    nprobe = index.nprobe_for(num_candidates)
    sf = tail_mode_batch()
    key = (index.C, index.Lmax, D, nprobe, metric, index.metric, sf)
    prog = _PROGRAMS.get(key)
    if prog is None:
        prog = make_ivf_search(index.C, index.Lmax, D, nprobe, metric,
                               quantizer_metric=index.metric,
                               scatter_free=sf)
        _PROGRAMS[key] = prog
    # offbudget: transient per-query upload
    q = jax.device_put(np.asarray(query_np, np.float32))  # tpulint: offbudget
    return prog(q, index.centroids, index.lists, vecs)


def make_ivf_search(C: int, Lmax: int, D: int, nprobe: int, metric: str,
                    quantizer_metric: str = "cosine",
                    scatter_free: bool = False):
    """Compiled IVF probe+score program for one shape class."""
    jax = _jax()
    import jax.numpy as jnp
    from jax import lax

    from elasticsearch_tpu.ops.knn import knn_scores

    @jax.jit
    def run(query, centroids, lists, vecs):
        # 1. probe: closest nprobe centroids under the SAME metric the
        # lists were clustered with (cosine/dot → normalized dot; l2 →
        # norm-expanded squared distance), so probing agrees with build
        csim = _quantizer_affinity(jnp, query[None, :], centroids,
                                   quantizer_metric)[0]  # [C]
        _, probe = lax.top_k(csim, nprobe)  # [nprobe]
        # 2. candidates: padded ids of the probed lists
        cand = lists[probe].reshape(-1)  # [nprobe * Lmax], pad = D sentinel
        valid = cand < D
        safe = jnp.where(valid, cand, 0)
        cvecs = vecs[safe]  # [nprobe*Lmax, dims]
        # 3. exact metric on candidates only — f32: the whole point of IVF
        # is to spend full precision on a small candidate set (the brute
        # path's bf16 trade-off buys nothing on a matmul this size)
        cscores = knn_scores(query[None, :], cvecs, metric=metric,
                             use_bf16=False)[0]
        # 4. expand to the whole-segment score vector
        if scatter_free:
            # each vector belongs to exactly ONE list, so candidate ids
            # are unique: sort (cand, score) by id and gather each doc's
            # single entry via boundary search — no serialized TPU
            # scatter (padding sorts past every real doc)
            sc, ss = lax.sort((cand, jnp.where(valid, cscores, -jnp.inf)),
                              num_keys=1)
            bounds = jnp.searchsorted(sc, jnp.arange(D + 1,
                                                     dtype=sc.dtype))
            lo, n = bounds[:-1], bounds[1:] - bounds[:-1]
            W = sc.shape[0]
            scores = jnp.where(n > 0,
                               ss[jnp.clip(lo, 0, W - 1)], -jnp.inf)
            mask = n > 0
        else:
            scores = jnp.full(D, -jnp.inf, jnp.float32)
            scores = scores.at[cand].max(
                jnp.where(valid, cscores, -jnp.inf), mode="drop")
            mask = jnp.zeros(D, bool).at[cand].max(valid, mode="drop")
        return scores, mask

    return run
