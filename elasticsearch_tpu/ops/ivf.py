"""IVF-flat approximate kNN — coarse k-means quantizer + inverted lists.

No ES 2.0 counterpart (the reference predates vector search); the north-star
plan (SURVEY §2.4 knn row, BASELINE configs[3]) calls for an ANN path beside
the brute-force MXU matmul. The classical IVF recipe (train a coarse
quantizer, bucket vectors by nearest centroid, probe the closest nprobe
lists at query time) maps exceptionally well to TPU:

  * k-means training IS batched matmuls: assignment = argmax(vecs @ cᵀ),
    update = segment-sum — both MXU/VPU-shaped, no pointer chasing.
  * inverted lists become a PADDED [C, Lmax] id matrix (static shapes —
    no ragged CSR walks); probing = one gather + one small matmul.
  * probe selection, candidate scoring, and top-k fuse into one XLA
    program; `num_candidates` tunes nprobe.

Recall/latency contract mirrors FAISS IVF-flat: with C ≈ 4√N lists and
nprobe sized so probed lists cover ≥ num_candidates vectors, recall@10 on
clustered data ≥ 0.95 at a fraction of brute-force FLOPs.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import numpy as np

from elasticsearch_tpu.utils.shapes import pow2_bucket


def _jax():
    import jax

    return jax


# ---------------------------------------------------------------------------
# k-means (device)
# ---------------------------------------------------------------------------

def _quantizer_affinity(jnp, vecs, cents, metric: str):
    """[N, C] affinity used for BOTH k-means assignment and query-time
    probing — argmax row-wise picks the nearest centroid under the field's
    similarity. l2_norm uses the norm expansion (argmin ||v-c||^2 ==
    argmax v.c - ||c||^2/2); cosine/dot normalize centroids (dot against a
    unit-norm direction — standard spherical k-means for MIPS/cosine)."""
    if metric in ("l2_norm", "l2"):
        vc = jnp.matmul(vecs, cents.T, preferred_element_type=jnp.float32)
        return vc - 0.5 * jnp.sum(cents * cents, axis=-1)[None, :]
    cn = cents / jnp.maximum(
        jnp.linalg.norm(cents, axis=-1, keepdims=True), 1e-12)
    return jnp.matmul(vecs, cn.T, preferred_element_type=jnp.float32)


def kmeans(vecs_np: np.ndarray, C: int, iters: int = 8, seed: int = 1234,
           metric: str = "cosine"):
    """Train C centroids over vecs [N, dims] (host in, host out).

    Deterministic: init = evenly strided sample of the corpus (stable across
    runs — no RNG in the build path, mirroring how segment freezes must be
    reproducible for recovery). Empty clusters re-seed from the farthest
    vectors of the biggest cluster's assignment pass.

    The assignment metric follows the field's similarity (advisor r2):
    l2_norm fields cluster/probe by squared-l2, cosine/dot by normalized
    dot — so the inverted lists agree with query-time probing. Returns
    (centroids, assign) where `assign` is ONE FINAL assignment pass against
    the FINAL centroids (not the stale pre-update assignment), keeping the
    lists consistent with the quantizer actually probed at query time.
    """
    jax = _jax()
    import jax.numpy as jnp

    N, dims = vecs_np.shape
    C = min(C, N)
    stride = max(N // C, 1)
    cents = vecs_np[:: stride][:C].astype(np.float32).copy()

    @partial(jax.jit, static_argnames=("nc", "metric"))
    def step(vecs, cents, *, nc, metric):
        # one [N, C] matmul on the MXU
        sim = _quantizer_affinity(jnp, vecs, cents, metric)
        assign = jnp.argmax(sim, axis=1)
        one = jnp.zeros((nc,), jnp.float32).at[assign].add(1.0)
        sums = jnp.zeros((nc, vecs.shape[1]), jnp.float32).at[assign].add(vecs)
        new = sums / jnp.maximum(one[:, None], 1.0)
        # keep old centroid where a cluster went empty
        new = jnp.where(one[:, None] > 0, new, cents)
        return new, assign

    @partial(jax.jit, static_argnames=("metric",))
    def assign_only(vecs, cents, *, metric):
        return jnp.argmax(_quantizer_affinity(jnp, vecs, cents, metric), axis=1)

    # offbudget: k-means build temporaries — freed when the build returns
    d_vecs = jax.device_put(vecs_np.astype(np.float32))  # tpulint: offbudget
    d_cents = jax.device_put(cents)  # tpulint: offbudget
    for _ in range(iters):
        d_cents, _ = step(d_vecs, d_cents, nc=C, metric=metric)
    assign = assign_only(d_vecs, d_cents, metric=metric)
    return np.asarray(d_cents), np.asarray(assign)


# ---------------------------------------------------------------------------
# index
# ---------------------------------------------------------------------------

@dataclass
class IvfIndex:
    centroids: Any  # f32[C, dims] (device)
    lists: Any  # i32[C, Lmax] doc ids, padded with `sentinel` (device)
    list_lens: Any  # i32[C] (device)
    C: int
    Lmax: int
    sentinel: int  # = max_docs of the owning segment
    avg_len: float
    metric: str = "cosine"  # quantizer metric (follows the field similarity)

    @property
    def ntotal(self) -> int:
        """Indexed vector count (avg_len is n / C at build time)."""
        return max(int(round(self.avg_len * self.C)), 1)

    def nprobe_for(self, num_candidates: int) -> int:
        """nprobe sized so probed lists cover ≈ num_candidates vectors.

        num_candidates clamps to [1, ntotal] BEFORE the coverage math
        (the final max/min already bounded the result to [1, C]; the
        early clamp keeps the sizing honest at the edges — asking for
        more candidates than indexed vectors means "probe everything",
        C exactly, not whatever ceil(nc / avg_len) lands on when lists
        run short)."""
        nc = min(max(int(num_candidates), 1), self.ntotal)
        n = int(np.ceil(nc / max(self.avg_len, 1.0)))
        return max(1, min(n, self.C))


def build_ivf(vecs_np: np.ndarray, exists_np: np.ndarray, max_docs: int,
              C: Optional[int] = None, iters: int = 8,
              metric: str = "cosine") -> Optional[IvfIndex]:
    """Build an IVF index over the live vectors of one segment slab."""
    jax = _jax()

    # host-side BUILD path (freeze-time, never traced): the ragged live-id
    # set is exactly what the padded [C, Lmax] device lists exist to absorb
    ids = np.nonzero(exists_np)[0].astype(np.int32)  # tpulint: host
    n = ids.size
    if n < 64:
        return None  # brute force is strictly better at this scale
    live = vecs_np[ids]
    if C is None:
        C = int(max(8, min(4 * np.sqrt(n), n // 8)))
    cents, assign = kmeans(live, C, iters=iters, metric=metric)
    C = cents.shape[0]
    counts = np.bincount(assign, minlength=C)
    Lmax = pow2_bucket(int(counts.max()) if counts.size else 1)
    lists = np.full((C, Lmax), max_docs, np.int32)
    fill = np.zeros(C, np.int64)
    for i, a in zip(ids, assign):
        lists[a, fill[a]] = i
        fill[a] += 1
    # IVF device caches live as long as the owning VectorColumn — place
    # through the residency choke point so their HBM is accounted
    from elasticsearch_tpu import resources

    put = resources.RESIDENCY.device_put
    return IvfIndex(
        centroids=put(cents, label="ivf.centroids"),
        lists=put(lists, label="ivf.lists"),
        list_lens=put(counts.astype(np.int32), label="ivf.list_lens"),
        C=C, Lmax=Lmax, sentinel=max_docs,
        avg_len=float(n) / C, metric=metric,
    )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------

_PROGRAMS: dict = {}


def ivf_candidate_scores(index: IvfIndex, vecs, query_np: np.ndarray,
                         num_candidates: int, metric: str, D: int,
                         pq=None, fine_k: Optional[int] = None,
                         filter_words=None):
    """Scatter ANN candidate scores into a whole-segment [D] score vector.

    Probes the nprobe closest lists (nprobe sized so probed lists cover
    ≈ num_candidates vectors) and emits dense f32[D] scores (−inf
    elsewhere) + bool[D] mask — the same (scores, mask) contract every
    other query program has, so IVF composes with filters/bool/rescore
    unchanged.

    Without ``pq`` every probed candidate's f32 vector is gathered and
    scored exactly — the r05 path whose cost scales linearly with
    num_candidates (the measured 389 -> 12.6 qps cliff). With ``pq`` (a
    PqIndex over the same slab) the pipeline is asymmetric coarse->fine:
    an ADC table-sum ranks ALL candidates from uint8 codes (O(M) bytes
    each), then only the top ``fine_k`` survivors pay the exact f32
    gather+re-rank — cost stops scaling with num_candidates.

    ``filter_words`` (packed uint32[D/32], ops/bitvec.pack_mask) is an
    optional PRE-filter: candidates failing it are dropped before the
    coarse rank, so the fine stage spends its budget entirely on docs
    the filter admits (ES applies the kNN filter during the search).
    """
    jax = _jax()

    from elasticsearch_tpu.ops.scoring import tail_mode_batch

    nprobe = index.nprobe_for(num_candidates)
    sf = tail_mode_batch()
    # offbudget: transient per-query upload
    q = jax.device_put(np.asarray(query_np, np.float32))  # tpulint: offbudget
    from elasticsearch_tpu.monitor.programs import REGISTRY, static_sig

    if pq is None and filter_words is None:
        key = (index.C, index.Lmax, D, nprobe, metric, index.metric, sf)
        prog = _PROGRAMS.get(key)
        if prog is None:
            from elasticsearch_tpu.parallel import aot

            prog = make_ivf_search(index.C, index.Lmax, D, nprobe, metric,
                                   quantizer_metric=index.metric,
                                   scatter_free=sf)
            # factory-key discipline (ROADMAP #6): the kernel entry rides
            # the AOT blob cache like every executor program
            prog = aot.wrap(prog, "ivf_search", key)
            _PROGRAMS[key] = prog
        # observatory: kernel-entry dispatch time on the shape-class key
        with REGISTRY.timed("ivf_search",
                            static_sig(C=index.C, Lmax=index.Lmax, D=D,
                                       nprobe=nprobe)):
            return prog(q, index.centroids, index.lists, vecs)

    from elasticsearch_tpu.monitor import kernels
    from elasticsearch_tpu.ops import pallas_kernels as pk

    W = nprobe * index.Lmax
    fk = max(1, min(int(fine_k or 64), W, D))
    use_filter = filter_words is not None
    # this dispatcher runs EAGERLY (the Pallas ADC's first real-TPU call
    # may fail at Mosaic lowering time) — same latch discipline as BM25
    force_xla = False
    for _attempt in range(2):
        tile = (0 if force_xla or pq is None
                else pk.adc_pallas_tile(W, pq.M, pq.K))
        key = ("pq", index.C, index.Lmax, D, nprobe, metric, index.metric,
               sf, fk, use_filter, tile,
               (pq.M, pq.K, pq.dsub, pq.metric) if pq is not None else None)
        prog = _PROGRAMS.get(key)
        if prog is None:
            prog = make_ivf_pq_search(
                index.C, index.Lmax, D, nprobe, metric,
                quantizer_metric=index.metric, scatter_free=sf, fine_k=fk,
                pq_meta=((pq.M, pq.K, pq.dsub, pq.metric)
                         if pq is not None else None),
                use_filter=use_filter, adc_tile=tile)
            if not tile:
                # the Pallas-tiled variant keeps its eager first-call
                # latch (Mosaic lowering may fail on device); only the
                # XLA shape classes ride the AOT blob cache
                from elasticsearch_tpu.parallel import aot

                prog = aot.wrap(
                    prog, "ivf_pq_search" if pq is not None else "ivf_search",
                    key)
            _PROGRAMS[key] = prog
        args = [q, index.centroids, index.lists, vecs]
        if pq is not None:
            args += [pq.codes_dev(), pq.codebooks]
        if use_filter:
            args.append(filter_words)
        try:
            # timed() records nothing when the dispatch raises — the
            # Pallas→XLA retry must not pollute the execute histogram
            with REGISTRY.timed(
                    "ivf_pq_search" if pq is not None else "ivf_search",
                    static_sig(C=index.C, Lmax=index.Lmax, D=D,
                               nprobe=nprobe, fk=fk,
                               filtered=use_filter, tile=tile)):
                out = prog(*args)
        except Exception as e:
            if tile:
                pk.note_adc_failure(e)
                force_xla = True
                continue
            raise
        if pq is not None:
            if tile:
                pk.note_adc_success()
            kernels.record("adc_pallas" if tile else "adc_xla")
        return out
    raise AssertionError("unreachable: ADC retry loop exits via return")


def make_ivf_search(C: int, Lmax: int, D: int, nprobe: int, metric: str,
                    quantizer_metric: str = "cosine",
                    scatter_free: bool = False):
    """Compiled IVF probe+score program for one shape class."""
    jax = _jax()
    import jax.numpy as jnp
    from jax import lax

    from elasticsearch_tpu.ops.knn import knn_scores

    @jax.jit
    def run(query, centroids, lists, vecs):
        # 1. probe: closest nprobe centroids under the SAME metric the
        # lists were clustered with (cosine/dot → normalized dot; l2 →
        # norm-expanded squared distance), so probing agrees with build
        csim = _quantizer_affinity(jnp, query[None, :], centroids,
                                   quantizer_metric)[0]  # [C]
        _, probe = lax.top_k(csim, nprobe)  # [nprobe]
        # 2. candidates: padded ids of the probed lists
        cand = lists[probe].reshape(-1)  # [nprobe * Lmax], pad = D sentinel
        valid = cand < D
        safe = jnp.where(valid, cand, 0)
        cvecs = vecs[safe]  # [nprobe*Lmax, dims]
        # 3. exact metric on candidates only — f32: the whole point of IVF
        # is to spend full precision on a small candidate set (the brute
        # path's bf16 trade-off buys nothing on a matmul this size)
        cscores = knn_scores(query[None, :], cvecs, metric=metric,
                             use_bf16=False)[0]
        # 4. expand to the whole-segment score vector
        if scatter_free:
            # each vector belongs to exactly ONE list, so candidate ids
            # are unique: sort (cand, score) by id and gather each doc's
            # single entry via boundary search — no serialized TPU
            # scatter (padding sorts past every real doc)
            sc, ss = lax.sort((cand, jnp.where(valid, cscores, -jnp.inf)),
                              num_keys=1)
            bounds = jnp.searchsorted(sc, jnp.arange(D + 1,
                                                     dtype=sc.dtype))
            lo, n = bounds[:-1], bounds[1:] - bounds[:-1]
            W = sc.shape[0]
            scores = jnp.where(n > 0,
                               ss[jnp.clip(lo, 0, W - 1)], -jnp.inf)
            mask = n > 0
        else:
            scores = jnp.full(D, -jnp.inf, jnp.float32)
            scores = scores.at[cand].max(
                jnp.where(valid, cscores, -jnp.inf), mode="drop")
            mask = jnp.zeros(D, bool).at[cand].max(valid, mode="drop")
        return scores, mask

    return run


def make_ivf_pq_search(C: int, Lmax: int, D: int, nprobe: int, metric: str,
                       quantizer_metric: str = "cosine",
                       scatter_free: bool = False, fine_k: int = 64,
                       pq_meta=None, use_filter: bool = False,
                       adc_tile: int = 0):
    """Compiled asymmetric coarse->fine IVF program for one shape class.

    Stages (all one fused XLA program; statically shaped throughout):

      1. probe — closest nprobe centroids under the quantizer metric.
      2. pre-filter — candidates failing the packed bit-vector filter
         (``use_filter``) drop out of the validity lane BEFORE any
         scoring, so the fine budget is spent on admissible docs only.
      3. coarse — ADC table-sum over uint8 codes (``pq_meta`` =
         (M, K, dsub, pq_metric)); the Pallas tiled kernel when
         ``adc_tile`` > 0, the XLA gather form otherwise. With no PQ
         tier the "coarse" stage IS the exact f32 scoring of every
         candidate (the pre-PQ path, kept for pre-filter-only callers).
      4. fine — exact f32 re-rank of the top ``fine_k`` ADC survivors
         only; their exact scores scatter into the [D] row. Scores the
         executor sees are always exact-metric f32 — PQ never leaks an
         approximate score past this program.
    """
    jax = _jax()
    import jax.numpy as jnp
    from jax import lax

    from elasticsearch_tpu.ops.bitvec import test_bits
    from elasticsearch_tpu.ops.knn import knn_scores

    @jax.jit
    def run(query, centroids, lists, vecs, *rest):
        rest = list(rest)
        if pq_meta is not None:
            codes, codebooks = rest[0], rest[1]
            rest = rest[2:]
        words = rest[0] if use_filter else None
        csim = _quantizer_affinity(jnp, query[None, :], centroids,
                                   quantizer_metric)[0]  # [C]
        _, probe = lax.top_k(csim, nprobe)
        cand = lists[probe].reshape(-1)  # [W], pad = D sentinel
        valid = cand < D
        safe = jnp.where(valid, cand, 0)
        if use_filter:
            valid = valid & test_bits(words, safe)
        if pq_meta is not None:
            from elasticsearch_tpu.ops.pq import adc_lut, adc_sum

            M, K, dsub, pq_metric = pq_meta
            lut = adc_lut(jnp, query, codebooks, pq_metric)
            ccodes = codes[safe]  # [W, M] uint8 — M bytes per candidate
            if adc_tile:
                from elasticsearch_tpu.ops.pallas_kernels import \
                    adc_scores_pallas

                coarse = adc_scores_pallas(ccodes.astype(jnp.int32), lut,
                                           tile=adc_tile)
            else:
                coarse = adc_sum(jnp, ccodes, lut)
            coarse = jnp.where(valid, coarse, -jnp.inf)
            fv, fpos = lax.top_k(coarse, fine_k)
            fids = jnp.take(cand, fpos)
            fvalid = fv > -jnp.inf
            fsafe = jnp.where(fvalid, fids, 0)
            fvecs = vecs[fsafe]  # [fine_k, dims] — the ONLY f32 gather
            fscores = knn_scores(query[None, :], fvecs, metric=metric,
                                 use_bf16=False)[0]
            fscores = jnp.where(fvalid, fscores, -jnp.inf)
        else:
            # pre-filter-only caller: exact scores for every candidate
            cvecs = vecs[safe]
            cs = knn_scores(query[None, :], cvecs, metric=metric,
                            use_bf16=False)[0]
            fids, fvalid = cand, valid
            fscores = jnp.where(valid, cs, -jnp.inf)
        tgt = jnp.where(fvalid, fids, D)  # invalid -> out of range, dropped
        if scatter_free:
            # survivor ids are unique (one inverted list per vector);
            # same sort + boundary-search expansion as make_ivf_search
            sc, ss = lax.sort((tgt, fscores), num_keys=1)
            bounds = jnp.searchsorted(sc, jnp.arange(D + 1, dtype=sc.dtype))
            lo, n = bounds[:-1], bounds[1:] - bounds[:-1]
            Wf = sc.shape[0]
            scores = jnp.where(n > 0, ss[jnp.clip(lo, 0, Wf - 1)], -jnp.inf)
            mask = n > 0
        else:
            scores = jnp.full(D, -jnp.inf, jnp.float32).at[tgt].max(
                fscores, mode="drop")
            mask = jnp.zeros(D, bool).at[tgt].max(fvalid, mode="drop")
        return scores, mask

    return run
