"""IndexShard: one shard = engine (write path) + searcher (read path).

Reference: org/elasticsearch/index/shard/IndexShard.java — lifecycle
(CREATED→RECOVERING→STARTED), stats, and the engine/searcher pairing.
"""
from __future__ import annotations

import os
from typing import Optional

from elasticsearch_tpu.analysis.registry import AnalysisRegistry
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mappings import Mappings
from elasticsearch_tpu.search.service import ShardSearcher


class IndexShard:
    def __init__(
        self,
        index_name: str,
        shard_id: int,
        mappings: Mappings,
        analysis: AnalysisRegistry,
        data_path: Optional[str] = None,
    ):
        self.index_name = index_name
        self.shard_id = shard_id
        self.state = "CREATED"
        translog_path = None
        if data_path:
            translog_path = os.path.join(data_path, index_name, str(shard_id), "translog")
        self.engine = Engine(mappings, analysis, translog_path=translog_path)
        self.searcher = ShardSearcher(self.engine.segments, mappings, analysis,
                                      shard_ord=shard_id, index_name=index_name)
        self.state = "STARTED"

    def recover(self):
        self.state = "RECOVERING"
        self.engine.recover_from_translog()
        self.engine.refresh()
        self.state = "STARTED"

    @property
    def segments(self):
        return self.engine.segments

    def refresh(self):
        self.engine.refresh()
        # searcher holds the same list object; refresh keeps it in sync
        self.searcher.segments = self.engine.segments

    def stats(self) -> dict:
        e = self.engine.stats
        return {
            "docs": {"count": self.engine.num_docs},
            "indexing": {"index_total": e.index_total, "delete_total": e.delete_total,
                         "index_time_in_millis": int(e.index_time_ms)},
            "get": {"total": e.get_total},
            "refresh": {"total": e.refresh_total},
            "flush": {"total": e.flush_total},
            "merges": {"total": e.merge_total},
            "segments": {
                "count": len(self.engine.segments),
                "memory_in_bytes": sum(s.memory_bytes() for s in self.engine.segments),
            },
            "translog": {"operations": self.engine.translog.size_in_ops},
        }

    def close(self):
        self.engine.close()
        self.state = "CLOSED"
