"""IndexShard: one shard = engine (write path) + searcher (read path).

Reference: org/elasticsearch/index/shard/IndexShard.java — lifecycle
(CREATED→RECOVERING→STARTED), stats, and the engine/searcher pairing.
"""
from __future__ import annotations

import os
from typing import Optional

from elasticsearch_tpu.analysis.registry import AnalysisRegistry
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.mappings import Mappings
from elasticsearch_tpu.search.service import ShardSearcher


class IndexShard:
    def __init__(
        self,
        index_name: str,
        shard_id: int,
        mappings: Mappings,
        analysis: AnalysisRegistry,
        data_path: Optional[str] = None,
    ):
        self.index_name = index_name
        self.shard_id = shard_id
        self.state = "CREATED"
        translog_path = None
        if data_path:
            translog_path = os.path.join(data_path, index_name, str(shard_id), "translog")
        self.engine = Engine(mappings, analysis, translog_path=translog_path,
                             index_name=index_name)
        self.searcher = ShardSearcher(self.engine.segments, mappings, analysis,
                                      shard_ord=shard_id, index_name=index_name)
        self.state = "STARTED"

    def recover(self) -> int:
        self.state = "RECOVERING"
        replayed = self.engine.recover_from_translog()
        self.engine.refresh()
        self.state = "STARTED"
        return replayed

    @property
    def segments(self):
        return self.engine.segments

    def refresh(self):
        self.engine.refresh()
        # searcher holds the same list object; refresh keeps it in sync
        self.searcher.segments = self.engine.segments

    def stats(self) -> dict:
        e = self.engine.stats
        segs = self.engine.segments
        fd_fields: dict = {}
        fd_evictions = fd_rehydrations = 0
        for seg in segs:
            for fname, b in seg.fielddata_field_bytes().items():
                fd_fields[fname] = fd_fields.get(fname, 0) + b
            ev, rh = seg.fielddata_evictions()
            fd_evictions += ev
            fd_rehydrations += rh
        comp_fields = self._completion_sizes(segs)
        indexing = {"index_total": e.index_total,
                    "delete_total": e.delete_total,
                    "index_time_in_millis": int(e.index_time_ms)}
        if e.types:
            indexing["types"] = {t: dict(ts) for t, ts in e.types.items()}
        return {
            "docs": {"count": self.engine.num_docs},
            "indexing": indexing,
            "get": {"total": e.get_total},
            "search": self.searcher.stats.to_json(),
            "refresh": {"total": e.refresh_total},
            "flush": {"total": e.flush_total},
            "merges": {"total": e.merge_total},
            "segments": {
                "count": len(segs),
                "memory_in_bytes": sum(s.memory_bytes() for s in segs),
            },
            # resident bytes + REAL evict/rehydrate counters: columns load
            # lazily into the evictable fielddata tier now
            # (resources/residency.py), so these move under HBM pressure
            "fielddata": {
                "memory_size_in_bytes": sum(fd_fields.values()),
                "evictions": fd_evictions,
                "rehydrations": fd_rehydrations,
                "fields": {f: {"memory_size_in_bytes": b}
                           for f, b in fd_fields.items()},
            },
            "completion": {
                "size_in_bytes": sum(comp_fields.values()),
                "fields": {f: {"size_in_bytes": b}
                           for f, b in comp_fields.items()},
            },
            # full TranslogStats shape (ops/generation/bytes/last_sync +
            # tragic/corruption accounting) for the monitor endpoint
            "translog": self.engine.translog.stats(),
            # replication safety (reference: SeqNoStats in the _stats
            # shards level): what checkpoint-based recovery negotiates on
            "seq_no": self.engine.seq_no_stats(),
            # Lucene CommitStats analogue: stable engine identity +
            # refresh/flush generation (the `shards` level echoes it)
            "commit": {"id": self.engine.commit_id,
                       "generation": e.refresh_total + e.flush_total + 1},
        }

    def _completion_sizes(self, segs) -> dict:
        """Per-field bytes held by the completion suggester's sorted
        prefix arrays (reference: CompletionStats per-field FST sizes)."""
        comp_names = [fm.name for fm in self.searcher.mappings.all_fields()
                      if getattr(fm, "type", None) == "completion"]
        if not comp_names:
            return {}
        from elasticsearch_tpu.search.suggest import _segment_completions

        out: dict = {}
        for seg in segs:
            for fname in comp_names:
                inputs, meta = _segment_completions(seg, fname)
                if not inputs:
                    continue
                b = sum(len(s.encode()) + 16 for s in inputs)
                b += sum(len(str(m[2]).encode()) for m in meta)
                out[fname] = out.get(fname, 0) + b
        return out

    def close(self):
        self.engine.close()
        self.state = "CLOSED"
