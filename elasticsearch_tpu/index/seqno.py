"""Sequence numbers and checkpoints: the replication-safety substrate.

Reference: org/elasticsearch/index/seqno/ — SequenceNumbers.java
(UNASSIGNED/NO_OPS_PERFORMED sentinels), LocalCheckpointTracker.java (the
max-contiguous-processed-seqno tracker, bitset over the window above the
checkpoint) and ReplicationTracker.java (global checkpoint = min local
checkpoint over the in-sync copy set). This is the ES 6.x seq-no upgrade
grafted onto the 2.0 architecture the paper reproduces: every engine op
gets a (primary term, seq no) identity assigned by the primary, each copy
tracks the highest contiguous seq no it has durably processed (its LOCAL
checkpoint), and the replication group derives the GLOBAL checkpoint that
peer recovery uses to replay only the missing op suffix instead of
re-shipping every live doc.

TPU relevance: segments here are device-resident arrays regenerated from
_source (BM25S-style eager scoring, arXiv:2407.03618), so a full-copy
recovery is not "rsync some files" — it re-freezes whole device slabs.
Checkpointed ops-replay is what makes a node bounce under write load
cheap.
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Set

#: no operations have been performed yet / empty-copy checkpoint
NO_OPS_PERFORMED = -1
#: an op that never got a sequence number (legacy translog frames)
UNASSIGNED_SEQ_NO = -2


class LocalCheckpointTracker:
    """Tracks the max contiguous processed seq no for ONE shard copy.

    The primary calls ``generate()`` to assign the next seq no under its
    term; every copy (primary included) calls ``mark_processed`` once the
    op is applied. Replica appends can arrive out of order (concurrent
    fanout), so processed seq nos above the checkpoint park in a set and
    the checkpoint advances only over a contiguous prefix — exactly the
    reference's CountedBitSet window, sans the fixed-size paging."""

    def __init__(self, max_seq_no: int = NO_OPS_PERFORMED,
                 local_checkpoint: int = NO_OPS_PERFORMED):
        self._lock = threading.Lock()
        self._next = max_seq_no + 1
        self._checkpoint = local_checkpoint
        self._pending: Set[int] = set()  # processed seq nos > checkpoint

    def generate(self) -> int:
        """Assign the next seq no (primary only)."""
        with self._lock:
            s = self._next
            self._next += 1
            return s

    def mark_processed(self, seq_no: int) -> None:
        if seq_no < 0:
            return  # UNASSIGNED: legacy op, contributes nothing
        with self._lock:
            if seq_no >= self._next:
                self._next = seq_no + 1
            if seq_no <= self._checkpoint:
                return  # duplicate delivery (retried fanout)
            self._pending.add(seq_no)
            while self._checkpoint + 1 in self._pending:
                self._checkpoint += 1
                self._pending.discard(self._checkpoint)

    def advance_to(self, checkpoint: int) -> None:
        """Adopt a checkpoint wholesale (full-copy recovery: the target
        received the source's complete state, so every seq no up to the
        source's local checkpoint is by definition processed here)."""
        with self._lock:
            if checkpoint <= self._checkpoint:
                return
            self._checkpoint = checkpoint
            self._next = max(self._next, checkpoint + 1)
            self._pending = {s for s in self._pending if s > checkpoint}
            while self._checkpoint + 1 in self._pending:
                self._checkpoint += 1
                self._pending.discard(self._checkpoint)

    @property
    def checkpoint(self) -> int:
        with self._lock:
            return self._checkpoint

    @property
    def max_seq_no(self) -> int:
        with self._lock:
            return self._next - 1

    def has_gaps(self) -> bool:
        """True when ops above the checkpoint arrived out of order and a
        hole is still unfilled (replica mid-fanout)."""
        with self._lock:
            return bool(self._pending)


class GlobalCheckpointTracker:
    """Derives the replication group's GLOBAL checkpoint: the highest seq
    no every IN-SYNC copy has processed (reference: ReplicationTracker —
    min over in-sync allocation ids' reported local checkpoints).

    Copies are keyed by an allocation id (engine commit id in-process,
    node id cross-host). A copy with no report yet counts as
    NO_OPS_PERFORMED, so adding an un-synced copy to the in-sync set
    drags the global checkpoint down — which is why recovery only
    graduates a copy INTO the set after its checkpoint caught up. The
    global checkpoint is monotonic: late/stale reports never move it
    backwards."""

    def __init__(self, in_sync: Optional[Iterable[str]] = None):
        self._lock = threading.Lock()
        self._local: Dict[str, int] = {}
        self._in_sync: Set[str] = set(in_sync or ())
        self._global = NO_OPS_PERFORMED

    def update_local(self, alloc_id: str, local_checkpoint: int) -> None:
        with self._lock:
            cur = self._local.get(alloc_id, NO_OPS_PERFORMED)
            if local_checkpoint > cur:
                self._local[alloc_id] = local_checkpoint
            self._recompute()

    def mark_in_sync(self, alloc_id: str,
                     local_checkpoint: Optional[int] = None) -> None:
        with self._lock:
            self._in_sync.add(alloc_id)
            if local_checkpoint is not None:
                cur = self._local.get(alloc_id, NO_OPS_PERFORMED)
                self._local[alloc_id] = max(cur, local_checkpoint)
            self._recompute()

    def remove(self, alloc_id: str) -> None:
        """A copy failed/left: it stops holding the global checkpoint
        back (reference: in-sync set shrink on shard-failed)."""
        with self._lock:
            self._in_sync.discard(alloc_id)
            self._local.pop(alloc_id, None)
            self._recompute()

    def set_in_sync(self, alloc_ids: Iterable[str]) -> None:
        with self._lock:
            self._in_sync = set(alloc_ids)
            self._recompute()

    def _recompute(self) -> None:
        if not self._in_sync:
            return  # nothing in sync: keep the last known value
        floor = min(self._local.get(a, NO_OPS_PERFORMED)
                    for a in self._in_sync)
        if floor > self._global:
            self._global = floor

    @property
    def global_checkpoint(self) -> int:
        with self._lock:
            return self._global

    @property
    def in_sync(self) -> Set[str]:
        with self._lock:
            return set(self._in_sync)
