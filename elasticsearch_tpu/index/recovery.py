"""Shard recovery: local (translog replay) and peer (primary → replica).

Reference: org/elasticsearch/indices/recovery/RecoverySourceHandler.java /
RecoveryTarget.java — peer recovery phase 1 copies segment files, phase 2
replays the translog operations that arrived during the copy; local
recovery (gateway) replays the on-disk translog into a fresh engine.

TPU adaptation: segments are derived from sources, so "copying segment
files" = shipping each live root doc (id, source, version, _type/_parent/
routing meta) and re-indexing it on the target with external_gte
versioning — the target's SegmentBuilder regenerates identical device
arrays. Phase 2 falls out for free: ops indexed on the primary during the
copy simply win the version comparison on the target.
"""
from __future__ import annotations

from typing import Optional

from elasticsearch_tpu.tracing import check_cancelled
from elasticsearch_tpu.utils.errors import VersionConflictException


def recover_peer(source_engine, target_engine) -> dict:
    """Copy the source engine's live docs into the target (phase 1 + 2).

    Returns recovery stats (docs copied / skipped). Cooperatively
    cancellable between docs (tracing/tasks.py) — an aborted stream
    leaves the target partially synced but versioned, so a later retry
    resumes idempotently."""
    copied = skipped = 0
    # snapshot the id list first: concurrent writes during recovery are
    # handled by versioning, not by locking the whole copy
    with source_engine._lock:
        ids = [(doc_id, loc.version, loc.doc_type, loc.parent, loc.routing)
               for doc_id, loc in source_engine._locations.items()
               if not loc.deleted]
    for doc_id, version, doc_type, parent, routing in ids:
        check_cancelled()
        got = source_engine.get(doc_id)
        if got is None:  # deleted mid-recovery; phase-2 op will handle it
            skipped += 1
            continue
        try:
            target_engine.index(
                doc_id, got["_source"], version=version,
                version_type="external_gte",
                doc_type=doc_type, parent=parent, routing=routing,
                _replay=True,
            )
            copied += 1
        except VersionConflictException:
            skipped += 1  # target already has a newer op
    target_engine.refresh()
    return {"copied": copied, "skipped": skipped}


def recover_local(shard) -> None:
    """Gateway recovery: replay the shard's own translog (wraps
    IndexShard.recover for symmetry with the reference's
    IndexShardGateway.recover)."""
    shard.recover()
