"""Shard recovery: local (translog replay) and peer (primary → replica).

Reference: org/elasticsearch/indices/recovery/RecoverySourceHandler.java /
RecoveryTarget.java. In the seq-no era peer recovery is CHECKPOINT-BASED:
the target reports its local checkpoint, the source runs a log-matching
check (the op at the target's checkpoint must carry the term the target
recorded for it), and when the retained translog covers the whole suffix
the source replays ONLY the ops above the checkpoint. The pre-seqno
full copy — ship every live doc and re-index on the target — survives as
the fallback for diverged copies, flushed-away ops, and legacy frames.

TPU adaptation: "copying segment files" = shipping each live root doc
(id, source, version, seq_no, term, _type/_parent/routing meta) and
re-indexing it with external_gte versioning — the target's SegmentBuilder
regenerates identical device arrays. That regeneration is exactly why
ops-replay matters here: BM25S-style eager device scoring makes a
segment rebuild expensive, so a node bounce must not be a full-copy
storm. Ops indexed on the source during either mode win the version
comparison on the target (phase 2 for free).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from elasticsearch_tpu.tracing import check_cancelled
from elasticsearch_tpu.utils.errors import (
    DocumentMissingException,
    VersionConflictException,
)
from elasticsearch_tpu.utils.faults import FAULTS


class RecoveryRegistry:
    """Per-index record of recovery executions, feeding the real
    ``GET {index}/_recovery`` / ``_cat/recovery`` endpoints (reference:
    RecoveriesCollection + RecoveryState). Entries are plain dicts the
    running recovery mutates in place:

        shard, type ("gateway"|"replica"|"peer"|"relocation"), mode
        ("ops"|"full"),
        stage ("init"|"index"|"translog"|"finalize"|"done"|"failed"),
        source, target, ops_replayed, docs_copied, docs_skipped,
        start_millis, total_time_in_millis

    ``mode`` is the acceptance-visible bit: "ops" proves the recovery
    replayed a translog suffix instead of re-shipping the shard.
    ``type=relocation`` marks allocator-driven moves (the live
    allocation loop — cluster/allocator.py); their entries additionally
    carry ``aot_seeded``, the count of peer-compiled ``.aotx`` executor
    blobs that rode the stream into this node's blob tier (fleet-wide
    AOT distribution: a joining node must compile nothing a peer
    already compiled)."""

    def __init__(self, max_entries: int = 64):
        self._lock = threading.Lock()
        self._entries: "deque[dict]" = deque(maxlen=max_entries)
        # in-flight streams this index is SERVING (recovery source side);
        # target-side state lives in the entries themselves
        self._source_active = 0

    def source_started(self) -> None:
        with self._lock:
            self._source_active += 1

    def source_finished(self) -> None:
        with self._lock:
            self._source_active = max(0, self._source_active - 1)

    @property
    def source_active(self) -> int:
        with self._lock:
            return self._source_active

    def start(self, shard: int, rtype: str, source: str = "local",
              target: str = "local") -> dict:
        entry = {"shard": shard, "type": rtype, "mode": None,
                 "stage": "init", "source": source, "target": target,
                 "ops_replayed": 0, "docs_copied": 0, "docs_skipped": 0,
                 "start_millis": int(time.time() * 1000),
                 "total_time_in_millis": 0, "_t0": time.perf_counter()}
        with self._lock:
            self._entries.append(entry)
        return entry

    @staticmethod
    def finish(entry: dict, ok: bool = True) -> None:
        entry["total_time_in_millis"] = int(
            (time.perf_counter() - entry.pop("_t0", time.perf_counter()))
            * 1000)
        entry["stage"] = "done" if ok else "failed"

    def entries(self, shard: Optional[int] = None) -> list:
        with self._lock:
            out = [dict(e) for e in self._entries]
        if shard is not None:
            out = [e for e in out if e["shard"] == shard]
        return out

    def latest_for(self, shard: int) -> Optional[dict]:
        with self._lock:
            for e in reversed(self._entries):
                if e["shard"] == shard:
                    return dict(e)
        return None

    def current(self) -> list:
        return [e for e in self.entries()
                if e["stage"] not in ("done", "failed")]


def recover_peer(source_engine, target_engine,
                 entry: Optional[dict] = None) -> dict:
    """Sync the target copy from the source (phase 1 + 2).

    Checkpoint handshake first: if the target's history is a clean prefix
    of the source's and the source's translog still holds every op above
    the target's local checkpoint, replay just that suffix
    (``mode="ops"``). Otherwise fall back to the full doc copy
    (``mode="full"``) — which ships TOMBSTONES too, so a target that
    already held a doc from an earlier aborted recovery still sees a
    delete that landed mid-copy, and prunes stale-era docs the source no
    longer has. Cooperatively cancellable between ops/docs
    (tracing/tasks.py); an aborted stream leaves the target partially
    synced but versioned, so a later retry resumes idempotently.

    Returns recovery stats; ``entry`` (a RecoveryRegistry dict) is
    mutated with live stage/counters when provided."""
    entry = entry if entry is not None else {}
    ckpt = target_engine.local_checkpoint
    ops = source_engine.recovery_ops(ckpt, target_engine.term_at(ckpt))
    if ops is not None:
        entry.update(mode="ops", stage="translog")
        replayed = skipped = 0
        for op in ops:
            check_cancelled()
            FAULTS.check("recovery.ops_replay", seq_no=op.get("seq_no"),
                         index=getattr(source_engine, "index_name", ""))
            try:
                target_engine.apply_translog_op(op)
                replayed += 1
            except (VersionConflictException, DocumentMissingException):
                # newer state already covers this op: a NO-OP, but its
                # seq no still counts as processed or the checkpoint
                # stalls on the hole forever
                target_engine.note_noop(op.get("seq_no"), op.get("term"))
                skipped += 1
            entry["ops_replayed"] = replayed
            entry["docs_skipped"] = skipped
        # an idle promoted primary has a newer term but no ops yet: the
        # term still propagates so the copy fences its old primary
        target_engine.bump_term(source_engine.primary_term)
        entry["stage"] = "finalize"
        target_engine.refresh()
        return {"mode": "ops", "ops_replayed": replayed, "skipped": skipped,
                "copied": 0}
    return _recover_full_copy(source_engine, target_engine, entry)


def _recover_full_copy(source_engine, target_engine, entry: dict) -> dict:
    """The pre-seqno stream: snapshot (ids + tombstones) and re-index.
    Concurrent writes during recovery are handled by versioning, not by
    locking the whole copy."""
    entry.update(mode="full", stage="index")
    copied = skipped = 0
    with source_engine._lock:
        snapshot = [(doc_id, loc.version, loc.doc_type, loc.parent,
                     loc.routing, loc.deleted, loc.seq_no, loc.term)
                    for doc_id, loc in source_engine._locations.items()]
        src_term = source_engine.primary_term
        src_ckpt = source_engine.local_checkpoint
        src_term_seq = dict(source_engine._term_seq)
    snapshot_ids = {doc_id for doc_id, *_ in snapshot}
    for doc_id, version, doc_type, parent, routing, deleted, seq_no, term \
            in snapshot:
        check_cancelled()
        if deleted:
            # tombstones ride the stream: a target holding the doc from
            # an earlier aborted recovery must see the delete (the id
            # snapshot used to drop these — docs deleted mid-copy were
            # lost to such targets forever)
            try:
                target_engine.delete(doc_id, version=version,
                                     version_type="external_gte",
                                     seq_no=seq_no, primary_term=term,
                                     _replay=True, _history=True)
            except DocumentMissingException:
                # target never held it: nothing to tombstone, but the
                # op's seq no is still processed (no-op)
                target_engine.note_noop(seq_no, term)
            except VersionConflictException:
                target_engine.note_noop(seq_no, term)
                skipped += 1
            continue
        got = source_engine.get(doc_id)
        if got is None:  # deleted mid-copy: its tombstone fans out live
            skipped += 1
            continue
        try:
            target_engine.index(
                doc_id, got["_source"], version=version,
                version_type="external_gte",
                doc_type=doc_type, parent=parent, routing=routing,
                seq_no=seq_no, primary_term=term,
                _replay=True, _history=True,
            )
            copied += 1
        except VersionConflictException:
            target_engine.note_noop(seq_no, term)
            skipped += 1  # target already has a newer op
        entry["docs_copied"] = copied
        entry["docs_skipped"] = skipped
    # prune stale-era docs the source no longer has: a diverged copy (a
    # demoted primary that acked nothing but applied locally) may hold
    # docs from an OLDER term, which external_gte can never remove. Docs
    # from the current term above the snapshot horizon are live-fanout
    # arrivals racing this copy and must survive.
    with target_engine._lock:
        extras = [(doc_id, loc.seq_no, loc.term)
                  for doc_id, loc in target_engine._locations.items()
                  if not loc.deleted and doc_id not in snapshot_ids
                  and (loc.term < src_term
                       or (loc.term == src_term and 0 <= loc.seq_no
                           <= src_ckpt))]
    for doc_id, stale_seq, stale_term in extras:
        try:
            # the tombstone reuses the pruned doc's own (seq no, term):
            # this is a local cleanup, not a replicated op — it must not
            # consume a number from the primary's stream nor extend a
            # term's recorded history (same rule as the distributed twin
            # in search_action._on_recover)
            target_engine.delete(doc_id, version_type="force", version=0,
                                 seq_no=stale_seq,
                                 primary_term=stale_term,
                                 _replay=True, _history=True)
        except DocumentMissingException:
            pass
    # the target now mirrors the source's state wholesale: adopt its
    # checkpoint + per-term history so the NEXT recovery can be ops-based
    target_engine.adopt_seq_state(src_term_seq, src_ckpt, src_term)
    entry["stage"] = "finalize"
    target_engine.refresh()
    return {"mode": "full", "copied": copied, "skipped": skipped,
            "ops_replayed": 0}


def recover_local(shard) -> None:
    """Gateway recovery: replay the shard's own translog (wraps
    IndexShard.recover for symmetry with the reference's
    IndexShardGateway.recover)."""
    shard.recover()
