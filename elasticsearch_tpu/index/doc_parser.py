"""Document parsing: JSON source → ParsedDocument.

Reference: org/elasticsearch/index/mapper/DocumentMapper.java +
DocumentParser-era logic inside FieldMapper.parse — walks the JSON tree,
flattens objects to dotted paths, applies analyzers for analyzed fields,
collects doc values, handles arrays (multi-values), copy_to, and dynamic
mapping of unseen fields.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.analysis.registry import AnalysisRegistry
from elasticsearch_tpu.index.mappings import FieldMapping, Mappings
from elasticsearch_tpu.utils.errors import MapperParsingException

Token = Tuple[str, int]


@dataclass
class ParsedDocument:
    doc_id: str
    source: dict
    # text field -> list of (term, position)
    text_tokens: Dict[str, List[Token]] = field(default_factory=dict)
    # keyword/numeric/bool/date/ip field -> list of values (multi-valued)
    doc_values: Dict[str, List[Any]] = field(default_factory=dict)
    # dense_vector field -> vector
    vectors: Dict[str, List[float]] = field(default_factory=dict)
    # field -> raw values for stored fields
    stored: Dict[str, List[Any]] = field(default_factory=dict)
    routing: Optional[str] = None

    def field_length(self, fname: str) -> int:
        return len(self.text_tokens.get(fname, ()))


class DocumentParser:
    def __init__(self, mappings: Mappings, analysis: AnalysisRegistry):
        self.mappings = mappings
        self.analysis = analysis

    def parse(self, doc_id: str, source: dict, routing: Optional[str] = None) -> ParsedDocument:
        if not isinstance(source, dict):
            raise MapperParsingException("document source must be a JSON object")
        parsed = ParsedDocument(doc_id=doc_id, source=source, routing=routing)
        self._walk(source, "", parsed)
        return parsed

    def _walk(self, obj: dict, prefix: str, parsed: ParsedDocument):
        for key, value in obj.items():
            full = f"{prefix}{key}"
            if isinstance(value, dict):
                fm = self.mappings.get(full)
                if fm is None or fm.type in ("object", "nested", "geo_point"):
                    if fm is not None and fm.type == "geo_point":
                        self._index_value(fm, value, parsed)
                    else:
                        self._walk(value, f"{full}.", parsed)
                    continue
                self._index_value(fm, value, parsed)
                continue
            if isinstance(value, list) and value and isinstance(value[0], dict):
                fm = self.mappings.get(full)
                if fm is not None and fm.type == "completion":
                    self._index_value(fm, value, parsed)
                    continue
                # array of objects: flatten each (nested semantics refined in R2)
                for item in value:
                    self._walk(item, f"{full}.", parsed)
                continue
            fm = self.mappings.get(full)
            if fm is None:
                fm = self.mappings.dynamic_map(full, value)
                if fm is None:
                    continue
            self._index_value(fm, value, parsed)
            for sub in fm.fields.values():
                self._index_value(sub, value, parsed)
            for target in fm.copy_to:
                tfm = self.mappings.get(target) or self.mappings.dynamic_map(target, value)
                if tfm is not None:
                    self._index_value(tfm, value, parsed)

    def _index_value(self, fm: FieldMapping, value: Any, parsed: ParsedDocument):
        values = value if isinstance(value, list) and not fm.is_vector else [value]
        if fm.type == "completion":
            # completion entries ({input, output, weight, payload} or plain
            # strings) are kept verbatim on host; the suggester builds its
            # per-segment sorted prefix array from them (search/suggest.py)
            parsed.stored.setdefault(fm.name, []).extend(values)
            return
        if fm.store:
            parsed.stored.setdefault(fm.name, []).extend(values)
        if fm.is_vector:
            norm = self.mappings.normalize_value(fm, value)
            if norm is not None:
                parsed.vectors[fm.name] = norm
            return
        for v in values:
            norm = self.mappings.normalize_value(fm, v)
            if norm is None:
                continue
            if fm.is_text:
                if not fm.index:
                    continue
                analyzer = self.analysis.get(fm.analyzer)
                toks = analyzer.analyze(str(norm))
                bucket = parsed.text_tokens.setdefault(fm.name, [])
                # multi-valued text: position gap of 100 between values (ES
                # position_increment_gap default) so phrases don't cross values
                offset = (bucket[-1][1] + 100) if bucket else 0
                bucket.extend((t, p + offset) for t, p in toks)
            elif fm.type == "token_count":
                analyzer = self.analysis.get(fm.analyzer)
                parsed.doc_values.setdefault(fm.name, []).append(len(analyzer.analyze(str(v))))
            else:
                if fm.is_keyword and fm.ignore_above and len(str(norm)) > fm.ignore_above:
                    continue
                if fm.type == "boolean":
                    norm = 1 if norm else 0
                if fm.type == "geo_point":
                    parsed.doc_values.setdefault(fm.name + ".lat", []).append(norm[0])
                    parsed.doc_values.setdefault(fm.name + ".lon", []).append(norm[1])
                    continue
                parsed.doc_values.setdefault(fm.name, []).append(norm)
