"""Document parsing: JSON source → ParsedDocument.

Reference: org/elasticsearch/index/mapper/DocumentMapper.java +
DocumentParser-era logic inside FieldMapper.parse — walks the JSON tree,
flattens objects to dotted paths, applies analyzers for analyzed fields,
collects doc values, handles arrays (multi-values), copy_to, and dynamic
mapping of unseen fields.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.analysis.registry import AnalysisRegistry
from elasticsearch_tpu.index.mappings import (
    KEYWORD_TYPES, NUMERIC_TYPES, TEXT_TYPES, FieldMapping, Mappings)
from elasticsearch_tpu.utils.errors import MapperParsingException

Token = Tuple[str, int]


@dataclass
class ParsedDocument:
    doc_id: str
    source: dict
    # text field -> list of (term, position)
    text_tokens: Dict[str, List[Token]] = field(default_factory=dict)
    # keyword/numeric/bool/date/ip field -> list of values (multi-valued)
    doc_values: Dict[str, List[Any]] = field(default_factory=dict)
    # dense_vector field -> vector
    vectors: Dict[str, List[float]] = field(default_factory=dict)
    # field -> raw values for stored fields
    stored: Dict[str, List[Any]] = field(default_factory=dict)
    routing: Optional[str] = None
    # block-join (reference: mapper/object/ObjectMapper nested=true → Lucene
    # block indexing): nested sub-docs indexed immediately before their root
    children: List["ParsedDocument"] = field(default_factory=list)
    nested_path: Optional[str] = None  # set on child docs
    nested_ord: int = -1  # index within the parent's array at nested_path
    # _type / _parent meta (parent-child joins) + anything merge must replay
    meta: Dict[str, Any] = field(default_factory=dict)

    def field_length(self, fname: str) -> int:
        return len(self.text_tokens.get(fname, ()))


def _ttl_to_millis(t) -> int:
    """_ttl value → millis: bare numbers (REST delivers them as strings)
    are millis; unit strings go through interval parsing; anything else is
    a 400 mapper error, never a raw ValueError."""
    from elasticsearch_tpu.utils.dates import interval_to_millis

    if isinstance(t, (int, float)):
        return int(t)
    s = str(t).strip()
    if s.replace(".", "", 1).isdigit():
        return int(float(s))
    try:
        ms = interval_to_millis(s)
    except ValueError:
        ms = None
    if ms is None:
        raise MapperParsingException(f"failed to parse ttl value [{t}]")
    return int(ms)


class DocumentParser:
    def __init__(self, mappings: Mappings, analysis: AnalysisRegistry):
        self.mappings = mappings
        self.analysis = analysis

    def parse(self, doc_id: str, source: dict, routing: Optional[str] = None,
              doc_type: Optional[str] = None, parent: Optional[str] = None,
              timestamp: Optional[Any] = None, ttl: Optional[Any] = None,
              ttl_expiry: Optional[int] = None) -> ParsedDocument:
        if not isinstance(source, dict):
            raise MapperParsingException("document source must be a JSON object")
        parsed = ParsedDocument(doc_id=doc_id, source=source, routing=routing)
        if doc_type:
            # _type/_parent as ordinary keyword doc-value columns (reference:
            # mapper/internal/TypeFieldMapper, ParentFieldMapper) — the
            # has_child/has_parent join reads them back from the segment
            parsed.doc_values["_type"] = [str(doc_type)]
            parsed.meta["_type"] = str(doc_type)
        if parent:
            parsed.doc_values["_parent"] = [str(parent)]
            parsed.meta["_parent"] = str(parent)
        if routing:
            parsed.meta["routing"] = str(routing)
        self._walk(source, "", parsed)
        self._index_meta_fields(parsed, source, timestamp, ttl, ttl_expiry)
        return parsed

    def _index_meta_fields(self, parsed: ParsedDocument, source: dict,
                           timestamp, ttl, ttl_expiry) -> None:
        """Opt-in meta fields (reference: mapper/internal/
        TimestampFieldMapper.java:1-336, TTLFieldMapper.java:1-228,
        SizeFieldMapper, FieldNamesFieldMapper). Resolved values land in
        parsed.meta so merges and translog replay reproduce them exactly."""
        import json as _json
        import time as _time

        from elasticsearch_tpu.utils.dates import parse_date

        m = self.mappings
        now_ms = int(_time.time() * 1000)
        if m._timestamp_enabled:
            if timestamp is not None:
                ts = (int(timestamp) if isinstance(timestamp, (int, float))
                      else int(parse_date(
                          timestamp, "strict_date_optional_time||epoch_millis")))
            elif m._timestamp_default not in (None, "now"):
                ts = int(parse_date(
                    m._timestamp_default,
                    "strict_date_optional_time||epoch_millis"))
            else:
                ts = now_ms
            parsed.doc_values["_timestamp"] = [ts]
            parsed.meta["timestamp"] = ts
        if m._ttl_enabled:
            if ttl_expiry is not None:
                expiry = int(ttl_expiry)
            else:
                t = ttl if ttl is not None else m._ttl_default
                if t is None:
                    expiry = None
                else:
                    ttl_ms = _ttl_to_millis(t)
                    # the expiry base is the op's timestamp even when the
                    # _timestamp meta field itself is disabled (reference:
                    # TTLFieldMapper reads the IndexRequest timestamp)
                    base = parsed.meta.get("timestamp")
                    if base is None and timestamp is not None:
                        base = (int(timestamp)
                                if isinstance(timestamp, (int, float))
                                else int(parse_date(
                                    timestamp,
                                    "strict_date_optional_time"
                                    "||epoch_millis")))
                    if base is None:
                        base = now_ms
                    expiry = int(base + ttl_ms)
                    if ttl is not None and expiry <= now_ms:
                        # an explicit ttl whose expiry (timestamp + ttl) is
                        # already past is a request error (reference:
                        # AlreadyExpiredException from TTLFieldMapper)
                        from elasticsearch_tpu.utils.errors import \
                            AlreadyExpiredException

                        raise AlreadyExpiredException(
                            parsed.doc_id if hasattr(parsed, "doc_id")
                            else "", base, ttl_ms)
            if expiry is not None:
                parsed.doc_values["_ttl"] = [expiry]
                parsed.meta["ttl_expiry"] = expiry
        if m._size_enabled:
            parsed.doc_values["_size"] = [
                len(_json.dumps(source, separators=(",", ":")))]
        if m._field_names_enabled:
            names = (set(parsed.text_tokens) | set(parsed.doc_values)
                     | set(parsed.vectors))
            names -= {"_all", "_timestamp", "_ttl", "_size"}
            if names:
                parsed.doc_values["_field_names"] = sorted(names)

    def _nested_children(self, full: str, items: List[dict], parsed: ParsedDocument):
        """Each object under a nested path becomes its own block doc with
        fields at the full dotted path; searched via NestedQuery's
        child→parent scatter join."""
        for i, item in enumerate(items):
            child = ParsedDocument(
                doc_id=f"{parsed.doc_id}|{full}|{i}",
                source=None,  # child _source lives inside the root's _source
                nested_path=full,
                nested_ord=i,
            )
            if isinstance(item, dict):
                self._walk(item, f"{full}.", child)
            parsed.children.append(child)

    def _walk(self, obj: dict, prefix: str, parsed: ParsedDocument):
        for key, value in obj.items():
            full = f"{prefix}{key}"
            if isinstance(value, dict):
                fm = self.mappings.get(full)
                if full in self.mappings.nested_paths:
                    self._nested_children(full, [value], parsed)
                    continue
                if fm is None or fm.type in ("object", "nested", "geo_point",
                                             "geo_shape"):
                    if fm is not None and fm.type in ("geo_point",
                                                      "geo_shape"):
                        self._index_value(fm, value, parsed)
                    else:
                        self._walk(value, f"{full}.", parsed)
                    continue
                self._index_value(fm, value, parsed)
                continue
            if isinstance(value, list) and value and isinstance(value[0], dict):
                fm = self.mappings.get(full)
                if fm is not None and fm.type == "completion":
                    self._index_value(fm, value, parsed)
                    continue
                if fm is not None and fm.type == "geo_shape":
                    # array of shapes: each indexed, not object-flattened
                    for shape in value:
                        self._index_value(fm, shape, parsed)
                    continue
                if full in self.mappings.nested_paths:
                    self._nested_children(full, value, parsed)
                    continue
                # array of objects (non-nested): flatten each — values from
                # different objects mingle, the documented ES object-array
                # semantics that nested mappings exist to avoid
                for item in value:
                    self._walk(item, f"{full}.", parsed)
                continue
            fm = self.mappings.get(full)
            if fm is None:
                fm = self.mappings.dynamic_map(full, value)
                if fm is None:
                    continue
            self._index_value(fm, value, parsed)
            # multi-fields/copy_to re-index the same value — the _all stream
            # gets it once, from the root field only
            for sub in fm.fields.values():
                self._index_value(sub, value, parsed, to_all=False)
            for target in fm.copy_to:
                tfm = self.mappings.get(target) or self.mappings.dynamic_map(target, value)
                if tfm is not None:
                    self._index_value(tfm, value, parsed, to_all=False)

    _ALL_TYPES = TEXT_TYPES | KEYWORD_TYPES | NUMERIC_TYPES | {
        "date", "boolean", "ip", "text", "keyword"}

    def _append_to_all(self, parsed: ParsedDocument, raw: Any):
        """Feed one value into the _all token stream (reference:
        mapper/internal/AllFieldMapper.java — every included field's value
        re-analyzed with the index default analyzer, values separated by a
        position gap so phrases don't cross field boundaries)."""
        analyzer = self.analysis.get(self.mappings.default_analyzer)
        toks = analyzer.analyze(str(raw))
        if not toks:
            return
        bucket = parsed.text_tokens.setdefault("_all", [])
        offset = (bucket[-1][1] + 100) if bucket else 0
        bucket.extend((t, p + offset) for t, p in toks)

    def _index_value(self, fm: FieldMapping, value: Any, parsed: ParsedDocument,
                     to_all: bool = True):
        values = value if isinstance(value, list) and not fm.is_vector else [value]
        if (to_all and self.mappings._all_enabled and fm.include_in_all is not False
                and fm.index and not fm.name.startswith("_")
                and fm.type in self._ALL_TYPES):
            for v in values:
                if v is not None:
                    self._append_to_all(parsed, v)
        if fm.type == "completion":
            # completion entries ({input, output, weight, payload} or plain
            # strings) are kept verbatim on host; the suggester builds its
            # per-segment sorted prefix array from them (search/suggest.py)
            parsed.stored.setdefault(fm.name, []).extend(values)
            return
        if fm.store:
            parsed.stored.setdefault(fm.name, []).extend(values)
        if fm.is_vector:
            norm = self.mappings.normalize_value(fm, value)
            if norm is not None:
                parsed.vectors[fm.name] = norm
            return
        for v in values:
            norm = self.mappings.normalize_value(fm, v)
            if norm is None:
                continue
            if fm.is_text:
                if not fm.index:
                    continue
                analyzer = self.analysis.get(fm.analyzer)
                toks = analyzer.analyze(str(norm))
                bucket = parsed.text_tokens.setdefault(fm.name, [])
                # multi-valued text: position gap of 100 between values (ES
                # position_increment_gap default) so phrases don't cross values
                offset = (bucket[-1][1] + 100) if bucket else 0
                bucket.extend((t, p + offset) for t, p in toks)
            elif fm.type == "token_count":
                analyzer = self.analysis.get(fm.analyzer)
                parsed.doc_values.setdefault(fm.name, []).append(len(analyzer.analyze(str(v))))
            else:
                if fm.is_keyword and fm.ignore_above and len(str(norm)) > fm.ignore_above:
                    continue
                if fm.type == "boolean":
                    norm = 1 if norm else 0
                if fm.type == "geo_point":
                    parsed.doc_values.setdefault(fm.name + ".lat", []).append(norm[0])
                    parsed.doc_values.setdefault(fm.name + ".lon", []).append(norm[1])
                    continue
                if fm.type == "geo_shape":
                    # covering-cell tokens under `<field>.__cells`; freeze's
                    # field discovery auto-builds the keyword postings the
                    # geo_shape query filters on (search/geo.py)
                    from elasticsearch_tpu.search.geo import \
                        shape_index_tokens
                    from elasticsearch_tpu.utils.errors import \
                        QueryParsingException

                    if not isinstance(norm, dict):
                        raise MapperParsingException(
                            f"geo_shape field [{fm.name}] expects a GeoJSON "
                            "object")
                    try:
                        toks = shape_index_tokens(norm)
                    except QueryParsingException as e:
                        # index-time parse failures are mapper errors
                        raise MapperParsingException(
                            f"failed to parse [{fm.name}]: {e}") from e
                    parsed.doc_values.setdefault(
                        fm.name + ".__cells", []).extend(toks)
                    continue
                parsed.doc_values.setdefault(fm.name, []).append(norm)
