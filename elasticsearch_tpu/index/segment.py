"""TPU segment: immutable, device-resident columnar index structures.

This replaces Lucene's on-disk segment codecs (reference: Lucene 5.2 postings
formats used by org/elasticsearch/index/engine/InternalEngine.java and
index/store/). Where Lucene stores block-compressed postings streamed
doc-at-a-time through iterators, a TpuSegment keeps every searchable
structure as a *static-shaped dense array in device memory*:

- Inverted index per indexed field: flattened CSR — ``doc_ids[nnz]``,
  ``tf[nnz]``, ``tfnorm[nnz]`` (BM25 tf-normalization precomputed at freeze,
  the BM25S "eager scoring" trick), plus host-side ``offsets[V+1]`` and the
  term dictionary. Query programs slice per-term runs with
  ``lax.dynamic_slice`` at power-of-two bucket widths, so one compiled
  program serves every query of the same shape class.
- ``term_ids[nnz]`` (which term each posting belongs to) enables whole-field
  ``segment_sum`` reductions — the basis of the terms aggregation.
- Doc values per numeric/keyword/date/bool field: dense columns padded to
  ``max_docs`` (power of two). 64-bit values (longs, date millis) keep an
  exact int32 (hi, lo) pair for exact range comparison plus an f32
  channel for arithmetic, and an exact numpy mirror on host for fetch.
  Columns freeze as HOST arrays and load lazily into the EVICTABLE
  fielddata residency tier on first search touch (resources/residency.py
  — the fielddata breaker gates the load, pressure evicts LRU device
  copies, the next touch rehydrates from the retained host array).
- Dense vectors: one ``[max_docs, dims]`` slab (f32; bf16 copy made by the
  kNN op) — MXU-friendly.
- ``live``: deletion mask (Lucene liveDocs equivalent).
- ``_source``/stored fields/_id map stay on host (never needed on device).

All device arrays are padded so that *every* segment exposes shapes drawn
from a small set of buckets; XLA compiles one program per bucket, not per
segment.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field as dfield
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.index.doc_parser import ParsedDocument
from elasticsearch_tpu.index.mappings import Mappings
from elasticsearch_tpu.utils.shapes import pow2_bucket, pad_to

# BM25 constants (Lucene BM25Similarity defaults, k1=1.2 b=0.75)
K1 = 1.2
B = 0.75


def _jnp():
    import jax.numpy as jnp

    return jnp


def _device_put(x):
    # every always-resident segment placement goes through the residency
    # choke point (accounting; admission control is the engine's
    # per-segment breaker charge at freeze — see _charge_segment)
    from elasticsearch_tpu import resources

    return resources.RESIDENCY.device_put(x, tier="segments")


def split_i64(v: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Split int64 into (hi, lo) int32 pair preserving order lexicographically.

    hi = v >> 32 (arithmetic, fits int32 for the full i64 range); lo = the
    unsigned low 32 bits biased by -2^31 so it fits int32 while keeping the
    ordering monotonic. (hi1,lo1) < (hi2,lo2) lexicographically iff v1 < v2 —
    used for exact 64-bit range masks on a device without native i64.
    """
    v = v.astype(np.int64)
    hi = (v >> 32).astype(np.int32)
    lo = ((v & 0xFFFFFFFF) - (1 << 31)).astype(np.int32)
    return hi, lo


# HbmBudget lives in resources/breakers.py now (the ad-hoc budget grew
# into the ES-shaped hierarchy); re-exported here for embedders/tests
# that construct standalone budgets.
from elasticsearch_tpu.resources import BREAKERS
from elasticsearch_tpu.resources.breakers import HbmBudget  # noqa: F401

# the fielddata-tier breaker now governs every lazily-loaded evictable
# device copy (columns, vector slabs, dense impact blocks) — kept under
# the old name for embedders. NOTE: import-time binding to the default
# service; in-package code resolves via resources.RESIDENCY.breakers at
# use time so swapped test singletons stay consistent
DENSE_IMPACT_BUDGET = BREAKERS.breaker("fielddata")

# node-wide breaker for always-resident segment HBM (postings, live
# masks): every freeze charges the segment's memory_bytes() against it;
# exhaustion fails the REQUEST with a typed CircuitBreakingException
# instead of device-OOMing the node (reference:
# common/breaker/CircuitBreaker.java via resources/breakers.py).
# Merges release-then-charge and never trip (they net-shrink memory).
SEGMENT_HBM_BUDGET = BREAKERS.breaker("segments")


def build_dense_impact(
    doc_ids_host: np.ndarray,
    tfnorm_host: np.ndarray,
    offsets: np.ndarray,
    df: np.ndarray,
    max_docs: int,
    *,
    df_threshold: Optional[int] = None,
    budget_bytes: int = 1 << 30,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Dense impact block for frequent terms (hybrid dense/sparse scoring).

    Terms whose postings run is long (df >= threshold) dominate scatter cost
    on TPU; we densify exactly those into rows of an ``impact[F_pad, D]``
    matrix so a query batch scores them with ONE MXU matmul
    (``qw[Q, F] @ impact[F, D]``), while the short tail stays CSR (cheap
    scatter). This is the BM25S eager-impact idea restructured for the MXU.

    Returns (dense_rows int32[V] with -1 for sparse terms, impact f32[F_pad, D])
    or None when no term qualifies.
    """
    V = df.shape[0]
    if V == 0:
        return None
    if df_threshold is None:
        # empirical sweet spot on TPU v5e: densify runs longer than D/256
        # (tail scatter windows stay <=256 wide; F stays within budget)
        df_threshold = max(128, max_docs // 256)
    cand = np.nonzero(df >= df_threshold)[0]
    if cand.size == 0:
        return None
    # cap by HBM budget on the PADDED row count (F_pad x D x 4 is what gets
    # allocated): round the cap down to a power of two, keep the highest-df
    # terms (longest runs = biggest win)
    max_rows = int(budget_bytes // (4 * max_docs))
    if max_rows < 8:  # F_pad minimum is 8
        return None
    max_rows = 1 << (max_rows.bit_length() - 1)
    if cand.size > max_rows:
        cand = cand[np.argsort(-df[cand], kind="stable")[:max_rows]]
        cand.sort()
    F_pad = pow2_bucket(cand.size, minimum=8)
    dense_rows = np.full(V, -1, dtype=np.int32)
    dense_rows[cand] = np.arange(cand.size, dtype=np.int32)
    impact = np.zeros((F_pad, max_docs), dtype=np.float32)
    for row, tid in enumerate(cand):
        s, e = int(offsets[tid]), int(offsets[tid + 1])
        impact[row, doc_ids_host[s:e]] = tfnorm_host[s:e]
    return dense_rows, impact


@dataclass
class InvertedField:
    """Frozen inverted index for one field (text or keyword)."""

    name: str
    vocab: Dict[str, int]  # term -> term id (host)
    terms: List[str]  # term id -> term
    df: np.ndarray  # int32[V] doc freq
    cf: np.ndarray  # int64[V] collection (total term) freq
    offsets: np.ndarray  # int64[V+1] CSR offsets into postings (host)
    # device arrays (jax) — padded to pow2 nnz
    doc_ids: Any  # int32[nnz_pad], padded entries = max_docs sentinel
    tf: Any  # f32[nnz_pad]
    tfnorm: Any  # f32[nnz_pad] — tf*(k1+1)/(tf+k1*(1-b+b*len/avg))
    term_ids: Any  # int32[nnz_pad], padded = V sentinel
    nnz: int
    num_docs: int
    total_terms: int
    avg_len: float
    # positions: host CSR aligned with postings order (for phrase/span)
    pos_offsets: Optional[np.ndarray] = None  # int64[nnz+1]
    positions: Optional[np.ndarray] = None  # int32[total_positions]
    # host mirror of unpadded doc_ids (phrase verification, merges)
    doc_ids_host: Optional[np.ndarray] = None
    # host mirror of tfnorm (dense-impact build, merges)
    tfnorm_host: Optional[np.ndarray] = None
    # host mirror of raw tf (on-disk codec, index/store.py)
    tf_host: Optional[np.ndarray] = None
    # lazy cache: sorted terms for prefix/wildcard expansion
    _sorted_terms: Any = None
    # device positional CSR (padded) — built lazily for phrase programs
    _pos_dev: Any = None
    # host mirror of the dense impact block (set when _dense is built)
    _dense_host: Any = None
    # lazy hybrid dense-impact block: False = checked & permanently absent
    # (no qualifying terms); (dense_rows np.i32[V], ResidentArray handle)
    # when present; None = not built yet (incl. transient budget denial)
    _dense: Any = None
    _dense_lock: Any = dfield(default_factory=threading.Lock)
    # lazy cross-device postings split for an OVERSIZED field (see
    # parallel/postings_shard.py): None = unchecked, False = declined
    _pshard: Any = None
    max_docs: int = 0

    def wants_postings_shard(self) -> bool:
        """True when this field's postings exceed the single-device budget
        (mesh_service uses this to route such indices to the host loop,
        where the sharded program runs)."""
        from elasticsearch_tpu.parallel.postings_shard import \
            POSTINGS_SHARD_NNZ

        return self.nnz >= POSTINGS_SHARD_NNZ

    def postings_split(self):
        """Build-once term-range split across devices, or None (field under
        the threshold, single device, or no host mirror to split from)."""
        if self._pshard is False:
            return None
        if self._pshard is not None:
            return self._pshard
        if not self.wants_postings_shard():
            return None
        with self._dense_lock:
            if self._pshard is None:
                from elasticsearch_tpu.parallel.postings_shard import \
                    build_split

                split = build_split(self, self.max_docs)
                self._pshard = split if split is not None else False
        return self._pshard or None

    @staticmethod
    def _dense_get(d):
        """(rows, device impact) from a built block, rehydrating an
        evicted one — BEST-EFFORT like the build: a breaker-denied
        rehydration falls back to the scatter path (None) instead of
        failing the request the block only accelerates."""
        from elasticsearch_tpu.utils.errors import CircuitBreakingException

        rows, handle = d
        try:
            return rows, handle.get()
        except CircuitBreakingException:
            return None

    def dense_block(self):
        """Lazy (dense_rows, device impact) for hybrid scoring, or None.

        Frequent terms (long postings runs) score via one MXU matmul instead
        of scatter-adds; see build_dense_impact. Built on first search that
        touches this field; small segments have no qualifying terms and pay
        nothing. Registered as an EVICTABLE fielddata-tier residency handle
        (resources/residency.py): when HBM is tight the registry evicts LRU
        copies first, and a denied build leaves the field on the scatter
        path to retry once budget frees up (only 'no qualifying terms' is
        cached as a permanent no). An evicted block rehydrates from the
        host mirror on the next touch.
        """
        d = self._dense
        if d is False:
            return None
        if d is not None:
            return self._dense_get(d)
        with self._dense_lock:
            if self._dense is False:
                return None
            if self._dense is not None:
                return self._dense_get(self._dense)
            if self.doc_ids_host is None or not self.max_docs:
                self._dense = False
                return None
            # budget check BEFORE the (expensive) host-side build; a denial
            # is transient — leave _dense = None so a later query retries.
            # Resolve the breaker through the LIVE registry (the one the
            # put_array charge below goes to) — the import-time module
            # binding would read a stale service when tests swap the
            # resources singletons
            from elasticsearch_tpu import resources

            min_bytes = 8 * 4 * self.max_docs
            granted = min(
                1 << 30,
                resources.RESIDENCY.breakers.breaker("fielddata").remaining())
            if granted < min_bytes:
                return None
            tfn = self.tfnorm_host
            if tfn is None:
                tfn = np.ones(self.nnz, dtype=np.float32)
            built = build_dense_impact(
                self.doc_ids_host, tfn, self.offsets, self.df, self.max_docs,
                budget_bytes=granted,
            )
            if built is None:
                self._dense = False  # no qualifying terms: permanent
                return None
            rows, impact = built
            # SURVEY §6 "quantized impacts" lever: bf16 device storage
            # halves the block's HBM and feeds the MXU without a cast
            # (~0.4% relative tfnorm error; bench quantifies the ranking
            # agreement). Host mirror stays f32 for mesh restacking.
            bf16 = os.environ.get("ESTPU_IMPACT_BF16", "").lower() in (
                "1", "true")
            dtype = None
            if bf16:
                import jax.numpy as jnp

                dtype = jnp.bfloat16
            # best_effort: the block is a pure acceleration — a denied
            # reservation (even after LRU eviction) leaves the field on
            # the scatter path instead of failing the request
            handle = resources.RESIDENCY.put_array(
                impact, label=f"dense_impact:{self.name}",
                tier="fielddata", dtype=dtype, best_effort=True)
            if handle is None:
                return None  # budget tight: retry later
            # host mirror: mesh prims restack [S, F, D] from it — pulling
            # the device copy back would be a huge d2h transfer (and on
            # network-attached chips big d2h pulls degrade the session)
            self._dense_host = impact
            self._dense = (rows, handle)
            return rows, handle.get()

    @property
    def nnz_pad(self) -> int:
        """Padded postings length WITHOUT forcing device placement (the
        lazy doc_ids accessor would device_put an oversized field's full
        array just to read its shape)."""
        return int(self._doc_ids_raw.shape[0])

    @property
    def vocab_size(self) -> int:
        return len(self.terms)

    def term_id(self, term: str) -> int:
        return self.vocab.get(term, -1)

    def term_slice(self, term: str) -> Tuple[int, int]:
        """(start, length) of the term's postings run; (0, 0) if absent."""
        tid = self.vocab.get(term, -1)
        if tid < 0:
            return 0, 0
        return int(self.offsets[tid]), int(self.offsets[tid + 1] - self.offsets[tid])

    def idf(self, term: str, num_docs: Optional[int] = None, df: Optional[int] = None) -> float:
        """Lucene 5 BM25 idf: ln(1 + (N - df + 0.5)/(df + 0.5)).

        num_docs/df overrides support dfs_query_then_fetch global stats.
        """
        n = self.num_docs if num_docs is None else num_docs
        d = (self.df[self.vocab[term]] if term in self.vocab else 0) if df is None else df
        return float(np.log(1.0 + (n - d + 0.5) / (d + 0.5)))


def _lazy_device_field(name: str):
    """Attach a lazy device-placement accessor for one postings array.

    Freeze passes device arrays for ordinary fields (placement cost paid
    once, off the query path) but HOST arrays for an OVERSIZED field — its
    scoring runs through the cross-device postings split
    (parallel/postings_shard.py), which slices the host mirror per device;
    the full single-device copy these accessors hand out must not be
    allocated unless some path actually asks for it (phrase/positional
    programs, terms aggs over the field). First access device_puts and
    caches, so a fallback path pays the transfer once, not per query.

    Attached after class creation: defining the property inside the
    dataclass body would make the descriptor look like a field default.
    """
    raw = f"_{name}_raw"

    def _get(self):
        v = self.__dict__[raw]
        if isinstance(v, np.ndarray):
            v = _device_put(v)
            self.__dict__[raw] = v
        return v

    def _set(self, v):
        self.__dict__[raw] = v

    return property(_get, _set)


for _pname in ("doc_ids", "tf", "tfnorm", "term_ids"):
    setattr(InvertedField, _pname, _lazy_device_field(_pname))
del _pname


def _resident_field(name: str):
    """Attach a lazy EVICTABLE device accessor for one doc-value column
    array (the fielddata tier of resources/residency.py).

    Freeze stores the HOST array; the first search that touches the
    column registers it with the residency registry (charging the
    fielddata breaker — this is the "lazy column load" that can trip
    ``indices.breaker.fielddata.limit``) and hands out the device copy.
    Under HBM pressure the registry drops the device copy LRU-first and
    the next touch rehydrates from the retained host array — the
    reference's fielddata load/evict cycle, with the host mirror playing
    the role of the Lucene disk image. Legacy callers that assign an
    already-placed device array keep working, unaccounted (bench paths).
    """
    raw = f"_{name}_res"
    raw_lock = f"_{name}_res_lock"

    def _get(self):
        v = self.__dict__.get(raw)
        if v is None:
            return None
        from elasticsearch_tpu.resources.residency import ResidentArray

        if isinstance(v, ResidentArray):
            return v.get()
        if isinstance(v, np.ndarray):
            # first-touch registration is locked (dict.setdefault is
            # atomic under the GIL): two concurrent searches must not
            # each charge the breaker and upload the same slab
            lock = self.__dict__.setdefault(raw_lock, threading.Lock())
            with lock:
                v = self.__dict__.get(raw)
                if isinstance(v, np.ndarray):
                    from elasticsearch_tpu import resources

                    v = resources.RESIDENCY.put_array(
                        v, label=f"column:{self.name}.{name}",
                        tier="fielddata")
                    self.__dict__[raw] = v
            if isinstance(v, ResidentArray):
                return v.get()
        return v  # pre-placed device array (legacy construction)

    def _set(self, v):
        self.__dict__[raw] = v

    return property(_get, _set)


@dataclass
class NumericColumn:
    name: str
    values: Any  # f32[max_docs] (device) — arithmetic channel, value - offset
    exists: Any  # bool[max_docs] (device)
    hi: Any = None  # int32[max_docs] exact pair (device) for 64-bit types
    lo: Any = None
    exact: Optional[np.ndarray] = None  # host i64/f64 mirror for fetch/sort
    exists_host: Optional[np.ndarray] = None  # host mirror (no d2h pulls)
    kind: str = "double"  # long|integer|double|float|date|boolean|ip|...
    # 64-bit kinds (dates = epoch millis ~1.7e12) overflow f32 precision, so
    # the arithmetic channel stores segment-relative values: f32 = exact -
    # offset, with offset = segment min. Consumers add offset back (aggs) or
    # shift query bounds down (range masks); exact compares use (hi, lo).
    offset: float = 0.0

    @property
    def has_pair(self) -> bool:
        """True when the exact (hi, lo) int32 pair exists. Presence check
        only — must NOT force the lazy device load (the mesh prims ask
        this and then restack from the host `exact` mirror)."""
        return self.__dict__.get("_hi_res") is not None


@dataclass
class KeywordColumn:
    """Ordinal doc values for keyword fields (single-valued fast path).

    Multi-valued keyword aggregation goes through the InvertedField's
    term_ids/segment_sum path instead; ords are -1 where missing/multi.
    """

    name: str
    ords: Any  # int32[max_docs] (device), -1 = missing
    exists: Any  # bool[max_docs]
    host_values: List[Optional[List[str]]] = dfield(default_factory=list)
    ords_host: Optional[np.ndarray] = None
    exists_host: Optional[np.ndarray] = None


@dataclass
class VectorColumn:
    name: str
    vecs: Any  # f32[max_docs, dims] (device)
    exists: Any  # bool[max_docs]
    dims: int
    vecs_host: Any = None  # host mirror (mesh stacking, IVF build)
    exists_host: Any = None
    similarity: str = "cosine"
    # lazy IVF-flat coarse quantizer (ops/ivf.py); False = build attempted
    # and declined (too few vectors)
    _ivf: Any = None
    # lazy PQ tier (ops/pq.py): None = unbuilt OR placement breaker-denied
    # (retryable — dense-impact discipline), False = declined (too few
    # vectors), PqIndex = ready. Host parts memoized separately so a
    # breaker denial never re-pays the k-means train + encode.
    _pq: Any = None
    _pq_parts: Any = None
    # memoized content-address (slabs are immutable; SHA-1 of the full
    # slab per freeze/snapshot call is measurable host CPU)
    _ck: Any = None
    _ck_max: int = -1

    def cache_key(self, max_docs: int) -> str:
        if self._ck is None or self._ck_max != max_docs:
            from elasticsearch_tpu.index import ivf_cache

            vh = (self.vecs_host if self.vecs_host is not None
                  else np.asarray(self.vecs))
            eh = (self.exists_host if self.exists_host is not None
                  else np.asarray(self.exists))
            self._ck = ivf_cache.content_key(vh, eh, self.similarity,
                                             max_docs)
            self._ck_max = max_docs
        return self._ck

    def get_ivf(self, max_docs: int):
        """Build-once IVF index over this (immutable) slab, consulting the
        content-addressed blob cache first so restarts / snapshot restores
        reload the persisted quantizer instead of re-running k-means
        (index/ivf_cache.py; counters ivf_cache_hit / ivf_build)."""
        # (uses the host mirrors — never forces the lazy device slab)
        if self._ivf is None:
            from elasticsearch_tpu.index import ivf_cache
            from elasticsearch_tpu.monitor import kernels
            from elasticsearch_tpu.ops.ivf import build_ivf

            vh = (self.vecs_host if self.vecs_host is not None
                  else np.asarray(self.vecs))
            eh = (self.exists_host if self.exists_host is not None
                  else np.asarray(self.exists))
            key = self.cache_key(max_docs)
            idx = ivf_cache.load(key)
            if idx is None:
                idx = build_ivf(vh, eh, max_docs, metric=self.similarity)
                if idx is not None:
                    kernels.record("ivf_build")
                    ivf_cache.store(key, idx)
            self._ivf = idx if idx is not None else False
        return self._ivf or None

    def get_pq(self, max_docs: int):
        """Build-once PQ tier over this (immutable) slab.

        Host parts come from the content-addressed blob cache when the
        slab content matches a persisted build (counter pq_cache_hit),
        else from a fresh train+encode (counter pq_build, re-persisted).
        Device placement is BEST-EFFORT: the uint8 code array registers
        as an evictable fielddata-tier handle, and a breaker denial
        returns None while leaving the build memoized — the caller keeps
        the exact fine-rank path and a later query retries placement
        only (the dense-impact contract)."""
        if self._pq is False:
            return None
        if self._pq is not None:
            return self._pq
        from elasticsearch_tpu.index import ivf_cache
        from elasticsearch_tpu.monitor import kernels
        from elasticsearch_tpu.ops.pq import build_pq, place_pq

        parts = self._pq_parts
        if parts is None:
            vh = (self.vecs_host if self.vecs_host is not None
                  else np.asarray(self.vecs))
            eh = (self.exists_host if self.exists_host is not None
                  else np.asarray(self.exists))
            key = self.cache_key(max_docs)
            parts = ivf_cache.load_pq(key)
            if parts is None:
                parts = build_pq(vh, eh, self.similarity)
                if parts is None:
                    self._pq = False  # too few vectors: permanent decline
                    return None
                kernels.record("pq_build")
                ivf_cache.store_pq(key, parts)
            self._pq_parts = parts
        idx = place_pq(parts, label=f"pq[{self.name}]")
        if idx is None:
            return None  # budget tight: retry later (self._pq stays None)
        self._pq = idx
        return idx


# doc-value columns load lazily into the evictable fielddata tier (see
# _resident_field): freeze stores host arrays, the first search places
# them, pressure evicts them, the next touch rehydrates
_COLUMN_RESIDENT_FIELDS = (
    (NumericColumn, ("values", "exists", "hi", "lo")),
    (KeywordColumn, ("ords", "exists")),
    (VectorColumn, ("vecs", "exists")),
)
for _ccls, _cfields in _COLUMN_RESIDENT_FIELDS:
    for _f in _cfields:
        setattr(_ccls, _f, _resident_field(_f))
del _ccls, _cfields, _f


def _column_resident(col, fields) -> Tuple[int, int, int]:
    """(resident_bytes, evictions, rehydrations) over one column's
    registered residency handles."""
    from elasticsearch_tpu.resources.residency import ResidentArray

    b = ev = rh = 0
    for nm in fields:
        h = col.__dict__.get(f"_{nm}_res")
        if isinstance(h, ResidentArray):
            if h.resident:
                b += h.nbytes
            ev += h.evictions
            rh += h.rehydrations
    return b, ev, rh


class TpuSegment:
    """One immutable frozen segment."""

    _next_id = 0

    def __init__(
        self,
        num_docs: int,
        max_docs: int,
        inverted: Dict[str, InvertedField],
        numerics: Dict[str, NumericColumn],
        keywords: Dict[str, KeywordColumn],
        vectors: Dict[str, VectorColumn],
        sources: List[Optional[dict]],
        stored: List[dict],
        ids: List[str],
        id_map: Dict[str, int],
        field_lengths: Dict[str, Any],
    ):
        TpuSegment._next_id += 1
        self.seg_id = TpuSegment._next_id
        self.num_docs = num_docs
        self.max_docs = max_docs  # pow2 padded
        self.inverted = inverted
        self.numerics = numerics
        self.keywords = keywords
        self.vectors = vectors
        self.sources = sources
        self.stored = stored
        self.ids = ids
        self.id_map = id_map
        self.field_lengths = field_lengths  # field -> f32[max_docs] device
        # deletion state: host-authoritative, device copy refreshed on change
        self._live_host = np.zeros(max_docs, dtype=bool)
        self._live_host[:num_docs] = True
        self._live_dev = _device_put(self._live_host)
        self._live_dirty = False
        self.deleted_count = 0
        # block-join (set by SegmentBuilder.freeze when the segment holds
        # nested child docs; None = every doc is a root)
        self.metas: List[dict] = []
        self.parent_id_host: Optional[np.ndarray] = None
        self.nested_code_host: Optional[np.ndarray] = None
        self.nested_ord_host: Optional[np.ndarray] = None
        self.nested_paths: Dict[str, int] = {}
        self.roots_host: Optional[np.ndarray] = None
        self.parent_id_dev: Any = None
        self.nested_code_dev: Any = None
        self.roots_dev: Any = None
        self.root_id_host: Optional[np.ndarray] = None
        self.ancestors_host: Dict[int, np.ndarray] = {}
        self.root_id_dev: Any = None
        self.ancestors_dev: Dict[int, Any] = {}

    @property
    def has_nested(self) -> bool:
        return self.parent_id_dev is not None

    # -- deletes ---------------------------------------------------------------

    def delete_local(self, local_id: int) -> bool:
        if 0 <= local_id < self.num_docs and self._live_host[local_id]:
            self._live_host[local_id] = False
            self._live_dirty = True  # device copy refreshed lazily on next read
            self.deleted_count += 1
            # cascade to the whole block: nested children die with the root
            if self.parent_id_host is not None:
                stack = [local_id]
                while stack:
                    p = stack.pop()
                    kids = np.nonzero(self.parent_id_host[: self.num_docs] == p)[0]
                    for k in kids:
                        if self._live_host[k]:
                            self._live_host[k] = False
                            self.deleted_count += 1
                            stack.append(int(k))
            return True
        return False

    @property
    def live(self):
        if self._live_dirty:
            self._live_dev = _device_put(self._live_host)
            self._live_dirty = False
        return self._live_dev

    @property
    def live_host(self) -> np.ndarray:
        return self._live_host

    @property
    def live_docs(self) -> int:
        return self.num_docs - self.deleted_count

    def memory_bytes(self) -> int:
        """Approximate ALWAYS-RESIDENT HBM footprint — the `segments`
        breaker charge at freeze (live mask + postings). Doc-value
        columns and vector slabs are NOT counted here: they load lazily
        into the evictable fielddata tier and charge the fielddata
        breaker on first touch (resources/residency.py)."""
        total = self.max_docs  # live mask
        for inv in self.inverted.values():
            total += inv.nnz_pad * (4 + 4 + 4 + 4)
        return total

    def _column_iter(self):
        """(column, resident-field names) for every doc-value column."""
        for col in self.numerics.values():
            yield col, ("values", "exists", "hi", "lo")
        for col in self.keywords.values():
            yield col, ("ords", "exists")
        for col in self.vectors.values():
            yield col, ("vecs", "exists")

    def fielddata_field_bytes(self) -> Dict[str, int]:
        """Per-field doc-value memory currently DEVICE-RESIDENT — the
        `fielddata` section of _stats (reference:
        index/fielddata/ShardFieldData.java per-field maps). Columns
        load lazily at first search and evict under HBM pressure, so
        like the reference this reports loaded bytes, not mapped bytes;
        for analyzed text the always-resident uninverted postings
        arrays play fielddata's sort/agg role and report in full."""
        out: Dict[str, int] = {}

        def add(name, b):
            if b:
                out[name] = out.get(name, 0) + b

        for col, fields in self._column_iter():
            add(col.name, _column_resident(col, fields)[0])
        for name, inv in self.inverted.items():
            if name in self.keywords or name in self.numerics \
                    or name.startswith("_"):
                continue
            add(name, inv.nnz_pad * 12)  # term_ids + doc_ids + tf
        return out

    def fielddata_evictions(self) -> Tuple[int, int]:
        """(evictions, rehydrations) over this segment's column and
        dense-impact residency handles — the once-zero-by-design
        `fielddata.evictions` counter is real now."""
        ev = rh = 0
        for col, fields in self._column_iter():
            _, e, r = _column_resident(col, fields)
            ev += e
            rh += r
        for inv in self.inverted.values():
            d = inv._dense
            if isinstance(d, tuple):
                ev += d[1].evictions
                rh += d[1].rehydrations
        return ev, rh


class SegmentBuilder:
    """Mutable in-memory indexing buffer; freeze() emits a TpuSegment.

    Mirrors the role of Lucene's IndexWriter RAM buffer + DWPT flush
    (reference: InternalEngine.refresh → Lucene flush), but the frozen form
    is device arrays rather than an on-disk codec.
    """

    def __init__(self, mappings: Mappings):
        self.mappings = mappings
        self.docs: List[ParsedDocument] = []
        # block-join metadata aligned with docs: immediate parent local id
        # (-1 for root docs) — children are emitted BEFORE their parent, the
        # Lucene block order (reference: nested docs in ParsedDocument.docs())
        self.parent_of: List[int] = []

    def add(self, parsed: ParsedDocument) -> int:
        """Append a doc block (descendants first, root last); returns the
        ROOT's local id."""
        child_locals: List[int] = []
        for child in parsed.children:
            child_locals.append(self.add(child))
        my_local = len(self.docs)
        self.docs.append(parsed)
        self.parent_of.append(-1)
        for cl in child_locals:
            self.parent_of[cl] = my_local
        return my_local

    def __len__(self) -> int:
        return len(self.docs)

    @property
    def num_docs(self) -> int:
        return len(self.docs)

    def freeze(self) -> Optional[TpuSegment]:
        if not self.docs:
            return None
        jnp = _jnp()
        n = len(self.docs)
        max_docs = pow2_bucket(n, minimum=64)

        # -- field discovery
        text_fields: Dict[str, None] = {}
        kw_fields: Dict[str, None] = {}
        num_fields: Dict[str, str] = {}
        vec_fields: Dict[str, Tuple[int, str]] = {}
        for d in self.docs:
            for f in d.text_tokens:
                text_fields.setdefault(f)
            for f, vec in d.vectors.items():
                fm = self.mappings.get(f)
                vec_fields.setdefault(f, (len(vec), fm.similarity if fm else "cosine"))
            for f, vals in d.doc_values.items():
                fm = self.mappings.get(f)
                kind = fm.type if fm else None
                if kind is None:
                    kind = "keyword" if isinstance(vals[0], str) else "double"
                if kind in ("keyword", "string_not_analyzed"):
                    kw_fields.setdefault(f)
                else:
                    num_fields[f] = kind

        inverted: Dict[str, InvertedField] = {}
        field_lengths: Dict[str, Any] = {}

        # -- text fields: build CSR postings with positions
        for fname in text_fields:
            inverted[fname] = self._build_inverted_text(fname, n, max_docs)
            lens = np.zeros(max_docs, dtype=np.float32)
            for i, d in enumerate(self.docs):
                lens[i] = d.field_length(fname)
            field_lengths[fname] = _device_put(lens)

        # -- keyword fields: inverted (for term filters + terms agg) + ords
        keywords: Dict[str, KeywordColumn] = {}
        for fname in kw_fields:
            inv, kwcol = self._build_keyword(fname, n, max_docs)
            inverted[fname] = inv
            keywords[fname] = kwcol

        # -- numeric-ish columns
        numerics: Dict[str, NumericColumn] = {}
        for fname, kind in num_fields.items():
            numerics[fname] = self._build_numeric(fname, kind, n, max_docs)

        # -- vectors
        vectors: Dict[str, VectorColumn] = {}
        for fname, (dims, sim) in vec_fields.items():
            mat = np.zeros((max_docs, dims), dtype=np.float32)
            exists = np.zeros(max_docs, dtype=bool)
            for i, d in enumerate(self.docs):
                v = d.vectors.get(fname)
                if v is not None:
                    mat[i] = np.asarray(v, dtype=np.float32)
                    exists[i] = True
            # host arrays: the device slab loads lazily into the
            # evictable fielddata tier on first touch (_resident_field)
            vc = VectorColumn(
                name=fname, vecs=mat, exists=exists,
                dims=dims, vecs_host=mat, exists_host=exists, similarity=sim,
            )
            fm = self.mappings.get(fname)
            opts = getattr(fm, "index_options", None) if fm is not None else None
            if opts and opts.get("type") in ("ivf", "ivf_flat", "ivf_pq"):
                # index-time ANN build (like Lucene building HNSW at flush):
                # refreshes/merges/restores pay the k-means here, never the
                # first query (r3 verdict weak #9)
                vc.get_ivf(max_docs)
            if opts and opts.get("type") == "ivf_pq":
                # PQ codes ride beside the coarse quantizer; best-effort —
                # a tight fielddata breaker leaves the exact fine-rank
                # path and a later query retries placement
                vc.get_pq(max_docs)
            vectors[fname] = vc

        ids = [d.doc_id for d in self.docs]
        seg = TpuSegment(
            num_docs=n,
            max_docs=max_docs,
            inverted=inverted,
            numerics=numerics,
            keywords=keywords,
            vectors=vectors,
            sources=[d.source for d in self.docs],
            stored=[d.stored for d in self.docs],
            ids=ids,
            id_map={doc_id: i for i, doc_id in enumerate(ids)},
            field_lengths=field_lengths,
        )
        seg.metas = [d.meta for d in self.docs]
        # block-join arrays (all-root fast path: leave device arrays None)
        if any(p >= 0 for p in self.parent_of):
            parent_id = np.full(max_docs, -1, dtype=np.int32)
            parent_id[:n] = np.asarray(self.parent_of, dtype=np.int32)
            nested_code = np.full(max_docs, -1, dtype=np.int32)
            nested_ord = np.full(max_docs, -1, dtype=np.int32)
            paths: Dict[str, int] = {}
            for i, d in enumerate(self.docs):
                if d.nested_path is not None:
                    code = paths.setdefault(d.nested_path, len(paths))
                    nested_code[i] = code
                    nested_ord[i] = d.nested_ord
            seg.parent_id_host = parent_id
            seg.nested_code_host = nested_code
            seg.nested_ord_host = nested_ord
            seg.nested_paths = paths
            roots = np.zeros(max_docs, dtype=bool)
            roots[:n] = parent_id[:n] < 0
            seg.roots_host = roots
            # transitive ancestors: root_id[d] = the block's root doc, and
            # per nested level L: ancestor_at[L][d] = d's ancestor whose
            # nested_code == L (-1 if none). Join targets for nested query /
            # reverse_nested at any depth, resolved by one device gather.
            root_id = np.arange(max_docs, dtype=np.int32)
            anc: Dict[int, np.ndarray] = {c: np.full(max_docs, -1, dtype=np.int32)
                                          for c in paths.values()}
            for i in range(n):
                # children precede parents, so walking up terminates fast
                j = i
                while parent_id[j] >= 0:
                    j = parent_id[j]
                    if nested_code[j] >= 0:
                        if anc[nested_code[j]][i] < 0:
                            anc[nested_code[j]][i] = j
                root_id[i] = j
                if nested_code[i] >= 0:
                    anc[nested_code[i]][i] = i  # a doc is its own level-ancestor
            seg.root_id_host = root_id
            seg.ancestors_host = anc
            seg.parent_id_dev = _device_put(parent_id)
            seg.nested_code_dev = _device_put(nested_code)
            seg.roots_dev = _device_put(roots)
            seg.root_id_dev = _device_put(root_id)
            seg.ancestors_dev = {c: _device_put(a) for c, a in anc.items()}
        return seg

    # -- builders --------------------------------------------------------------

    def _build_inverted_text(self, fname: str, n: int, max_docs: int) -> InvertedField:
        # term -> list[(doc, tf, positions)]
        vocab: Dict[str, int] = {}
        terms: List[str] = []
        post: List[List[Tuple[int, int, List[int]]]] = []
        total_terms = 0
        for i, d in enumerate(self.docs):
            toks = d.text_tokens.get(fname)
            if not toks:
                continue
            total_terms += len(toks)
            per_term: Dict[int, List[int]] = {}
            for t, p in toks:
                tid = vocab.get(t)
                if tid is None:
                    tid = len(terms)
                    vocab[t] = tid
                    terms.append(t)
                    post.append([])
                per_term.setdefault(tid, []).append(p)
            for tid, poss in per_term.items():
                post[tid].append((i, len(poss), poss))

        V = len(terms)
        df = np.array([len(p) for p in post], dtype=np.int32) if V else np.zeros(0, np.int32)
        cf = np.array([sum(tf for _, tf, _ in p) for p in post], dtype=np.int64) if V else np.zeros(0, np.int64)
        nnz = int(df.sum())
        ndocs_with_field = int(sum(1 for d in self.docs if d.text_tokens.get(fname)))
        avg_len = (total_terms / ndocs_with_field) if ndocs_with_field else 1.0

        doc_ids = np.full(nnz, 0, dtype=np.int32)
        tf_arr = np.zeros(nnz, dtype=np.float32)
        term_ids = np.zeros(nnz, dtype=np.int32)
        offsets = np.zeros(V + 1, dtype=np.int64)
        pos_offsets = np.zeros(nnz + 1, dtype=np.int64)
        positions_flat: List[int] = []
        k = 0
        for tid in range(V):
            offsets[tid] = k
            for doc, tf, poss in post[tid]:
                doc_ids[k] = doc
                tf_arr[k] = tf
                term_ids[k] = tid
                positions_flat.extend(poss)
                pos_offsets[k + 1] = len(positions_flat)
                k += 1
        offsets[V] = k

        # precompute BM25 tf-normalization (k1/b fixed at index time, like
        # Lucene BM25Similarity norms; idf is applied at query time so global
        # dfs stats can override per-segment stats)
        dl = np.array([self.docs[i].field_length(fname) for i in doc_ids], dtype=np.float32) if nnz else np.zeros(0, np.float32)
        tfnorm = tf_arr * (K1 + 1.0) / (tf_arr + K1 * (1.0 - B + B * dl / max(avg_len, 1e-9)))

        nnz_pad = pow2_bucket(max(nnz, 1), minimum=8)
        # an OVERSIZED field must not allocate its full postings on one
        # device at freeze — scoring goes through the cross-device split;
        # the lazy accessors place these host arrays only if a fallback
        # path (phrase, terms agg) actually asks for the full copy
        from elasticsearch_tpu.parallel.postings_shard import \
            POSTINGS_SHARD_NNZ
        put = (lambda a: a) if nnz >= POSTINGS_SHARD_NNZ else _device_put
        return InvertedField(
            name=fname,
            vocab=vocab,
            terms=terms,
            df=df,
            cf=cf,
            offsets=offsets,
            doc_ids=put(pad_to(doc_ids, nnz_pad, max_docs)),
            tf=put(pad_to(tf_arr, nnz_pad, 0.0)),
            tfnorm=put(pad_to(tfnorm.astype(np.float32), nnz_pad, 0.0)),
            term_ids=put(pad_to(term_ids, nnz_pad, V)),
            nnz=nnz,
            num_docs=ndocs_with_field,
            total_terms=total_terms,
            avg_len=avg_len,
            pos_offsets=pos_offsets,
            positions=np.array(positions_flat, dtype=np.int32),
            doc_ids_host=doc_ids,
            tfnorm_host=tfnorm.astype(np.float32),
            tf_host=tf_arr,
            max_docs=max_docs,
        )

    def _build_keyword(self, fname: str, n: int, max_docs: int):
        vocab: Dict[str, int] = {}
        terms: List[str] = []
        post: List[List[int]] = []
        ords = np.full(max_docs, -1, dtype=np.int32)
        exists = np.zeros(max_docs, dtype=bool)
        host_values: List[Optional[List[str]]] = [None] * max_docs
        for i, d in enumerate(self.docs):
            vals = d.doc_values.get(fname)
            if not vals:
                continue
            svals = [str(v) for v in vals]
            host_values[i] = svals
            exists[i] = True
            for v in svals:
                tid = vocab.get(v)
                if tid is None:
                    tid = len(terms)
                    vocab[v] = tid
                    terms.append(v)
                    post.append([])
                post[tid].append(i)
            if len(svals) == 1:
                ords[i] = vocab[svals[0]]

        V = len(terms)
        # sort terms lexicographically for deterministic ordinal order (ES
        # terms agg _term ordering relies on it)
        order = sorted(range(V), key=lambda t: terms[t])
        remap = {old: new for new, old in enumerate(order)}
        terms2 = [terms[o] for o in order]
        post2 = [sorted(set(post[o])) for o in order]
        vocab2 = {t: i for i, t in enumerate(terms2)}
        ords_re = np.where(ords >= 0, np.array([remap.get(o, -1) for o in range(V)] or [0], dtype=np.int32)[np.maximum(ords, 0)], -1).astype(np.int32) if V else ords

        df = np.array([len(p) for p in post2], dtype=np.int32) if V else np.zeros(0, np.int32)
        nnz = int(df.sum())
        doc_ids = np.zeros(nnz, dtype=np.int32)
        term_ids = np.zeros(nnz, dtype=np.int32)
        offsets = np.zeros(V + 1, dtype=np.int64)
        k = 0
        for tid in range(V):
            offsets[tid] = k
            for doc in post2[tid]:
                doc_ids[k] = doc
                term_ids[k] = tid
                k += 1
        offsets[V] = k
        nnz_pad = pow2_bucket(max(nnz, 1), minimum=8)
        ones = np.ones(nnz, dtype=np.float32)
        # same oversized-field treatment as _build_inverted_text
        from elasticsearch_tpu.parallel.postings_shard import \
            POSTINGS_SHARD_NNZ
        put = (lambda a: a) if nnz >= POSTINGS_SHARD_NNZ else _device_put
        inv = InvertedField(
            name=fname,
            vocab=vocab2,
            terms=terms2,
            df=df,
            cf=df.astype(np.int64),
            offsets=offsets,
            doc_ids=put(pad_to(doc_ids, nnz_pad, max_docs)),
            tf=put(pad_to(ones, nnz_pad, 0.0)),
            tfnorm=put(pad_to(ones, nnz_pad, 0.0)),
            term_ids=put(pad_to(term_ids, nnz_pad, V)),
            nnz=nnz,
            num_docs=int(exists.sum()),
            total_terms=nnz,
            avg_len=1.0,
            doc_ids_host=doc_ids,
            tfnorm_host=ones,
            max_docs=max_docs,
        )
        kwcol = KeywordColumn(
            name=fname,
            ords=ords_re,  # host: lazy evictable device copy (fielddata)
            exists=exists,
            host_values=host_values,
            ords_host=ords_re,
            exists_host=exists,
        )
        return inv, kwcol

    def _build_numeric(self, fname: str, kind: str, n: int, max_docs: int) -> NumericColumn:
        exists = np.zeros(max_docs, dtype=bool)
        needs_exact = kind in ("long", "date", "ip", "murmur3", "token_count", "integer")
        exact = np.zeros(max_docs, dtype=np.int64) if needs_exact else np.zeros(max_docs, dtype=np.float64)
        for i, d in enumerate(self.docs):
            vals = d.doc_values.get(fname)
            if not vals:
                continue
            exists[i] = True
            exact[i] = vals[0]  # multi-valued numerics: first value in the column (full set in _source)
        offset = 0.0
        if needs_exact and exists.any():
            offset = float(exact[exists].min())
        values = np.where(exists, (exact - offset).astype(np.float32), np.float32(0))
        col = NumericColumn(
            name=fname,
            values=values.astype(np.float32),  # host: lazy evictable
            exists=exists,                     # device copies (fielddata)
            exact=exact,
            exists_host=exists,
            kind=kind,
            offset=offset,
        )
        if needs_exact:
            hi, lo = split_i64(exact)
            col.hi = hi
            col.lo = lo
        return col
