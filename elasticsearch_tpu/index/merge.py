"""Merge policy: which segments to combine, and when.

Reference: org/elasticsearch/index/merge/policy/TieredMergePolicyProvider.java
wrapping Lucene's TieredMergePolicy — segments are grouped into size tiers;
when a tier holds more than ``segments_per_tier`` segments, the smallest
``max_merge_at_once`` of them merge into one. Deletes add merge pressure via
the reclaimable-doc ratio.

TPU adaptation: segment "size" is its live root-doc count (device arrays are
derived from docs, so doc count is the honest cost measure). The merge
itself (Engine.merge) re-parses live sources into one new SegmentBuilder —
the output is identical to what a codec-level merge would produce because
segments are pure functions of (source, mappings).
"""
from __future__ import annotations

from typing import List, Optional


class TieredMergePolicy:
    def __init__(
        self,
        segments_per_tier: int = 8,
        max_merge_at_once: int = 8,
        deletes_pct_allowed: float = 25.0,
    ):
        self.segments_per_tier = max(2, segments_per_tier)
        self.max_merge_at_once = max(2, max_merge_at_once)
        self.deletes_pct_allowed = deletes_pct_allowed

    def find_merge(self, segments: List) -> Optional[List]:
        """Segments to merge now, or None.

        Two triggers, checked in order:
        1. delete reclaim: any segment whose deleted fraction exceeds
           ``deletes_pct_allowed`` merges (possibly alone — rewriting it
           drops the tombstoned docs' arrays).
        2. tier overflow: more segments than segments_per_tier in the same
           pow2 size tier → merge the smallest max_merge_at_once of them.
        """
        if not segments:
            return None
        for seg in segments:
            denom = max(1, seg.num_docs)
            if 100.0 * seg.deleted_count / denom > self.deletes_pct_allowed:
                # fold the deletion-heavy segment together with its tier
                # neighbours when possible, alone otherwise
                tier = self._tier_of(seg)
                mates = [s for s in segments
                         if s is not seg and self._tier_of(s) == tier]
                return ([seg] + mates)[: self.max_merge_at_once]
        tiers = {}
        for seg in segments:
            tiers.setdefault(self._tier_of(seg), []).append(seg)
        for tier_segs in tiers.values():
            if len(tier_segs) >= self.segments_per_tier:
                tier_segs.sort(key=lambda s: s.live_docs)
                return tier_segs[: self.max_merge_at_once]
        return None

    @staticmethod
    def _tier_of(seg) -> int:
        n = max(1, seg.live_docs)
        return n.bit_length()  # pow2 tier
