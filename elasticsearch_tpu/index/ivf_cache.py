"""Content-addressed IVF blob cache: makes `store.read_ivf` a product input.

An IvfIndex's list entries are LOCAL doc ordinals padded with the owning
segment's ``max_docs`` sentinel, so a persisted blob is only valid for a
slab whose vectors sit at exactly the same ordinals. Rather than trying to
track segment identity across restarts / translog replays / snapshot
restores (where segment boundaries legitimately change — replay merges all
live docs into one segment), blobs are keyed by a digest of the exact slab
content: ``sha1(shape, metric, max_docs, vecs bytes, exists bytes)``. A key
hit therefore *guarantees* the ordinals line up and the blob can be loaded
verbatim; any content drift (deletes dropped on restore, different refresh
boundaries) simply misses and falls back to the k-means build, which then
re-persists under the new key.

Lifecycle (reference behavioral analogue: Lucene writes its HNSW/IVF graph
into segment files at flush and reopens it on restart —
org/elasticsearch/index/engine/InternalEngine.java's commit path; ES 2.0
itself has no vector format, this follows the north-star `dense_vector`
addition):

- `Node(data_path=...)` calls `register(<data>/_ivf)` before gateway
  recovery, so replayed segments can hit blobs written by the previous
  process; `Node.close()` unregisters it. Several Nodes in one process
  each register their own directory (refcounted — two Nodes over the
  same data_path share one registration).
- `VectorColumn.get_ivf` consults the cache before `build_ivf` and stores
  the blob after a build (counters: `ivf_cache_hit` / `ivf_build` in
  `monitor.kernels`, surfaced via `_nodes/stats`).
- Snapshots embed each segment's blobs; restore seeds them here so the
  target node's freeze skips the k-means when the restored slab content
  matches (single-segment shards with no pruned deletes).

The in-memory layer is content-addressed and process-global, which is safe
by construction even with several Nodes in one process: identical key ==
identical slab, so a blob can never be applied to the wrong data. The
durable tier is the union of the registered directories: loads scan all of
them, stores write to all of them. Writing a blob into a sibling node's
directory is additive cache pollution at worst (content addressing makes a
stale or foreign blob unreachable unless its exact slab recurs), and it is
what keeps every data-path node's cache warm across restarts regardless of
which node in the process built the quantizer.
"""
from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.monitor import kernels

_LOCK = threading.Lock()
_DIRS: Dict[str, int] = {}  # directory -> refcount (insertion-ordered)
_MEM: Dict[str, bytes] = {}
_MEM_CAP = 64  # blobs; FIFO eviction — disk layer is the durable tier


def register(directory: str) -> None:
    """Add ``directory`` to the durable tier (created on first store).
    Refcounted: a second Node over the same data_path shares it."""
    with _LOCK:
        _DIRS[directory] = _DIRS.get(directory, 0) + 1


def unregister(directory: str) -> None:
    """Drop one registration of ``directory`` (Node.close)."""
    with _LOCK:
        c = _DIRS.get(directory, 0) - 1
        if c > 0:
            _DIRS[directory] = c
        else:
            _DIRS.pop(directory, None)


def configure(directory: Optional[str]) -> None:
    """Back-compat shim: register(directory); None is a no-op."""
    if directory:
        register(directory)


def reset() -> None:
    """Drop all cache state (tests)."""
    with _LOCK:
        _DIRS.clear()
        _MEM.clear()


def content_key(vecs_host: np.ndarray, exists_host: np.ndarray,
                metric: str, max_docs: int) -> str:
    v = np.ascontiguousarray(vecs_host, dtype=np.float32)
    e = np.ascontiguousarray(exists_host, dtype=bool)
    h = hashlib.sha1()
    h.update(repr((v.shape, metric, int(max_docs))).encode())
    h.update(v.tobytes())
    h.update(e.tobytes())
    return h.hexdigest()


def _disk_paths(key: str, ext: str = "ivf") -> List[str]:
    with _LOCK:
        dirs = list(_DIRS)
    return [os.path.join(d, f"{key}.{ext}") for d in dirs]


def load(key: str):
    """Return a device-resident IvfIndex for ``key`` or None. A corrupt
    disk blob is deleted and treated as a miss (the build path re-creates
    it), never propagated."""
    from elasticsearch_tpu.index.store import CorruptStoreException, read_ivf

    with _LOCK:
        blob = _MEM.get(key)
    if blob is not None:
        try:
            idx = read_ivf(blob)
        except CorruptStoreException:
            with _LOCK:
                _MEM.pop(key, None)
        else:
            kernels.record("ivf_cache_hit")
            return idx
    for path in _disk_paths(key):
        if not os.path.exists(path):
            continue
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError:
            continue
        try:
            idx = read_ivf(blob)
        except CorruptStoreException:
            try:
                os.unlink(path)
            except OSError:
                pass
            continue
        kernels.record("ivf_cache_hit")
        return idx
    return None


def store(key: str, ivf: Any) -> bytes:
    """Persist ``ivf`` under ``key`` (memory + every registered directory).
    Returns the encoded blob (snapshot payloads reuse it)."""
    from elasticsearch_tpu.index.store import write_ivf

    blob = write_ivf(ivf)
    seed(key, blob)
    return blob


def load_pq(key: str):
    """Return host-side PqHostParts for ``key`` or None — the PQ sibling
    of :func:`load`, sharing the content-address (one slab digest keys
    both its IVF quantizer and its PQ codes, under different
    extensions). A corrupt blob is deleted and treated as a miss."""
    from elasticsearch_tpu.index.store import CorruptStoreException, read_pq

    mkey = f"pq:{key}"
    with _LOCK:
        blob = _MEM.get(mkey)
    if blob is not None:
        try:
            parts = read_pq(blob)
        except CorruptStoreException:
            with _LOCK:
                _MEM.pop(mkey, None)
        else:
            kernels.record("pq_cache_hit")
            return parts
    for path in _disk_paths(key, ext="pq"):
        if not os.path.exists(path):
            continue
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError:
            continue
        try:
            parts = read_pq(blob)
        except CorruptStoreException:
            try:
                os.unlink(path)
            except OSError:
                pass
            continue
        kernels.record("pq_cache_hit")
        return parts
    return None


def store_pq(key: str, parts: Any) -> bytes:
    """Persist host PqHostParts under ``key`` (memory + every registered
    directory). Returns the encoded blob (snapshot payloads reuse it)."""
    from elasticsearch_tpu.index.store import write_pq

    blob = write_pq(parts)
    seed_pq(key, blob)
    return blob


def seed_pq(key: str, blob: bytes) -> None:
    """Insert an already-encoded PQ blob (snapshot restore pre-seeding)."""
    _seed(f"pq:{key}", blob, _disk_paths(key, ext="pq"))


def seed(key: str, blob: bytes) -> None:
    """Insert an already-encoded IVF blob (snapshot restore pre-seeding)."""
    _seed(key, blob, _disk_paths(key))


# -- generic blob tier (program-key census, incidents, ...) ------------------

def frame_blob(payload: dict) -> bytes:
    """The generic tier's one digest framing: ``sha1-hex\\n{json}``.
    Corruption (torn write, bitrot) becomes a *detected* miss at
    :func:`unframe_blob` — every JSON blob family (census, incidents)
    shares this frame so the format can't silently diverge."""
    import json

    body = json.dumps(payload, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha1(body).hexdigest().encode("ascii") + b"\n" + body


def unframe_blob(blob: bytes) -> Optional[dict]:
    """Verify + decode a :func:`frame_blob` payload; None on any
    corruption (callers treat it as a miss and usually delete_blob)."""
    import json

    try:
        digest, _, body = blob.partition(b"\n")
        if hashlib.sha1(body).hexdigest().encode("ascii") != digest:
            return None
        payload = json.loads(body)
        return payload if isinstance(payload, dict) else None
    except Exception:
        return None


def load_blob(key: str, ext: str) -> Optional[bytes]:
    """Raw blob bytes for ``(key, ext)`` from memory or any registered
    directory, or None. No decoding here — callers validate (and call
    :func:`delete_blob` on corruption, so a bad blob becomes a clean
    miss instead of a crash). Lets siblings of the IVF/PQ artifacts —
    the per-index program-key census (resources/census.py) — ride the
    same durable tier without duplicating the directory registry."""
    mkey = f"{ext}:{key}"
    with _LOCK:
        blob = _MEM.get(mkey)
    if blob is not None:
        return blob
    for path in _disk_paths(key, ext=ext):
        if not os.path.exists(path):
            continue
        try:
            with open(path, "rb") as fh:
                return fh.read()
        except OSError:
            continue
    return None


def store_blob(key: str, blob: bytes, ext: str,
               overwrite: bool = True) -> None:
    """Persist raw bytes under ``(key, ext)`` (memory + every registered
    directory; best-effort on disk like every other blob here).

    ``overwrite`` defaults True: unlike the content-addressed IVF/PQ
    blobs (identical key ⇒ identical bytes, so skip-if-exists is a pure
    optimization), the generic tier's families are NAME-addressed and
    MUTABLE — the census merges on every flush, the incident index
    appends — and a skip-if-exists store would silently freeze the disk
    copy at its first write (the in-memory tier masking it until the
    process dies). Content-addressed callers (AOT executables) pass
    ``overwrite=False`` to keep the cheap skip."""
    _seed(f"{ext}:{key}", blob, _disk_paths(key, ext=ext),
          overwrite=overwrite)


def delete_blob(key: str, ext: str) -> None:
    """Drop ``(key, ext)`` everywhere — the corrupt-blob miss path."""
    with _LOCK:
        _MEM.pop(f"{ext}:{key}", None)
    for path in _disk_paths(key, ext=ext):
        try:
            os.unlink(path)
        except OSError:
            pass


def list_blob_keys(ext: str) -> List[str]:
    """Every key currently stored under ``ext``: memory tier plus each
    registered directory. This is the delta input for fleet-wide AOT
    blob distribution — a recovery target sends the `.aotx` keys it
    already HAS in its shard_sync request, and the source ships only the
    complement, so a joining node never compiles a program any peer
    already compiled."""
    prefix = f"{ext}:"
    with _LOCK:
        keys = {k[len(prefix):] for k in _MEM if k.startswith(prefix)}
        dirs = list(_DIRS)
    suffix = f".{ext}"
    for d in dirs:
        try:
            names = os.listdir(d)
        except OSError:
            continue
        keys.update(n[:-len(suffix)] for n in names if n.endswith(suffix))
    return sorted(keys)


def _seed(mkey: str, blob: bytes, paths: List[str],
          overwrite: bool = False) -> None:
    with _LOCK:
        if mkey not in _MEM and len(_MEM) >= _MEM_CAP:
            _MEM.pop(next(iter(_MEM)))
        _MEM[mkey] = blob
    for path in paths:
        if not overwrite and os.path.exists(path):
            continue
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # tmp name unique per WRITER, not just per process: overwrite-
        # mode stores race across threads (watchdog flush vs recovery
        # flush vs close), and a shared tmp lets one writer publish
        # another's half-written bytes via os.replace — the digest frame
        # would then detect-and-DELETE the census on next load, losing
        # the exact durability the overwrite exists to provide
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
