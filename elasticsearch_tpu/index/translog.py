"""Transaction log.

Reference: org/elasticsearch/index/translog/ — Translog.java (fs),
TranslogWriter-era logic: an append-only durability log, fsync policy,
generation rollover on flush ("commit"), and replay on recovery.

On-disk format (v2): binary frames
    [0xE5][u8 version][u32be len][u32be crc32(payload)][payload JSON bytes]
with the CRC computed by the native C++ codec (elasticsearch_tpu.native,
native/codec.cpp) — the same role as the reference's
BufferedChecksumStreamOutput (java.util.zip.CRC32): a torn or bit-rotted
tail is DETECTED, not silently half-parsed. Replay verifies every frame
and stops at the first bad one. Legacy v1 JSON-lines generations are still
readable (format auto-detected per file).
"""
from __future__ import annotations

import json
import os
import struct
import threading
from typing import Callable, Iterator, Optional

from elasticsearch_tpu.native import crc32

_MAGIC = 0xE5
_VERSION = 2
_HEADER = struct.Struct(">BBII")  # magic, version, len, crc


class Translog:
    def __init__(self, path: Optional[str], durability: str = "request", sync_interval: float = 5.0):
        """path=None → in-memory only (durability off, e.g. ephemeral tests).

        durability: "request" fsyncs every append (ES index.translog.durability=
        request); "async" relies on OS flush + periodic sync.
        """
        self.path = path
        self.durability = durability
        self._lock = threading.Lock()
        self._ops_since_sync = 0
        self.generation = 1
        self._fh = None
        self._mem: list = []
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # find latest generation
            base = os.path.basename(path)
            d = os.path.dirname(path) or "."
            gens = []
            for f in os.listdir(d):
                if f.startswith(base + ".") and f.rpartition(".")[2].isdigit():
                    gens.append(int(f.rpartition(".")[2]))
            self.generation = max(gens) if gens else 1
            # never append v2 frames to a legacy v1 (JSON-lines) generation:
            # the per-file format sniff is first-byte based, so mixing would
            # make replay silently drop the v2 tail. Roll to a fresh
            # generation instead; the old one stays readable for replay.
            gp = self._gen_path(self.generation)
            if os.path.exists(gp) and os.path.getsize(gp) > 0:
                with open(gp, "rb") as f:
                    if f.read(1)[0] != _MAGIC:
                        self.generation += 1
            self._fh = open(self._gen_path(self.generation), "ab")

    def _gen_path(self, gen: int) -> str:
        return f"{self.path}.{gen}"

    @property
    def size_in_ops(self) -> int:
        if self.path is None:
            return len(self._mem)
        with self._lock:
            return self._count_ops()

    def _count_ops(self) -> int:
        return sum(1 for _ in self._iter_file(self._gen_path(self.generation)))

    def append(self, op: dict):
        payload = json.dumps(op, separators=(",", ":")).encode()
        with self._lock:
            if self._fh is None:
                self._mem.append(op)
                return
            self._fh.write(_HEADER.pack(_MAGIC, _VERSION, len(payload),
                                        crc32(payload)))
            self._fh.write(payload)
            self._ops_since_sync += 1
            if self.durability == "request":
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._ops_since_sync = 0

    def sync(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._ops_since_sync = 0

    def replay(self, from_generation: int = 1) -> Iterator[dict]:
        """Yield ops from all generations >= from_generation (recovery)."""
        if self.path is None:
            yield from list(self._mem)
            return
        self.sync()
        for gen in range(from_generation, self.generation + 1):
            yield from self._iter_file(self._gen_path(gen))

    @staticmethod
    def _iter_file(p: str) -> Iterator[dict]:
        """Parse one generation file; CRC-verified frames (v2) or legacy
        JSON lines (v1). Stops cleanly at the first torn/corrupt record."""
        if not os.path.exists(p):
            return
        with open(p, "rb") as f:
            first = f.read(1)
            f.seek(0)
            if first and first[0] != _MAGIC:  # legacy v1 JSON lines
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        return  # torn tail write: stop at corruption
                return
            while True:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    return  # clean EOF or torn header
                magic, version, n, crc = _HEADER.unpack(header)
                if magic != _MAGIC or version != _VERSION:
                    return
                payload = f.read(n)
                if len(payload) < n or crc32(payload) != crc:
                    return  # torn or corrupted frame: recovery stops here
                try:
                    yield json.loads(payload)
                except json.JSONDecodeError:
                    return

    def commit(self):
        """Roll to a new generation and drop old ones (called on flush:
        flushed segments now own the data, like Translog.commit)."""
        with self._lock:
            if self._fh is None:
                self._mem.clear()
                return
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            old_gen = self.generation
            self.generation += 1
            self._fh = open(self._gen_path(self.generation), "ab")
            for gen in range(1, old_gen + 1):
                p = self._gen_path(gen)
                if os.path.exists(p):
                    os.remove(p)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
