"""Transaction log.

Reference: org/elasticsearch/index/translog/ — Translog.java (fs),
TranslogWriter-era logic: an append-only durability log, fsync policy,
generation rollover on flush ("commit"), and replay on recovery.

On-disk format (v2): binary frames
    [0xE5][u8 version][u32be len][u32be crc32(payload)][payload JSON bytes]
with the CRC computed by the native C++ codec (elasticsearch_tpu.native,
native/codec.cpp) — the same role as the reference's
BufferedChecksumStreamOutput (java.util.zip.CRC32): a torn or bit-rotted
tail is DETECTED, not silently half-parsed. Replay verifies every frame
and stops at the first bad one. Legacy v1 JSON-lines generations are still
readable (format auto-detected per file).

Lock order: ``Translog._lock`` sits BELOW ``Engine._lock`` (the engine
appends under its own lock) and above only the process-shared
native/metrics locks — the position tpulint R013's interprocedural lock
graph verifies acyclic; never call back into the engine from under it.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import time
from typing import Callable, Iterator, Optional

from elasticsearch_tpu.native import crc32
from elasticsearch_tpu.utils.faults import FAULTS

_MAGIC = 0xE5
_VERSION = 2
_HEADER = struct.Struct(">BBII")  # magic, version, len, crc


class TranslogClosedException(OSError):
    """Append/sync against a translog whose channel was closed by a
    tragic IO event (or an explicit close). An OSError subclass so the
    engine's tragic-event handler treats it like any other IO failure."""


class Translog:
    def __init__(self, path: Optional[str], durability: str = "request", sync_interval: float = 5.0):
        """path=None → in-memory only (durability off, e.g. ephemeral tests).

        durability: "request" fsyncs every append (ES index.translog.durability=
        request); "async" relies on OS flush + periodic sync.
        """
        self.path = path
        self.durability = durability
        self._lock = threading.Lock()
        self._ops_since_sync = 0
        self.generation = 1
        self._fh = None
        self._mem: list = []
        # stats() counters — all mutated under _lock
        self._ops_appended = 0
        self._bytes_written = 0
        self._sync_count = 0
        self._last_sync: Optional[float] = None
        self._corrupt_tail_events = 0
        self._corrupt_tail_bytes = 0
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # find latest generation
            base = os.path.basename(path)
            d = os.path.dirname(path) or "."
            gens = []
            for f in os.listdir(d):
                if f.startswith(base + ".") and f.rpartition(".")[2].isdigit():
                    gens.append(int(f.rpartition(".")[2]))
            self.generation = max(gens) if gens else 1
            # never append v2 frames to a legacy v1 (JSON-lines) generation:
            # the per-file format sniff is first-byte based, so mixing would
            # make replay silently drop the v2 tail. Roll to a fresh
            # generation instead; the old one stays readable for replay.
            gp = self._gen_path(self.generation)
            if os.path.exists(gp) and os.path.getsize(gp) > 0:
                with open(gp, "rb") as f:
                    if f.read(1)[0] != _MAGIC:
                        self.generation += 1
            self._fh = open(self._gen_path(self.generation), "ab")
            # size reflects the CURRENT generation on disk, so a restart
            # with a large un-committed translog reports its real flush
            # pressure (reference: TranslogStats sizeInBytes)
            self._bytes_written = self._fh.tell()

    def _gen_path(self, gen: int) -> str:
        return f"{self.path}.{gen}"

    @property
    def size_in_ops(self) -> int:
        if self.path is None:
            return len(self._mem)
        with self._lock:
            return self._count_ops()

    def _count_ops(self) -> int:
        return sum(1 for _ in self._iter_file(self._gen_path(self.generation)))

    def append(self, op: dict):
        """Durably record one op. An IO/fsync failure is TRAGIC: the
        channel is closed before the error propagates, so no later append
        can extend a generation whose tail may hold a torn frame (the
        CRC framing makes replay stop cleanly at that tail). Reference:
        TranslogWriter.closeWithTragicEvent — a translog that failed a
        write must never accept another op."""
        payload = json.dumps(op, separators=(",", ":")).encode()
        with self._lock:
            if self._fh is None:
                if self.path is None:
                    self._mem.append(op)
                    return
                raise TranslogClosedException(
                    f"translog [{self.path}] is closed")
            start = self._fh.tell()
            try:
                FAULTS.check("translog.append", path=self.path)
                self._fh.write(_HEADER.pack(_MAGIC, _VERSION, len(payload),
                                            crc32(payload)))
                self._fh.write(payload)
                self._ops_since_sync += 1
                if self.durability == "request":
                    self._sync_locked()
                # bumped only once durability is settled: a tragic append
                # must not count as appended
                self._ops_appended += 1
                self._bytes_written += _HEADER.size + len(payload)
            except OSError:
                # drop the unacknowledged frame where possible so replay
                # state is exactly the acknowledged ops (best-effort: if
                # the disk is the problem, the CRC framing still stops
                # replay at the torn frame)
                self._close_tragic(truncate_to=start)
                raise

    def sync(self):
        with self._lock:
            if self._fh is not None:
                try:
                    self._sync_locked()
                except OSError:
                    self._close_tragic()
                    raise

    def _sync_locked(self):
        t0 = time.perf_counter()
        self._fh.flush()
        FAULTS.check("translog.fsync", path=self.path)
        os.fsync(self._fh.fileno())
        self._ops_since_sync = 0
        self._sync_count += 1
        self._last_sync = time.time()
        # continuous metrics (process-shared registry: a Translog has no
        # node back-ref, the device-is-process-shared discipline): fsync
        # latency is THE write-amplification number under
        # durability=request — every indexed doc pays one
        try:
            from elasticsearch_tpu.monitor.metrics import SHARED

            SHARED.histogram(
                "estpu_translog_fsync_duration_seconds",
                "Translog flush+fsync latency").observe(
                    time.perf_counter() - t0)
            SHARED.counter("estpu_translog_fsyncs_total",
                           "Translog fsync operations").inc()
        except Exception:  # tpulint: allow[R006] — a metrics failure
            pass           # must never become a tragic translog event

    def _close_tragic(self, truncate_to: Optional[int] = None):
        """Close the channel after a failed write/fsync — best-effort,
        the original IO error is what propagates to the engine.
        ``truncate_to`` drops a frame whose durability was never
        confirmed, so a replay after the tragic event yields exactly the
        acknowledged ops."""
        fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
        if truncate_to is not None:
            try:
                os.truncate(self._gen_path(self.generation), truncate_to)
            except OSError:
                pass

    def stats(self) -> dict:
        """Counters for the monitor endpoint (reference: TranslogStats —
        numberOfOperations/translogSizeInBytes, plus our sync/corruption
        accounting)."""
        with self._lock:
            return {
                "operations": (len(self._mem) if self.path is None
                               else self._count_ops()),
                "ops_appended": self._ops_appended,
                "generation": self.generation,
                "size_in_bytes": self._bytes_written,
                "sync_count": self._sync_count,
                "last_sync_millis": (int(self._last_sync * 1000)
                                     if self._last_sync else 0),
                "corrupt_tail_events": self._corrupt_tail_events,
                "corrupt_tail_bytes_dropped": self._corrupt_tail_bytes,
                "closed": self.path is not None and self._fh is None,
            }

    def replay(self, from_generation: int = 1) -> Iterator[dict]:
        """Yield ops from all generations >= from_generation (recovery).

        A corrupt tail is DETECTED, reported (monitor/stats.py global
        recovery accounting + this translog's ``corrupt_tail_events``
        counter), and replay stops at it — acknowledged ops before the
        tear all replay; nothing after it is half-parsed."""
        if self.path is None:
            yield from list(self._mem)
            return
        self.sync()

        def on_corrupt(path: str, bytes_dropped: int, reason: str) -> None:
            from elasticsearch_tpu.monitor.stats import record_corrupt_tail

            with self._lock:
                self._corrupt_tail_events += 1
                self._corrupt_tail_bytes += int(bytes_dropped)
            record_corrupt_tail(path, bytes_dropped, reason)

        for gen in range(from_generation, self.generation + 1):
            yield from self._iter_file(self._gen_path(gen), on_corrupt)

    def ops_above(self, seq_no: int) -> Iterator[dict]:
        """Yield retained ops whose sequence number exceeds ``seq_no`` —
        the raw material of checkpoint-based peer recovery (reference:
        Translog.newSnapshot(fromSeqNo) in the seq-no era). Frames
        without a seq_no (legacy v1/v2 pre-seqno ops) are skipped: the
        caller detects the resulting coverage gap and falls back to a
        full copy. ``commit()`` dropping old generations is what bounds
        this — ops flushed away are gone, by design."""
        for op in self.replay():
            s = op.get("seq_no")
            if s is not None and s > seq_no:
                yield op

    @staticmethod
    def _iter_file(p: str,
                   on_corrupt: Optional[Callable[[str, int, str], None]]
                   = None) -> Iterator[dict]:
        """Parse one generation file; CRC-verified frames (v2) or legacy
        JSON lines (v1). Stops cleanly at the first torn/corrupt record;
        ``on_corrupt(path, bytes_dropped, reason)`` fires when the stop
        was corruption rather than clean EOF."""
        if not os.path.exists(p):
            return
        size = os.path.getsize(p)

        def corrupt(pos: int, reason: str) -> None:
            if on_corrupt is not None:
                on_corrupt(p, size - pos, reason)

        with open(p, "rb") as f:
            first = f.read(1)
            f.seek(0)
            if first and first[0] != _MAGIC:  # legacy v1 JSON lines
                pos = 0
                for line in f:
                    stripped = line.strip()
                    if stripped:
                        try:
                            op = json.loads(stripped)
                        except json.JSONDecodeError:
                            # torn tail write: stop at corruption
                            corrupt(pos, "unparseable v1 line")
                            return
                        yield op
                    pos += len(line)
                return
            while True:
                frame_start = f.tell()
                header = f.read(_HEADER.size)
                if not header:
                    return  # clean EOF
                if len(header) < _HEADER.size:
                    corrupt(frame_start, "torn frame header")
                    return
                magic, version, n, crc = _HEADER.unpack(header)
                if magic != _MAGIC or version != _VERSION:
                    corrupt(frame_start, "bad frame magic/version")
                    return
                payload = f.read(n)
                if len(payload) < n:
                    corrupt(frame_start, "torn frame payload")
                    return
                if crc32(payload) != crc:
                    corrupt(frame_start, "frame CRC mismatch")
                    return
                try:
                    op = json.loads(payload)
                except json.JSONDecodeError:
                    corrupt(frame_start, "frame JSON undecodable")
                    return
                yield op

    def commit(self):
        """Roll to a new generation and drop old ones (called on flush:
        flushed segments now own the data, like Translog.commit)."""
        with self._lock:
            if self._fh is None:
                if self.path is not None:
                    raise TranslogClosedException(
                        f"translog [{self.path}] is closed")
                self._mem.clear()
                return
            try:
                self._sync_locked()
            except OSError:
                self._close_tragic()
                raise
            self._fh.close()
            old_gen = self.generation
            self.generation += 1
            self._fh = open(self._gen_path(self.generation), "ab")
            self._bytes_written = 0  # fresh generation
            for gen in range(1, old_gen + 1):
                p = self._gen_path(gen)
                if os.path.exists(p):
                    os.remove(p)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
