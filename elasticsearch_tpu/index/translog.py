"""Transaction log.

Reference: org/elasticsearch/index/translog/ — Translog.java (fs),
TranslogWriter-era logic: an append-only durability log, fsync policy,
generation rollover on flush ("commit"), and replay on recovery.

Format: one JSON line per operation (index/delete) — the payload is tiny
relative to device work, and line-framing makes replay/corruption handling
trivial. A C++ varint/binary codec is the planned R2 upgrade; the interface
(append/replay/commit) stays the same.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterator, Optional


class Translog:
    def __init__(self, path: Optional[str], durability: str = "request", sync_interval: float = 5.0):
        """path=None → in-memory only (durability off, e.g. ephemeral tests).

        durability: "request" fsyncs every append (ES index.translog.durability=
        request); "async" relies on OS flush + periodic sync.
        """
        self.path = path
        self.durability = durability
        self._lock = threading.Lock()
        self._ops_since_sync = 0
        self.generation = 1
        self._fh = None
        self._mem: list = []
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # find latest generation
            base = os.path.basename(path)
            d = os.path.dirname(path) or "."
            gens = []
            for f in os.listdir(d):
                if f.startswith(base + ".") and f.rpartition(".")[2].isdigit():
                    gens.append(int(f.rpartition(".")[2]))
            self.generation = max(gens) if gens else 1
            self._fh = open(self._gen_path(self.generation), "ab")

    def _gen_path(self, gen: int) -> str:
        return f"{self.path}.{gen}"

    @property
    def size_in_ops(self) -> int:
        if self.path is None:
            return len(self._mem)
        with self._lock:
            return self._count_ops()

    def _count_ops(self) -> int:
        n = 0
        p = self._gen_path(self.generation)
        if os.path.exists(p):
            with open(p, "rb") as f:
                n = sum(1 for _ in f)
        return n

    def append(self, op: dict):
        line = json.dumps(op, separators=(",", ":"))
        with self._lock:
            if self._fh is None:
                self._mem.append(op)
                return
            self._fh.write(line.encode() + b"\n")
            self._ops_since_sync += 1
            if self.durability == "request":
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._ops_since_sync = 0

    def sync(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._ops_since_sync = 0

    def replay(self, from_generation: int = 1) -> Iterator[dict]:
        """Yield ops from all generations >= from_generation (recovery)."""
        if self.path is None:
            yield from list(self._mem)
            return
        self.sync()
        for gen in range(from_generation, self.generation + 1):
            p = self._gen_path(gen)
            if not os.path.exists(p):
                continue
            with open(p, "rb") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        # torn tail write (crash mid-append): stop at corruption
                        return

    def commit(self):
        """Roll to a new generation and drop old ones (called on flush:
        flushed segments now own the data, like Translog.commit)."""
        with self._lock:
            if self._fh is None:
                self._mem.clear()
                return
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            old_gen = self.generation
            self.generation += 1
            self._fh = open(self._gen_path(self.generation), "ab")
            for gen in range(1, old_gen + 1):
                p = self._gen_path(gen)
                if os.path.exists(p):
                    os.remove(p)

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
