"""Index engine: the write path.

Reference: org/elasticsearch/index/engine/InternalEngine.java — in-memory
indexing buffer, near-real-time refresh, flush (durability handoff to
segments), versioned CRUD with optimistic concurrency, realtime GET served
from the not-yet-refreshed buffer, tombstone deletes, and merge scheduling.

TPU adaptation: "refresh" freezes the RAM buffer into an immutable
device-resident TpuSegment (instead of a Lucene flush-to-codec); deletes
flip bits in per-segment live masks; merge re-indexes live docs' _source
through the analysis chain into one new segment (equivalent output to a
postings-level merge because segments are derived purely from source+
mappings; noted deviation from Lucene's codec-level merge).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.analysis.registry import AnalysisRegistry
from elasticsearch_tpu.index.doc_parser import DocumentParser, ParsedDocument
from elasticsearch_tpu.index.mappings import Mappings
from elasticsearch_tpu.index.segment import SegmentBuilder, TpuSegment
from elasticsearch_tpu.index.seqno import (
    NO_OPS_PERFORMED,
    LocalCheckpointTracker,
)
from elasticsearch_tpu.index.translog import Translog
from elasticsearch_tpu.utils.errors import (
    DocumentMissingException,
    EngineFailedException,
    StalePrimaryException,
    VersionConflictException,
)
from elasticsearch_tpu.utils.faults import FAULTS


@dataclass
class DocLocation:
    version: int
    deleted: bool = False
    # "buffer" or a segment id; buffer docs re-resolve on refresh
    where: Any = "buffer"
    local_id: int = -1
    source: Optional[dict] = None  # for realtime get of buffered docs
    # _type/_parent meta preserved across partial updates & re-index
    doc_type: Optional[str] = None
    parent: Optional[str] = None
    routing: Optional[str] = None
    # resolved _timestamp (epoch millis) / _ttl expiry — served by GET
    # fields=_timestamp/_ttl without a segment lookup
    timestamp: Optional[int] = None
    ttl_expiry: Optional[int] = None
    # replication identity: the (primary term, seq no) the op that wrote
    # this state carried — recovery's full-copy path ships them so a
    # rebuilt copy keeps the same op lineage (index/seqno.py)
    seq_no: int = -2  # UNASSIGNED_SEQ_NO
    term: int = 0


@dataclass
class EngineStats:
    index_total: int = 0
    delete_total: int = 0
    get_total: int = 0
    refresh_total: int = 0
    flush_total: int = 0
    merge_total: int = 0
    index_time_ms: float = 0.0
    # per-doc-type indexing counters (reference: ShardIndexingService
    # typeStats feeding IndexingStats.Stats per type — the `types` scope
    # of _stats)
    types: Dict[str, Dict[str, int]] = field(default_factory=dict)

    def on_type(self, doc_type: Optional[str], op: str) -> None:
        ts = self.types.setdefault(doc_type or "_doc",
                                   {"index_total": 0, "delete_total": 0})
        ts[op] += 1


class Engine:
    """In-memory Lucene-equivalent: buffer → frozen TpuSegments, doc
    identity, versioning, translog durability.

    Lock order (verified acyclic by tpulint R013's interprocedural lock
    graph — keep it that way): ``Engine._lock`` is the OUTERMOST lock of
    the write path; under it we take ``Translog._lock`` (appends/fsync),
    ``LocalCheckpointTracker._lock`` (seqno advance), and the
    process-shared metrics/native locks. Nothing below may call back
    into an Engine public method while holding its own lock.
    """

    def __init__(
        self,
        mappings: Mappings,
        analysis: AnalysisRegistry,
        translog_path: Optional[str] = None,
        refresh_interval_docs: int = 0,
        merge_segment_count: int = 8,
        index_name: str = "",
    ):
        self.index_name = index_name  # for typed errors: "engine for [x]"
        self.mappings = mappings
        self.analysis = analysis
        self.parser = DocumentParser(mappings, analysis)
        self.translog = Translog(translog_path)
        self.buffer = SegmentBuilder(mappings)
        self.segments: List[TpuSegment] = []
        self._locations: Dict[str, DocLocation] = {}
        self._buffer_ids: Dict[str, int] = {}
        self._lock = threading.RLock()
        self.stats = EngineStats()
        # commit identity for the _stats shards level (reference: Lucene
        # SegmentInfos commit id/generation in CommitStats)
        import uuid as _uuid

        self.commit_id = _uuid.uuid4().hex
        self.merge_segment_count = merge_segment_count
        from elasticsearch_tpu.index.merge import TieredMergePolicy

        self.merge_policy = TieredMergePolicy(
            segments_per_tier=merge_segment_count)
        self._auto_id = 0
        # tragic-event state: non-None after a durability-critical IO
        # failure; every later write 503s (reference: failEngine)
        self.failed_reason: Optional[str] = None
        # replication safety (index/seqno.py): the term under which this
        # copy believes its shard's primary operates, the local-checkpoint
        # tracker, and the per-term max-seq-no history used for the
        # log-matching check peer recovery does before ops replay
        self.primary_term = 1
        self.seq = LocalCheckpointTracker()
        self._term_seq: Dict[int, int] = {}

    # -- primary terms / sequence numbers ---------------------------------------

    @property
    def local_checkpoint(self) -> int:
        return self.seq.checkpoint

    @property
    def max_seq_no(self) -> int:
        return self.seq.max_seq_no

    def bump_term(self, term: int) -> None:
        """Adopt a higher primary term (promotion, or learning the new
        term from a newer primary's op/recovery stream)."""
        with self._lock:
            if term > self.primary_term:
                self.primary_term = term

    def _fence_term(self, op_term: Optional[int],
                    history: bool = False) -> int:
        """Term handling for one op. LIVE ops (a primary's own writes,
        replica fanout) are FENCED: an op from a term older than this
        copy's current one comes from a demoted primary and is rejected;
        a newer term is adopted. HISTORY ops (translog replay, recovery
        streams) apply under their original recorded term without
        fencing — replaying a term-1 op onto a term-2 copy is the normal
        shape of catching up, not a zombie write (reference: the request-
        level term check in TransportReplicationAction fences live ops;
        recovery replays history below the current term freely). Must
        hold ``_lock``."""
        if op_term is None:
            return self.primary_term  # primary-local op: current term
        if history:
            return op_term
        if op_term < self.primary_term:
            raise StalePrimaryException(self.index_name, "?", op_term,
                                        self.primary_term)
        if op_term > self.primary_term:
            self.primary_term = op_term
        return op_term

    def _note_op(self, term: int, seq_no: int) -> None:
        """Record (term, seq no) into the per-term history and the local
        checkpoint tracker. Must hold ``_lock``."""
        if seq_no < 0:
            return
        self.seq.mark_processed(seq_no)
        cur = self._term_seq.get(term, NO_OPS_PERFORMED)
        if seq_no > cur:
            self._term_seq[term] = seq_no

    def term_at(self, seq_no: int) -> Optional[int]:
        """The primary term the op at ``seq_no`` ran under — the lowest
        term whose recorded max seq no covers it (term boundaries are
        strict: a new primary continues numbering past its predecessor).
        None when this engine holds no record of that seq no."""
        if seq_no < 0:
            return 0  # vacuous: an empty copy matches any history
        with self._lock:
            for term in sorted(self._term_seq):
                if self._term_seq[term] >= seq_no:
                    return term
        return None

    def seq_no_stats(self) -> dict:
        return {"max_seq_no": self.max_seq_no,
                "local_checkpoint": self.local_checkpoint,
                "primary_term": self.primary_term}

    def note_noop(self, seq_no: Optional[int], term: Optional[int]) -> None:
        """Mark an op's seq no processed WITHOUT applying it — the no-op
        path for a replayed/fanned op whose effect is already covered by
        newer state (version conflict, tombstoned doc). Without this, a
        skipped op leaves a permanent hole above the local checkpoint:
        the checkpoint (and hence the shard's global checkpoint) stalls
        forever and every later recovery re-replays from the hole — or,
        once the source flushes those ops away, falls back to full copies
        for good. Reference: InternalEngine records NOOP operations for
        exactly this (Engine.NoOp)."""
        if seq_no is None:
            return
        with self._lock:
            self._note_op(term if term is not None else self.primary_term,
                          seq_no)

    def adopt_seq_state(self, term_seq: Dict[int, int], checkpoint: int,
                        term: int) -> None:
        """Full-copy recovery target: the source shipped its complete
        state, so adopt its checkpoint and per-term history. Entries for
        terms BELOW the source's current term are REPLACED, not merged —
        a diverged copy's phantom ops (a zombie write that advanced its
        old-term max past the source's) would otherwise poison
        ``term_at`` and fail the log-matching check on every future
        handshake. Current-term entries max-merge: live fanout ops racing
        the copy legitimately extend that term past the snapshot."""
        with self._lock:
            fresh = {int(t): m for t, m in (term_seq or {}).items()}
            for t, m in self._term_seq.items():
                if t >= term and m > fresh.get(t, NO_OPS_PERFORMED):
                    fresh[t] = m
            self._term_seq = fresh
            self.seq.advance_to(checkpoint)
            if term > self.primary_term:
                self.primary_term = term

    def recovery_ops(self, checkpoint: int,
                     last_term: Optional[int] = None) -> Optional[list]:
        """Recovery source: the translog op suffix above the target's
        ``checkpoint``, or None when ops-based replay is unsafe and the
        caller must fall back to a full copy. Unsafe means: the target is
        ahead of us (diverged zombie copy), the target's history doesn't
        match ours at its checkpoint (log-matching check — the op at the
        target's checkpoint must carry the term the target says it does),
        or the retained translog no longer covers the whole suffix
        (generations dropped by a flush commit)."""
        with self._lock:
            if checkpoint > self.seq.checkpoint:
                return None  # target claims ops we never assigned/diverged
            if checkpoint >= 0 and last_term is not None:
                t = self.term_at(checkpoint)
                if t is None or t != last_term:
                    return None  # diverged history: full copy required
            # coverage is judged against the max seq no AT THIS POINT;
            # the log scan below runs OUTSIDE the engine lock so a
            # recovery handshake never stalls client writes — ops that
            # land during the scan reach the target via live fanout
            # (phase-2 semantics), exactly like ops landing after the
            # snapshot would
            upper = self.seq.max_seq_no
        by_seq: Dict[int, dict] = {}
        try:
            for op in self.translog.ops_above(checkpoint):
                s = op["seq_no"]
                prev = by_seq.get(s)
                if prev is None or op.get("term", 0) >= prev.get("term", 0):
                    by_seq[s] = op
        except OSError:
            return None  # unreadable log: full copy
        need = range(checkpoint + 1, upper + 1)
        if any(s not in by_seq for s in need):
            return None  # retention gap (flushed away): full copy
        return [by_seq[s] for s in sorted(by_seq) if s <= upper]

    # -- tragic events -----------------------------------------------------------

    @property
    def is_failed(self) -> bool:
        return self.failed_reason is not None

    def fail(self, reason: str) -> None:
        """Fail the engine closed after a tragic event. Idempotent; the
        translog channel is already closed by its own tragic handler,
        but close again defensively for non-translog callers."""
        with self._lock:
            if self.failed_reason is not None:
                return
            self.failed_reason = reason
            try:
                self.translog.close()
            except OSError:
                pass  # the channel is what failed; state flag is what matters
        # flight recorder (outside the engine lock — R013): a tragic
        # engine event is exactly the evidence that dies with the
        # process; engines have no node back-ref, so fan process-wide
        try:
            from elasticsearch_tpu.monitor import flight

            flight.record("engine_failures", index=self.index_name,
                          reason=reason)
        except Exception:  # tpulint: allow[R006] — recording must never
            pass           # compound a tragic event

    def _ensure_open(self) -> None:
        if self.failed_reason is not None:
            raise EngineFailedException(self.index_name, self.failed_reason)

    def _translog_append(self, entry: dict) -> None:
        """Append with tragic-event semantics: an IO/fsync failure fails
        the engine CLOSED and the triggering op is NOT acknowledged —
        so the set of acknowledged ops is exactly the set replay can
        reproduce (no silently-lost writes). The op's in-memory mutation
        is NOT rolled back (segment live-masks can't un-delete), so reads
        may see it until restart — a documented deviation from the
        reference, which closes reads too (docs/ROBUSTNESS.md)."""
        try:
            self.translog.append(entry)
        except OSError as e:
            self.fail(f"translog append failed: {e}")
            raise EngineFailedException(
                self.index_name, f"translog append failed: {e}") from e

    # -- write path ------------------------------------------------------------

    def index(
        self,
        doc_id: Optional[str],
        source: dict,
        version: Optional[int] = None,
        version_type: str = "internal",
        op_type: str = "index",
        routing: Optional[str] = None,
        doc_type: Optional[str] = None,
        parent: Optional[str] = None,
        timestamp: Optional[object] = None,
        ttl: Optional[object] = None,
        ttl_expiry: Optional[int] = None,
        seq_no: Optional[int] = None,
        primary_term: Optional[int] = None,
        _replay: bool = False,
        _history: bool = False,
    ) -> Tuple[str, int, bool]:
        """Index/create a document. Returns (id, new_version, created).

        Version semantics mirror InternalEngine.index: internal versioning
        requires the provided version to equal the current one; external
        requires it to be strictly greater. op_type=create fails if the doc
        exists (DocWriteRequest.OpType.CREATE).

        seq_no/primary_term: None on the primary (a fresh seq no is
        assigned under the engine's current term); replicas, translog
        replay, and recovery streams pass the primary-assigned identity
        through — and an op from a stale term is rejected with
        StalePrimaryException before any state mutates.
        """
        t0 = time.perf_counter()
        with self._lock:
            self._ensure_open()
            op_term = self._fence_term(primary_term, history=_history)
            if doc_id is None:
                self._auto_id += 1
                doc_id = f"auto_{self._auto_id}_{int(time.time() * 1000)}"
            doc_id = str(doc_id)
            loc = self._locations.get(doc_id)
            current = loc.version if (loc and not loc.deleted) else 0
            exists = loc is not None and not loc.deleted
            if op_type == "create" and exists:
                raise VersionConflictException(self.index_name, doc_id,
                                               current, 0)
            if version is not None:
                if version_type == "force":
                    # force: set the version unconditionally (reference:
                    # VersionType.FORCE, 2.0-era repair tool semantics)
                    new_version = version
                elif version_type in ("external", "external_gt", "external_gte"):
                    ok = (loc is None or version > loc.version
                          or (version_type == "external_gte" and version >= loc.version))
                    if not ok:
                        raise VersionConflictException("", doc_id, loc.version, version)
                    new_version = version
                else:
                    if current != version:
                        raise VersionConflictException("", doc_id, current, version)
                    new_version = current + 1
            else:
                new_version = (loc.version if loc else 0) + 1

            parsed = self.parser.parse(doc_id, source, routing=routing,
                                       doc_type=doc_type, parent=parent,
                                       timestamp=timestamp, ttl=ttl,
                                       ttl_expiry=ttl_expiry)
            # seq no assignment AFTER validation: a rejected op must not
            # consume a number (we keep the primary's stream contiguous
            # instead of logging no-ops for failures)
            if seq_no is None:
                seq_no = self.seq.generate()
            self._remove_existing(doc_id)
            local = self.buffer.add(parsed)
            self._buffer_ids[doc_id] = local
            self._locations[doc_id] = DocLocation(
                version=new_version, deleted=False, where="buffer", local_id=local,
                source=source, doc_type=doc_type, parent=parent, routing=routing,
                timestamp=parsed.meta.get("timestamp"),
                ttl_expiry=parsed.meta.get("ttl_expiry"),
                seq_no=seq_no, term=op_term,
            )
            if not _replay:
                entry = {"op": "index", "id": doc_id, "source": source,
                         "version": new_version, "routing": routing,
                         "seq_no": seq_no, "term": op_term}
                if doc_type:
                    entry["doc_type"] = doc_type
                if parent:
                    entry["parent"] = parent
                # resolved meta-field values: replay must reproduce them
                # exactly (re-resolving "now" later would drift)
                if "timestamp" in parsed.meta:
                    entry["timestamp"] = parsed.meta["timestamp"]
                if "ttl_expiry" in parsed.meta:
                    entry["ttl_expiry"] = parsed.meta["ttl_expiry"]
                self._translog_append(entry)
            # checkpoint advances only once durability settled: a tragic
            # append raised above and this op stays un-processed
            self._note_op(op_term, seq_no)
            self.stats.index_total += 1
            self.stats.on_type(doc_type, "index_total")
            self.stats.index_time_ms += (time.perf_counter() - t0) * 1000
            return doc_id, new_version, not exists

    def delete(self, doc_id: str, version: Optional[int] = None,
               version_type: str = "internal",
               seq_no: Optional[int] = None,
               primary_term: Optional[int] = None,
               _replay: bool = False,
               _history: bool = False) -> int:
        with self._lock:
            self._ensure_open()
            op_term = self._fence_term(primary_term, history=_history)
            doc_id = str(doc_id)
            loc = self._locations.get(doc_id)
            if loc is None or loc.deleted:
                raise DocumentMissingException("", doc_id)
            if version is not None:
                if version_type == "internal" and loc.version != version:
                    raise VersionConflictException("", doc_id, loc.version,
                                                   version)
                if version_type in ("external", "external_gt") \
                        and version <= loc.version:
                    raise VersionConflictException("", doc_id, loc.version,
                                                   version)
                if version_type == "external_gte" and version < loc.version:
                    raise VersionConflictException("", doc_id, loc.version,
                                                   version)
            if seq_no is None:
                seq_no = self.seq.generate()
            self._remove_existing(doc_id)
            if version is not None and version_type in (
                    "external", "external_gt", "external_gte", "force"):
                new_version = version  # external deletes stamp the version
            else:
                new_version = loc.version + 1
            self._locations[doc_id] = DocLocation(
                version=new_version, deleted=True, where=None,
                seq_no=seq_no, term=op_term)
            if not _replay:
                self._translog_append({"op": "delete", "id": doc_id,
                                       "version": new_version,
                                       "seq_no": seq_no, "term": op_term})
            self._note_op(op_term, seq_no)
            self.stats.delete_total += 1
            self.stats.on_type(loc.doc_type, "delete_total")
            return new_version

    def update(self, doc_id: str, partial: Optional[dict] = None,
               script: Optional[str] = None, script_params: Optional[dict] = None,
               upsert: Optional[dict] = None, doc_as_upsert: bool = False,
               scripted_upsert: bool = False,
               doc_type: Optional[str] = None, routing: Optional[str] = None,
               parent: Optional[str] = None, version: Optional[int] = None,
               version_type: str = "internal",
               timestamp: Optional[object] = None,
               ttl: Optional[object] = None,
               primary_term: Optional[int] = None) -> Tuple[int, bool]:
        """Partial update (RestUpdateAction semantics): merge `partial` into
        the current source, or create from `upsert` when missing. Only
        internal versioning applies (reference: UpdateRequest.validate
        rejects external version types)."""
        if version is not None and version_type not in ("internal",):
            from elasticsearch_tpu.utils.errors import \
                ActionRequestValidationException

            raise ActionRequestValidationException(
                f"version type [{version_type}] is not supported by the "
                f"update API")
        with self._lock:
            doc_id = str(doc_id)
            got = self.get(doc_id)
            if got is None:
                if version is not None:
                    # versioned update on a missing doc is a conflict, even
                    # with an upsert (TransportUpdateAction)
                    raise VersionConflictException("", doc_id, -1, version)
                if upsert is not None:
                    up = dict(upsert)
                    if scripted_upsert and script is not None:
                        # scripted_upsert: the script transforms the upsert
                        # doc before the insert (UpdateHelper.prepare)
                        up = self._run_update_script(
                            script, script_params or {}, up)
                    _, v, _ = self.index(doc_id, up, doc_type=doc_type,
                                         routing=routing, parent=parent,
                                         timestamp=timestamp, ttl=ttl,
                                         primary_term=primary_term)
                    return v, True
                if doc_as_upsert and partial is not None:
                    _, v, _ = self.index(doc_id, partial, doc_type=doc_type,
                                         routing=routing, parent=parent,
                                         timestamp=timestamp, ttl=ttl,
                                         primary_term=primary_term)
                    return v, True
                raise DocumentMissingException("", doc_id)
            if version is not None and got["_version"] != version:
                raise VersionConflictException("", doc_id, got["_version"],
                                               version)
            source = dict(got["_source"])
            if script is not None:
                source = self._run_update_script(script, script_params or {}, source)
            elif partial is not None:
                _deep_merge(source, partial)
            # carry _type/_parent/routing through the re-index, else a
            # partial update would sever the parent-child join
            loc = self._locations.get(doc_id)
            _, v, _ = self.index(
                doc_id, source,
                routing=(loc.routing if loc and loc.routing else routing),
                doc_type=loc.doc_type if loc else doc_type,
                parent=(loc.parent if loc and loc.parent else parent),
                timestamp=timestamp, ttl=ttl,
                primary_term=primary_term,
            )
            return v, False

    def _run_update_script(self, script: str, params: dict, source: dict) -> dict:
        """Update scripts mutate ctx._source; painless-lite is expression-only,
        so we support the common `ctx._source.<field> = <expr>` statement list.
        Groovy binds params as BARE variables (`ctx._source.foo = bar` with
        params {bar: ...}) — the expression compiler binds them directly
        (AST-level, so string literals equal to a param name are never
        touched)."""
        from elasticsearch_tpu.search.scripting import compile_script
        from elasticsearch_tpu.utils.errors import ScriptException

        reserved = {"doc", "params", "Math", "ctx", "_score", "_source",
                    "true", "false", "null"}
        extra = tuple(pn for pn in (params or {})
                      if pn.isidentifier() and pn not in reserved)
        for stmt in script.split(";"):
            stmt = stmt.strip()
            if not stmt:
                continue
            if "=" in stmt and "==" not in stmt.split("=", 1)[0]:
                lhs, _, rhs = stmt.partition("=")
                lhs = lhs.strip()
                prefix = "ctx._source."
                if not lhs.startswith(prefix):
                    raise ScriptException(f"update script must assign ctx._source.*: [{stmt}]")
                field = lhs[len(prefix):]
                rhs = rhs.strip()
                for fname, fval in source.items():
                    rhs = rhs.replace(f"ctx._source.{fname}", repr(fval))
                cs = compile_script(rhs, extra_vars=extra)
                val = cs.run(lambda f: None, params=params)
                if hasattr(val, "item"):
                    val = val.item()
                source[field] = val
            else:
                raise ScriptException(f"unsupported update script statement [{stmt}]")
        return source

    def _remove_existing(self, doc_id: str):
        loc = self._locations.get(doc_id)
        if loc is None or loc.deleted:
            return
        if loc.where == "buffer":
            # mark the buffered doc dead; freeze() skips tombstoned entries
            idx = self._buffer_ids.pop(doc_id, None)
            if idx is not None:
                self.buffer.docs[idx] = None  # type: ignore[assignment]
        else:
            for seg in self.segments:
                if seg.seg_id == loc.where:
                    seg.delete_local(loc.local_id)
                    break

    # -- read path -------------------------------------------------------------

    def get(self, doc_id: str, realtime: bool = True) -> Optional[dict]:
        """Realtime get: buffered docs are visible before refresh (ES serves
        these from the translog; we keep the source on the DocLocation)."""
        with self._lock:
            self.stats.get_total += 1
            doc_id = str(doc_id)
            loc = self._locations.get(doc_id)
            if loc is None or loc.deleted:
                return None
            if loc.where == "buffer":
                if not realtime:
                    return None
                return {"_id": doc_id, "_type": loc.doc_type or "_doc",
                        "_version": loc.version, "_source": loc.source,
                        "found": True}
            for seg in self.segments:
                if seg.seg_id == loc.where:
                    return {
                        "_id": doc_id,
                        "_type": loc.doc_type or "_doc",
                        "_version": loc.version,
                        "_source": seg.sources[loc.local_id],
                        "found": True,
                    }
            return None

    def exists(self, doc_id: str) -> bool:
        loc = self._locations.get(str(doc_id))
        return loc is not None and not loc.deleted

    @property
    def num_docs(self) -> int:
        with self._lock:
            return sum(1 for l in self._locations.values() if not l.deleted)

    # -- lifecycle -------------------------------------------------------------

    def purge_expired(self) -> int:
        """Delete docs whose _ttl expiry has passed (reference: indices/ttl/
        IndicesTTLService.java — the TTL purger; here it runs on refresh and
        merge). Expiry columns scan vectorized; deletes go through the
        normal tombstone path so versions/translog stay consistent."""
        if not getattr(self.mappings, "_ttl_enabled", False) \
                or self.failed_reason is not None:
            return 0  # a failed engine accepts no deletes (reads still serve)
        import numpy as np

        now = int(time.time() * 1000)
        expired: List[str] = []
        with self._lock:
            for seg in self.segments:
                col = seg.numerics.get("_ttl")
                if col is None or col.exact is None:
                    continue
                n = seg.num_docs
                hit = np.nonzero(seg.live_host[:n]
                                 & np.asarray(col.exists)[:n]
                                 & (col.exact[:n] < now))[0]
                expired.extend(seg.ids[int(i)] for i in hit)
            for d in self.buffer.docs:
                if (d is not None and d.doc_values.get("_ttl")
                        and d.doc_values["_ttl"][0] < now):
                    expired.append(d.doc_id)
            for doc_id in expired:
                try:
                    self.delete(doc_id)
                except DocumentMissingException:
                    pass
        return len(expired)

    def refresh(self) -> bool:
        """Freeze the buffer into a new searchable segment (NRT refresh)."""
        with self._lock:
            self.purge_expired()
            # roots only: tombstoned roots leave orphan children in the
            # buffer arrays; re-adding a root re-emits its block
            live_docs = [d for d, p in zip(self.buffer.docs, self.buffer.parent_of)
                         if d is not None and p == -1]
            if not live_docs:
                return False
            # refresh failure is RETRYABLE, not tragic: the buffer keeps
            # the docs and a later refresh serves them (unlike a translog
            # failure, nothing acknowledged is at risk)
            FAULTS.check("segment.freeze", index=self.index_name)
            fresh = SegmentBuilder(self.mappings)
            for d in live_docs:
                fresh.add(d)
            seg = fresh.freeze()
            try:
                self._charge_segment(seg)
            except Exception:
                # reclaim before giving up: merging away deleted docs is the
                # one path that frees breaker budget, and it would otherwise
                # be unreachable (maybe_merge only runs after a SUCCESSFUL
                # refresh) — a tripped breaker must not wedge forever
                self.maybe_merge()
                self._charge_segment(seg)
            self.segments.append(seg)
            for doc_id, local in list(seg.id_map.items()):
                loc = self._locations.get(doc_id)
                if loc is not None and loc.where == "buffer":
                    loc.where = seg.seg_id
                    loc.local_id = local
                    loc.source = None
            self.buffer = SegmentBuilder(self.mappings)
            self._buffer_ids.clear()
            self.stats.refresh_total += 1
            self.maybe_merge()
            return True

    def flush(self):
        """refresh + translog commit (durability handed to segments).

        NOTE: segments live in device/host memory; true on-disk segment
        persistence is the snapshot API's job (index/snapshots.py). Flush
        semantics here = translog generation rollover after refresh, same
        contract as InternalEngine.flush."""
        with self._lock:
            self.refresh()
            try:
                self.translog.commit()
            except OSError as e:
                # commit fsyncs before dropping generations — a failure
                # here is as tragic as a failed append
                self.fail(f"translog commit failed: {e}")
                raise EngineFailedException(
                    self.index_name, f"translog commit failed: {e}") from e
            self.stats.flush_total += 1

    def merge(self, max_segments: Optional[int] = None,
              subset: Optional[List[TpuSegment]] = None):
        """Merge segments by re-indexing live docs' source through the
        parser. With ``subset``: a policy-selected partial merge (tiered);
        without: force-merge everything down to one segment (optimize)."""
        with self._lock:
            self.purge_expired()
            if subset is None and len(self.segments) <= (max_segments or 1):
                return
            targets = subset if subset is not None else list(self.segments)
            target_ids = {s.seg_id for s in targets}
            builder = SegmentBuilder(self.mappings)
            from elasticsearch_tpu.tracing import check_cancelled

            for seg in targets:
                # cooperative cancellation between source segments: a
                # cancelled force-merge task (POST /_optimize) aborts
                # before the freeze — nothing committed, nothing lost
                check_cancelled()
                live = seg.live_host
                roots = seg.roots_host
                for local, doc_id in enumerate(seg.ids):
                    if live[local] and (roots is None or roots[local]):
                        meta = seg.metas[local] if local < len(seg.metas) else {}
                        builder.add(self.parser.parse(
                            doc_id, seg.sources[local],
                            routing=meta.get("routing"),
                            doc_type=meta.get("_type"), parent=meta.get("_parent"),
                            timestamp=meta.get("timestamp"),
                            ttl_expiry=meta.get("ttl_expiry")))
            merged = builder.freeze()
            keep = [s for s in self.segments if s.seg_id not in target_ids]
            # release-then-charge: a merge nets memory DOWN, so it charges
            # unconditionally (force) — only NEW data (refresh) can trip
            # the breaker
            from elasticsearch_tpu.index.segment import SEGMENT_HBM_BUDGET

            for s in targets:
                SEGMENT_HBM_BUDGET.release(getattr(s, "_hbm_charged", 0))
                s._hbm_charged = 0
            if merged is not None:
                merged._hbm_charged = merged.memory_bytes()
                SEGMENT_HBM_BUDGET.force(merged._hbm_charged)
                keep.append(merged)
                for doc_id, local in merged.id_map.items():
                    loc = self._locations.get(doc_id)
                    if loc is not None and not loc.deleted:
                        loc.where = merged.seg_id
                        loc.local_id = local
            self.segments[:] = keep  # in place: searchers share this list
            self.stats.merge_total += 1

    def maybe_merge(self):
        """Background-style merge check (reference: InternalEngine's
        maybeMerge via EsConcurrentMergeScheduler — synchronous here)."""
        with self._lock:
            found = self.merge_policy.find_merge(self.segments)
            if found and len(found) >= 1:
                self.merge(subset=found)

    def recover_from_translog(self) -> int:
        """Replay the translog (crash recovery / shard recovery). Frames
        carry (term, seq_no), so replay restores the seq-no tracker, the
        per-term history, AND the primary term itself — a term bump
        survives engine close/reopen. Returns ops replayed."""
        from elasticsearch_tpu.index.seqno import UNASSIGNED_SEQ_NO

        replayed = 0
        max_term = 0
        with self._lock:
            for op in self.translog.replay():
                max_term = max(max_term, op.get("term", 0))
                # legacy (pre-seqno) frames stay UNASSIGNED: minting a
                # fresh number here would fabricate checkpoint/term
                # history the primary never assigned, and a later
                # log-matching handshake could falsely pass on it
                seq = op.get("seq_no", UNASSIGNED_SEQ_NO)
                seq = UNASSIGNED_SEQ_NO if seq is None else seq
                if op["op"] == "index":
                    self.index(op["id"], op["source"], routing=op.get("routing"),
                               doc_type=op.get("doc_type"), parent=op.get("parent"),
                               timestamp=op.get("timestamp"),
                               ttl_expiry=op.get("ttl_expiry"),
                               seq_no=seq,
                               primary_term=op.get("term"),
                               _replay=True, _history=True)
                    self._locations[op["id"]].version = op["version"]
                    replayed += 1
                elif op["op"] == "delete":
                    try:
                        self.delete(op["id"], seq_no=seq,
                                    primary_term=op.get("term"),
                                    _replay=True, _history=True)
                        replayed += 1
                    except DocumentMissingException:
                        pass
            # the highest term in the log IS this copy's term: a bump
            # survives close/reopen
            self.bump_term(max_term)
        return replayed

    def apply_translog_op(self, op: dict) -> None:
        """Apply ONE foreign translog op (the ops-based peer-recovery
        stream): the op's own version rides external_gte so a newer state
        already on this copy (a racing live-fanout write) wins, and its
        (term, seq_no) identity is preserved. Raises VersionConflict /
        DocumentMissing for the caller to count as already-newer skips."""
        if op["op"] == "delete":
            self.delete(op["id"], version=op.get("version"),
                        version_type="external_gte" if op.get("version")
                        is not None else "internal",
                        seq_no=op.get("seq_no"), primary_term=op.get("term"),
                        _replay=True, _history=True)
            return
        self.index(op["id"], op["source"], version=op.get("version"),
                   version_type="external_gte" if op.get("version")
                   is not None else "internal",
                   routing=op.get("routing"), doc_type=op.get("doc_type"),
                   parent=op.get("parent"), timestamp=op.get("timestamp"),
                   ttl_expiry=op.get("ttl_expiry"),
                   seq_no=op.get("seq_no"), primary_term=op.get("term"),
                   _replay=True, _history=True)

    def _charge_segment(self, seg) -> None:
        """Charge a fresh segment against the node HBM breaker; raises
        CircuitBreakingException (429) when the budget would be exceeded —
        the refresh fails, buffered docs stay buffered, the node survives."""
        from elasticsearch_tpu.index.segment import SEGMENT_HBM_BUDGET
        from elasticsearch_tpu.utils.errors import CircuitBreakingException

        n = seg.memory_bytes()
        if not SEGMENT_HBM_BUDGET.reserve(n):
            raise CircuitBreakingException(
                f"[segments] data for new segment would be "
                f"[{SEGMENT_HBM_BUDGET.used + n}/{SEGMENT_HBM_BUDGET.total}]"
                f" bytes, which is larger than the limit")
        seg._hbm_charged = n

    def close(self):
        from elasticsearch_tpu.index.segment import SEGMENT_HBM_BUDGET

        for seg in self.segments:
            SEGMENT_HBM_BUDGET.release(getattr(seg, "_hbm_charged", 0))
            seg._hbm_charged = 0
        self.translog.close()


def _deep_merge(dst: dict, src: dict):
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
