"""Snapshot / restore to a filesystem repository.

Reference: org/elasticsearch/snapshots/SnapshotsService.java,
repositories/fs/FsRepository.java, repositories/blobstore/
BlobStoreRepository.java — snapshots are incremental at the file level:
unchanged segment files are referenced, not re-copied.

TPU adaptation: device-resident segment arrays are *derived* state
(rebuilt deterministically from _source + mappings by SegmentBuilder), so
the durable unit is the segment's doc block: ids + sources + meta
(_type/_parent/routing) + versions + tombstones. Incrementality matches
the reference's: each frozen segment serializes to a content-addressed
blob (sha256 of its canonical JSON); re-snapshotting an index only writes
blobs for segments that changed since the last snapshot. Restore replays
blobs through the ordinary write path, which regenerates identical device
arrays (same inversion Lucene gets by copying codec files).

Layout under the repository root:
    blobs/<sha256>.json.gz      one frozen segment's doc block
    snapshots/<name>.json       snapshot manifest (indices, blob refs)
    index.json                  repository catalog (snapshot list)
"""
from __future__ import annotations

import base64
import gzip
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.utils.errors import ElasticsearchTpuException


class SnapshotMissingException(ElasticsearchTpuException):
    status = 404
    error_type = "snapshot_missing_exception"


class SnapshotException(ElasticsearchTpuException):
    status = 400
    error_type = "snapshot_exception"


class FsRepository:
    """Content-addressed blob store on the local filesystem."""

    def __init__(self, name: str, location: str, compress: bool = True):
        self.name = name
        self.location = location
        self.compress = compress
        os.makedirs(os.path.join(location, "blobs"), exist_ok=True)
        os.makedirs(os.path.join(location, "snapshots"), exist_ok=True)

    # -- blobs -----------------------------------------------------------------

    def put_blob(self, payload: dict) -> str:
        raw = json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()
        sha = hashlib.sha256(raw).hexdigest()
        path = os.path.join(self.location, "blobs", f"{sha}.json.gz")
        if not os.path.exists(path):  # incremental: content-addressed
            tmp = path + ".tmp"
            with gzip.open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)
        return sha

    def get_blob(self, sha: str) -> dict:
        path = os.path.join(self.location, "blobs", f"{sha}.json.gz")
        if not os.path.exists(path):
            raise SnapshotException(f"missing blob [{sha}] in repository [{self.name}]")
        with gzip.open(path, "rb") as f:
            return json.loads(f.read())

    # -- manifests -------------------------------------------------------------

    def _catalog_path(self) -> str:
        return os.path.join(self.location, "index.json")

    def catalog(self) -> List[str]:
        p = self._catalog_path()
        if not os.path.exists(p):
            return []
        with open(p) as f:
            return json.load(f).get("snapshots", [])

    def _write_catalog(self, names: List[str]):
        tmp = self._catalog_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"snapshots": sorted(names)}, f)
        os.replace(tmp, self._catalog_path())

    def put_manifest(self, name: str, manifest: dict):
        path = os.path.join(self.location, "snapshots", f"{name}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, path)
        cat = self.catalog()
        if name not in cat:
            cat.append(name)
            self._write_catalog(cat)

    def get_manifest(self, name: str) -> dict:
        path = os.path.join(self.location, "snapshots", f"{name}.json")
        if not os.path.exists(path):
            raise SnapshotMissingException(
                f"[{self.name}:{name}] is missing")
        with open(path) as f:
            return json.load(f)

    def delete_snapshot(self, name: str):
        path = os.path.join(self.location, "snapshots", f"{name}.json")
        if not os.path.exists(path):
            raise SnapshotMissingException(f"[{self.name}:{name}] is missing")
        os.remove(path)
        self._write_catalog([n for n in self.catalog() if n != name])
        self._gc_blobs()

    def _gc_blobs(self):
        """Drop blobs referenced by no remaining snapshot (reference:
        BlobStoreRepository cleanup after delete)."""
        live = set()
        for name in self.catalog():
            m = self.get_manifest(name)
            for idx in m["indices"].values():
                for shard in idx["shards"]:
                    live.update(shard["blobs"])
        blob_dir = os.path.join(self.location, "blobs")
        for fn in os.listdir(blob_dir):
            sha = fn.split(".", 1)[0]
            if sha not in live:
                os.remove(os.path.join(blob_dir, fn))


# ---------------------------------------------------------------------------
# snapshot / restore over a Node
# ---------------------------------------------------------------------------

def _segment_payload(seg) -> dict:
    """Canonical doc block of one frozen segment (roots only — children are
    re-derived from the root source on restore)."""
    docs = []
    roots = seg.roots_host
    for local, doc_id in enumerate(seg.ids):
        if not seg.live_host[local]:
            continue
        if roots is not None and not roots[local]:
            continue
        meta = seg.metas[local] if local < len(seg.metas) else {}
        docs.append({
            "id": doc_id,
            "source": seg.sources[local],
            "meta": meta,
        })
    payload = {"docs": docs}
    # carry each built IVF quantizer so restore can seed the
    # content-addressed cache (index/ivf_cache.py) instead of re-running
    # k-means — hits whenever the restored slab content matches (the
    # single-segment, no-pruned-deletes case; drift misses and rebuilds)
    ivf_blobs = []
    for fname, vc in getattr(seg, "vectors", {}).items():
        ivf = vc._ivf
        if not ivf:
            continue
        from elasticsearch_tpu.index import ivf_cache

        # memoized on the (immutable) column — no re-hash per snapshot
        key = vc.cache_key(seg.max_docs)
        blob = ivf_cache.store(key, ivf)
        ivf_blobs.append({
            "field": fname, "key": key,
            "blob": base64.b64encode(blob).decode("ascii"),
        })
    if ivf_blobs:
        payload["ivf"] = ivf_blobs
    return payload


def create_snapshot(node, repo: FsRepository, snap_name: str,
                    indices: Optional[List[str]] = None,
                    include_global_state: bool = True) -> dict:
    if snap_name in repo.catalog():
        raise SnapshotException(
            f"snapshot [{repo.name}:{snap_name}] already exists")
    # None = all indices; an explicit (even empty) list is taken literally —
    # a non-matching pattern must NOT silently widen to the whole cluster
    names = sorted(node.indices) if indices is None else indices
    if not names:
        raise SnapshotException("no indices matched the snapshot request")
    manifest: dict = {
        "snapshot": snap_name,
        "state": "SUCCESS",
        "start_time_ms": int(time.time() * 1000),
        "indices": {},
    }
    for iname in names:
        svc = node.indices.get(iname)
        if svc is None:
            raise SnapshotException(f"index [{iname}] not found")
        # freeze the buffer so the snapshot is a refresh-consistent view
        svc.refresh()
        shards_meta = []
        for shard in svc.shards:
            blobs = []
            versions: Dict[str, int] = {}
            for seg in shard.segments:
                blobs.append(repo.put_blob(_segment_payload(seg)))
            for doc_id, loc in shard.engine._locations.items():
                if not loc.deleted:
                    versions[doc_id] = loc.version
            shards_meta.append({"blobs": blobs, "versions": versions})
        manifest["indices"][iname] = {
            "settings": svc.settings,
            "mappings": svc.mappings.to_json(),
            "aliases": svc.aliases,
            "shards": shards_meta,
        }
    if include_global_state:
        manifest["global_state"] = {
            "templates": dict(node.cluster_state.templates),
            "search_templates": dict(getattr(node, "search_templates", {})),
        }
    manifest["end_time_ms"] = int(time.time() * 1000)
    repo.put_manifest(snap_name, manifest)
    return {"snapshot": {
        "snapshot": snap_name, "state": "SUCCESS",
        "indices": list(manifest["indices"]),
        "shards": {"total": sum(len(i["shards"]) for i in manifest["indices"].values()),
                   "failed": 0,
                   "successful": sum(len(i["shards"]) for i in manifest["indices"].values())},
    }}


def restore_snapshot(node, repo: FsRepository, snap_name: str,
                     indices: Optional[List[str]] = None,
                     rename_pattern: Optional[str] = None,
                     rename_replacement: Optional[str] = None) -> dict:
    import fnmatch as _fn
    import re as _re

    manifest = repo.get_manifest(snap_name)
    restored = []
    for iname, imeta in manifest["indices"].items():
        # patterns match against MANIFEST names (the indices being restored
        # don't exist on the node, so node-side resolution can't apply)
        if indices and not any(_fn.fnmatch(iname, pat) for pat in indices):
            continue
        target = iname
        if rename_pattern and rename_replacement is not None:
            target = _re.sub(rename_pattern, rename_replacement, iname)
        if target in node.indices:
            raise SnapshotException(
                f"cannot restore index [{target}]: an open index with that "
                f"name already exists (close or delete it first)")
        node.create_index(target, {
            "settings": imeta["settings"],
            "mappings": imeta["mappings"],
        })
        svc = node.indices[target]
        svc.aliases.update(imeta.get("aliases", {}))
        for shard_meta in imeta["shards"]:
            versions = shard_meta.get("versions", {})
            for sha in shard_meta["blobs"]:
                payload = repo.get_blob(sha)
                for entry in payload.get("ivf", []):
                    from elasticsearch_tpu.index import ivf_cache

                    ivf_cache.seed(entry["key"],
                                   base64.b64decode(entry["blob"]))
                for doc in payload["docs"]:
                    meta = doc.get("meta", {})
                    svc.index_doc(
                        doc["id"], doc["source"],
                        routing=meta.get("routing") or meta.get("_parent"),
                        doc_type=meta.get("_type"),
                        parent=meta.get("_parent"),
                        version=versions.get(doc["id"]),
                        version_type="external",
                    )
        svc.refresh()
        restored.append(target)
    if "global_state" in manifest and not indices:
        node.cluster_state.templates.update(manifest["global_state"].get("templates", {}))
        if hasattr(node, "search_templates"):
            node.search_templates.update(
                manifest["global_state"].get("search_templates", {}))
    return {"snapshot": {"snapshot": snap_name, "indices": restored,
                         "shards": {"failed": 0}}}


def snapshot_info(repo: FsRepository, snap_name: str) -> dict:
    m = repo.get_manifest(snap_name)
    return {
        "snapshot": snap_name,
        "state": m.get("state", "SUCCESS"),
        "indices": list(m.get("indices", {})),
        "start_time_in_millis": m.get("start_time_ms", 0),
        "end_time_in_millis": m.get("end_time_ms", 0),
    }
